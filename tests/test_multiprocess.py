"""Multi-process cluster test: a coordinator server + a member server in a
separate OS process, joined via seed discovery, sharing the WAL; shard
assignment, remote ingestion and cross-process scatter-gather queries.

The closest analog of the reference's multi-jvm specs
(``standalone/src/multi-jvm/.../IngestionAndRecoverySpec``,
``ClusterSingletonFailoverSpec``) — real process isolation, real TCP.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.parse
import urllib.request

import pytest

from filodb_tpu.config import ServerConfig
from filodb_tpu.standalone import FiloServer

START = 1_600_000_000


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def test_two_process_cluster(tmp_path):
    wal_dir = str(tmp_path / "wal")
    exec_port = _free_port()
    coord_cfg = {
        "node_name": "coord", "data_dir": str(tmp_path / "coord"),
        "wal_dir": wal_dir, "http_port": 0, "gateway_port": _free_port(),
        "executor_port": exec_port,
        "datasets": {"timeseries": {
            "num_shards": 4, "min_num_nodes": 2, "spread": 1,
            "store": {"max_chunk_size": 100, "groups_per_shard": 2}}},
    }
    member_cfg = dict(coord_cfg)
    member_cfg.update({
        "node_name": "member-1", "data_dir": str(tmp_path / "member"),
        "http_port": 0, "gateway_port": 0, "executor_port": 0,
        "seeds": [f"127.0.0.1:{exec_port}"],
    })
    member_path = tmp_path / "member.json"
    member_path.write_text(json.dumps(member_cfg))

    cfg_path = tmp_path / "coord.json"
    cfg_path.write_text(json.dumps(coord_cfg))
    coord = FiloServer(ServerConfig.load(str(cfg_path))).start()
    member = subprocess.Popen(
        [sys.executable, "-m", "filodb_tpu.standalone", "--config",
         str(member_path)],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        cwd="/root/repo", stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        # wait until both nodes own shards (coordinator assigns on join)
        deadline = time.monotonic() + 180
        sm = coord.cluster.shard_managers["timeseries"]
        while time.monotonic() < deadline:
            owners = set(filter(None, sm.mapper.owners))
            if owners == {"coord", "member-1"}:
                break
            assert member.poll() is None, member.stdout.read()[-3000:]
            time.sleep(0.2)
        assert set(filter(None, sm.mapper.owners)) == {"coord", "member-1"}

        # feed data through the gateway: records route to all 4 shard WALs
        with socket.create_connection(
                ("127.0.0.1", coord.gateway.port)) as s:
            for i in range(200):
                for inst in range(8):
                    ts_ns = (START + i * 10) * 1_000_000_000
                    s.sendall(
                        f"cpu_usage,_ws_=demo,_ns_=App-0,instance=i{inst} "
                        f"value={i} {ts_ns}\n".encode())
        coord.gateway.sink.flush()

        # query through the coordinator: leaves dispatch across processes
        deadline = time.monotonic() + 60
        count = 0
        while time.monotonic() < deadline:
            body = _get(coord.http.port,
                        "/promql/timeseries/api/v1/query_range",
                        query='count(cpu_usage{_ws_="demo",_ns_="App-0"})',
                        start=START + 1000, end=START + 1000, step=60)
            res = body["data"]["result"]
            if res:
                count = float(res[0]["values"][0][1])
                if count == 8:
                    break
            time.sleep(0.3)
        assert count == 8.0
        # member process really owns shards with data
        member_shards = coord.cluster.nodes["member-1"] \
            .owned_shards("timeseries")
        assert member_shards
    finally:
        member.send_signal(signal.SIGTERM)
        try:
            member.wait(timeout=10)
        except subprocess.TimeoutExpired:
            member.kill()
        coord.shutdown()


def test_singleton_failover(tmp_path):
    """Coordinator process dies → surviving member promotes itself, adopts
    running shards, recovers the dead coordinator's shards from the shared
    WAL, and serves queries (reference ClusterSingletonFailoverSpec)."""
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.core.record import IngestRecord, RecordContainer
    from filodb_tpu.coordinator.ingestion import route_container
    from filodb_tpu.kafka.log import SegmentedFileLog

    wal_dir = str(tmp_path / "wal")
    coord_port = _free_port()
    base = {
        "wal_dir": wal_dir, "http_port": 0, "gateway_port": 0,
        "enable_failover": True,
        "datasets": {"timeseries": {
            "num_shards": 4, "min_num_nodes": 2, "spread": 1,
            "store": {"max_chunk_size": 100, "groups_per_shard": 2}}},
    }
    coord_cfg = dict(base, node_name="a-coord",
                     data_dir=str(tmp_path / "coord"),
                     executor_port=coord_port)
    member_cfg = dict(base, node_name="b-member",
                      data_dir=str(tmp_path / "member"), executor_port=0,
                      seeds=[f"127.0.0.1:{coord_port}"])

    # publish data into the shared WAL before anything starts
    container = RecordContainer()
    for i in range(200):
        for inst in range(8):
            key = PartKey.create("gauge", {
                "_metric_": "fo_metric", "_ws_": "demo", "_ns_": "App-0",
                "instance": f"i{inst}"})
            container.add(IngestRecord(key, (START + i * 10) * 1000,
                                       (float(i),)))
    logs = {s: SegmentedFileLog(f"{wal_dir}/timeseries/shard-{s}")
            for s in range(4)}
    for shard, cont in route_container(container, 4, 1).items():
        logs[shard].append(cont)
    for log_ in logs.values():
        log_.close()

    coord_path = tmp_path / "coord.json"
    coord_path.write_text(json.dumps(coord_cfg))
    member_path = tmp_path / "member.json"
    member_path.write_text(json.dumps(member_cfg))

    coord_proc = subprocess.Popen(
        [sys.executable, "-m", "filodb_tpu.standalone", "--config",
         str(coord_path)],
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd="/root/repo",
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    member = None
    try:
        # wait for the coordinator's control port
        deadline = time.monotonic() + 60
        from filodb_tpu.coordinator.remote import RemotePlanDispatcher
        while time.monotonic() < deadline:
            if RemotePlanDispatcher("127.0.0.1", coord_port,
                                    timeout=0.5).ping():
                break
            assert coord_proc.poll() is None
            time.sleep(0.2)
        member = FiloServer(ServerConfig.load(str(member_path))).start()
        # member owns some shards once the coordinator assigns
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if member.node.owned_shards("timeseries"):
                break
            time.sleep(0.2)
        assert member.node.owned_shards("timeseries")

        # kill the coordinator; member must promote and serve everything
        coord_proc.kill()
        coord_proc.wait(timeout=10)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if getattr(member, "is_coordinator", False):
                break
            time.sleep(0.2)
        assert member.is_coordinator
        # all four shards now active on the surviving member
        deadline = time.monotonic() + 30
        count = 0
        while time.monotonic() < deadline:
            try:
                body = _get(member.http.port,
                            "/promql/timeseries/api/v1/query_range",
                            query='count(fo_metric{_ws_="demo",_ns_="App-0"})',
                            start=START + 1000, end=START + 1000, step=60)
            except Exception:
                time.sleep(0.3)
                continue
            res = body["data"]["result"]
            if res:
                count = float(res[0]["values"][0][1])
                if count == 8:
                    break
            time.sleep(0.3)
        assert count == 8.0
    finally:
        if coord_proc.poll() is None:
            coord_proc.kill()
        if member is not None:
            member.shutdown()


def test_deployment_matrix_consul_remote_store_networked_wal(tmp_path):
    """Full round-5 deployment shape in one cluster: Consul seed discovery
    (no explicit seeds anywhere), a remote chunk-store tier shared by both
    nodes, and the networked WAL broker — zero shared filesystem. Ingest
    crosses processes; the durability tier survives a member restart."""
    import sys as _sys
    _sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__))))
    from test_consul_discovery import FakeConsulAgent

    from filodb_tpu.core.store.remotestore import (
        ChunkStoreServer,
        RemoteColumnStore,
    )

    consul = FakeConsulAgent().start()
    tier = ChunkStoreServer(root=str(tmp_path / "tier")).start()
    try:
        exec_port = _free_port()
        wal_port = _free_port()
        coord_cfg = {
            "node_name": "coord", "data_dir": str(tmp_path / "coord"),
            "http_port": 0, "gateway_port": _free_port(),
            "executor_port": exec_port,
            "wal_server_port": wal_port,
            "store_remote": f"127.0.0.1:{tier.port}",
            "consul": {"host": "127.0.0.1", "port": consul.port,
                       "service": "filodb"},
            "datasets": {"timeseries": {
                "num_shards": 4, "min_num_nodes": 2, "spread": 1,
                "store": {"max_chunk_size": 50, "groups_per_shard": 2}}},
        }
        member_cfg = dict(coord_cfg)
        member_cfg.update({
            "node_name": "member-1", "data_dir": str(tmp_path / "member"),
            "http_port": 0, "gateway_port": 0, "executor_port": 0,
            "wal_server_port": 0,
            "wal_remote": f"127.0.0.1:{wal_port}",
        })
        cfg_path = tmp_path / "coord.json"
        cfg_path.write_text(json.dumps(coord_cfg))
        member_path = tmp_path / "member.json"
        member_path.write_text(json.dumps(member_cfg))

        coord = FiloServer(ServerConfig.load(str(cfg_path))).start()
        # the coordinator registered itself; the member discovers it via
        # Consul — its config carries NO seed list
        assert "coord" in consul.services
        member = subprocess.Popen(
            [sys.executable, "-m", "filodb_tpu.standalone", "--config",
             str(member_path)],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            cwd="/root/repo", stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        try:
            deadline = time.monotonic() + 180
            sm = coord.cluster.shard_managers["timeseries"]
            while time.monotonic() < deadline:
                owners = set(filter(None, sm.mapper.owners))
                if owners == {"coord", "member-1"}:
                    break
                assert member.poll() is None, member.stdout.read()[-3000:]
                time.sleep(0.2)
            assert set(filter(None, sm.mapper.owners)) == \
                {"coord", "member-1"}

            with socket.create_connection(
                    ("127.0.0.1", coord.gateway.port)) as s:
                for i in range(120):
                    for inst in range(8):
                        # distinct _ns_ shard keys spread series over all
                        # four shards (both nodes own data)
                        ts_ns = (START + i * 10) * 1_000_000_000
                        s.sendall(
                            f"matrix_metric,_ws_=demo,_ns_=App-{inst % 4},"
                            f"instance=i{inst} value={i} {ts_ns}\n".encode())
            coord.gateway.sink.flush()

            deadline = time.monotonic() + 60
            count = 0
            while time.monotonic() < deadline:
                body = _get(coord.http.port,
                            "/promql/timeseries/api/v1/query_range",
                            query='count(matrix_metric)',
                            start=START + 1000, end=START + 1000, step=60)
                res = body["data"]["result"]
                if res:
                    count = float(res[0]["values"][0][1])
                    if count == 8:
                        break
                time.sleep(0.3)
            assert count == 8.0

            # flush the coordinator-owned shards; chunks must land in
            # the shared REMOTE tier (member shards flush on their own
            # schedule in the other process)
            flushed_shards = []
            expected_keys = 0
            for sh, owner in enumerate(sm.mapper.owners):
                if owner == "coord":
                    shard_obj = coord.memstore.get_shard("timeseries", sh)
                    shard_obj.flush_all()
                    flushed_shards.append(sh)
                    expected_keys += shard_obj.num_partitions
            assert flushed_shards and expected_keys >= 1
            probe = RemoteColumnStore("127.0.0.1", tier.port)
            deadline = time.monotonic() + 30
            tiered = 0
            while time.monotonic() < deadline:
                tiered = sum(
                    len(probe.scan_part_keys("timeseries", sh))
                    for sh in flushed_shards)
                if tiered >= expected_keys:
                    break
                time.sleep(0.5)
            assert tiered >= expected_keys
            probe.close()
        finally:
            member.send_signal(signal.SIGTERM)
            try:
                member.wait(timeout=10)
            except subprocess.TimeoutExpired:
                member.kill()
            coord.shutdown()
        # consul: coordinator deregistered on shutdown
        assert "coord" not in consul.services
    finally:
        tier.shutdown()
        consul.stop()
