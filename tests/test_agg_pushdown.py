"""Two-phase aggregation pushdown: the pushed-down (map on children,
reduce at root) plan must be indistinguishable from the single-phase
full-gather plan for every pushdown-capable op, locally and over TCP
plan shipping, including partial results with a lost child and result
cache hits across the two plan forms.

Equivalence is semantic, not bit-level: partials reduce per shard before
the root combine, so float32 kernel sums associate differently — asserted
at kernel-dtype tolerance (stddev/stdvar looser: the sum-of-squares
difference cancels catastrophically in low precision).

Also covers the wire-frame compression that rides along: flag-bit framing,
negotiation with pre-compression peers, and the bounded-inflate guard.
"""

import socket
import struct
import threading
import zlib

import numpy as np
import pytest

from filodb_tpu.coordinator import planner as planner_mod
from filodb_tpu.coordinator import remote as remote_mod
from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.coordinator.remote import (
    PlanExecutorServer,
    RemotePlanDispatcher,
    _recv_frame,
    _recv_msg,
    _send_msg,
    reset_pool,
)
from filodb_tpu.coordinator.wire import decode, encode
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.query.exec import transformers as tf
from filodb_tpu.query.exec.plan import (
    DistConcatExec,
    ReduceAggregateExec,
    SelectRawPartitionsExec,
)
from filodb_tpu.testing.data import (
    counter_series,
    counter_stream,
    gauge_stream,
    histogram_series,
    histogram_stream,
    machine_metrics_series,
)
from filodb_tpu.utils.resilience import reset_breakers

NUM_SHARDS = 4
START = 1_600_000_000
QS = START + 100
QE = START + 2000
STEP = 60


def build_store():
    ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=100,
                                              groups_per_shard=4))
    streams = [
        gauge_stream(machine_metrics_series(10, ns="App-2"), 240,
                     start_ms=START * 1000, interval_ms=10_000, seed=11),
        counter_stream(counter_series(6, ns="App-1"), 240,
                       start_ms=START * 1000, interval_ms=10_000, seed=3,
                       reset_every=100),
        histogram_stream(histogram_series(4), 240,
                         start_ms=START * 1000, interval_ms=10_000, seed=7),
    ]
    for stream in streams:
        ingest_routed(ms, "timeseries", stream, NUM_SHARDS, spread=1)
    return ms


@pytest.fixture(scope="module")
def store():
    return build_store()


@pytest.fixture(scope="module")
def svc(store):
    return QueryService(store, "timeseries", NUM_SHARDS, spread=1)


def assert_equivalent(a, b, rtol=2e-5):
    m0, m1 = a.result, b.result
    i0 = {k: i for i, k in enumerate(m0.keys)}
    i1 = {k: i for i, k in enumerate(m1.keys)}
    assert set(i0) == set(i1), set(i0) ^ set(i1)
    if m0.num_series:
        assert np.array_equal(m0.steps_ms, m1.steps_ms)
    for k, i in i0.items():
        x = np.asarray(m0.values[i])
        y = np.asarray(m1.values[i1[k]])
        assert np.array_equal(np.isnan(x), np.isnan(y)), k
        assert np.allclose(x, y, rtol=rtol, atol=1e-9, equal_nan=True), k


# every pushdown-capable op (with by / without / ungrouped forms), the
# bypass ops, and shapes layered above the aggregate
OP_QUERIES = [
    ("sum(heap_usage)", 2e-5),
    ("sum(heap_usage) by (host)", 2e-5),
    ("sum(rate(http_requests_total[5m])) by (job)", 2e-5),
    ("sum(heap_usage) without (host)", 2e-5),
    ("avg(heap_usage) by (host)", 2e-5),
    ("avg(heap_usage)", 2e-5),
    ("count(heap_usage) without (host)", 2e-5),
    ("count(heap_usage)", 2e-5),
    ("min(heap_usage) by (host)", 2e-5),
    ("max(heap_usage)", 2e-5),
    ("group(heap_usage) by (host)", 2e-5),
    ("stddev(heap_usage) by (host)", 2e-3),
    ("stdvar(heap_usage)", 2e-3),
    ("topk(3, heap_usage)", 2e-5),
    ("topk(2, heap_usage) by (host)", 2e-5),
    ("bottomk(2, heap_usage) by (host)", 2e-5),
    # declared bypass list: identical because neither form pushes down
    ("quantile(0.9, heap_usage) by (host)", 2e-5),
    ('count_values("v", heap_usage)', 2e-5),
    # histogram-valued matrices aggregate per bucket
    ("sum(rate(http_req_latency[5m])) by (host)", 2e-5),
    ("histogram_quantile(0.9, sum(rate(http_req_latency[5m])))", 2e-5),
    # transforms above the aggregate see identical inputs
    ("abs(sum(heap_usage) by (host)) * 2", 2e-5),
]


class TestLocalEquivalence:
    @pytest.mark.parametrize("promql,rtol", OP_QUERIES)
    def test_pushed_matches_unpushed(self, svc, promql, rtol):
        svc.planner.agg_pushdown = "off"
        unpushed = svc.query_range(promql, QS, STEP, QE)
        svc.planner.agg_pushdown = "always"
        try:
            pushed = svc.query_range(promql, QS, STEP, QE)
        finally:
            svc.planner.agg_pushdown = "auto"
        assert_equivalent(unpushed, pushed, rtol)


class TestPlanShapes:
    def _materialize(self, mode, dispatcher_for_shard=None,
                     promql="sum(heap_usage) by (host)"):
        pl = SingleClusterPlanner("timeseries", NUM_SHARDS, spread=1,
                                  dispatcher_for_shard=dispatcher_for_shard)
        pl.agg_pushdown = mode
        from filodb_tpu.promql.parser import TimeStepParams, parse_query
        plan = parse_query(promql, TimeStepParams(QS, STEP, QE))
        return pl.materialize(plan)

    def test_always_pushes_map_stage_into_leaves(self):
        ep = self._materialize("always")
        assert isinstance(ep, ReduceAggregateExec) and ep.pushdown
        assert len(ep.children_plans) == NUM_SHARDS
        for leaf in ep.children_plans:
            assert isinstance(leaf, SelectRawPartitionsExec)
            assert isinstance(leaf.transformers[-1],
                              tf.AggregatePartialMapper)

    def test_auto_all_local_bypasses(self):
        # local shards keep the single big device reduce: the win is wire
        # bytes, and there is no wire
        ep = self._materialize("auto")
        assert isinstance(ep, ReduceAggregateExec) and not ep.pushdown
        assert isinstance(ep.children_plans[0], DistConcatExec)

    def test_auto_remote_pushes(self):
        disp = RemotePlanDispatcher("127.0.0.1", 65000)
        ep = self._materialize("auto", dispatcher_for_shard=lambda s: disp)
        assert ep.pushdown

    def test_off_never_pushes(self):
        disp = RemotePlanDispatcher("127.0.0.1", 65000)
        ep = self._materialize("off", dispatcher_for_shard=lambda s: disp)
        assert not ep.pushdown

    @pytest.mark.parametrize("promql", [
        "quantile(0.9, heap_usage) by (host)",
        'count_values("v", heap_usage)',
    ])
    def test_bypass_ops_never_push(self, promql):
        ep = self._materialize("always", promql=promql)
        assert isinstance(ep, ReduceAggregateExec) and not ep.pushdown

    def test_decision_counters_move(self):
        a0 = planner_mod.PUSHDOWN_APPLIED.value
        b0 = planner_mod.PUSHDOWN_BYPASSED.value
        self._materialize("always")
        self._materialize("off")
        assert planner_mod.PUSHDOWN_APPLIED.value == a0 + 1
        assert planner_mod.PUSHDOWN_BYPASSED.value == b0 + 1

    def test_pushdown_plan_round_trips_on_wire(self):
        ep = self._materialize("always")
        rt = decode(encode(ep))
        assert isinstance(rt, ReduceAggregateExec) and rt.pushdown
        mapper = rt.children_plans[0].transformers[-1]
        assert isinstance(mapper, tf.AggregatePartialMapper)
        assert (mapper.op, mapper.by) == ("sum", ("host",))


class TestRemoteDispatch:
    @pytest.fixture()
    def remote_env(self, store):
        reset_breakers()
        reset_pool()
        srv = PlanExecutorServer(store).start()
        disp = RemotePlanDispatcher("127.0.0.1", srv.port)
        svc = QueryService(store, "timeseries", NUM_SHARDS, spread=1)
        svc.planner.dispatcher_for_shard = lambda s: disp
        yield svc
        srv.stop()
        reset_pool()

    @pytest.mark.parametrize("promql,rtol", [
        ("sum(heap_usage) by (host)", 2e-5),
        ("avg(rate(http_requests_total[5m])) by (job)", 2e-5),
        ("stddev(heap_usage)", 2e-3),
        ("topk(2, heap_usage) by (host)", 2e-5),
    ])
    def test_remote_pushdown_equivalence(self, remote_env, promql, rtol):
        svc = remote_env
        svc.planner.agg_pushdown = "off"
        unpushed = svc.query_range(promql, QS, STEP, QE)
        svc.planner.agg_pushdown = "auto"  # remote children: auto pushes
        pushed = svc.query_range(promql, QS, STEP, QE)
        assert_equivalent(unpushed, pushed, rtol)

    def test_pushdown_ships_fewer_bytes(self, remote_env):
        svc = remote_env
        promql = "sum(heap_usage) by (host)"

        def received(mode):
            svc.planner.agg_pushdown = mode
            before = remote_mod.BYTES_RECEIVED.value
            svc.query_range(promql, QS, STEP, QE)
            return remote_mod.BYTES_RECEIVED.value - before

        off, on = received("off"), received("auto")
        assert 0 < on < off

    def test_lost_child_partial_equivalence(self, store):
        # shard 3's peer is dead: both plan forms degrade to the same
        # partial result (3 of 4 children) instead of failing
        reset_breakers()
        reset_pool()
        srv = PlanExecutorServer(store).start()
        live = RemotePlanDispatcher("127.0.0.1", srv.port)
        with socket.socket() as s:  # a port with nothing listening
            s.bind(("127.0.0.1", 0))
            dead_port = s.getsockname()[1]
        dead = RemotePlanDispatcher("127.0.0.1", dead_port, timeout=2.0)
        svc = QueryService(store, "timeseries", NUM_SHARDS, spread=1)
        svc.planner.dispatcher_for_shard = \
            lambda sh: dead if sh == 3 else live
        try:
            svc.planner.agg_pushdown = "off"
            unpushed = svc.query_range("sum(heap_usage) by (host)",
                                       QS, STEP, QE)
            reset_breakers()
            svc.planner.agg_pushdown = "auto"
            pushed = svc.query_range("sum(heap_usage) by (host)",
                                     QS, STEP, QE)
        finally:
            srv.stop()
            reset_pool()
            reset_breakers()
        assert unpushed.partial and pushed.partial
        assert any("shards [3]" in w for w in pushed.warnings)
        assert_equivalent(unpushed, pushed)


class TestResultCacheAcrossPlanForms:
    def test_pushed_and_unpushed_hit_the_same_entries(self, store):
        # the cache keys on the LOGICAL plan: whether the exec tree pushed
        # the map stage down must not change the cache identity
        svc = QueryService(store, "timeseries", NUM_SHARDS, spread=1,
                           result_cache={"extent_steps": 7})
        from filodb_tpu.query import result_cache as rc
        promql = "sum(rate(http_requests_total[5m])) by (job)"
        svc.planner.agg_pushdown = "off"
        unpushed = svc.query_range(promql, QS, STEP, QE)
        hits_before = rc.cache_hits.value
        svc.planner.agg_pushdown = "always"
        pushed = svc.query_range(promql, QS, STEP, QE)
        assert rc.cache_hits.value > hits_before
        assert_equivalent(unpushed, pushed)


# ---------------------------------------------------------------------------
# wire-frame compression


def _sockpair():
    a, b = socket.socketpair()
    a.settimeout(5)
    b.settimeout(5)
    return a, b


class TestWireCompression:
    def test_large_frame_round_trips_compressed(self):
        a, b = _sockpair()
        try:
            msg = ("ok", ["x" * 200] * 500)  # compressible, > threshold
            n = _send_msg(a, msg, compress=True)
            raw_len = len(encode(msg))
            assert n < 4 + raw_len  # actually shrank on the wire
            got, nrecv = _recv_frame(b)
            assert got == msg and nrecv == n
        finally:
            a.close()
            b.close()

    def test_small_frame_stays_raw(self):
        a, b = _sockpair()
        try:
            n = _send_msg(a, ("ping",), compress=True)
            hdr = b.recv(4, socket.MSG_PEEK)
            (word,) = struct.unpack("<I", hdr)
            assert not word & remote_mod._FLAG_COMPRESSED
            assert _recv_msg(b) == ("ping",)
            assert n == 4 + (word & ~remote_mod._FLAG_COMPRESSED)
        finally:
            a.close()
            b.close()

    def test_uncompressed_peer_frames_still_decode(self):
        a, b = _sockpair()
        try:
            _send_msg(a, ("ok", True))  # compress=False: legacy framing
            assert _recv_msg(b) == ("ok", True)
        finally:
            a.close()
            b.close()

    def test_bounded_inflate_rejects_bombs(self):
        # a tiny compressed frame expanding past the cap must be refused
        # before it allocates, like an oversized raw frame
        a, b = _sockpair()
        try:
            packed = zlib.compress(b"\x00" * 4_000_000, 9)
            a.sendall(struct.pack(
                "<I", len(packed) | remote_mod._FLAG_COMPRESSED) + packed)
            with pytest.raises(ConnectionError):
                _recv_frame(b, cap=65536)
        finally:
            a.close()
            b.close()

    def test_negotiation_with_pre_compression_peer(self, store):
        # emulate an old server: same framing, no hello support — the
        # dialer records the refusal and the connection stays usable
        def old_server(srv_sock, stop):
            while not stop.is_set():
                try:
                    conn, _ = srv_sock.accept()
                except OSError:
                    return
                with conn:
                    try:
                        while True:
                            msg = _recv_msg(conn)
                            if msg[0] == "ping":
                                _send_msg(conn, ("pong",))
                            else:
                                _send_msg(conn, (
                                    "err", f"unknown message {msg[0]!r}"))
                    except (ConnectionError, OSError):
                        pass

        reset_pool()
        srv_sock = socket.socket()
        srv_sock.bind(("127.0.0.1", 0))
        srv_sock.listen(1)
        port = srv_sock.getsockname()[1]
        stop = threading.Event()
        t = threading.Thread(target=old_server, args=(srv_sock, stop),
                             daemon=True)
        t.start()
        try:
            disp = RemotePlanDispatcher("127.0.0.1", port, timeout=5.0)
            assert disp.ping()  # hello rejected, connection survives
            assert remote_mod._peer_caps[("127.0.0.1", port)] is False
        finally:
            stop.set()
            srv_sock.close()
            reset_pool()
            remote_mod._peer_caps.pop(("127.0.0.1", port), None)

    def test_new_peers_negotiate_compression(self, store):
        reset_pool()
        srv = PlanExecutorServer(store).start()
        try:
            disp = RemotePlanDispatcher("127.0.0.1", srv.port)
            assert disp.ping()
            assert remote_mod._peer_caps[("127.0.0.1", srv.port)] is True
        finally:
            srv.stop()
            reset_pool()
            remote_mod._peer_caps.pop(("127.0.0.1", srv.port), None)
