"""Span tracing (reference: Kamon spans around ExecPlan execution,
``ExecPlan.scala:101``; ODP span ``OnDemandPagingShard.scala:48``)."""

import json
import urllib.request

import numpy as np

from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.testing.data import (
    counter_series,
    counter_stream,
    gauge_stream,
    machine_metrics_series,
)
from filodb_tpu.utils.tracing import span, start_trace

START = 1_600_000_000


class TestSpans:
    def test_nesting_and_timing(self):
        with start_trace() as trace:
            with span("outer", q="x"):
                with span("inner"):
                    pass
            with span("sibling"):
                pass
        names = [(s.name, s.depth) for s in trace.spans]
        assert names == [("outer", 0), ("inner", 1), ("sibling", 0)]
        assert all(s.duration_s >= 0 for s in trace.spans)
        assert trace.find("outer")[0].tags == {"q": "x"}

    def test_noop_without_trace(self):
        # no active trace: span() must not record or fail
        with span("orphan") as s:
            assert s is None

    def test_exec_path_spans(self):
        ms = TimeSeriesMemStore()
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=100))
        keys = counter_series(4, metric="m")
        ingest_routed(ms, "timeseries",
                      counter_stream(keys, 200, start_ms=START * 1000), 1, 0)
        svc = QueryService(ms, "timeseries", 1, spread=0)  # exec engine
        with start_trace() as trace:
            r = svc.query_range("sum(rate(m[5m]))", START + 600, 60,
                                START + 1200)
        assert r.result.num_series == 1
        names = {s.name for s in trace.spans}
        assert "parse" in names
        assert "plan-materialize" in names
        assert "exec-dispatch" in names
        # exec nodes appear by class name, nested under the dispatch
        dispatch = trace.find("exec-dispatch")[0]
        node_spans = [s for s in trace.spans if s.depth > dispatch.depth]
        assert node_spans, "no exec-node spans recorded"

    def test_odp_span(self, tmp_path):
        from filodb_tpu.core.store.localstore import (
            LocalDiskColumnStore,
            LocalDiskMetaStore,
        )
        cs = LocalDiskColumnStore(str(tmp_path / "d"))
        meta = LocalDiskMetaStore(str(tmp_path / "d"))
        ms = TimeSeriesMemStore(cs, meta)
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=50))
        keys = machine_metrics_series(2)
        shard = ms.get_shard("timeseries", 0)
        for sd in gauge_stream(keys, 200, start_ms=START * 1000):
            shard.ingest(sd)
        shard.flush_all(ingestion_time=1)
        for p in shard.partitions:
            if p:
                shard.evict_partition_chunks(p.part_id)
        svc = QueryService(ms, "timeseries", 1, spread=0)
        with start_trace() as trace:
            svc.query_range("count_over_time(heap_usage[30m])",
                            START + 1900, 60, START + 1900)
        odp = trace.find("odp-page")
        assert odp and odp[0].tags.get("partitions_paged", 0) > 0

    def test_debug_trace_endpoint(self):
        from filodb_tpu.http.fastserver import FastHttpServer
        ms = TimeSeriesMemStore()
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=100))
        keys = counter_series(3, metric="m")
        ingest_routed(ms, "timeseries",
                      counter_stream(keys, 100, start_ms=START * 1000), 1, 0)
        svc = QueryService(ms, "timeseries", 1, spread=0)
        srv = FastHttpServer({"timeseries": svc}, port=0).start()
        try:
            url = (f"http://127.0.0.1:{srv.port}/promql/timeseries/api/v1/"
                   f"debug/trace?query=sum(rate(m[5m]))&start={START + 300}"
                   f"&end={START + 900}&step=60")
            with urllib.request.urlopen(url, timeout=30) as r:
                body = json.loads(r.read())
            data = body["data"]
            assert data["result_series"] == 1
            assert data["stats"]["samples_scanned"] > 0
            names = [s["name"] for s in data["spans"]]
            assert "parse" in names
            assert all(np.isfinite(s["duration_ms"]) for s in data["spans"])
        finally:
            srv.stop()
