"""Accelerator-probe fast-fail helpers in ``bench.py``: outcome cache
(TTL disk record) and the total probe time budget. Pure host-side logic —
no jax, no subprocess probes (``_probe_once`` is stubbed)."""

import json
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


@pytest.fixture
def isolated(tmp_path, monkeypatch):
    """Fresh cache path, no env short-circuits, no real CPU forcing."""
    monkeypatch.setattr(bench, "PROBE_CACHE_PATH",
                        str(tmp_path / "probe_cache.json"))
    monkeypatch.setattr(bench, "_force_cpu", lambda: None)
    monkeypatch.delenv("FILODB_BENCH_CPU", raising=False)
    monkeypatch.delenv("FILODB_BENCH_PROBE_ATTEMPTS", raising=False)
    return tmp_path


class TestProbeCache:
    def test_round_trip(self, isolated):
        bench._probe_cache_write("tpu")
        rec = bench._probe_cache_read()
        assert rec["platform"] == "tpu"

    def test_absent_and_corrupt_return_none(self, isolated):
        assert bench._probe_cache_read() is None
        with open(bench.PROBE_CACHE_PATH, "w") as f:
            f.write("not json{")
        assert bench._probe_cache_read() is None

    def test_stale_entry_expires(self, isolated):
        with open(bench.PROBE_CACHE_PATH, "w") as f:
            json.dump({"platform": "tpu", "ts": time.time() - 10_000}, f)
        assert bench._probe_cache_read() is None
        assert bench._probe_cache_read(ttl_s=100_000)["platform"] == "tpu"


class TestEnsureBackend:
    def test_env_short_circuit(self, isolated, monkeypatch):
        monkeypatch.setenv("FILODB_BENCH_CPU", "1")
        monkeypatch.setattr(bench, "_probe_once", lambda t: (
            pytest.fail("probe must not run under FILODB_BENCH_CPU")))
        plat, log = bench._ensure_backend()
        assert plat == "cpu"
        assert log[0]["outcome"] == "skipped"

    def test_cached_outcome_skips_probe(self, isolated, monkeypatch):
        bench._probe_cache_write("cpu")
        monkeypatch.setattr(bench, "_probe_once", lambda t: (
            pytest.fail("probe must not run on a cache hit")))
        plat, log = bench._ensure_backend()
        assert plat == "cpu"
        assert log[0]["outcome"] == "cached"

    def test_success_is_cached(self, isolated, monkeypatch):
        monkeypatch.setattr(bench, "_probe_once",
                            lambda t: ("tpu", {"outcome": "ok",
                                               "platform": "tpu"}))
        plat, log = bench._ensure_backend()
        assert plat == "tpu"
        assert bench._probe_cache_read()["platform"] == "tpu"

    def test_zero_budget_falls_back_immediately(self, isolated, monkeypatch):
        monkeypatch.setattr(bench, "PROBE_BUDGET_S", 0.0)
        monkeypatch.setattr(bench, "_probe_once", lambda t: (
            pytest.fail("no probe may start with the budget spent")))
        plat, log = bench._ensure_backend()
        assert plat == "cpu"
        assert log[-1]["outcome"] == "budget_exhausted"
        # the CPU fallback is cached too: the next run starts instantly
        assert bench._probe_cache_read()["platform"] == "cpu"

    def test_backoff_respects_budget(self, isolated, monkeypatch):
        """A failed attempt whose backoff would overshoot the budget must
        fall back without sleeping (BENCH_r05 burned ~16 min here)."""
        monkeypatch.setattr(bench, "PROBE_BUDGET_S", 5.0)
        monkeypatch.setattr(bench, "_probe_once",
                            lambda t: (None, {"outcome": "timeout"}))
        monkeypatch.setattr(bench.time, "sleep", lambda s: (
            pytest.fail("must not sleep past the probe budget")))
        t0 = time.time()
        plat, log = bench._ensure_backend()
        assert plat == "cpu"
        assert time.time() - t0 < 2.0
        assert [r["outcome"] for r in log] == ["timeout",
                                               "budget_exhausted"]
