"""Kafka wire-protocol adapter vs a protocol-level fake broker.

Validates the client speaks the real v0 wire format (framing, headers,
CRC'd MessageSet v0) and that ``KafkaReplayLog`` satisfies the ReplayLog
SPI a shard ingests from (reference ``KafkaIngestionStream.scala``).
"""

import pytest

from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.kafka.kafka_protocol import (
    FakeKafkaBroker,
    KafkaProtocolClient,
    KafkaProtocolError,
    KafkaReplayLog,
    decode_message_set,
    encode_message_set,
)
from filodb_tpu.testing.data import gauge_stream, machine_metrics_series

START = 1_600_000_000


@pytest.fixture
def broker():
    b = FakeKafkaBroker().start()
    b.create_topic("timeseries-dev", 4)
    yield b
    b.stop()


@pytest.fixture
def client(broker):
    c = KafkaProtocolClient("127.0.0.1", broker.port)
    yield c
    c.close()


class TestMessageSet:
    def test_round_trip(self):
        entries = [(0, b"k0", b"v0"), (1, None, b"v1"), (2, b"k2", b"")]
        out = decode_message_set(encode_message_set(entries))
        assert out == entries

    def test_partial_trailing_message_ignored(self):
        data = encode_message_set([(0, None, b"hello")])
        out = decode_message_set(data[:-3])
        assert out == []

    def test_crc_mismatch_raises(self):
        data = bytearray(encode_message_set([(0, None, b"hello")]))
        data[-1] ^= 0xFF
        with pytest.raises(ValueError, match="crc"):
            decode_message_set(bytes(data))


class TestProtocolClient:
    def test_api_versions(self, client):
        vers = client.api_versions()
        assert 0 in vers and 1 in vers and 2 in vers and 3 in vers

    def test_metadata(self, client, broker):
        md = client.metadata(["timeseries-dev"])
        assert md["brokers"][0][2] == broker.port
        parts = md["topics"]["timeseries-dev"]["partitions"]
        assert sorted(parts) == [0, 1, 2, 3]

    def test_produce_fetch_offsets(self, client):
        base = client.produce("timeseries-dev", 1,
                              [(None, b"m0"), (b"key", b"m1")])
        assert base == 0
        assert client.produce("timeseries-dev", 1, [(None, b"m2")]) == 2
        hw, msgs = client.fetch("timeseries-dev", 1, 0)
        assert hw == 3
        assert [v for _, _, v in msgs] == [b"m0", b"m1", b"m2"]
        assert msgs[1][1] == b"key"
        # offsets API
        assert client.list_offsets("timeseries-dev", 1, -2) == 0  # earliest
        assert client.list_offsets("timeseries-dev", 1, -1) == 3  # latest

    def test_fetch_from_mid_offset(self, client):
        client.produce("timeseries-dev", 0,
                       [(None, f"m{i}".encode()) for i in range(10)])
        hw, msgs = client.fetch("timeseries-dev", 0, 7)
        assert [o for o, _, _ in msgs] == [7, 8, 9]

    def test_fetch_out_of_range(self, client):
        client.produce("timeseries-dev", 2, [(None, b"x")])
        with pytest.raises(KafkaProtocolError):
            client.fetch("timeseries-dev", 2, 99)

    def test_fetch_respects_max_bytes(self, client):
        client.produce("timeseries-dev", 3,
                       [(None, bytes(1000)) for _ in range(20)])
        _, msgs = client.fetch("timeseries-dev", 3, 0, max_bytes=3000)
        assert 1 <= len(msgs) < 20

    def test_unknown_topic(self, client):
        with pytest.raises(KafkaProtocolError):
            client.fetch("nope", 0, 0)


class TestKafkaReplayLog:
    def test_append_read_latest(self, broker):
        lg = KafkaReplayLog("127.0.0.1", broker.port, "timeseries-dev", 0)
        keys = machine_metrics_series(2)
        stream = list(gauge_stream(keys, 40, start_ms=START * 1000,
                                   batch=10))
        offs = [lg.append(sd.container) for sd in stream]
        assert offs == list(range(len(stream)))
        assert lg.latest_offset == len(stream) - 1
        got = list(lg.read_from(0))
        assert len(got) == len(stream)
        assert [sd.offset for sd in got] == offs
        # containers round-trip through the broker byte-exactly
        assert got[0].container.serialize() == stream[0].container.serialize()
        # resume from a checkpoint
        tail = list(lg.read_from(5))
        assert [sd.offset for sd in tail] == offs[5:]
        lg.close()

    def test_retention_truncation_skips_forward(self, broker):
        lg = KafkaReplayLog("127.0.0.1", broker.port, "timeseries-dev", 1)
        keys = machine_metrics_series(1)
        for sd in gauge_stream(keys, 30, start_ms=START * 1000, batch=10):
            lg.append(sd.container)
        broker.truncate_before("timeseries-dev", 1, 2)
        got = list(lg.read_from(0))  # head truncated: resume at earliest
        assert [sd.offset for sd in got] == [2]
        lg.close()

    def test_shard_ingests_from_kafka(self, broker):
        """End-to-end: the shard consumes RecordContainer bytes from the
        broker exactly as from any other ReplayLog (partition == shard)."""
        lg = KafkaReplayLog("127.0.0.1", broker.port, "timeseries-dev", 2)
        keys = machine_metrics_series(4)
        for sd in gauge_stream(keys, 100, start_ms=START * 1000, batch=25):
            lg.append(sd.container)
        ms = TimeSeriesMemStore()
        shard = ms.setup("timeseries", 0, StoreConfig(max_chunk_size=50))
        for sd in lg.read_from(0):
            shard.ingest(sd)
        assert shard.stats.rows_ingested.value == 400
        assert shard.latest_offset == lg.latest_offset
        pids = shard.lookup_partitions([], 0, 2**62)
        assert len(pids) == 4
        ts, vals = shard.partition(pids[0]).read_samples(0, 2**62)
        assert len(ts) == 100
        lg.close()


class TestReviewRegressions:
    def test_tombstone_does_not_wedge_read(self, broker, client):
        """A null-value (tombstone) message must advance the cursor, not
        spin the poll loop forever on one offset."""
        client.produce("timeseries-dev", 0, [(None, b"a")])
        client.produce("timeseries-dev", 0, [(b"k", None)])  # tombstone
        client.produce("timeseries-dev", 0, [(None, b"b")])
        lg = KafkaReplayLog("127.0.0.1", broker.port, "timeseries-dev", 0)
        got = list(lg.read_from(0))
        assert [sd.offset for sd in got] == [0, 2]
        lg.close()

    def test_missing_topic_is_log_op_error(self, broker):
        """Deterministic broker answers surface as LogOpError (the ingest
        worker's give-up taxonomy), not as retryable transport errors."""
        from filodb_tpu.kafka.log_server import LogOpError
        lg = KafkaReplayLog("127.0.0.1", broker.port, "no-such-topic", 0)
        with pytest.raises(LogOpError):
            list(lg.read_from(0))
        lg.close()

    def test_producer_consumer_use_separate_connections(self, broker):
        lg = KafkaReplayLog("127.0.0.1", broker.port, "timeseries-dev", 1)
        lg.append(RecordContainerStub())
        assert lg.client is not lg._consumer
        lg.close()


class RecordContainerStub:
    def serialize(self):
        return b"\x02" + b"\x00" * 4  # empty v2 container
