"""Mesh engine under shard imbalance and ingest churn (VERDICT r3 #9).

The round-3 dryrun only exercised 30 balanced, static series; these tests
stress the two production realities it skipped:
- skewed shard→series distributions (shard-key hashing is never uniform),
- concurrent ingest ticking ``data_version`` so the device-resident batch
  cache must invalidate, rebuild and re-upload without serving stale data.
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.record import IngestRecord, RecordContainer, SomeData
from filodb_tpu.core.store.config import StoreConfig

START = 1_600_000_000
NUM_SHARDS = 4


def skewed_store(per_shard=(50, 5, 5, 5), n_samples=120):
    """Shard 0 carries 10x the series of the others (10:1 imbalance)."""
    ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=100,
                                              groups_per_shard=4))
    rng = np.random.default_rng(5)
    for shard_num, count in enumerate(per_shard):
        shard = ms.get_shard("timeseries", shard_num)
        keys = [PartKey.create("prom-counter", {
            "_metric_": "skew_total", "_ws_": "demo", "_ns_": "App-0",
            "shardtag": f"s{shard_num}", "instance": f"i{shard_num}-{j}"})
            for j in range(count)]
        vals = np.cumsum(rng.integers(1, 10, size=(count, n_samples)),
                         axis=1)
        for t in range(n_samples):
            c = RecordContainer()
            for k, key in enumerate(keys):
                c.add(IngestRecord(key, (START + t * 10) * 1000,
                                   (float(vals[k, t]),)))
            shard.ingest(SomeData(c, t))
    return ms


def services(ms):
    exec_svc = QueryService(ms, "timeseries", NUM_SHARDS, spread=1)
    mesh_svc = QueryService(ms, "timeseries", NUM_SHARDS, spread=1,
                            engine="mesh")
    return exec_svc, mesh_svc


def assert_same(r_exec, r_mesh):
    e, m = r_exec.result, r_mesh.result
    assert sorted(map(str, e.keys)) == sorted(map(str, m.keys))
    order_e = np.argsort([str(k) for k in e.keys])
    order_m = np.argsort([str(k) for k in m.keys])
    np.testing.assert_allclose(e.values[order_e], m.values[order_m],
                               rtol=1e-6, atol=1e-9, equal_nan=True)


class TestSkewedShards:
    @pytest.fixture(scope="class")
    def store(self):
        return skewed_store()

    def q(self, svc, query):
        return svc.query_range(query, START + 300, 60, START + 1100)

    def test_sum_rate_parity_under_skew(self, store):
        e, m = services(store)
        for query in ('sum(rate(skew_total[5m]))',
                      'sum(rate(skew_total[5m])) by (shardtag)',
                      'rate(skew_total[5m])'):
            assert_same(self.q(e, query), self.q(m, query))

    def test_all_shards_contribute(self, store):
        _, m = services(store)
        r = self.q(m, 'sum(rate(skew_total[5m])) by (shardtag)').result
        tags = {k.label_map.get("shardtag") for k in r.keys}
        assert tags == {"s0", "s1", "s2", "s3"}

    def test_extreme_skew_single_hot_shard(self):
        ms = skewed_store(per_shard=(64, 1, 1, 1))
        e, m = services(ms)
        q = 'sum(rate(skew_total[5m])) by (shardtag)'
        assert_same(self.q(e, q), self.q(m, q))


class TestIngestChurn:
    def _tick(self, ms, keys_by_shard, t, value):
        for shard_num, keys in keys_by_shard.items():
            shard = ms.get_shard("timeseries", shard_num)
            c = RecordContainer()
            for key in keys:
                c.add(IngestRecord(key, (START + t * 10) * 1000, (value,)))
            shard.ingest(SomeData(c, 100_000 + t))

    def test_churn_invalidates_batch_cache(self):
        """Every ingest tick bumps data_version; queries must never serve
        stale cached batches, and the cache must recover (hit again) once
        data stops changing."""
        ms = skewed_store(per_shard=(20, 2, 2, 2), n_samples=60)
        _, m = services(ms)
        eng = m.mesh_engine
        keys_by_shard = {
            s: [PartKey.create("prom-counter", {
                "_metric_": "skew_total", "_ws_": "demo", "_ns_": "App-0",
                "shardtag": f"s{s}", "instance": f"i{s}-0"})]
            for s in range(NUM_SHARDS)}
        query = 'sum(increase(skew_total[10m]))'

        def total(res):
            v = res.result.values
            return float(np.nansum(v))

        # churn phase: interleave ingest ticks and queries; the counter
        # keeps increasing, so increase() must reflect every tick
        last = None
        for t in range(60, 72):
            self._tick(ms, keys_by_shard, t, 10_000.0 + t * 50)
            r = m.query_range(query, START + t * 10, 10, START + t * 10)
            cur = total(r)
            if last is not None:
                assert cur >= last - 1e-6, "stale batch served under churn"
            last = cur
        # quiescent phase: identical repeated queries reuse the cached
        # device-resident batch (no rebuilds)
        args = (START + 700, 10, START + 710)
        m.query_range(query, *args)
        cache = eng._batch_cache
        entries_before = {k: id(v) for k, v in cache.items()}
        for _ in range(3):
            m.query_range(query, *args)
        entries_after = {k: id(v) for k, v in cache.items()}
        assert entries_before == entries_after, \
            "cache rebuilt without data changes"

    def test_churn_with_new_series_appearing(self):
        """New series mid-stream change the batch SHAPE (row count), not
        just versions — results must include them immediately."""
        ms = skewed_store(per_shard=(10, 1, 1, 1), n_samples=60)
        _, m = services(ms)
        q = 'sum(rate(skew_total[5m])) by (shardtag)'
        r1 = m.query_range(q, START + 590, 10, START + 590).result
        rows1 = len(r1.keys)
        # a brand-new series on the hot shard
        shard = ms.get_shard("timeseries", 0)
        c = RecordContainer()
        newkey = PartKey.create("prom-counter", {
            "_metric_": "skew_total", "_ws_": "demo", "_ns_": "App-0",
            "shardtag": "s-new", "instance": "fresh"})
        for t in range(55, 60):
            c.add(IngestRecord(newkey, (START + t * 10) * 1000,
                               (float(t * 7),)))
        shard.ingest(SomeData(c, 999_999))
        r2 = m.query_range(q, START + 590, 10, START + 590).result
        tags = {k.label_map.get("shardtag") for k in r2.keys}
        assert "s-new" in tags
        assert len(r2.keys) == rows1 + 1

    def test_mesh_hit_rate_accounting(self):
        ms = skewed_store(per_shard=(10, 1, 1, 1), n_samples=30)
        _, m = services(ms)
        eng = m.mesh_engine
        for _ in range(5):
            m.query_range('sum(rate(skew_total[5m]))',
                          START + 250, 10, START + 280)
        assert eng.hits >= 5
        assert eng.hit_rate > 0.9
