"""Memory-pressure partition eviction + evicted-partkey bloom.

Reference boundaries replaced:
- ``TimeSeriesShard.scala:1611`` evictForHeadroom (time-ordered partition
  eviction of fully-persisted series),
- ``TimeSeriesShard.scala:457`` evictedPartKeys bloom filter (ingest-side
  identity restore for previously-evicted series),
- ``OnDemandPagingShard.scala:27`` (queries over evicted partitions page
  chunks back from the column store).
"""

import numpy as np

from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.core.store.localstore import (
    LocalDiskColumnStore,
    LocalDiskMetaStore,
)
from filodb_tpu.testing.data import gauge_stream, machine_metrics_series

START = 1_600_000_000
MS = 1000


def build(tmp_path, n_series=32, n_samples=200, **cfg):
    cs = LocalDiskColumnStore(str(tmp_path / "store"))
    ms = TimeSeriesMemStore(cs, LocalDiskMetaStore(str(tmp_path / "meta")))
    shard = ms.setup("timeseries", 0, StoreConfig(
        max_chunk_size=50, groups_per_shard=4, flush_interval_ms=0, **cfg))
    keys = machine_metrics_series(n_series, metric="gauge_metric")
    stream = gauge_stream(keys, n_samples, start_ms=START * MS,
                          interval_ms=10_000, seed=5)
    for batch in stream:
        shard.ingest(batch)
    # persist everything so partitions become evictable
    shard.flush_all()
    return ms, shard


class TestPartitionEviction:
    def test_evict_then_query_pages_from_store(self, tmp_path):
        ms, shard = build(tmp_path)
        svc = QueryService(ms, "timeseries", 1, spread=0)
        q = 'sum(sum_over_time(gauge_metric[10m]))'
        t0, t1 = START + 600, START + 1800
        before = svc.query_range(q, t0, 60, t1)

        n = shard.evict_cold_partitions(max_evict=10**9)
        assert n > 0
        assert shard.stats.partitions_evicted.value == n
        # evicted pids: no partition object, index entry retained
        assert any(p is None for p in shard.partitions)

        shard.batch_cache.clear()
        after = svc.query_range(q, t0, 60, t1)
        assert after.result.num_series == before.result.num_series
        np.testing.assert_allclose(
            np.asarray(after.result.values),
            np.asarray(before.result.values), rtol=1e-6, equal_nan=True)

    def test_unpersisted_partition_not_evictable(self, tmp_path):
        ms, shard = build(tmp_path)
        # new un-flushed samples arrive
        keys = machine_metrics_series(4, metric="gauge_metric")
        for batch in gauge_stream(keys, 3, start_ms=(START + 3000) * MS,
                                  interval_ms=10_000, seed=6,
                                  start_offset=10_000):
            shard.ingest(batch)
        evicted = shard.evict_cold_partitions(max_evict=10**9)
        # the 4 partitions with unflushed buffer samples must survive
        live = sum(1 for p in shard.partitions
                   if p is not None and p.num_samples > 0)
        assert live >= 4
        assert evicted == len(shard.index) - live

    def test_reingest_restores_identity(self, tmp_path):
        ms, shard = build(tmp_path, n_series=8)
        pid0 = shard.lookup_partitions([], START * MS, 2**62)
        starts = {pid: shard.index.start_time(pid) for pid in pid0}
        n = shard.evict_cold_partitions(max_evict=10**9)
        assert n == len(starts)

        # same series come back with NEW samples
        keys = machine_metrics_series(8, metric="gauge_metric")
        for batch in gauge_stream(keys, 5, start_ms=(START + 4000) * MS,
                                  interval_ms=10_000, seed=7,
                                  start_offset=10_000):
            shard.ingest(batch)
        assert shard.stats.partitions_restored.value == 8
        # one live index entry per series (old entries retired)
        pids = shard.lookup_partitions([], 0, 2**62)
        assert len(pids) == 8
        for pid in pids:
            # original startTime transferred to the restored pid
            assert shard.index.start_time(pid) == min(starts.values()) \
                or shard.index.start_time(pid) in starts.values()

    def test_bloom_false_negative_free(self, tmp_path):
        from filodb_tpu.core.memstore.native_shard import part_key_blob
        ms, shard = build(tmp_path, n_series=16)
        blobs = [part_key_blob(shard.partition(pid).part_key)
                 for pid in shard.lookup_partitions([], 0, 2**62)]
        shard.evict_cold_partitions(max_evict=10**9)
        for b in blobs:
            assert b in shard.evicted_keys  # no false negatives

    def test_bloom_survives_snapshot_restart(self, tmp_path):
        from filodb_tpu.core.memstore.native_shard import part_key_blob
        ms, shard = build(tmp_path, n_series=8)
        blobs = [part_key_blob(shard.partition(pid).part_key)
                 for pid in shard.lookup_partitions([], 0, 2**62)]
        shard.evict_cold_partitions(max_evict=10**9)
        shard.snapshot_index()

        ms2 = TimeSeriesMemStore(shard.column_store, shard.meta_store)
        shard2 = ms2.setup("timeseries", 0, StoreConfig(
            max_chunk_size=50, groups_per_shard=4, flush_interval_ms=0))
        shard2.recover_index()
        assert shard2.evicted_keys.count == shard.evicted_keys.count
        for b in blobs:
            assert b in shard2.evicted_keys

    def test_pressure_soak_thousands_of_evictions(self, tmp_path):
        """Sustained over-budget ingest: thousands of evictions, zero query
        errors, results identical to the never-evicted answer."""
        cs = LocalDiskColumnStore(str(tmp_path / "soak"))
        ms = TimeSeriesMemStore(cs, LocalDiskMetaStore(str(tmp_path / "m")))
        shard = ms.setup("timeseries", 0, StoreConfig(
            max_chunk_size=32, groups_per_shard=4, flush_interval_ms=0))
        svc = QueryService(ms, "timeseries", 1, spread=0)
        total_evicted = 0
        waves = 6
        per_wave = 700
        for w in range(waves):
            keys = machine_metrics_series(
                per_wave, metric="gauge_metric", ns=f"wave{w}")
            for batch in gauge_stream(keys, 40,
                                      start_ms=(START + w * 400) * MS,
                                      interval_ms=10_000, seed=w,
                                      start_offset=(w + 1) * 100_000):
                shard.ingest(batch)
            shard.flush_all()
            total_evicted += shard.evict_cold_partitions(
                max_evict=per_wave)
            # queries keep answering mid-pressure
            r = svc.query_range(
                f'count(gauge_metric{{_ns_="wave{w}"}})',
                START + w * 400 + 100, 60, START + w * 400 + 300)
            assert r.result.num_series >= 0  # no exception = pass
        assert total_evicted >= 3000
        assert shard.stats.partitions_evicted.value == total_evicted
        # full historical query sweeps every wave via ODP
        shard.batch_cache.clear()
        r = svc.query_range('count(gauge_metric)', START + 100, 300,
                            START + waves * 400)
        assert r.result.num_series == 1
