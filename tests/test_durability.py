"""Durability & recovery end-to-end tests.

Mirrors the reference's full recovery story
(``standalone/src/multi-jvm/scala/filodb/standalone/
IngestionAndRecoverySpec.scala``): ingest through a replayable log with
flush/checkpoint, "crash" (new process state), recover index from the column
store, replay the log from min(checkpoint) honoring group watermarks, and
verify query correctness — plus on-demand paging of evicted chunks.
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.record import RecordContainer
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.core.store.localstore import (
    LocalDiskColumnStore,
    LocalDiskMetaStore,
)
from filodb_tpu.kafka.log import FileLog, InMemoryLog
from filodb_tpu.testing.data import gauge_stream, machine_metrics_series

START = 1_600_000_000


class TestFileLog:
    def test_append_read(self, tmp_path):
        log = FileLog(str(tmp_path / "shard0.log"))
        keys = machine_metrics_series(3)
        offs = []
        for sd in gauge_stream(keys, 50, start_ms=START * 1000):
            offs.append(log.append(sd.container))
        assert offs == list(range(len(offs)))
        entries = list(log.read_from(0))
        assert len(entries) == len(offs)
        assert entries[0].offset == 0
        total = sum(len(e.container) for e in entries)
        assert total == 3 * 50

    def test_read_from_middle(self, tmp_path):
        log = FileLog(str(tmp_path / "s.log"), index_every=4)
        keys = machine_metrics_series(1)
        for sd in gauge_stream(keys, 100, batch=10, start_ms=START * 1000):
            log.append(sd.container)
        entries = list(log.read_from(7))
        assert entries[0].offset == 7

    def test_reopen_persists(self, tmp_path):
        p = str(tmp_path / "s.log")
        log = FileLog(p)
        keys = machine_metrics_series(1)
        for sd in gauge_stream(keys, 30, start_ms=START * 1000):
            log.append(sd.container)
        n = log.latest_offset
        log.close()
        log2 = FileLog(p)
        assert log2.latest_offset == n
        assert len(list(log2.read_from(0))) == n + 1

    def test_serialization_round_trip(self):
        keys = machine_metrics_series(2)
        sd = next(gauge_stream(keys, 2, start_ms=0))
        data = sd.container.serialize()
        out = RecordContainer.deserialize(data)
        assert len(out) == len(sd.container)
        r0, r1 = out.records[0], sd.container.records[0]
        assert r0.part_key == r1.part_key
        assert r0.timestamp == r1.timestamp
        assert r0.values == r1.values


class TestLocalDiskStore:
    def test_chunks_round_trip(self, tmp_path):
        from filodb_tpu.core.memstore.partition import TimeSeriesPartition
        from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
        cs = LocalDiskColumnStore(str(tmp_path))
        key = machine_metrics_series(1)[0]
        part = TimeSeriesPartition(0, key, DEFAULT_SCHEMAS["gauge"],
                                   max_chunk_size=50)
        for i in range(100):
            part.ingest(i * 1000, (float(i),))
        chunks = part.make_flush_chunks()
        cs.write_chunks("ds", 0, key, chunks, ingestion_time=999)
        back = cs.read_chunks("ds", 0, key, 0, 10**15)
        assert len(back) == len(chunks)
        ts = np.concatenate([c.decode_column(0) for c in back])
        assert len(ts) == 100
        # idempotent rewrite (recovery re-flush)
        cs.write_chunks("ds", 0, key, chunks, ingestion_time=999)
        assert len(cs.read_chunks("ds", 0, key, 0, 10**15)) == len(chunks)
        # ingestion-time scan (downsampler path)
        scanned = list(cs.scan_chunks_by_ingestion_time("ds", 0, 0, 10**12))
        assert len(scanned) == 1 and scanned[0][0] == key
        cs.close()

    def test_partkeys_upsert(self, tmp_path):
        from filodb_tpu.core.store.api import PartKeyRecord
        cs = LocalDiskColumnStore(str(tmp_path))
        key = machine_metrics_series(1)[0]
        cs.write_part_keys("ds", 0, [PartKeyRecord(key, 100, 200)])
        cs.write_part_keys("ds", 0, [PartKeyRecord(key, 150, 500)])
        recs = cs.scan_part_keys("ds", 0)
        assert len(recs) == 1
        assert recs[0].start_time == 100 and recs[0].end_time == 500
        cs.close()


_SERVERS: dict = {}


def _mk_store(tmp_path, kind="local"):
    """Build a memstore on a local-disk column store, or on a REMOTE
    chunk-server fronting the same disk layout (both impls must pass every
    durability scenario — proving the store API abstracts, VERDICT r3 #6)."""
    if kind == "remote":
        from filodb_tpu.core.store.remotestore import (
            ChunkStoreServer, RemoteColumnStore, RemoteMetaStore)
        srv = _SERVERS.get(str(tmp_path))
        if srv is None:
            srv = _SERVERS[str(tmp_path)] = ChunkStoreServer(
                root=str(tmp_path / "data")).start()
        cs = RemoteColumnStore("127.0.0.1", srv.port)
        meta = RemoteMetaStore("127.0.0.1", srv.port)
    elif kind == "object":
        from filodb_tpu.core.store.objectstore import (
            ObjectStoreColumnStore, ObjectStoreMetaStore)
        from filodb_tpu.testing.fake_s3 import FakeS3
        # dir-backed fake: a new store instance over the same root models a
        # process restart reading back from the object service
        cs = ObjectStoreColumnStore(FakeS3(root=str(tmp_path / "s3")),
                                    segment_target_bytes=64 * 1024)
        meta = ObjectStoreMetaStore(cs)
    else:
        cs = LocalDiskColumnStore(str(tmp_path / "data"))
        meta = LocalDiskMetaStore(str(tmp_path / "data"))
    ms = TimeSeriesMemStore(cs, meta)
    ms.setup("timeseries", 0, StoreConfig(max_chunk_size=50,
                                          groups_per_shard=4))
    return ms


@pytest.fixture(params=["local", "remote", "object"])
def store_kind(request):
    return request.param


class TestCrashRecovery:
    def test_full_recovery_cycle(self, tmp_path, store_kind):
        keys = machine_metrics_series(8)
        log = FileLog(str(tmp_path / "log" / "shard0.log"))
        stream = list(gauge_stream(keys, 200, start_ms=START * 1000,
                                   batch=50))
        for sd in stream:
            log.append(sd.container)

        # phase 1: ingest 60%, flush, ingest 20% more unflushed, "crash"
        ms1 = _mk_store(tmp_path, store_kind)
        shard1 = ms1.get_shard("timeseries", 0)
        n60 = int(len(stream) * 0.6)
        n80 = int(len(stream) * 0.8)
        for sd in log.read_from(0):
            if sd.offset >= n60:
                break
            shard1.ingest(sd)
        shard1.flush_all(ingestion_time=1)
        for sd in log.read_from(n60):
            if sd.offset >= n80:
                break
            shard1.ingest(sd)
        # crash: no flush of the last 20%; drop everything in-memory
        ms1.column_store.close()
        ms1.meta_store.close()

        # phase 2: restart, recover, replay
        ms2 = _mk_store(tmp_path, store_kind)
        shard2 = ms2.get_shard("timeseries", 0)
        restored = shard2.recover_index()
        assert restored == 8
        start_offset = shard2.setup_watermarks_for_recovery()
        assert start_offset == n60 - 1
        for sd in log.read_from(start_offset):
            shard2.ingest(sd)

        # phase 3: verify no data loss and no duplication via a full query
        svc = QueryService(ms2, "timeseries", 1, spread=0)
        r = svc.query_range(
            'count_over_time(heap_usage[45m])',
            START + 2400, 60, START + 2400).result
        # 200 samples @10s per series; 45m window at +2400s covers them all
        # (windows are left-exclusive (t-w, t], so 40m would miss t=START)
        assert r.num_series == 8
        np.testing.assert_array_equal(r.values[:, 0], 200.0)

    def test_odp_after_eviction(self, tmp_path):
        keys = machine_metrics_series(4)
        ms = _mk_store(tmp_path)
        shard = ms.get_shard("timeseries", 0)
        for sd in gauge_stream(keys, 300, start_ms=START * 1000):
            shard.ingest(sd)
        shard.flush_all(ingestion_time=1)
        # evict persisted chunks from memory
        evicted = sum(shard.evict_partition_chunks(p.part_id)
                      for p in shard.partitions if p)
        assert evicted > 0
        svc = QueryService(ms, "timeseries", 1, spread=0)
        r = svc.query_range('count_over_time(heap_usage[55m])',
                            START + 3000, 60, START + 3000).result
        assert r.num_series == 4
        np.testing.assert_array_equal(r.values[:, 0], 300.0)
        from filodb_tpu.core.memstore.odp import odp_chunks_paged
        assert odp_chunks_paged.value > 0

    def test_odp_cache_hit_second_query(self, tmp_path):
        keys = machine_metrics_series(2)
        ms = _mk_store(tmp_path)
        shard = ms.get_shard("timeseries", 0)
        for sd in gauge_stream(keys, 100, start_ms=START * 1000):
            shard.ingest(sd)
        shard.flush_all(ingestion_time=1)
        for p in shard.partitions:
            if p:
                shard.evict_partition_chunks(p.part_id)
        svc = QueryService(ms, "timeseries", 1, spread=0)
        q = lambda: svc.query_range(  # noqa: E731
            'sum_over_time(heap_usage[10m])', START + 900, 60,
            START + 900).result
        r1, r2 = q(), q()
        np.testing.assert_array_equal(r1.values, r2.values)


class TestSegmentedLog:
    def _fill(self, log, n, keys=None):
        keys = keys or machine_metrics_series(1)
        offs = []
        for sd in gauge_stream(keys, n, batch=1, start_ms=START * 1000):
            offs.append(log.append(sd.container))
        return offs

    def test_rolls_segments(self, tmp_path):
        from filodb_tpu.kafka.log import SegmentedFileLog
        log = SegmentedFileLog(str(tmp_path / "wal"), segment_entries=10)
        offs = self._fill(log, 35)
        assert offs == list(range(35))
        import os
        segs = [f for f in os.listdir(tmp_path / "wal")
                if f.startswith("seg-")]
        assert len(segs) == 4
        assert [sd.offset for sd in log.read_from(0)] == list(range(35))
        assert [sd.offset for sd in log.read_from(17)] == list(range(17, 35))

    def test_truncate_before(self, tmp_path):
        from filodb_tpu.kafka.log import SegmentedFileLog
        log = SegmentedFileLog(str(tmp_path / "wal"), segment_entries=10)
        self._fill(log, 35)
        removed = log.truncate_before(25)
        assert removed == 2  # segments [0..9], [10..19] gone; [20..29] kept
        assert log.earliest_offset == 20
        assert [sd.offset for sd in log.read_from(0)][0] == 20
        assert [sd.offset for sd in log.read_from(28)] == list(range(28, 35))

    def test_reopen_preserves_offsets(self, tmp_path):
        from filodb_tpu.kafka.log import SegmentedFileLog
        p = str(tmp_path / "wal")
        log = SegmentedFileLog(p, segment_entries=10)
        self._fill(log, 25)
        log.truncate_before(15)
        log.close()
        log2 = SegmentedFileLog(p, segment_entries=10)
        assert log2.latest_offset == 24
        assert log2.earliest_offset == 10
        offs = [sd.offset for sd in log2.read_from(0)]
        assert offs == list(range(10, 25))
        # appends continue from the global offset
        more = self._fill(log2, 1)
        assert more == [25]
