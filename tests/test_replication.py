"""Continuous shard replication + HA serving chaos tests.

Mirrors the reference's multi-jvm recovery specs for the replication
subsystem (``coordinator/replication.py``):

- followers bootstrap warm read-only images and reach IN_SYNC, publishing
  watermarks through the sequenced shard-event feed;
- failover is a map flip: an in-sync follower is promoted with ONE
  sequenced ACTIVE event and ZERO object-store GETs (the sealed segments
  it already tailed are never re-read);
- kill-a-node soak: continuous queries across kill → detection →
  promotion → rejoin-as-follower see zero failures and zero wrong
  results vs an unkilled control cluster, with zero replica divergence
  at teardown (lockcheck + racecheck armed throughout);
- a deferred (rate-limited) reassignment skips shards whose replica set
  already produced a leader, and promotes a caught-up follower instead
  of cold-assigning (the double-assign regression);
- hedged replica reads: EWMA ordering, hedge-timer launches, failover on
  failure, open breakers to the back;
- ``filo-cli replicacheck`` exits 1 on watermark divergence.
"""

import io
import json
import threading
import time

import numpy as np
import pytest

from filodb_tpu.coordinator.bootstrap import ShardUpdateSubscriber
from filodb_tpu.coordinator.cluster import FilodbCluster, Node
from filodb_tpu.coordinator.ingestion import route_container
from filodb_tpu.coordinator.replication import (
    FOLLOWER_READS,
    HEDGED,
    HEDGED_WON,
    ReplicaCandidate,
    ReplicaDispatcher,
    assert_no_divergence,
    check_replicas,
)
from filodb_tpu.coordinator.shard_manager import ShardManager
from filodb_tpu.coordinator.shardmapper import ShardStatus
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.api import InMemoryColumnStore, InMemoryMetaStore
from filodb_tpu.core.store.config import IngestionConfig, StoreConfig
from filodb_tpu.core.store.objectstore import GETS, open_object_store
from filodb_tpu.kafka.log import InMemoryLog
from filodb_tpu.query.exec.plan import PlanDispatcher
from filodb_tpu.testing.data import gauge_stream, machine_metrics_series
from filodb_tpu.utils import lockcheck, racecheck
from filodb_tpu.utils.metrics import get_counter
from filodb_tpu.utils.resilience import (
    FaultInjector,
    breaker_for,
    record_peer_latency,
    reset_breakers,
    reset_peer_latency,
)

START = 1_600_000_000
NUM_SHARDS = 4
QUERY = 'sum(heap_usage{_ns_="App-3"})'


@pytest.fixture(autouse=True)
def _clean():
    FaultInjector.reset()
    reset_breakers()
    reset_peer_latency()
    yield
    FaultInjector.reset()
    reset_breakers()
    reset_peer_latency()


def _publish(logs, stream, num_shards, spread=1):
    for sd in stream:
        for shard, cont in route_container(sd.container, num_shards,
                                           spread).items():
            logs[shard].append(cont)


@pytest.fixture
def replica_env(tmp_path):
    # lock-order checker + shared-state race sanitizer armed for the whole
    # cluster lifetime (same discipline as the migration chaos matrix):
    # any order cycle, blocking-under-lock, or unguarded write the
    # replication machinery introduces fails the test at teardown
    with lockcheck.session():
        with racecheck.session():
            stores = []
            logs = {s: InMemoryLog() for s in range(NUM_SHARDS)}
            keys = machine_metrics_series(12, ns="App-3")
            _publish(logs, gauge_stream(keys, 240, start_ms=START * 1000),
                     NUM_SHARDS)
            cluster = FilodbCluster(replica_in_sync_lag=0,
                                    replica_durable_sync_s=3600.0)
            # each node opens its OWN store instance over the shared
            # bucket (as real members would): follower bootstraps do real
            # durable-tier GETs, making the flip's zero-GET claim testable
            for n in ("node-a", "node-b", "node-c"):
                cs, meta = open_object_store(
                    {"endpoint": None, "bucket": "t"}, str(tmp_path))
                stores.append((cs, meta))
                cluster.join(Node(n, TimeSeriesMemStore(cs, meta)))
            config = IngestionConfig("timeseries", NUM_SHARDS,
                                     min_num_nodes=2,
                                     store=StoreConfig(max_chunk_size=60,
                                                       groups_per_shard=2))
            cluster.setup_dataset(config, logs)
            assert cluster.wait_active("timeseries", 15)
            yield cluster, logs
            cluster.stop()
            for cs, meta in stores:
                cs.close()
                meta.close()
            rvs = racecheck.violations()
        vs = lockcheck.violations()
    assert rvs == [], [v.render() for v in rvs]
    assert vs == [], [v.render() for v in vs]


def _query(cluster):
    svc = cluster.query_service("timeseries", spread=1)
    return svc.query_range(QUERY, START + 600, 300, START + 1500)


def _flush_leaders(cluster):
    """Seal + upload every leader shard's data so follower bootstraps have
    sealed segments to recover (the durable tier the flip must NOT
    re-read)."""
    for node in cluster.nodes.values():
        for (ds, s) in list(node._workers):
            node.memstore.get_shard(ds, s).flush_all()
        fl = getattr(node.memstore.column_store, "flush", None)
        if callable(fl):
            fl()


def _wait_in_sync(cluster, timeout=30.0, drive=True):
    """Wait until every shard has an IN_SYNC follower. ``drive`` re-runs
    ensure_replicas from this thread (tests without the heartbeat loop);
    with the failure detector running the heartbeat drives convergence."""
    sm = cluster.shard_managers["timeseries"]
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(sm.mapper.in_sync_followers(s) for s in range(NUM_SHARDS)):
            return
        if drive:
            cluster.ensure_replicas("timeseries")
        time.sleep(0.05)
    pytest.fail(f"replicas never in-sync: {sm.mapper.snapshot()}")


def _wait_caught_up(cluster, logs, timeout=20.0):
    """Wait until every shard has an IN_SYNC follower whose published
    watermark covers the log head."""
    sm = cluster.shard_managers["timeseries"]
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        ok = True
        for s in range(NUM_SHARDS):
            top = logs[s].latest_offset
            reps = sm.mapper.replicas_of(s)
            if not any(st.status == ShardStatus.IN_SYNC
                       and st.watermark >= top for st in reps.values()):
                ok = False
                break
        if ok:
            return
        time.sleep(0.05)
    pytest.fail(f"followers never caught up: {sm.mapper.snapshot()}")


class TestReplicaLifecycle:
    def test_followers_reach_in_sync(self, replica_env):
        cluster, logs = replica_env
        cluster.replication = 1
        cluster.ensure_replicas("timeseries")
        _wait_in_sync(cluster)
        sm = cluster.shard_managers["timeseries"]
        for s in range(NUM_SHARDS):
            owner = sm.mapper.node_for(s)
            followers = sm.mapper.in_sync_followers(s)
            assert followers and owner not in followers
            name = followers[0]
            # follower is read-only: never registered as an ingest worker
            assert ("timeseries", s) not in cluster.nodes[name]._workers
            # published watermark covers the log head
            st = sm.mapper.replicas_of(s)[name]
            assert st.watermark == logs[s].latest_offset
            # warm image mirrors the leader's partition set
            lshard = cluster.nodes[owner].memstore.get_shard("timeseries", s)
            fshard = cluster.nodes[name].memstore.get_shard("timeseries", s)
            assert fshard.num_partitions == lshard.num_partitions
        # the shardmap snapshot carries the replica sets
        snap = cluster.shard_statuses("timeseries")
        assert all(e.get("replicas") for e in snap), snap
        assert check_replicas(cluster, "timeseries") == []

    def test_unhealthy_leader_served_by_follower_with_warning(
            self, replica_env):
        cluster, _ = replica_env
        baseline = _query(cluster)
        cluster.replication = 1
        cluster.ensure_replicas("timeseries")
        _wait_in_sync(cluster)
        sm = cluster.shard_managers["timeseries"]
        owner = sm.mapper.node_for(0)
        cluster.nodes[owner].alive = False  # unhealthy, not yet detected
        try:
            r = _query(cluster)
            assert any("served by follower" in w for w in r.warnings), \
                r.warnings
            np.testing.assert_allclose(r.result.values,
                                       baseline.result.values, rtol=1e-9)
        finally:
            cluster.nodes[owner].alive = True
        reset_breakers()  # failures recorded against the leader while down
        r2 = _query(cluster)
        assert not any("served by follower" in w for w in r2.warnings)


class TestPromotionMapFlip:
    """Failover = map flip: ONE sequenced ACTIVE event, the follower's
    warm image takes over at its applied offset, and the durable tier is
    never re-read (GET accounting proves no sealed-segment replay)."""

    def test_zero_get_flip(self, replica_env):
        cluster, _ = replica_env
        baseline = _query(cluster)
        _flush_leaders(cluster)
        cluster.replication = 1
        cluster.ensure_replicas("timeseries")
        _wait_in_sync(cluster)
        sm = cluster.shard_managers["timeseries"]
        a_shards = sm.mapper.shards_of("node-a")
        assert a_shards
        expected = {s: sm.mapper.in_sync_followers(s)[0] for s in a_shards}
        prom0 = get_counter("filodb_replica_promotions",
                            {"dataset": "timeseries"}).value
        gets0 = GETS.value
        cluster.leave("node-a")
        # the flip itself performed ZERO object-store reads: no manifest
        # refresh, no index recovery, no sealed-segment replay
        assert GETS.value == gets0
        assert get_counter("filodb_replica_promotions",
                           {"dataset": "timeseries"}).value - prom0 \
            == len(a_shards)
        for s, follower in expected.items():
            assert sm.mapper.node_for(s) == follower
            assert sm.mapper.statuses[s] == ShardStatus.ACTIVE
            # promoted out of the replica set, into the ingest path
            assert follower not in sm.mapper.replicas_of(s)
            assert ("timeseries", s) in cluster.nodes[follower]._workers
            assert ("timeseries", s, follower) not in \
                cluster.replica_syncers
        after = _query(cluster)
        np.testing.assert_allclose(after.result.values,
                                   baseline.result.values, rtol=1e-9)


@pytest.mark.slow
class TestKillNodeSoak:
    """Kill a node under continuous query load: zero failed queries, zero
    wrong results vs an unkilled control cluster, rejoin as follower,
    zero divergence at teardown."""

    def test_kill_promote_rejoin_soak(self, replica_env):
        cluster, logs = replica_env
        sm = cluster.shard_managers["timeseries"]
        # unkilled control cluster over the same logs: the equivalence
        # oracle for every result the soak observes
        control = FilodbCluster()
        control.join(Node("control", TimeSeriesMemStore(
            InMemoryColumnStore(), InMemoryMetaStore())))
        control.setup_dataset(
            IngestionConfig("timeseries", NUM_SHARDS, min_num_nodes=1,
                            store=StoreConfig(max_chunk_size=60,
                                              groups_per_shard=2)),
            logs)
        assert control.wait_active("timeseries", 15)
        svc = control.query_service("timeseries", spread=1)
        baseline = svc.query_range(QUERY, START + 600, 300,
                                   START + 1500).result.values
        control.stop()
        np.testing.assert_allclose(_query(cluster).result.values, baseline,
                                   rtol=1e-9)

        _flush_leaders(cluster)
        cluster.replication = 1
        cluster.ensure_replicas("timeseries")
        _wait_in_sync(cluster)
        # second batch OUTSIDE the query window: followers genuinely
        # ingest post-bootstrap rows (their high-water timestamps become
        # comparable to the leaders') without perturbing the oracle
        keys = machine_metrics_series(12, ns="App-3")
        _publish(logs, gauge_stream(keys, 60,
                                    start_ms=(START + 2400) * 1000),
                 NUM_SHARDS)
        _wait_caught_up(cluster, logs)

        a_shards = sm.mapper.shards_of("node-a")
        assert a_shards
        prom0 = get_counter("filodb_replica_promotions",
                            {"dataset": "timeseries"}).value
        # freeze replica placement across the kill so the only durable
        # reads possible during the flip window would be the promotion's
        # own (there must be none) — re-replication is re-enabled after
        cluster.replication = 0
        cluster.start_failure_detector()

        stats = {"ok": 0, "bad": 0, "fail": []}
        stop_ev = threading.Event()

        def soak():
            while not stop_ev.is_set():
                try:
                    vals = _query(cluster).result.values
                except Exception as e:  # noqa: BLE001 - tallied, asserted
                    stats["fail"].append(repr(e))
                    continue
                if vals.shape == baseline.shape and \
                        np.allclose(vals, baseline, rtol=1e-9):
                    stats["ok"] += 1
                else:
                    stats["bad"] += 1

        t = threading.Thread(target=soak, daemon=True, name="soak")
        t.start()
        time.sleep(0.3)
        gets0 = GETS.value
        node_a = cluster.nodes["node-a"]
        node_a.kill()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if "node-a" not in cluster.nodes and all(
                    sm.mapper.node_for(s) not in (None, "node-a")
                    and sm.mapper.statuses[s] == ShardStatus.ACTIVE
                    for s in range(NUM_SHARDS)):
                break
            time.sleep(0.02)
        else:
            pytest.fail(f"failover never settled: {sm.mapper.snapshot()}")
        time.sleep(0.5)  # keep querying well past the flip
        stop_ev.set()
        t.join(timeout=10)

        # zero failed queries and zero wrong results across kill →
        # detection → promotion (results may carry warnings; they may
        # never be wrong or absent)
        assert stats["fail"] == [], stats["fail"]
        assert stats["bad"] == 0
        assert stats["ok"] >= 10, stats
        # the flip replayed nothing from the durable tier
        assert GETS.value == gets0
        assert get_counter("filodb_replica_promotions",
                           {"dataset": "timeseries"}).value - prom0 \
            == len(a_shards)
        # the dead node's follower roles died with it
        assert not any(k[2] == "node-a" for k in cluster.replica_syncers)
        r = _query(cluster)
        assert not any("served by follower" in w for w in r.warnings)
        np.testing.assert_allclose(r.result.values, baseline, rtol=1e-9)

        # rejoin as follower: the warm ex-leader image is reused, no
        # leader roles reassigned (rows it already holds dedup on replay)
        cluster.replication = 1
        node_a.alive = True
        cluster.join(node_a)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(sm.mapper.in_sync_followers(s)
                   for s in range(NUM_SHARDS)) \
                    and sm.mapper.follower_shards("node-a"):
                break
            time.sleep(0.05)
        else:
            pytest.fail(f"rejoin never converged: {sm.mapper.snapshot()}")
        assert sm.mapper.shards_of("node-a") == []
        np.testing.assert_allclose(_query(cluster).result.values, baseline,
                                   rtol=1e-9)
        # chaos teardown gate: zero replica divergence
        assert_no_divergence(cluster, "timeseries", timeout_s=15)


class TestDeferredPromotionRaces:
    """Regression: a deferred (rate-limited) shard must not be
    double-assigned over a leader the replica path produced meanwhile."""

    def _two_losses(self, interval=0.2):
        sm = ShardManager("ds", 4, min_num_nodes=2,
                          reassignment_min_interval_s=interval)
        for n in ("n1", "n2", "n3", "n4"):
            sm.add_member(n)
        lost = sm.mapper.shards_of("n1")
        sm.remove_member("n1")  # stamps the reassignment clock
        victim = sm.mapper.node_for(lost[0])
        relost = sm.mapper.shards_of(victim)
        sm.remove_member(victim)  # inside the interval: deferred
        assert set(relost) <= sm._deferred
        return sm, relost

    def test_deferred_skips_shard_promotion_already_owns(self):
        sm, relost = self._two_losses()
        s0 = relost[0]
        survivor = sm.nodes[0]
        # a promotion claims the shard while it sits deferred
        sm.promote(s0, survivor)
        time.sleep(0.25)
        events = sm.check_deferred()
        # the retry must NOT re-assign the promoted shard over its leader
        assert not any(e.shard == s0 and e.status == ShardStatus.ASSIGNED
                       for e in events), events
        assert sm.mapper.node_for(s0) == survivor
        assert s0 not in sm._deferred

    def test_deferred_promotes_caught_up_follower(self):
        sm, relost = self._two_losses()
        s0 = relost[0]
        survivor = sm.nodes[0]
        # a follower catches up while the shard sits deferred
        sm.replica_update(s0, survivor, ShardStatus.IN_SYNC, watermark=7)
        time.sleep(0.25)
        events = sm.check_deferred()
        flips = [e for e in events if e.shard == s0 and not e.replica]
        assert flips and flips[0].status == ShardStatus.ACTIVE
        assert flips[0].node == survivor
        assert sm.mapper.node_for(s0) == survivor
        assert survivor not in sm.mapper.replicas_of(s0)
        assert s0 not in sm._deferred


class _StubDispatcher(PlanDispatcher):
    def __init__(self, result, delay=0.0, error=None):
        self.result = result
        self.delay = delay
        self.error = error
        self.calls = 0

    def dispatch(self, plan, ctx):
        self.calls += 1
        if self.delay:
            time.sleep(self.delay)
        if self.error:
            raise self.error
        return self.result


class TestHedgedReads:
    def test_hedge_timer_launches_follower_and_wins(self):
        slow = _StubDispatcher("leader", delay=0.5)
        fast = _StubDispatcher("follower")
        rd = ReplicaDispatcher(0, [
            ReplicaCandidate("hx-leader", slow),
            ReplicaCandidate("hx-follower", fast, follower=True),
        ], hedge_timeout_s=0.02)
        h0, w0, f0 = HEDGED.value, HEDGED_WON.value, FOLLOWER_READS.value
        assert rd.dispatch(None, None) == "follower"
        assert HEDGED.value - h0 == 1
        assert HEDGED_WON.value - w0 == 1
        assert FOLLOWER_READS.value - f0 == 1

    def test_failure_failover_is_not_hedged(self):
        dead = _StubDispatcher(None, error=ConnectionError("down"))
        ok = _StubDispatcher("follower")
        rd = ReplicaDispatcher(0, [
            ReplicaCandidate("hf-leader", dead),
            ReplicaCandidate("hf-follower", ok, follower=True),
        ], hedge_timeout_s=5.0)
        h0 = HEDGED.value
        assert rd.dispatch(None, None) == "follower"
        assert HEDGED.value == h0  # failover, not a hedge

    def test_all_replicas_failing_raises(self):
        rd = ReplicaDispatcher(0, [
            ReplicaCandidate("af-a", _StubDispatcher(
                None, error=ConnectionError("a"))),
            ReplicaCandidate("af-b", _StubDispatcher(
                None, error=ConnectionError("b")), follower=True),
        ], hedge_timeout_s=0.01)
        with pytest.raises(ConnectionError):
            rd.dispatch(None, None)

    def test_open_breaker_candidate_goes_last(self):
        breaker_for("ob-leader").force_open()
        a = _StubDispatcher("leader")
        b = _StubDispatcher("follower")
        rd = ReplicaDispatcher(0, [
            ReplicaCandidate("ob-leader", a),
            ReplicaCandidate("ob-follower", b, follower=True),
        ], hedge_timeout_s=5.0)
        assert rd.dispatch(None, None) == "follower"
        assert a.calls == 0  # never dispatched at the open peer

    def test_ewma_latency_orders_candidates(self):
        record_peer_latency("ew-slow", 0.5)
        record_peer_latency("ew-fast", 0.001)
        rd = ReplicaDispatcher(0, [
            ReplicaCandidate("ew-slow", _StubDispatcher("s")),
            ReplicaCandidate("ew-fast", _StubDispatcher("f"),
                             follower=True),
        ])
        assert [c.key for c in rd._ordered()] == ["ew-fast", "ew-slow"]
        # unknown latencies keep construction order (leader first)
        reset_peer_latency()
        assert [c.key for c in rd._ordered()] == ["ew-slow", "ew-fast"]


class TestDivergenceCheck:
    def test_stalled_follower_reported(self, replica_env):
        cluster, logs = replica_env
        cluster.replication = 1
        cluster.ensure_replicas("timeseries")
        _wait_in_sync(cluster)
        div0 = get_counter("filodb_replica_divergence").value
        # freeze one follower's tail (its IN_SYNC claim goes stale), then
        # advance the leaders past it — picking a shard that actually
        # carries data (the series set routes to a subset of shards)
        key = next(k for k in cluster.replica_syncers
                   if logs[k[1]].latest_offset >= 0)
        _, stalled_shard, stalled_node = key
        cluster.replica_syncers[key].stop()
        keys = machine_metrics_series(12, ns="App-3")
        _publish(logs, gauge_stream(keys, 20,
                                    start_ms=(START + 2400) * 1000),
                 NUM_SHARDS)
        deadline = time.monotonic() + 10
        found = []
        while time.monotonic() < deadline:
            found = [i for i in check_replicas(cluster, "timeseries")
                     if i["shard"] == stalled_shard
                     and i["follower"] == stalled_node
                     and i["kind"] == "watermark_lag"]
            if found:
                break
            time.sleep(0.05)
        assert found, "stalled follower never reported divergent"
        assert get_counter("filodb_replica_divergence").value > div0


def _shardmap_doc(leader_wm, rep_wm, rep_status="in_sync"):
    return {"data": {"shards": [
        {"shard": 0, "node": "n1", "status": "active",
         "watermark": leader_wm,
         "replicas": [{"node": "n2", "status": rep_status,
                       "watermark": rep_wm}]}], "tenants": []}}


class TestReplicacheckCli:
    def _patch(self, monkeypatch, doc):
        import urllib.request
        monkeypatch.setattr(urllib.request, "urlopen",
                            lambda url: io.StringIO(json.dumps(doc)))

    def test_clean_exits_zero(self, monkeypatch, capsys):
        from filodb_tpu import cli
        self._patch(monkeypatch, _shardmap_doc(10, 10))
        assert cli.main(["--host", "h:1", "replicacheck"]) == 0
        assert "0 divergent" in capsys.readouterr().out

    def test_divergent_exits_one(self, monkeypatch, capsys):
        from filodb_tpu import cli
        self._patch(monkeypatch, _shardmap_doc(10, 5))
        assert cli.main(["--host", "h:1", "replicacheck"]) == 1
        out = capsys.readouterr().out
        assert "DIVERGED (lag 5)" in out and "1 divergent" in out

    def test_lagging_follower_skipped(self, monkeypatch, capsys):
        from filodb_tpu import cli
        self._patch(monkeypatch, _shardmap_doc(10, 2, rep_status="lagging"))
        assert cli.main(["--host", "h:1", "replicacheck"]) == 0
        assert "skip (lagging)" in capsys.readouterr().out

    def test_shardmap_renders_replica_sets(self, monkeypatch, capsys):
        from filodb_tpu import cli
        self._patch(monkeypatch, _shardmap_doc(10, 10))
        cli.main(["--host", "h:1", "shardmap"])
        assert "n2:in_sync@10" in capsys.readouterr().out


class _EventFeed:
    """Stub dispatcher bridging ShardManager.events_since over the wire
    format the standalone executor serves (6-tuples)."""

    def __init__(self, sm):
        self.sm = sm

    def call(self, method, dataset, since_seq, epoch):
        assert method == "shard_events"
        events, seq, resynced, ep = self.sm.events_since(since_seq, epoch)
        wire = [(e.shard, e.status.name, e.node, e.progress, e.replica,
                 e.watermark) for e in events]
        return wire, seq, resynced, ep


class TestReplicaEventWire:
    def test_replica_events_mirror_round_trip(self):
        sm = ShardManager("ds", 4, min_num_nodes=1)
        sm.add_member("n1")
        sub = ShardUpdateSubscriber("ds", 4, _EventFeed(sm))
        sub.poll()
        assert sub.mapper.node_for(0) == "n1"
        sm.replica_update(0, "n2", ShardStatus.FOLLOWING, watermark=3)
        sm.replica_update(0, "n2", ShardStatus.IN_SYNC, watermark=9)
        sub.poll()
        st = sub.mapper.replicas_of(0)["n2"]
        assert st.status == ShardStatus.IN_SYNC and st.watermark == 9
        assert sub.mapper.in_sync_followers(0) == ["n2"]
        sm.drop_replica(0, "n2")
        sub.poll()
        assert sub.mapper.replicas_of(0) == {}
        # a resync snapshot also carries replica sets
        sm.replica_update(1, "n3", ShardStatus.IN_SYNC, watermark=4)
        fresh = ShardUpdateSubscriber("ds", 4, _EventFeed(sm))
        fresh.poll()
        assert fresh.mapper.in_sync_followers(1) == ["n3"]

    def test_legacy_four_tuple_events_still_apply(self):
        class _Legacy:
            def call(self, *_):
                return [(0, "ACTIVE", "n1", 100)], 1, False, "e1"

        sub = ShardUpdateSubscriber("ds", 4, _Legacy())
        sub.poll()
        assert sub.mapper.node_for(0) == "n1"
        assert sub.mapper.statuses[0] == ShardStatus.ACTIVE
