"""Overload soak: a full node (HTTP + gateway + governor + watchdog) under
4x-capacity mixed query/ingest load with injected scan latency. Every
request resolves to 200, partial, or 503 — no hangs, no unexpected
exceptions — admitted-query p99 stays bounded, and the sheds are visible
in the /metrics scrape. Deterministic fault injection; runs in tier-1."""

import json
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from filodb_tpu.config import ServerConfig
from filodb_tpu.standalone import FiloServer
from filodb_tpu.utils import governor as gov
from filodb_tpu.utils import lockcheck, racecheck
from filodb_tpu.utils.resilience import (
    DeadlineExceeded,
    FaultInjector,
    reset_breakers,
)

pytestmark = pytest.mark.chaos

START = 1_600_000_000
CAPACITY = 2          # admission slots; load drives 4x this
LOAD_THREADS = 4 * CAPACITY
LOAD_SECONDS = 3.0
CHILD_DELAY_S = 0.15  # injected per scatter-gather child


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture
def server(tmp_path):
    gov.reset()
    reset_breakers()
    FaultInjector.reset()
    cfg_path = tmp_path / "server.json"
    cfg_path.write_text(json.dumps({
        "node_name": "soak-node",
        "data_dir": str(tmp_path / "data"),
        "http_port": 0,
        "datasets": {"timeseries": {
            "num_shards": 2, "spread": 1, "engine": "exec",
            "store": {"max_chunk_size": 100, "groups_per_shard": 2}}},
        "resilience": {"query_timeout_s": 10.0},
        "governor": {"admission_capacity": CAPACITY,
                     "max_queue_wait_s": 0.3,
                     "retry_after_s": 1.0,
                     "watchdog_interval_s": 0.1},
    }))
    cfg = ServerConfig.load(str(cfg_path))
    object.__setattr__(cfg, "gateway_port", _free_port())
    # runtime lock-order checker covers the whole soak: admission,
    # watchdog, HTTP, and gateway locks are all created (wrapped) inside
    # the session, and any order cycle or blocking call made under one
    # of them during the 4x-overload run fails the test at teardown
    with lockcheck.session():
        # race sanitizer beside it: the server's shard maps and metric
        # registry are tracked, and an unguarded or mixed-guard write
        # observed anywhere in the 4x-overload run fails at teardown
        with racecheck.session():
            srv = FiloServer(cfg).start()
            yield srv
            srv.shutdown()
            rvs = racecheck.violations()
        vs = lockcheck.violations()
    assert rvs == [], [v.render() for v in rvs]
    assert vs == [], [v.render() for v in vs]
    FaultInjector.reset()
    gov.reset()
    reset_breakers()


def _ingest(srv, n_points=120, host="h0"):
    with socket.create_connection(("127.0.0.1", srv.gateway.port)) as s:
        for i in range(n_points):
            ts_ns = (START + i * 10) * 1_000_000_000
            s.sendall(f"cpu_usage,host={host},_ws_=demo,_ns_=App-0 "
                      f"value={50 + i % 7} {ts_ns}\n".encode())
    srv.gateway.sink.flush()


def _http_query(port, timeout=10.0):
    """One HTTP range query; returns (status, retry_after_header_or_None)."""
    qs = urllib.parse.urlencode({
        "query": "cpu_usage", "start": START, "end": START + 1100,
        "step": 60})
    url = f"http://127.0.0.1:{port}/promql/timeseries/api/v1/query_range?{qs}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, None
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get("Retry-After")


def _p99(latencies):
    xs = sorted(latencies)
    return xs[min(len(xs) - 1, int(0.99 * len(xs)))]


def _scrape(port):
    url = f"http://127.0.0.1:{port}/metrics"
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read().decode()


def _counter_total(text, name):
    total = 0.0
    for line in text.splitlines():
        if line.startswith(name):
            total += float(line.rsplit(" ", 1)[1])
    return total


class TestOverloadSoak:
    def test_mixed_overload_sheds_cleanly(self, server):
        srv = server
        svc = srv.http.services["timeseries"]
        _ingest(srv)
        # the injected latency applies to BOTH phases so the p99 comparison
        # isolates the effect of load, not of the fault
        FaultInjector.arm("gather.child", delay_s=CHILD_DELAY_S, times=None)

        def run_query():
            return svc.query_range("cpu_usage", START, 60, START + 1100)

        for _ in range(2):  # warm compile caches off the clock
            run_query()
        unloaded = []
        for _ in range(8):
            t0 = time.perf_counter()
            r = run_query()
            unloaded.append(time.perf_counter() - t0)
            assert not r.partial
        p99_unloaded = _p99(unloaded)

        stop = time.monotonic() + LOAD_SECONDS
        ok_lat, outcomes, errors = [], [], []
        lock = threading.Lock()

        def query_worker():
            while time.monotonic() < stop:
                t0 = time.perf_counter()
                try:
                    r = run_query()
                    dt = time.perf_counter() - t0
                    with lock:
                        outcomes.append("partial" if r.partial else "ok")
                        ok_lat.append(dt)
                except gov.QueryRejected as e:
                    with lock:
                        outcomes.append("shed")
                    assert e.retry_after_s > 0
                except DeadlineExceeded:
                    with lock:
                        outcomes.append("timeout")
                except Exception as e:  # noqa: BLE001 — soak: nothing else
                    with lock:
                        errors.append(repr(e))

        def http_worker():
            while time.monotonic() < stop:
                try:
                    code, retry_after = _http_query(srv.http.port)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(repr(e))
                    continue
                with lock:
                    outcomes.append(f"http_{code}")
                if code == 503:
                    assert retry_after is not None  # clients can back off

        def ingest_worker():
            i = 0
            while time.monotonic() < stop:
                try:
                    _ingest(srv, n_points=30, host=f"h{i % 5}")
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(repr(e))
                i += 1

        threads = ([threading.Thread(target=query_worker, daemon=True)
                    for _ in range(LOAD_THREADS)]
                   + [threading.Thread(target=http_worker, daemon=True)
                      for _ in range(2)]
                   + [threading.Thread(target=ingest_worker, daemon=True)])
        for t in threads:
            t.start()
        for t in threads:
            # generous join bound: a hang here is exactly the bug the
            # admission gate exists to prevent
            t.join(timeout=60)
            assert not t.is_alive(), "worker wedged under overload"

        assert not errors, errors
        kinds = set(outcomes)
        # only the three sanctioned outcomes (plus their HTTP encodings)
        assert kinds <= {"ok", "partial", "shed", "timeout",
                         "http_200", "http_503"}, kinds
        assert "ok" in kinds or "http_200" in kinds  # node kept serving
        assert "shed" in kinds or "http_503" in kinds  # overload was shed
        # admitted latency stays bounded: queue waits are deadline-capped
        assert _p99(ok_lat) <= 2 * max(p99_unloaded, 0.5), \
            (p99_unloaded, _p99(ok_lat))

        text = _scrape(srv.http.port)
        assert _counter_total(text, "filodb_governor_admitted_total") > 0
        assert _counter_total(text, "filodb_governor_rejected_total") > 0
        assert "filodb_governor_state " in text
        assert "gateway_queue_depth" in text

    def test_critical_state_keeps_cheap_queries_alive(self, server):
        srv = server
        svc = srv.http.services["timeseries"]
        _ingest(srv)
        # drive the WATCHDOG (not the gate directly): a pinned fake source
        # pushes utilization past critical_threshold on its next tick
        srv.watchdog.add_source("pinned", lambda: 0.99)
        deadline = time.monotonic() + 5
        while gov.governor().state != gov.CRITICAL \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert gov.governor().state == gov.CRITICAL
        with pytest.raises(gov.QueryRejected):
            svc.query_range("cpu_usage", START, 60, START + 1100)
        # instant (cheap) queries keep flowing under memory pressure
        r = svc.query_range("cpu_usage", START + 600, 0, START + 600)
        assert r.result.num_series >= 1
        # recovery: source drops, watchdog walks the node back to OK
        srv.watchdog.sources = [(n, f) for n, f in srv.watchdog.sources
                                if n != "pinned"]
        deadline = time.monotonic() + 5
        while gov.governor().state != gov.OK and time.monotonic() < deadline:
            time.sleep(0.05)
        assert gov.governor().state == gov.OK
        r = svc.query_range("cpu_usage", START, 60, START + 1100)
        assert not r.partial
