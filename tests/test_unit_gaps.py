"""Coverage for under-tested units: Prom JSON rendering, index lifecycle,
config layering, metrics exposition, aggregation edges, store reopen.
"""

import json

import numpy as np
import pytest

from filodb_tpu.config import ServerConfig
from filodb_tpu.core.filters import ColumnFilter, Equals, EqualsRegex
from filodb_tpu.core.memstore.index import PartKeyIndex
from filodb_tpu.core.partkey import PartKey
from filodb_tpu.http import promjson
from filodb_tpu.query.model import QueryResult, RangeVectorKey, StepMatrix


def mk_result(keys, values, steps):
    return QueryResult(StepMatrix(keys, np.asarray(values, float),
                                  np.asarray(steps, np.int64)))


class TestPromJson:
    def test_matrix_drops_nan(self):
        r = mk_result([RangeVectorKey.of({"_metric_": "m", "a": "1"})],
                      [[1.0, np.nan, 3.0]], [1000, 2000, 3000])
        body = promjson.matrix_json(r)
        series = body["data"]["result"][0]
        assert series["metric"] == {"__name__": "m", "a": "1"}
        assert series["values"] == [[1.0, "1.0"], [3.0, "3.0"]]
        assert body["queryStats"]["resultSeries"] == 0  # stats not populated

    def test_all_nan_series_omitted(self):
        r = mk_result([RangeVectorKey.of({"a": "1"}),
                       RangeVectorKey.of({"a": "2"})],
                      [[np.nan, np.nan], [1.0, 2.0]], [1000, 2000])
        body = promjson.matrix_json(r)
        assert len(body["data"]["result"]) == 1

    def test_inf_formatting(self):
        r = mk_result([RangeVectorKey.of({})], [[np.inf, -np.inf]],
                      [1000, 2000])
        vals = promjson.matrix_json(r)["data"]["result"][0]["values"]
        assert vals[0][1] == "+Inf" and vals[1][1] == "-Inf"

    def test_vector_takes_last_step(self):
        r = mk_result([RangeVectorKey.of({"x": "y"})], [[1.0, 7.5]],
                      [1000, 2000])
        body = promjson.vector_json(r)
        assert body["data"]["result"][0]["value"] == [2.0, "7.5"]

    def test_histogram_flattening(self):
        m = StepMatrix([RangeVectorKey.of({"app": "a"})],
                       np.arange(6, dtype=float).reshape(1, 2, 3),
                       np.array([1000, 2000], np.int64),
                       les=np.array([0.5, 1.0, np.inf]))
        body = promjson.matrix_json(QueryResult(m))
        les = sorted(s["metric"]["le"] for s in body["data"]["result"])
        assert les == ["+Inf", "0.5", "1.0"]

    def test_json_serializable(self):
        r = mk_result([RangeVectorKey.of({"a": "b"})], [[1.5]], [1000])
        json.dumps(promjson.matrix_json(r))
        json.dumps(promjson.vector_json(r))
        json.dumps(promjson.error_json("boom"))


class TestIndexLifecycle:
    def key(self, i):
        return PartKey.create("gauge", {"_metric_": "m", "i": str(i)})

    def test_remove_then_readd(self):
        idx = PartKeyIndex()
        idx.add_part_key(0, self.key(0), 100)
        idx.remove_part_key(0)
        assert idx.part_ids_from_filters(
            [ColumnFilter("i", Equals("0"))], 0, 10**15) == []
        idx.add_part_key(1, self.key(0), 200)
        assert idx.part_ids_from_filters(
            [ColumnFilter("i", Equals("0"))], 0, 10**15) == [1]
        assert len(idx) == 1

    def test_empty_regex_matches_missing_label(self):
        idx = PartKeyIndex()
        idx.add_part_key(0, self.key(0), 100)
        # absent label matches ^$ regex (prom semantics)
        out = idx.part_ids_from_filters(
            [ColumnFilter("nope", EqualsRegex(""))], 0, 10**15)
        assert out == [0]

    def test_update_end_time_filters(self):
        idx = PartKeyIndex()
        idx.add_part_key(0, self.key(0), 100)
        idx.update_end_time(0, 500)
        assert idx.part_ids_from_filters([], 600, 700) == []
        assert idx.part_ids_from_filters([], 400, 700) == [0]


class TestConfig:
    def test_layering(self, tmp_path):
        p = tmp_path / "c.json"
        p.write_text(json.dumps({
            "node_name": "x",
            "datasets": {"timeseries": {"num_shards": 8,
                                        "store": {"max_chunk_size": 77}}},
        }))
        cfg = ServerConfig.load(str(p))
        assert cfg.node_name == "x"
        ds = cfg.datasets["timeseries"]
        assert ds.num_shards == 8
        assert ds.store.max_chunk_size == 77
        # defaults preserved for unset keys
        assert ds.store.groups_per_shard == 20
        assert cfg.http_port == 8080

    def test_defaults_without_file(self):
        cfg = ServerConfig.load(None)
        assert "timeseries" in cfg.datasets
        assert cfg.spreads["timeseries"] == 1


class TestMetricsExposition:
    def test_histogram_buckets_render(self):
        from filodb_tpu.utils import metrics
        h = metrics.Histogram("test_render_hist", {"who": "me"})
        h.observe(0.003)
        h.observe(0.2)
        text = metrics.render_prometheus()
        assert 'test_render_hist_bucket{who="me",le="0.005"} 1' in text
        assert "test_render_hist_count" in text


class TestAggregationEdges:
    def test_group_and_stdvar(self):
        import jax.numpy as jnp
        from filodb_tpu.query.engine.aggregations import aggregate
        v = np.array([[1.0, 4.0], [3.0, np.nan]])
        g = np.zeros(2, np.int32)
        grp = np.asarray(aggregate("group", jnp.asarray(v), jnp.asarray(g), 1))
        np.testing.assert_array_equal(grp[0], [1.0, 1.0])
        sv = np.asarray(aggregate("stdvar", jnp.asarray(v), jnp.asarray(g), 1))
        np.testing.assert_allclose(sv[0, 0], np.var([1.0, 3.0]), rtol=1e-12)
        assert sv[0, 1] == 0.0  # single sample -> zero variance

    def test_count_values_via_transformer(self):
        from filodb_tpu.query.exec.transformers import AggregateMapReduce
        m = StepMatrix(
            [RangeVectorKey.of({"i": str(i)}) for i in range(4)],
            np.array([[1.0], [1.0], [2.0], [np.nan]]),
            np.array([1000], np.int64))
        out = AggregateMapReduce("count_values", ("ver",)).apply(m)
        got = {k.label_map["ver"]: out.values[i, 0]
               for i, k in enumerate(out.keys)}
        assert got == {"1": 2.0, "2": 1.0}


class TestLocalStoreReopen:
    def test_reopen_after_close(self, tmp_path):
        from filodb_tpu.core.store.localstore import LocalDiskColumnStore
        from filodb_tpu.core.store.api import PartKeyRecord
        key = PartKey.create("gauge", {"_metric_": "m"})
        cs = LocalDiskColumnStore(str(tmp_path))
        cs.write_part_keys("ds", 0, [PartKeyRecord(key, 1, 2)])
        cs.close()
        cs2 = LocalDiskColumnStore(str(tmp_path))
        assert len(cs2.scan_part_keys("ds", 0)) == 1
        cs2.close()


class TestMemberRegistry:
    def test_roles_and_coordinator(self, tmp_path):
        from filodb_tpu.coordinator.bootstrap import MemberRegistry
        reg = MemberRegistry(str(tmp_path / "members.txt"))
        reg.register("coord", "a", "127.0.0.1", 1000)
        reg.register("member", "b", "127.0.0.1", 1001)
        assert reg.current_coordinator() == "a"
        # promotion appends a new coord line; latest wins
        reg.register("coord", "b", "127.0.0.1", 1001)
        assert reg.current_coordinator() == "b"
        members = reg.members()
        assert members["b"][0] == "coord"
        assert members["a"] == ("coord", "127.0.0.1", 1000)


class TestCrossProcessTailing:
    def test_tailer_sees_appends_from_other_instance(self, tmp_path):
        # the shard owner tails segments the gateway process appends to on a
        # shared FS: a second (read-only) log instance over the same dir
        # must see records appended after it opened, and new rolled segments
        from filodb_tpu.kafka.log import SegmentedFileLog
        from filodb_tpu.testing.data import gauge_stream, machine_metrics_series
        keys = machine_metrics_series(1)
        writer = SegmentedFileLog(str(tmp_path / "wal"), segment_entries=5)
        stream = list(gauge_stream(keys, 12, batch=1))
        for sd in stream[:3]:
            writer.append(sd.container)
        tailer = SegmentedFileLog(str(tmp_path / "wal"), segment_entries=5,
                                  read_only=True)
        assert len(list(tailer.read_from(0))) == 3
        # appends after the tailer opened — incl. a segment roll at 5
        for sd in stream[3:]:
            writer.append(sd.container)
        got = [e.offset for e in tailer.read_from(0)]
        assert got == list(range(12))
        # tailer never truncates or writes: appender continues cleanly
        for sd in gauge_stream(keys, 1, batch=1, start_ms=10**9):
            writer.append(sd.container)
        assert len(list(tailer.read_from(0))) == 13
        import pytest as _pytest
        with _pytest.raises(OSError, match="read-only"):
            tailer.append(stream[0].container)
        writer.close()
        tailer.close()


class TestTornWAL:
    def test_torn_tail_ignored_on_recovery(self, tmp_path):
        from filodb_tpu.kafka.log import FileLog
        from filodb_tpu.testing.data import gauge_stream, machine_metrics_series
        p = str(tmp_path / "wal.log")
        log = FileLog(p)
        keys = machine_metrics_series(1)
        for sd in gauge_stream(keys, 10, batch=1, start_ms=0):
            log.append(sd.container)
        log.close()
        # simulate a torn write: garbage length header + partial payload
        with open(p, "ab") as f:
            f.write((99999).to_bytes(4, "little") + b"partial-garbage")
        log2 = FileLog(p)
        assert log2.latest_offset == 9  # torn tail dropped
        assert len(list(log2.read_from(0))) == 10
        # appends continue cleanly after the torn tail
        for sd in gauge_stream(keys, 1, batch=1, start_ms=10**9):
            log2.append(sd.container)
        assert log2.latest_offset == 10
        # the torn bytes were truncated, so the full log (including the
        # post-recovery append) reads back cleanly
        entries = list(log2.read_from(0))
        assert len(entries) == 11
        assert entries[-1].offset == 10
        last_recs = list(entries[-1].container)
        assert last_recs[0].timestamp >= 10**9
        log2.close()
        # and the file survives a further reopen
        log3 = FileLog(p)
        assert len(list(log3.read_from(0))) == 11
        log3.close()


class TestAlignAfter:
    def test_offsets_never_reused_after_checkpointed_torn_tail(self, tmp_path):
        # A torn tail can destroy records whose offsets were already
        # checkpointed; align_after must push the next append past them.
        from filodb_tpu.kafka.log import SegmentedFileLog
        from filodb_tpu.testing.data import gauge_stream, machine_metrics_series
        keys = machine_metrics_series(1)
        log = SegmentedFileLog(str(tmp_path / "wal"))
        for sd in gauge_stream(keys, 5, batch=1):
            log.append(sd.container)
        assert log.latest_offset == 4
        # checkpoint said offset 6 was acked (records 5,6 torn away)
        log.align_after(6)
        sd = next(gauge_stream(keys, 1, batch=1, start_ms=10**9))
        assert log.append(sd.container) == 7
        offsets = [e.offset for e in log.read_from(0)]
        assert offsets == [0, 1, 2, 3, 4, 7]
        log.close()
        # survives reopen: segment numbering carries the gap
        log2 = SegmentedFileLog(str(tmp_path / "wal"))
        assert [e.offset for e in log2.read_from(0)] == [0, 1, 2, 3, 4, 7]
        assert log2.latest_offset == 7
        log2.close()

    def test_align_after_noop_when_already_past(self, tmp_path):
        from filodb_tpu.kafka.log import SegmentedFileLog
        from filodb_tpu.testing.data import gauge_stream, machine_metrics_series
        keys = machine_metrics_series(1)
        log = SegmentedFileLog(str(tmp_path / "wal"))
        for sd in gauge_stream(keys, 5, batch=1):
            log.append(sd.container)
        log.align_after(2)  # behind the tip: nothing changes
        assert log.latest_offset == 4
        assert len(log._segments) == 1
        log.close()


class TestWalFsync:
    def test_fsync_knob_plumbed(self, tmp_path):
        import json
        from filodb_tpu.config import ServerConfig
        from filodb_tpu.kafka.log import SegmentedFileLog
        from filodb_tpu.testing.data import gauge_stream, machine_metrics_series
        p = tmp_path / "server.json"
        p.write_text(json.dumps({"wal_fsync": True,
                                 "data_dir": str(tmp_path / "d")}))
        cfg = ServerConfig.load(str(p))
        assert cfg.wal_fsync is True
        log = SegmentedFileLog(str(tmp_path / "wal"), fsync=cfg.wal_fsync)
        assert log._segments[0][1].fsync is True
        keys = machine_metrics_series(1)
        for sd in gauge_stream(keys, 3, batch=1):
            log.append(sd.container)
        assert len(list(log.read_from(0))) == 3
        log.close()


class TestRemoteProtocol:
    def test_unknown_control_message(self):
        from filodb_tpu.coordinator.remote import (
            PlanExecutorServer,
            RemotePlanDispatcher,
        )
        srv = PlanExecutorServer(None).start()
        try:
            d = RemotePlanDispatcher("127.0.0.1", srv.port)
            with pytest.raises(RuntimeError, match="unknown message"):
                d.call("no_such_op", 1, 2)
            assert d.ping()  # connection still healthy after the error
        finally:
            srv.stop()


class TestLogicalParserFilters:
    def test_in_filter_renders_as_regex(self):
        from filodb_tpu.core.filters import ColumnFilter, In
        from filodb_tpu.core.partkey import METRIC_LABEL
        from filodb_tpu.core.filters import Equals
        from filodb_tpu.query import logical as lp
        from filodb_tpu.query.logical_parser import to_promql
        raw = lp.RawSeries(
            (ColumnFilter(METRIC_LABEL, Equals("m")),
             ColumnFilter("host", In(frozenset(["a", "b"])))),
            0, 1000)
        plan = lp.PeriodicSeries(raw, 0, 1000, 10_000)
        assert to_promql(plan) == 'm{host=~"a|b"}'
