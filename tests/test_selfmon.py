"""Self-monitoring (_meta dataset) + ingest-path observability.

Covers the ingest-observability round end to end: the registry sampler
(``utils/selfmon.py``), the sampled gateway->shard freshness stamps, the
replay-log lag helper, the Prometheus exposition hardening (label-value
escaping, scrape-error accounting), the TSDB/ingest status routes on both
HTTP fronts, and the full loop — a standalone node with selfmon enabled
writes its own registry into ``_meta``, the shipped ``selfmon_default``
alert group fires ``FilodbIngestLagHigh`` under an injected ingest stall,
and the alert resolves once the stall clears.
"""

import json
import socket
import threading
import time
import urllib.request

import pytest

from filodb_tpu.config import ServerConfig
from filodb_tpu.core.partkey import METRIC_LABEL
from filodb_tpu.core.record import RecordContainer
from filodb_tpu.kafka.log import InMemoryLog
from filodb_tpu.standalone import FiloServer
from filodb_tpu.utils import metrics as metrics_mod
from filodb_tpu.utils import selfmon as selfmon_mod
from filodb_tpu.utils.metrics import (
    Counter,
    Gauge,
    GaugeFn,
    Histogram,
    render_prometheus,
)
from filodb_tpu.utils.resilience import FaultInjector
from filodb_tpu.utils.selfmon import E2EStamps, MetaMonitor, registry_samples


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        assert r.status == 200
        return json.load(r)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# registry sampler


class TestRegistrySamples:
    def test_families_follow_exposition_naming(self):
        Counter("selfmon_ut_ctr").inc(3)
        Gauge("selfmon_ut_gauge").set(7.5)
        h = Histogram("selfmon_ut_hist", bounds=(1.0, 5.0))
        h.observe(2.0)
        out = dict((labels[METRIC_LABEL], v) for labels, v in
                   registry_samples({"node": "n1"})
                   if labels[METRIC_LABEL].startswith("selfmon_ut_"))
        assert out["selfmon_ut_ctr_total"] == 3.0
        assert out["selfmon_ut_gauge"] == 7.5
        assert out["selfmon_ut_hist_count"] == 1.0
        assert out["selfmon_ut_hist_sum"] == 2.0
        # buckets only on request (they multiply _meta cardinality)
        assert "selfmon_ut_hist_bucket" not in out
        buck = [(labels, v) for labels, v in
                registry_samples({}, include_buckets=True)
                if labels[METRIC_LABEL] == "selfmon_ut_hist_bucket"]
        assert {lbl["le"] for lbl, _ in buck} == {"1.0", "5.0"}

    def test_base_labels_win_on_collision(self):
        Counter("selfmon_ut_clash", {"node": "from_tag"}).inc()
        hits = [labels for labels, _ in registry_samples({"node": "base"})
                if labels[METRIC_LABEL] == "selfmon_ut_clash_total"]
        assert hits and hits[0]["node"] == "base"
        assert hits[0]["exported_node"] == "from_tag"

    def test_none_and_nan_gaugefns_are_skipped(self):
        GaugeFn("selfmon_ut_none", lambda: None)
        GaugeFn("selfmon_ut_boom", lambda: 1 / 0)
        names = {labels[METRIC_LABEL] for labels, _ in registry_samples({})}
        assert "selfmon_ut_none" not in names
        assert "selfmon_ut_boom" not in names  # NaN would poison _meta


class TestMetaMonitor:
    def test_tick_writes_one_container(self):
        written = []

        class Sink:
            def write(self, cont):
                written.append(cont)
                return len(cont), {}

        mon = MetaMonitor(Sink(), node="nX", instance="nX:1")
        t0 = selfmon_mod.TICKS.value
        n = mon.tick()
        assert n > 0 and len(written) == 1 and len(written[0]) == n
        assert selfmon_mod.TICKS.value == t0 + 1
        assert selfmon_mod.SERIES.value == float(n)

    def test_tick_error_is_counted_not_raised(self):
        class BadSink:
            def write(self, cont):
                raise RuntimeError("sink down")

        mon = MetaMonitor(BadSink())
        e0 = selfmon_mod.ERRORS.value
        assert mon.tick() == 0  # selfmon must never take down the node
        assert selfmon_mod.ERRORS.value == e0 + 1


# ---------------------------------------------------------------------------
# freshness stamps + lag helpers


class TestE2EStamps:
    def test_sampling_and_observe(self):
        st = E2EStamps(sample_every=2, max_pending=4)
        for off in (1, 2, 3, 4, 5, 6):
            st.maybe_stamp("ds", 0, off)
        # every 2nd container stamped: offsets 1, 3, 5
        assert [o for o, _ in st._pending[("ds", 0)]] == [1, 3, 5]
        c0 = selfmon_mod.INGEST_E2E.count
        st.observe("ds", 0, 4)  # pops 1 and 3
        assert selfmon_mod.INGEST_E2E.count == c0 + 2
        assert [o for o, _ in st._pending[("ds", 0)]] == [5]
        st.observe("ds", 0, 10)
        assert selfmon_mod.INGEST_E2E.count == c0 + 3

    def test_pending_is_bounded(self):
        st = E2EStamps(sample_every=1, max_pending=3)
        for off in range(10):
            st.maybe_stamp("ds", 1, off)
        assert [o for o, _ in st._pending[("ds", 1)]] == [7, 8, 9]

    def test_offset_lag_clamped_at_zero(self):
        lg = InMemoryLog()
        assert lg.offset_lag(-1) == 0  # empty log, nothing consumed
        c = RecordContainer()
        first = lg.append(c)
        last = lg.append(c)
        assert lg.offset_lag(first - 1) == last - first + 1
        assert lg.offset_lag(last) == 0
        assert lg.offset_lag(last + 5) == 0  # ahead of log: clamp, not -5


# ---------------------------------------------------------------------------
# exposition hardening (satellites: escaping + scrape-error accounting)


class TestExpositionHardening:
    def test_label_values_escaped(self):
        Gauge("selfmon_ut_esc",
              {"path": 'a\\b"c\nd'}).set(1.0)
        text = render_prometheus()
        line = next(ln for ln in text.splitlines()
                    if ln.startswith("selfmon_ut_esc{"))
        assert 'path="a\\\\b\\"c\\nd"' in line
        assert "\n" not in line  # raw newline would corrupt the scrape

    def test_broken_gaugefn_counted_and_rendered_nan(self):
        GaugeFn("selfmon_ut_broken", lambda: [][1])
        s0 = metrics_mod.SCRAPE_ERRORS.value
        text = render_prometheus()
        assert metrics_mod.SCRAPE_ERRORS.value > s0
        line = next(ln for ln in text.splitlines()
                    if ln.startswith("selfmon_ut_broken"))
        assert line.endswith("nan")
        # family advertised so dashboards can alert on it
        assert "filodb_metric_scrape_errors_total" in text


# ---------------------------------------------------------------------------
# status routes on both HTTP fronts + CLI


class TestStatusRoutes:
    @pytest.fixture(params=["fast", "threaded"])
    def server(self, request, tmp_path):
        cfg_path = tmp_path / "server.json"
        cfg_path.write_text(json.dumps({
            "node_name": "status-node",
            "data_dir": str(tmp_path / "data"),
            "http_port": 0,
            "gateway_port": 0,
            "http_impl": request.param,
            "datasets": {"timeseries": {
                "num_shards": 2, "spread": 1,
                "store": {"max_chunk_size": 50, "groups_per_shard": 2}}},
        }))
        cfg = ServerConfig.load(str(cfg_path))
        object.__setattr__(cfg, "gateway_port", _free_port())
        srv = FiloServer(cfg).start()
        yield srv
        srv.shutdown()

    def _ingest(self, srv, n=80):
        start = int(time.time())
        with socket.create_connection(("127.0.0.1",
                                       srv.gateway.port)) as s:
            for i in range(n):
                ts_ns = (start + i) * 1_000_000_000
                s.sendall(f"status_metric,host=h{i % 4},_ws_=demo,"
                          f"_ns_=App-0 value={i} {ts_ns}\n".encode())
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            srv.gateway.sink.flush()
            if sum(sh.stats.rows_ingested.value
                   for sh in srv.memstore.shards_for("timeseries")) >= n:
                return
            time.sleep(0.2)
        raise AssertionError("ingest never completed")

    def test_status_tsdb_and_ingest(self, server, capsys):
        srv = server
        self._ingest(srv)
        tsdb = _get(srv.http.port, "/api/v1/status/tsdb")
        assert tsdb["status"] == "success"
        assert "timeseries" in tsdb["data"]
        d = tsdb["data"]["timeseries"]
        assert d["headStats"]["numShards"] == 2
        assert d["headStats"]["numSeries"] >= 4  # 4 distinct hosts
        assert len(d["shards"]) == 2
        for sh in d["shards"]:
            assert set(sh) >= {"shard", "numSeries", "indexRamBytes",
                               "encodedBytes", "samplesEncoded"}
        by_metric = {e["name"]: e for e in d["seriesCountByMetricName"]}
        assert by_metric["status_metric"]["value"] >= 4
        by_label = {e["name"] for e in d["labelValueCountByLabelName"]}
        assert "host" in by_label

        ing = _get(srv.http.port, "/api/v1/status/ingest")
        assert ing["status"] == "success"
        di = ing["data"]["datasets"]["timeseries"]
        for sh in di["shards"]:
            assert sh["ingestedOffset"] >= 0
            assert sh["offsetLag"] == 0  # fully drained after the wait
            assert sh["ingestLagSeconds"] is not None
        assert "queueDepth" in ing["data"]["objectstore"]
        assert "oldestTaskAgeSeconds" in ing["data"]["objectstore"]

        # topk / dataset filters parse
        one = _get(srv.http.port,
                   "/api/v1/status/tsdb?dataset=timeseries&topk=1")
        assert list(one["data"]) == ["timeseries"]
        assert len(one["data"]["timeseries"]
                   ["seriesCountByMetricName"]) <= 1

        # operator CLI renders both views from the same API
        from filodb_tpu.cli import main as cli_main
        cli_main(["--host", f"127.0.0.1:{srv.http.port}", "status"])
        out = capsys.readouterr().out
        assert "status_metric" in out
        cli_main(["--host", f"127.0.0.1:{srv.http.port}", "lag"])
        out = capsys.readouterr().out
        assert "timeseries" in out and "OFF_LAG" in out


# ---------------------------------------------------------------------------
# the full loop: _meta dataset + default lag alert under an injected stall


class TestSelfMonE2E:
    @pytest.fixture
    def server(self, tmp_path):
        FaultInjector.reset()
        # hermetic alert input: earlier tests in the same process may have
        # leaked per-shard freshness GaugeFns whose shard objects are still
        # referenced (server threads, fixture cycles) — a foreign
        # filodb_ingest_lag_seconds series with a 2020-epoch high-water
        # mark reads as ~1.9e8 s of lag and pins
        # max(filodb_ingest_lag_seconds) > threshold forever. Purge the
        # families the shipped alerts aggregate over; this server's own
        # shards re-register theirs at start.
        from filodb_tpu.utils import metrics as metrics_mod
        with metrics_mod._lock:
            for key in [k for k, m in metrics_mod._registry.items()
                        if m.name in ("filodb_ingest_lag_seconds",
                                      "filodb_ingest_offset_lag",
                                      "filodb_ingest_checkpoint_lag",
                                      "filodb_breaker_state")]:
                del metrics_mod._registry[key]
        cfg_path = tmp_path / "server.json"
        cfg_path.write_text(json.dumps({
            "node_name": "selfmon-node",
            "data_dir": str(tmp_path / "data"),
            "http_port": 0,
            "gateway_port": 0,
            "rules": {"tick_s": 0.2},
            "selfmon": {
                "enabled": True,
                "interval_s": 0.25,
                "lag_alert_threshold_s": 3.0,
                "lag_alert_for": "0s",
                "alert_interval": "1s",
            },
            "datasets": {"timeseries": {
                "num_shards": 1, "spread": 0,
                "store": {"max_chunk_size": 50, "groups_per_shard": 2}}},
        }))
        cfg = ServerConfig.load(str(cfg_path))
        object.__setattr__(cfg, "gateway_port", _free_port())
        srv = FiloServer(cfg).start()
        yield srv
        FaultInjector.reset()
        srv.shutdown()

    def test_meta_loop_alert_fires_and_resolves(self, server):
        srv = server
        # _meta rides the normal dataset machinery
        assert "_meta" in srv.config.datasets

        # continuous wall-clock-fresh writes; the lag gauge measures
        # now - max ingested ts, so freshness only means something while
        # data keeps flowing
        stop = threading.Event()

        def writer():
            with socket.create_connection(("127.0.0.1",
                                           srv.gateway.port)) as s:
                i = 0
                while not stop.is_set():
                    ts_ns = int(time.time() * 1e9)
                    s.sendall(f"live_metric,host=h{i % 3},_ws_=demo,"
                              f"_ns_=App-0 value={i} {ts_ns}\n".encode())
                    i += 1
                    time.sleep(0.05)

        wt = threading.Thread(target=writer, daemon=True)
        wt.start()
        try:
            # the node's own registry becomes queryable through _meta
            deadline = time.monotonic() + 30
            result = []
            while time.monotonic() < deadline:
                srv.gateway.sink.flush()
                now = int(time.time())
                q = _get(srv.http.port,
                         f"/promql/_meta/api/v1/query_range?"
                         f"query=filodb_selfmon_ticks_total"
                         f"&start={now - 60}&end={now}&step=5")
                result = q["data"]["result"]
                if result and result[0]["values"]:
                    break
                time.sleep(0.3)
            assert result, "_meta never became queryable"
            assert result[0]["metric"]["_ns_"] == "selfmon"

            # shipped alert group is loaded alongside user groups
            groups = _get(srv.http.port,
                          "/api/v1/rules")["data"]["groups"]
            assert any(g["name"] == "selfmon_default" for g in groups)

            # stall the user dataset's ingest (not _meta: selfmon must
            # keep observing while the thing it watches is stuck)
            FaultInjector.arm(
                "shard.ingest", delay_s=6.0, times=2,
                match=lambda ctx: ctx.get("dataset") != "_meta")

            def firing():
                alerts = _get(srv.http.port,
                              "/api/v1/alerts")["data"]["alerts"]
                return [a for a in alerts if a["state"] == "firing"
                        and a["labels"]["alertname"]
                        == "FilodbIngestLagHigh"]

            deadline = time.monotonic() + 45
            fired = []
            while time.monotonic() < deadline and not fired:
                srv.gateway.sink.flush()
                fired = firing()
                time.sleep(0.4)
            assert fired, "lag alert never fired under injected stall"
            assert fired[0]["labels"]["severity"] == "warning"

            # stall clears (fault exhausted) -> backlog drains -> lag
            # drops -> the alert resolves (generous deadline: under a
            # full-suite run the sampler/rules loops share the GIL with
            # everything the suite leaked)
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline and firing():
                assert wt.is_alive(), "writer thread died mid-test"
                srv.gateway.sink.flush()
                time.sleep(0.4)
            assert not firing(), "lag alert never resolved after stall"
        finally:
            stop.set()
            wt.join(timeout=5)

        # sampled gateway->shard freshness probe closed the loop too
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.http.port}/metrics") as r:
            text = r.read().decode()
        e2e = [ln for ln in text.splitlines()
               if ln.startswith("filodb_ingest_e2e_seconds_count")]
        assert e2e and float(e2e[0].rsplit(" ", 1)[1]) >= 1

        # ingest status surfaces _meta next to the user dataset
        ing = _get(srv.http.port, "/api/v1/status/ingest")
        assert {"timeseries", "_meta"} <= set(ing["data"]["datasets"])
