"""Fused decode->window Pallas kernel parity (interpret mode).

One Pallas program decodes bit-packed device pages, counter-corrects and
window-evaluates in VMEM (VERDICT r3 #4: the decoded [P, S] tensors never
round-trip HBM). Must match kernels.range_eval_masked exactly; real-TPU
timing runs via bench.py's kernel microbench.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from filodb_tpu.memory.device_pages import encode_f32_page, encode_ts_page
from filodb_tpu.query.engine.device_batch import _assemble, pack_series_pages
from filodb_tpu.query.engine.kernels import range_eval_masked
from filodb_tpu.query.engine.pallas_kernels import fused_decode_rate_pallas


def _mk(per_series_spec, seed=3):
    rng = np.random.default_rng(seed)
    per_series = []
    for spec in per_series_spec:
        n = spec["n"]
        ts = np.cumsum(rng.integers(8000, 12000, n)).astype(np.int64)
        vals = np.cumsum(rng.integers(0, 20, n)).astype(np.float64)
        if spec.get("reset_at") is not None:
            vals[spec["reset_at"]:] -= vals[spec["reset_at"]]
        per_series.append([(encode_ts_page(ts), encode_f32_page(vals), n)])
    return pack_series_pages(per_series, start=0)


@pytest.mark.parametrize("kind,counter", [("rate", True),
                                          ("increase", True),
                                          ("delta", False)])
def test_fused_matches_xla_reference(kind, counter):
    packed, counts = _mk([{"n": 150}, {"n": 120, "reset_at": 60},
                          {"n": 140}])
    steps = np.linspace(700_000, 1_200_000, 6).astype(np.int32)
    window = np.int32(300_000)
    packed_d = tuple(jnp.asarray(a) for a in packed)
    ts_d, vals_d, valid_d = _assemble(*packed_d,
                                      jnp.asarray(np.int32(12000 * 151)))
    ref = np.asarray(range_eval_masked(kind, ts_d, vals_d, valid_d,
                                       jnp.asarray(steps),
                                       jnp.asarray(window),
                                       counter=counter))
    got = np.asarray(fused_decode_rate_pallas(
        packed_d, jnp.asarray(steps), jnp.asarray(window), kind=kind,
        counter=counter, interpret=True))
    n = 3
    np.testing.assert_allclose(got[:n], ref[:n], rtol=2e-5, atol=1e-6,
                               equal_nan=True)


def test_fused_empty_windows_are_nan():
    packed, _ = _mk([{"n": 100}])
    # steps far beyond the data: no samples in any window
    steps = np.array([10**9, 2 * 10**9], np.int32)
    packed_d = tuple(jnp.asarray(a) for a in packed)
    got = np.asarray(fused_decode_rate_pallas(
        packed_d, jnp.asarray(steps), jnp.asarray(np.int32(300_000)),
        interpret=True))
    assert np.isnan(got[0]).all()
