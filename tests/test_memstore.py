"""Memstore tests.

Mirrors ``core/src/test/scala/filodb.core/memstore/TimeSeriesMemStoreSpec.scala``
and ``TimeSeriesPartitionSpec.scala``: ingest → chunk encode → flush →
checkpoint → recovery watermarks → index lookups.
"""

import numpy as np

from filodb_tpu.core.filters import ColumnFilter, Equals, EqualsRegex, NotEquals
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.memstore.partition import TimeSeriesPartition
from filodb_tpu.core.partkey import (
    PartKey,
    ingestion_shard,
    shard_key_hash,
    shards_for_shard_key,
)
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.core.store.api import InMemoryColumnStore, InMemoryMetaStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.testing.data import (
    counter_stream,
    gauge_stream,
    histogram_series,
    histogram_stream,
    machine_metrics_series,
)


def small_config(**kw):
    defaults = dict(max_chunk_size=100, groups_per_shard=4)
    defaults.update(kw)
    return StoreConfig(**defaults)


class TestPartition:
    def test_ingest_and_read(self):
        key = machine_metrics_series(1)[0]
        p = TimeSeriesPartition(0, key, DEFAULT_SCHEMAS["gauge"], max_chunk_size=50)
        for i in range(120):
            assert p.ingest(i * 1000, (float(i),))
        assert p.num_samples == 120
        assert len(p.chunks) == 2  # two full chunks + 20 in buffer
        ts, vals = p.read_samples(0, 10**15)
        assert len(ts) == 120
        np.testing.assert_array_equal(vals, np.arange(120, dtype=np.float64))

    def test_out_of_order_dropped(self):
        key = machine_metrics_series(1)[0]
        p = TimeSeriesPartition(0, key, DEFAULT_SCHEMAS["gauge"])
        assert p.ingest(1000, (1.0,))
        assert not p.ingest(1000, (2.0,))  # duplicate
        assert not p.ingest(500, (3.0,))   # out of order
        assert p.ingest(2000, (4.0,))
        assert p.num_samples == 2

    def test_time_range_read(self):
        key = machine_metrics_series(1)[0]
        p = TimeSeriesPartition(0, key, DEFAULT_SCHEMAS["gauge"], max_chunk_size=10)
        for i in range(100):
            p.ingest(i * 1000, (float(i),))
        ts, vals = p.read_samples(25_000, 74_000)
        assert ts[0] == 25_000 and ts[-1] == 74_000
        assert len(ts) == 50

    def test_flush_chunks_marks(self):
        key = machine_metrics_series(1)[0]
        p = TimeSeriesPartition(0, key, DEFAULT_SCHEMAS["gauge"], max_chunk_size=10)
        for i in range(25):
            p.ingest(i * 1000, (float(i),))
        chunks = p.make_flush_chunks()
        assert sum(c.num_rows for c in chunks) == 25
        p.mark_flushed(max(c.id for c in chunks))
        for i in range(25, 30):
            p.ingest(i * 1000, (float(i),))
        chunks2 = p.make_flush_chunks()
        assert sum(c.num_rows for c in chunks2) == 5


class TestShardIngest:
    def test_ingest_gauge_stream(self):
        ms = TimeSeriesMemStore()
        shard = ms.setup("timeseries", 0, small_config())
        keys = machine_metrics_series(10)
        for data in gauge_stream(keys, 300):
            shard.ingest(data)
        assert shard.num_partitions == 10
        assert shard.stats.rows_ingested.value == 3000
        pids = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("heap_usage"))], 0, 10**15)
        assert len(pids) == 10

    def test_index_filters(self):
        ms = TimeSeriesMemStore()
        shard = ms.setup("timeseries", 0, small_config())
        keys = machine_metrics_series(10)
        for data in gauge_stream(keys, 10):
            shard.ingest(data)
        f = [ColumnFilter("_metric_", Equals("heap_usage")),
             ColumnFilter("instance", Equals("instance-3"))]
        assert len(shard.lookup_partitions(f, 0, 10**15)) == 1
        f = [ColumnFilter("instance", EqualsRegex("instance-[0-4]"))]
        assert len(shard.lookup_partitions(f, 0, 10**15)) == 5
        f = [ColumnFilter("host", NotEquals("H0"))]
        assert len(shard.lookup_partitions(f, 0, 10**15)) == 7
        assert shard.label_values("host") == ["H0", "H1", "H2", "H3"]
        assert "instance" in shard.label_names()

    def test_time_bounded_lookup(self):
        ms = TimeSeriesMemStore()
        shard = ms.setup("timeseries", 0, small_config())
        keys = machine_metrics_series(2)
        for data in gauge_stream(keys, 10, start_ms=1_000_000):
            shard.ingest(data)
        f = [ColumnFilter("_metric_", Equals("heap_usage"))]
        # query window entirely before series start → excluded
        assert shard.lookup_partitions(f, 0, 999_999) == []
        assert len(shard.lookup_partitions(f, 0, 1_000_001)) == 2


class TestFlushAndRecovery:
    def test_flush_writes_chunks_and_checkpoints(self):
        cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
        ms = TimeSeriesMemStore(cs, meta)
        shard = ms.setup("timeseries", 0, small_config())
        keys = machine_metrics_series(4)
        for data in gauge_stream(keys, 100):
            shard.ingest(data)
        written = shard.flush_all(ingestion_time=12345)
        assert written >= 4
        # all data persisted: read back chunks for one key
        chunks = cs.read_chunks("timeseries", 0, keys[0], 0, 10**15)
        assert sum(c.num_rows for c in chunks) == 100
        # checkpoints written for all groups
        cps = meta.read_checkpoints("timeseries", 0)
        assert len(cps) == 4
        assert min(cps.values()) == shard.latest_offset

    def test_checkpoint_captured_before_buffer_snapshot(self):
        # Rows ingested WHILE a flush is in progress must stay above the
        # group watermark (they live only in unsealed buffers); the
        # checkpoint must be the offset captured before snapshotting, not
        # the post-flush ingested offset.
        cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
        ms = TimeSeriesMemStore(cs, meta)
        shard = ms.setup("timeseries", 0, small_config(groups_per_shard=1))
        keys = machine_metrics_series(2)
        stream = list(gauge_stream(keys, 50, batch=1))
        for data in stream[:40]:
            shard.ingest(data)
        pre_flush_offset = shard.latest_offset

        late = stream[40:]
        orig_write = cs.write_chunks

        def write_and_ingest_mid_flush(*a, **kw):
            # simulate concurrent ingest racing the flush I/O
            while late:
                shard._ingest_locked(late[0], late[0].offset)
                late.pop(0)
            return orig_write(*a, **kw)

        cs.write_chunks = write_and_ingest_mid_flush
        shard.flush_group(0)
        cps = meta.read_checkpoints("timeseries", 0)
        assert cps[0] == pre_flush_offset
        assert shard.group_watermarks[0] == pre_flush_offset
        assert shard.latest_offset > pre_flush_offset

    def test_no_duplicates_when_mid_flush_rows_replay_after_crash(self):
        # Rows ingested mid-flush can be BOTH persisted (their partition's
        # buffer snapshot ran after they landed) and above the checkpoint.
        # After a crash, replay must not double-ingest them: recovery seeds
        # each partition's out-of-order floor from the max persisted ts.
        cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
        ms = TimeSeriesMemStore(cs, meta)
        shard = ms.setup("timeseries", 0, small_config(groups_per_shard=1))
        keys = machine_metrics_series(2)
        stream = list(gauge_stream(keys, 50, batch=1))
        for data in stream[:40]:
            shard.ingest(data)

        late = stream[40:]
        orig_write = cs.write_chunks

        def write_and_ingest_mid_flush(*a, **kw):
            while late:
                shard._ingest_locked(late[0], late[0].offset)
                late.pop(0)
            return orig_write(*a, **kw)

        cs.write_chunks = write_and_ingest_mid_flush
        # During this flush the hook fires at the FIRST partition's chunk
        # write, so the SECOND partition's buffer seal (which happens later
        # in the group loop) includes its late rows: those rows end up
        # persisted AND above the group checkpoint. Crash follows — no
        # further flush advances the checkpoint.
        shard.flush_group(0)
        cs.write_chunks = orig_write

        # crash + restart: fresh memstore on the same stores
        ms2 = TimeSeriesMemStore(cs, meta)
        shard2 = ms2.setup("timeseries", 0, small_config(groups_per_shard=1))
        shard2.recover_index()
        shard2.setup_watermarks_for_recovery()
        for data in stream:
            shard2.ingest(data)
        shard2.flush_all()
        # every persisted timestamp for every series must be unique
        for key in keys:
            chunks = cs.read_chunks("timeseries", 0, key, 0, 10**15)
            all_ts = [t for c in chunks for t in c.decode_column(0)]
            assert len(all_ts) == len(set(all_ts)), \
                f"duplicate persisted samples for {key}"

    def test_floor_applies_to_partitions_recreated_by_replay(self):
        # Crash between write_chunks and write_part_keys: the part-key
        # record is missing, so recover_index doesn't restore the partition
        # — replay re-creates it and must still get the persisted-ts floor.
        cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
        ms = TimeSeriesMemStore(cs, meta)
        shard = ms.setup("timeseries", 0, small_config(groups_per_shard=1))
        keys = machine_metrics_series(1)
        stream = list(gauge_stream(keys, 30, batch=1))
        for data in stream:
            shard.ingest(data)
        orig_wpk = cs.write_part_keys
        # crash after write_chunks but before write_part_keys (and therefore
        # before the checkpoint, which flush_group writes after part keys)
        def crash(*a, **kw):
            raise RuntimeError("simulated crash")

        cs.write_part_keys = crash
        import pytest as _pytest
        with _pytest.raises(RuntimeError, match="simulated crash"):
            shard.flush_group(0)
        cs.write_part_keys = orig_wpk

        ms2 = TimeSeriesMemStore(cs, meta)
        shard2 = ms2.setup("timeseries", 0, small_config(groups_per_shard=1))
        assert shard2.recover_index() == 0  # no part-key record survived
        # replay the WAL, then live tail rows arrive before the next flush
        tail = list(gauge_stream(keys, 10, batch=1,
                                 start_ms=30 * 60_000,
                                 start_offset=len(stream)))
        for data in stream + tail:
            shard2.ingest(data)
        shard2.flush_all()
        chunks = cs.read_chunks("timeseries", 0, keys[0], 0, 10**15)
        all_ts = sorted(t for c in chunks for t in c.decode_column(0))
        # no duplicates AND no silent loss: without the replay-seeded floor
        # the re-built buffer re-seals under the crashed flush's partial
        # chunk id and the store's id-dedup drops the tail samples
        assert len(all_ts) == len(set(all_ts)), "duplicate persisted samples"
        assert len(set(all_ts)) == 40, f"lost samples: {len(set(all_ts))}/40"

    def test_evicted_chunks_keep_dedup_floor(self):
        key = machine_metrics_series(1)[0]
        p = TimeSeriesPartition(0, key, DEFAULT_SCHEMAS["gauge"],
                                max_chunk_size=10)
        for i in range(20):
            p.ingest(i * 1000, (float(i),))
        p.mark_flushed(max(c.id for c in p.chunks))
        # 20 ingests at chunk size 10 auto-sealed two chunks; buffer is empty
        evicted = p.evict_flushed_chunks()
        assert evicted == 2
        # timestamps covered by the evicted chunks must still be rejected
        assert not p.ingest(5_000, (99.0,))
        assert not p.ingest(9_000, (99.0,))
        # fresh timestamps keep flowing
        assert p.ingest(30_000, (30.0,))

    def test_recovery_skips_below_watermark(self):
        cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
        ms = TimeSeriesMemStore(cs, meta)
        shard = ms.setup("timeseries", 0, small_config())
        keys = machine_metrics_series(4)
        stream = list(gauge_stream(keys, 100))
        half = len(stream) // 2
        for data in stream[:half]:
            shard.ingest(data)
        shard.flush_all()
        ingested_before = shard.stats.rows_ingested.value

        # simulate restart: new store, same column/meta stores
        ms2 = TimeSeriesMemStore(cs, meta)
        shard2 = ms2.setup("timeseries", 0, small_config())
        assert shard2.recover_index() == 4
        start = shard2.setup_watermarks_for_recovery()
        assert start == stream[half - 1].offset
        # replay everything from offset 0: below-watermark rows are skipped
        for data in stream:
            shard2.ingest(data)
        assert shard2.stats.rows_skipped.value > 0
        # no duplicates in memory: only above-watermark rows were replayed
        # (flushed rows live in the column store and are served via ODP)
        total = sum(p.num_samples for p in shard2.partitions if p)
        assert total == 100 * 4 - ingested_before
        assert ingested_before + shard2.stats.rows_ingested.value == 100 * 4

    def test_purge_expired(self):
        ms = TimeSeriesMemStore()
        config = small_config(retention_ms=1_000_000)
        shard = ms.setup("timeseries", 0, config)
        old_keys = machine_metrics_series(2, metric="old_metric")
        new_keys = machine_metrics_series(2, metric="new_metric")
        for data in gauge_stream(old_keys, 5, start_ms=0):
            shard.ingest(data)
        for data in gauge_stream(new_keys, 5, start_ms=5_000_000):
            shard.ingest(data)
        assert shard.purge_expired(now_ms=6_000_000) == 2
        assert shard.num_partitions == 2
        f = [ColumnFilter("_metric_", Equals("old_metric"))]
        assert shard.lookup_partitions(f, 0, 10**15) == []


class TestHistogramIngest:
    def test_histogram_round_trip(self):
        ms = TimeSeriesMemStore()
        shard = ms.setup("timeseries", 0, small_config())
        keys = histogram_series(2)
        for data in histogram_stream(keys, 50):
            shard.ingest(data)
        part = shard.partitions[0]
        ts, hist = part.read_samples(0, 10**15)
        assert len(ts) == 50
        assert hist.rows.shape == (50, 10)
        # cumulative in both directions: non-decreasing across buckets & time
        assert (np.diff(hist.rows, axis=1) >= 0).all()
        assert (np.diff(hist.rows, axis=0) >= 0).all()


class TestShardRouting:
    def test_spread_semantics(self):
        skh = shard_key_hash({"_ws_": "demo", "_ns_": "App-1",
                              "_metric_": "heap_usage"})
        shards = shards_for_shard_key(skh, 32, spread=2)
        assert len(shards) == 4
        # every series of this shard key lands in the fan-out set
        for i in range(50):
            pk = PartKey.create("gauge", {
                "_ws_": "demo", "_ns_": "App-1", "_metric_": "heap_usage",
                "instance": f"i{i}"})
            s = ingestion_shard(skh, pk.part_hash, 32, spread=2)
            assert s in shards

    def test_hash_stability(self):
        pk = PartKey.create("gauge", {"_metric_": "m", "_ws_": "w", "_ns_": "n"})
        assert pk.part_hash == PartKey.create(
            "gauge", {"_ns_": "n", "_ws_": "w", "_metric_": "m"}).part_hash

    def test_counter_stream_resets(self):
        ms = TimeSeriesMemStore()
        shard = ms.setup("timeseries", 0, small_config())
        from filodb_tpu.testing.data import counter_series
        keys = counter_series(2)
        for data in counter_stream(keys, 100, reset_every=30):
            shard.ingest(data)
        part = shard.partitions[0]
        ts, vals = part.read_samples(0, 10**15)
        assert (np.diff(vals) < 0).sum() >= 2  # resets present


class TestMemoryPressure:
    def test_enforce_memory_evicts_oldest_first(self):
        from filodb_tpu.core.store.api import InMemoryColumnStore, InMemoryMetaStore
        cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
        ms = TimeSeriesMemStore(cs, meta)
        shard = ms.setup("timeseries", 0, small_config(max_chunk_size=50))
        old = machine_metrics_series(2, metric="old_m")
        new = machine_metrics_series(2, metric="new_m")
        for data in gauge_stream(old, 200, start_ms=0):
            shard.ingest(data)
        for data in gauge_stream(new, 200, start_ms=10_000_000):
            shard.ingest(data)
        shard.flush_all(ingestion_time=1)
        used = shard.chunk_bytes()
        assert used > 0
        evicted = shard.enforce_memory(budget_bytes=used // 2)
        assert evicted > 0
        assert shard.chunk_bytes() <= used // 2
        # oldest partitions were evicted first; newest still resident
        newest = max((p for p in shard.partitions if p),
                     key=lambda p: p.latest_ts)
        assert len(newest.chunks) > 0
        # evicted data still queryable via ODP
        from filodb_tpu.coordinator.query_service import QueryService
        svc = QueryService(ms, "timeseries", 1, spread=0)
        r = svc.query_range('count_over_time(old_m[40m])', 2395, 60,
                            2395).result
        np.testing.assert_array_equal(r.values[:, 0], 200.0)
