"""Standing queries: incremental recording rules and alert evaluation.

The contract under test (see ``filodb_tpu/rules/manager.py``):

- recorded series are equivalent to polling the same PromQL over the
  same absolute step-aligned range (identical key sets, identical NaN
  masks, values at kernel-dtype tolerance — the repo-wide equivalence
  standard from test_result_cache.py);
- per-tick evaluation cost is proportional to newly-completed steps ONLY
  (asserted via the evaluated-steps counter: idle ticks cost zero);
- alerts run the inactive→pending→firing machine with ``for:``
  hysteresis and emit synthetic ``ALERTS``/``ALERTS_FOR_STATE`` series;
- state survives restart by recomputing from those series: a fresh
  manager resumes at the durable watermark with no skipped extent and no
  double-write;
- kill-points (``rules.eval``, ``rules.write``) prove crash-at-any-point
  safety: a failed tick leaves the watermark unmoved, and the retried
  window deduplicates against whatever the crash left behind;
- rule evaluations admit through the governor as their own lowest-
  priority cost class and are shed (watermark unmoved) under pressure;
- rule outputs pass per-tenant cardinality quotas like any other ingest.
"""

import math

import numpy as np
import pytest

from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.rules import (
    AlertingRule,
    MemstoreSink,
    RecordingRule,
    RuleGroup,
    RuleManager,
    load_groups,
)
from filodb_tpu.rules import manager as mgr_mod
from filodb_tpu.testing.data import gauge_stream, machine_metrics_series
from filodb_tpu.utils import governor as gov
from filodb_tpu.utils import lockcheck, racecheck
from filodb_tpu.utils.resilience import FaultInjector

NUM_SHARDS = 4
START = 1_600_000_000          # epoch sec (NOT on the 60s grid)
INTERVAL = 10_000              # ingest cadence, ms
GROUP_MS = 60_000              # rule-group interval, ms

# steps are absolute epoch multiples of the interval, never aligned to
# the data start: the first complete step after START is this
FIRST_STEP = (START * 1000 // GROUP_MS + 1) * GROUP_MS


def build_store(n_samples, num_shards=NUM_SHARDS):
    """Fresh store with gauge data in two namespaces (a single shard key
    reaches only 2^spread shards; two cover all four)."""
    ms = TimeSeriesMemStore()
    for s in range(num_shards):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=100,
                                              groups_per_shard=4))
    keys = (machine_metrics_series(8, ns="App-0")
            + machine_metrics_series(8, ns="App-1"))
    extend(ms, keys, n_samples, num_shards)
    return ms, keys


def extend(ms, keys, n_samples, num_shards=NUM_SHARDS):
    """Advance ingest to ``n_samples`` per series; the stream is
    deterministic from the start, and shards deduplicate the re-sent
    prefix as out-of-order, so only the new tail applies."""
    ingest_routed(ms, "timeseries",
                  gauge_stream(keys, n_samples, start_ms=START * 1000,
                               interval_ms=INTERVAL, seed=11),
                  num_shards, spread=1)


def make_svc(ms, num_shards=NUM_SHARDS):
    return QueryService(ms, "timeseries", num_shards, spread=1,
                        result_cache={"extent_steps": 8,
                                      "ooo_allowance_ms": 0})


def make_manager(svc, ms, groups, num_shards=NUM_SHARDS, **kw):
    sink = MemstoreSink(ms, "timeseries", num_shards, spread=1)
    return RuleManager(svc, sink, groups, ooo_allowance_ms=0, **kw)


def drain(mgr, limit=20):
    """Tick until a tick evaluates nothing; returns total evaluations."""
    total = 0
    for _ in range(limit):
        n = mgr.tick()
        if n == 0:
            return total
        total += n
    raise AssertionError("tick never converged")


def rec_group(name="heap", expr="avg_over_time(heap_usage[3m])",
              record="ns:heap:avg"):
    return RuleGroup(name=name, interval_ms=GROUP_MS, dataset="timeseries",
                     rules=(RecordingRule(record=record, expr=expr),))


def series_rows(res):
    """Index a range-query result's rows by (namespace, instance)."""
    m = res.result
    out = {}
    for i, key in enumerate(m.keys):
        labels = dict(key.labels)
        out[(labels.get("_ns_"), labels["instance"])] = \
            np.asarray(m.values)[i]
    return out


def assert_rows_equivalent(polled, recorded):
    p, r = series_rows(polled), series_rows(recorded)
    assert set(p) == set(r) and p
    for k in p:
        assert np.array_equal(np.isnan(p[k]), np.isnan(r[k])), k
        # kernel-dtype tolerance (float32), the repo-wide standard:
        # chunk batching may differ between the rule's extent evals and
        # the single-shot poll, so the final ulp may too
        assert np.allclose(p[k], r[k], rtol=2e-5, atol=1e-9,
                           equal_nan=True), k


class TestPollEquivalence:
    def test_recorded_equals_polled(self):
        # manager starts while only 5min of data exists (fresh start =
        # one step), then ingest advances 35 more minutes and the
        # manager catches up step by step
        ms, keys = build_store(30)
        svc = make_svc(ms)
        mgr = make_manager(svc, ms, [rec_group()])
        assert mgr.tick() == 1      # fresh start: exactly one step
        wm0 = mgr._state["heap"].last_step
        assert wm0 % GROUP_MS == 0  # absolute alignment
        extend(ms, keys, 240)
        drain(mgr)
        wm = mgr._state["heap"].last_step
        assert wm > wm0

        control = QueryService(ms, "timeseries", NUM_SHARDS, spread=1)
        polled = control.query_range("avg_over_time(heap_usage[3m])",
                                     wm0 // 1000, 60, wm // 1000)
        recorded = control.query_range("ns:heap:avg",
                                       wm0 // 1000, 60, wm // 1000)
        assert_rows_equivalent(polled, recorded)

    def test_recorded_series_carry_source_and_rule_labels(self):
        ms, keys = build_store(60)
        svc = make_svc(ms)
        g = RuleGroup(name="lbl", interval_ms=GROUP_MS,
                      dataset="timeseries",
                      rules=(RecordingRule(
                          record="ns:heap:max",
                          expr="max_over_time(heap_usage[2m])",
                          labels=(("tier", "gold"),)),))
        mgr = make_manager(svc, ms, [g])
        drain(mgr)
        res = svc.query_range('ns:heap:max{tier="gold"}',
                              FIRST_STEP // 1000, 60,
                              mgr._state["lbl"].last_step // 1000)
        m = res.result
        assert m.num_series == len(keys)
        for key in m.keys:
            labels = dict(key.labels)
            assert labels["tier"] == "gold"
            assert labels["_ws_"] == "demo"        # inherited, not default
            assert labels["_ns_"] in ("App-0", "App-1")
            assert "instance" in labels            # per-series identity


class TestIncrementality:
    def test_idle_ticks_cost_zero(self):
        ms, keys = build_store(30)
        svc = make_svc(ms)
        mgr = make_manager(svc, ms, [rec_group()])
        c0 = mgr_mod.rules_steps_evaluated.value
        assert mgr.tick() == 1
        assert mgr_mod.rules_steps_evaluated.value == c0 + 1
        for _ in range(3):          # no new data → no work at all
            assert mgr.tick() == 0
        assert mgr_mod.rules_steps_evaluated.value == c0 + 1

    def test_cost_proportional_to_new_steps_only(self):
        ms, keys = build_store(30)
        svc = make_svc(ms)
        mgr = make_manager(svc, ms, [rec_group()])
        mgr.tick()
        wm = mgr._state["heap"].last_step
        extend(ms, keys, 120)       # 15 more minutes of data
        horizon = min(s.max_ingested_ts
                      for s in ms.shards_for("timeseries"))
        expected = (horizon // GROUP_MS * GROUP_MS - wm) // GROUP_MS
        assert expected > 1
        c0 = mgr_mod.rules_steps_evaluated.value
        assert mgr.tick() == expected
        assert mgr_mod.rules_steps_evaluated.value == c0 + expected
        assert mgr.tick() == 0

    def test_catchup_cap_skips_and_counts(self):
        ms, keys = build_store(30)
        svc = make_svc(ms)
        mgr = make_manager(svc, ms, [rec_group()], max_catchup_steps=4)
        mgr.tick()
        wm0 = mgr._state["heap"].last_step
        s0 = mgr_mod.rules_steps_skipped.value
        extend(ms, keys, 480)       # ~70 new steps, far over the cap
        assert mgr.tick() == 4      # capped
        assert mgr_mod.rules_steps_skipped.value > s0
        assert mgr._state["heap"].last_step > wm0

    def test_horizon_floor_tracks_watermark(self):
        ms, keys = build_store(60)
        svc = make_svc(ms)
        mgr = make_manager(svc, ms, [rec_group()])
        # unrecovered: floor is very negative → nothing frozen yet
        assert svc.rules_horizon_floor() < 0
        drain(mgr)
        # MemstoreSink is synchronous: committed == visible
        assert svc.rules_horizon_floor() == mgr._state["heap"].last_step

    def test_horizon_floor_reads_never_block_on_state_lock(self):
        # the result cache calls the floor on EVERY cached query; a slow
        # evaluation (or catch-up) holding the state lock must not stall
        # it — the floor is a plain published int, read lock-free
        import threading

        ms, keys = build_store(60)
        svc = make_svc(ms)
        mgr = make_manager(svc, ms, [rec_group()])
        drain(mgr)
        expect = mgr._state["heap"].last_step
        acquired, release = threading.Event(), threading.Event()

        def hold():
            with mgr._lock:
                acquired.set()
                release.wait(5)

        t = threading.Thread(target=hold, daemon=True)
        t.start()
        assert acquired.wait(5)
        try:
            assert svc.rules_horizon_floor() == expect
        finally:
            release.set()
            t.join()

    def test_unrecovered_floor_bounded_not_sentinel(self):
        # a group stuck before first recovery must pin a BOUNDED floor
        # (horizon − (max_catchup_steps+1)·interval), not −2^62: the
        # cache-efficiency cost of a stuck group covers a bounded window
        ms, keys = build_store(60)
        svc = make_svc(ms)
        mgr = make_manager(svc, ms, [rec_group()], max_catchup_steps=4)

        def boom(*a, **kw):
            raise RuntimeError("recovery unavailable")

        mgr._recover = boom
        f0 = mgr_mod.rules_eval_failures.value
        assert mgr.tick() == 0
        assert mgr_mod.rules_eval_failures.value == f0 + 1
        horizon = min(s.max_ingested_ts
                      for s in ms.shards_for("timeseries"))
        assert svc.rules_horizon_floor() == horizon - 5 * GROUP_MS
        assert mgr_mod.rules_unrecovered_groups.value == 1
        # and the bound is conservative: recovery + full catch-up never
        # write below it
        del mgr._recover
        drain(mgr)
        assert mgr._state["heap"].last_step > horizon - 5 * GROUP_MS
        assert mgr_mod.rules_unrecovered_groups.value == 0


def ingest_temp(ms, sink, values_by_index):
    """Write a controlled single-series gauge through the sink (1-shard
    stores only: keeps the ingest-progress horizon deterministic)."""
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.core.record import IngestRecord, RecordContainer
    labels = {"_ws_": "demo", "_ns_": "App-0", "_metric_": "temp",
              "host": "h1"}
    cont = RecordContainer()
    for i, v in values_by_index:
        cont.add(IngestRecord(PartKey.create("gauge", labels),
                              START * 1000 + i * INTERVAL, (v,)))
    sink.write(cont)


class TestAlerting:
    def make(self, for_ms=120_000):
        ms = TimeSeriesMemStore()
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=100,
                                              groups_per_shard=4))
        svc = make_svc(ms, num_shards=1)
        sink = MemstoreSink(ms, "timeseries", 1, spread=0)
        g = RuleGroup(
            name="alerts", interval_ms=GROUP_MS, dataset="timeseries",
            rules=(AlertingRule(alert="TempHigh", expr="avg(temp) > 0.5",
                                for_ms=for_ms,
                                annotations=(("summary", "too hot"),)),))
        mgr = RuleManager(svc, sink, [g], ooo_allowance_ms=0)
        return ms, svc, sink, mgr

    def hot_after_cold(self, ms, sink, mgr):
        """Cold 10min → tick (fresh start) → hot 10min → catch up;
        returns (t0, wm): first hot-visible step and the watermark."""
        ingest_temp(ms, sink, [(i, 0.0) for i in range(60)])
        mgr.tick()
        ingest_temp(ms, sink, [(i, 1.0) for i in range(60, 120)])
        drain(mgr)
        hot_ms = START * 1000 + 60 * INTERVAL
        t0 = (hot_ms + GROUP_MS - 1) // GROUP_MS * GROUP_MS
        return t0, mgr._state["alerts"].last_step

    def test_pending_to_firing_with_for_hysteresis(self):
        ms, svc, sink, mgr = self.make()
        tr0 = mgr_mod.alerts_transitions.value
        t0, wm = self.hot_after_cold(ms, sink, mgr)
        snap = mgr.alerts_snapshot()
        assert len(snap) == 1
        a = snap[0]
        assert a["state"] == "firing"
        assert a["activeAt"] == t0 / 1000.0
        assert a["labels"]["alertname"] == "TempHigh"
        assert a["annotations"] == {"summary": "too hot"}

        # synthetic series: pending exactly until for: elapses, firing on
        pend = svc.query_range('ALERTS{alertstate="pending"}',
                               t0 // 1000, 60, wm // 1000)
        fire = svc.query_range('ALERTS{alertstate="firing"}',
                               t0 // 1000, 60, wm // 1000)
        pv = np.asarray(pend.result.values)[0]
        fv = np.asarray(fire.result.values)[0]
        # pending at t0 and t0+60; firing from t0+120 (for: 2m)
        assert not math.isnan(pv[0]) and not math.isnan(pv[1])
        assert math.isnan(fv[0]) and math.isnan(fv[1])
        assert not np.isnan(fv[2:]).any()
        # ALERTS_FOR_STATE carries seconds-active at each step — small
        # integers, float32-exact (an epoch timestamp would not be)
        fs = svc.query_range('ALERTS_FOR_STATE{alertname="TempHigh"}',
                             t0 // 1000, 60, wm // 1000)
        fsv = np.asarray(fs.result.values)[0]
        want = np.arange(0, (wm - t0) // 1000 + 1, 60, dtype=float)
        assert np.array_equal(fsv, want)
        # transitions: inactive→pending and pending→firing at least
        assert mgr_mod.alerts_transitions.value >= tr0 + 2
        assert mgr_mod.alerts_firing.value >= 1

    def test_recovery_resumes_firing_state(self):
        ms, svc, sink, mgr = self.make()
        t0, wm = self.hot_after_cold(ms, sink, mgr)
        orig = mgr._state["alerts"].alert_states["TempHigh"]
        assert orig, "precondition: alert active"

        mgr2 = RuleManager(svc, sink, [mgr.groups[0]], ooo_allowance_ms=0)
        assert mgr2.tick() == 0     # nothing re-evaluated
        rec = mgr2._state["alerts"].alert_states["TempHigh"]
        assert set(rec) == set(orig)
        for k in orig:
            assert rec[k].active_since_ms == orig[k].active_since_ms
            assert rec[k].active_since_ms == t0
            assert rec[k].firing and orig[k].firing

    def test_transitions_counted_only_on_commit(self):
        # a failed group write discards the staged alert states and the
        # same window is re-evaluated next tick; the transitions counter
        # must not count the discarded stage (unlike samples, a counter
        # bump cannot be deduplicated on retry)
        ms, svc, sink, mgr = self.make(for_ms=0)
        ingest_temp(ms, sink, [(i, 0.0) for i in range(30)])
        mgr.tick()
        ingest_temp(ms, sink, [(i, 1.0) for i in range(30, 90)])
        tr0 = mgr_mod.alerts_transitions.value
        try:
            FaultInjector.arm("rules.write", error=ConnectionError,
                              times=1)
            assert mgr.tick() == 0
            assert mgr_mod.alerts_transitions.value == tr0
        finally:
            FaultInjector.reset()
        drain(mgr)
        # exactly one inactive→pending and one pending→firing (for: 0
        # fires within the activation step), counted once despite the
        # earlier discarded evaluation of the same window
        assert mgr_mod.alerts_transitions.value == tr0 + 2

    def test_recovery_scoped_to_group(self):
        # two groups carry an equally-named alert; only one fires. The
        # restarted manager must recover each group's state from ITS OWN
        # for-state series (the _group_ stamp), not resurrect the other
        # group's instance under different for:/expr semantics
        ms = TimeSeriesMemStore()
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=100,
                                              groups_per_shard=4))
        svc = make_svc(ms, num_shards=1)
        sink = MemstoreSink(ms, "timeseries", 1, spread=0)

        def grp(name, expr):
            return RuleGroup(
                name=name, interval_ms=GROUP_MS, dataset="timeseries",
                rules=(AlertingRule(alert="TempHigh", expr=expr,
                                    for_ms=0),))

        groups = [grp("hot", "avg(temp) > 0.5"),
                  grp("cold", "avg(temp) > 2")]
        mgr = RuleManager(svc, sink, groups, ooo_allowance_ms=0)
        ingest_temp(ms, sink, [(i, 1.0) for i in range(120)])
        drain(mgr)
        assert mgr._state["hot"].alert_states["TempHigh"]
        assert not mgr._state["cold"].alert_states.get("TempHigh")

        mgr2 = RuleManager(svc, sink, groups, ooo_allowance_ms=0)
        assert mgr2.tick() == 0
        assert (set(mgr2._state["hot"].alert_states["TempHigh"])
                == set(mgr._state["hot"].alert_states["TempHigh"]))
        assert not mgr2._state["cold"].alert_states.get("TempHigh")

    def test_alert_deactivates_when_condition_clears(self):
        ms, svc, sink, mgr = self.make(for_ms=0)
        # cold → hot 5min → cold again, phased so the manager actually
        # evaluates through the whole episode
        ingest_temp(ms, sink, [(i, 0.0) for i in range(30)])
        mgr.tick()
        ingest_temp(ms, sink, [(i, 1.0) for i in range(30, 60)])
        drain(mgr)
        assert mgr.alerts_snapshot(), "precondition: firing during episode"
        ingest_temp(ms, sink, [(i, 0.0) for i in range(60, 120)])
        drain(mgr)
        assert mgr.alerts_snapshot() == []      # back to inactive
        # but the firing episode is durably recorded
        wm = mgr._state["alerts"].last_step
        res = svc.query_range('ALERTS{alertstate="firing"}',
                              FIRST_STEP // 1000, 60, wm // 1000)
        assert res.result.num_series == 1
        assert not np.isnan(np.asarray(res.result.values)).all()


class TestRestartRecovery:
    def test_no_double_write_no_gap(self):
        ms, keys = build_store(30)
        svc = make_svc(ms)
        mgr = make_manager(svc, ms, [rec_group()])
        mgr.tick()
        extend(ms, keys, 180)
        drain(mgr)
        wm = mgr._state["heap"].last_step

        def recorded_cells():
            r = svc.query_range("ns:heap:avg", FIRST_STEP // 1000, 60,
                                wm // 1000)
            return int((~np.isnan(np.asarray(r.result.values))).sum())

        cells = recorded_cells()
        assert cells > 0
        mgr2 = make_manager(svc, ms, [rec_group()])
        assert mgr2.tick() == 0
        assert mgr2._state["heap"].last_step == wm
        assert recorded_cells() == cells        # no double-write

        # each recorded step holds EXACTLY one stored sample per series
        wm_lo = wm - 4 * GROUP_MS
        r = svc.query_range("count_over_time(ns:heap:avg[60s])",
                            wm_lo // 1000, 60, wm // 1000)
        vals = np.asarray(r.result.values)
        assert vals.size and np.all(vals[~np.isnan(vals)] == 1.0)


class TestChaos:
    @pytest.fixture(autouse=True)
    def _clean(self):
        # runtime lock-order checker on for the whole chaos matrix: the
        # fault-injected retry paths must never block under a manager
        # lock or acquire locks in conflicting orders
        FaultInjector.reset()
        with lockcheck.session():
            # race sanitizer beside it: every RuleManager built in the
            # matrix registers its group states, and a commit that no
            # common lock guards across tick/recovery/API threads fails
            # the test at teardown
            with racecheck.session():
                yield
                rvs = racecheck.violations()
            vs = lockcheck.violations()
        FaultInjector.reset()
        assert rvs == [], [v.render() for v in rvs]
        assert vs == [], [v.render() for v in vs]

    def two_rule_group(self):
        return RuleGroup(
            name="pair", interval_ms=GROUP_MS, dataset="timeseries",
            rules=(RecordingRule(record="ns:a",
                                 expr="avg_over_time(heap_usage[3m])"),
                   RecordingRule(record="ns:b",
                                 expr="max_over_time(heap_usage[3m])")))

    def test_kill_at_eval_holds_watermark(self):
        ms, keys = build_store(30)
        svc = make_svc(ms)
        mgr = make_manager(svc, ms, [rec_group()])
        mgr.tick()
        wm = mgr._state["heap"].last_step
        extend(ms, keys, 90)
        f0 = mgr_mod.rules_eval_failures.value
        FaultInjector.arm("rules.eval", error=ConnectionError, times=1)
        assert mgr.tick() == 0
        assert mgr_mod.rules_eval_failures.value == f0 + 1
        assert mgr._state["heap"].last_step == wm   # unmoved
        # fault exhausted: the SAME window is retried — no skipped extent
        assert mgr.tick() > 0
        assert mgr._state["heap"].last_step > wm

    def test_kill_mid_group_write_then_retry_dedups(self):
        # fault on the SECOND rule's write: rule a's outputs land, the
        # watermark does not — the retry must re-write a (deduplicated)
        # and complete b with no gap and no double-write
        ms, keys = build_store(30)
        svc = make_svc(ms)
        mgr = make_manager(svc, ms, [self.two_rule_group()])
        mgr.tick()
        wm = mgr._state["pair"].last_step
        extend(ms, keys, 90)
        FaultInjector.arm("rules.write", error=ConnectionError,
                          match=lambda ctx: ctx.get("rule") == "ns:b")
        assert mgr.tick() == 0
        assert mgr._state["pair"].last_step == wm
        FaultInjector.reset()
        drain(mgr)
        wm2 = mgr._state["pair"].last_step
        assert wm2 > wm

        control = QueryService(ms, "timeseries", NUM_SHARDS, spread=1)
        for rec, expr in (("ns:a", "avg_over_time(heap_usage[3m])"),
                          ("ns:b", "max_over_time(heap_usage[3m])")):
            assert_rows_equivalent(
                control.query_range(expr, (wm + GROUP_MS) // 1000, 60,
                                    wm2 // 1000),
                control.query_range(rec, (wm + GROUP_MS) // 1000, 60,
                                    wm2 // 1000))
            # exactly one stored sample per step per series: the retried
            # re-write of rule a was absorbed by out-of-order dedup
            c = control.query_range(f"count_over_time({rec}[60s])",
                                    (wm + GROUP_MS) // 1000, 60,
                                    wm2 // 1000)
            vals = np.asarray(c.result.values)
            assert vals.size and np.all(vals[~np.isnan(vals)] == 1.0), rec

    def test_kill_between_outputs_and_commit_record(self):
        # crash after every rule output landed but before the watermark
        # marker: restart recovers the OLD watermark and re-evaluates the
        # window; dedup absorbs the duplicate outputs — no double-write
        ms, keys = build_store(30)
        svc = make_svc(ms)
        mgr = make_manager(svc, ms, [rec_group()])
        mgr.tick()
        extend(ms, keys, 90)
        wm = mgr._state["heap"].last_step

        orig_write = mgr.sink.write
        fired = {"n": 0}

        def flaky_write(cont):
            names = {r.part_key.label_map.get("_metric_")
                     for r in cont.records}
            if "FILODB_RULES_WATERMARK" in names and not fired["n"]:
                fired["n"] = 1
                raise ConnectionError("crash before commit record")
            return orig_write(cont)

        mgr.sink.write = flaky_write
        assert mgr.tick() == 0                   # failed after outputs
        assert mgr._state["heap"].last_step == wm
        # restart from durable state only
        mgr2 = make_manager(svc, ms, [rec_group()])
        assert drain(mgr2) > 0
        wm2 = mgr2._state["heap"].last_step
        control = QueryService(ms, "timeseries", NUM_SHARDS, spread=1)
        c = control.query_range("count_over_time(ns:heap:avg[60s])",
                                (wm + GROUP_MS) // 1000, 60, wm2 // 1000)
        vals = np.asarray(c.result.values)
        assert vals.size and np.all(vals[~np.isnan(vals)] == 1.0)
        assert not np.isnan(vals).any()          # and no gap


class TestGovernorIntegration:
    @pytest.fixture(autouse=True)
    def _clean(self):
        gov.reset()
        yield
        gov.reset()

    def test_shed_under_pressure_then_catchup_no_gap(self):
        ms, keys = build_store(30)
        svc = make_svc(ms)
        mgr = make_manager(svc, ms, [rec_group()])
        mgr.tick()
        wm = mgr._state["heap"].last_step
        extend(ms, keys, 90)
        gov.governor().set_state(gov.DEGRADED)
        s0 = mgr_mod.rules_evals_shed.value
        assert mgr.tick() == 0
        assert mgr_mod.rules_evals_shed.value == s0 + 1
        assert mgr._state["heap"].last_step == wm   # unmoved
        assert "shed" in mgr._state["heap"].last_error
        gov.governor().set_state(gov.OK)
        drain(mgr)
        wm2 = mgr._state["heap"].last_step
        assert wm2 > wm
        # every step between the shed point and now was evaluated
        r = svc.query_range("count_over_time(ns:heap:avg[60s])",
                            (wm + GROUP_MS) // 1000, 60, wm2 // 1000)
        vals = np.asarray(r.result.values)
        assert vals.size and not np.isnan(vals).any()

    def test_rules_cost_class_never_queues(self):
        g = gov.ResourceGovernor(gov.GovernorConfig(rules_max_inflight=1))
        with g.admit(cost=gov.RULES):
            with pytest.raises(gov.QueryRejected) as ei:
                with g.admit(cost=gov.RULES):
                    pass
            assert ei.value.reason == "rules"
            # interactive queries are unaffected by the rules cap
            with g.admit(cost=gov.EXPENSIVE):
                pass
        with g.admit(cost=gov.RULES):
            pass

    def test_rules_shed_when_capacity_contended(self):
        g = gov.ResourceGovernor(gov.GovernorConfig(admission_capacity=1))
        with g.admit(cost=gov.EXPENSIVE):
            # a rule evaluation never waits behind interactive load
            with pytest.raises(gov.QueryRejected) as ei:
                with g.admit(cost=gov.RULES):
                    pass
            assert ei.value.reason == "rules"


class TestTenantQuota:
    @pytest.fixture(autouse=True)
    def _clean(self):
        gov.reset()
        yield
        gov.reset()

    def test_rule_outputs_respect_cardinality_quota(self):
        from filodb_tpu.utils.metrics import get_counter
        # quota must be configured BEFORE shard construction (quotas are
        # applied to the tracker at setup)
        gov.configure(tenants={"demo/App-0": {"max_series": 10}})
        ms = TimeSeriesMemStore()
        shard = ms.setup("timeseries", 0, StoreConfig(max_chunk_size=100,
                                                      groups_per_shard=4))
        keys = machine_metrics_series(8, ns="App-0")
        ingest_routed(ms, "timeseries",
                      gauge_stream(keys, 60, start_ms=START * 1000,
                                   interval_ms=INTERVAL, seed=11),
                      1, spread=0)
        svc = make_svc(ms, num_shards=1)
        mgr = make_manager(svc, ms, [rec_group()], num_shards=1)
        d0 = shard.stats.quota_dropped.value
        drain(mgr)
        # 8 source series fit the quota of 10; the rule's 8 outputs do
        # not — the overflow is dropped and accounted to the tenant
        assert shard.stats.quota_dropped.value > d0
        assert get_counter("filodb_tenant_ingest_dropped",
                           {"tenant": "demo/App-0"}).value > 0
        assert shard.cardinality.cardinality(
            ["demo", "App-0"]).active_ts == 10


class TestResponseCacheIntegration:
    def test_rule_writes_bump_service_version(self):
        # regression (satellite): internal rule-output writes must bump
        # the data_version the HTTP response cache keys on, so a cached
        # pre-rule-write response can never be served afterwards
        from filodb_tpu.http.server import service_version
        ms, keys = build_store(60)
        svc = make_svc(ms)
        v0 = service_version(svc)
        mgr = make_manager(svc, ms, [rec_group()])
        assert drain(mgr) > 0
        assert service_version(svc) > v0

    def test_serial_zero_is_not_id_fallback(self):
        from filodb_tpu.http.server import response_cache_key

        class Svc:
            serial = 0

        key = response_cache_key(Svc(), "range", ("q", 1, 2, 3))
        assert key[0] == 0          # serial 0 is legitimate, not falsy


class TestStandaloneE2E:
    """Boot the full server with a rules: config block: evaluation rides
    the WAL (LogSink), outputs become first-class queryable series, and
    the Prom-compat endpoints + CLI surface the state."""

    @pytest.fixture
    def server(self, tmp_path):
        import json as _json
        import socket as _socket

        from filodb_tpu.config import ServerConfig
        from filodb_tpu.standalone import FiloServer
        cfg_path = tmp_path / "server.json"
        cfg_path.write_text(_json.dumps({
            "node_name": "rules-node",
            "data_dir": str(tmp_path / "data"),
            "http_port": 0,
            "gateway_port": 0,
            "rules": {
                "tick_s": 0.2,
                "groups": [{
                    "name": "std", "interval": "60s",
                    "rules": [
                        {"record": "job:scrape:sum",
                         "expr": "sum(scrape_metric)"},
                        {"alert": "ScrapeAlive",
                         "expr": "avg(scrape_metric) > -1",
                         "annotations": {"summary": "scrape data flows"}},
                    ]}]},
            "datasets": {"timeseries": {
                "num_shards": 2, "spread": 1,
                "store": {"max_chunk_size": 50, "groups_per_shard": 2}}},
        }))
        cfg = ServerConfig.load(str(cfg_path))
        with _socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            object.__setattr__(cfg, "gateway_port", s.getsockname()[1])
        srv = FiloServer(cfg).start()
        yield srv
        srv.shutdown()

    def _get(self, port, path):
        import json as _json
        import urllib.request
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}") as r:
            assert r.status == 200
            return _json.load(r)

    def test_rules_evaluate_and_surface_over_http(self, server, capsys):
        import socket as _socket
        import time as _time
        srv = server
        with _socket.create_connection(("127.0.0.1",
                                        srv.gateway.port)) as s:
            for i in range(150):
                ts_ns = (START + i * 10) * 1_000_000_000
                s.sendall(f"scrape_metric,host=h{i % 5},_ws_=demo,"
                          f"_ns_=App-0 value={i} {ts_ns}\n".encode())
        # rules use the default 300s ooo allowance here, so the horizon
        # trails max ts by 5min — still leaves ~19 complete steps
        deadline = _time.monotonic() + 30
        doc = None
        while _time.monotonic() < deadline:
            srv.gateway.sink.flush()
            doc = self._get(srv.http.port, "/api/v1/rules")
            groups = doc["data"]["groups"]
            if groups and groups[0]["watermark"]:
                break
            _time.sleep(0.3)
        assert doc["status"] == "success"
        g = doc["data"]["groups"][0]
        assert g["name"] == "std" and g["watermark"], doc
        kinds = {r["name"]: r["type"] for r in g["rules"]}
        assert kinds == {"job:scrape:sum": "recording",
                        "ScrapeAlive": "alerting"}
        assert all(r["health"] == "ok" for r in g["rules"])

        # the per-dataset Prom route serves the same groups
        ds = self._get(srv.http.port, "/promql/timeseries/api/v1/rules")
        assert ds["data"]["groups"][0]["name"] == "std"

        # recorded output is a first-class queryable series over HTTP
        wm = g["watermark"] // 1000
        deadline = _time.monotonic() + 15
        result = []
        while _time.monotonic() < deadline:
            q = self._get(
                srv.http.port,
                f"/promql/timeseries/api/v1/query_range?"
                f"query=job:scrape:sum&start={wm - 300}&end={wm}&step=60")
            result = q["data"]["result"]
            if result and result[0]["values"]:  # NaN cells are elided
                break
            _time.sleep(0.3)
        assert result, "recorded series never became queryable"

        # the always-true alert fires (for: 0 → immediately)
        alerts = self._get(srv.http.port, "/api/v1/alerts")["data"]["alerts"]
        assert [a for a in alerts if a["state"] == "firing"
                and a["labels"]["alertname"] == "ScrapeAlive"], alerts
        assert alerts[0]["annotations"] == {"summary": "scrape data flows"}

        # rules metrics made it to the exposition
        import urllib.request
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.http.port}/metrics") as r:
            text = r.read().decode()
        assert "filodb_rules_evals_total" in text
        assert "filodb_alerts_firing" in text

        # operator CLI renders groups + active alerts from the same API
        from filodb_tpu.cli import main as cli_main
        cli_main(["--host", f"127.0.0.1:{srv.http.port}", "rules"])
        out = capsys.readouterr().out
        assert "group std" in out
        assert "job:scrape:sum" in out
        assert "ScrapeAlive" in out and "firing" in out

    def test_threaded_front_accepts_rule_managers(self):
        # both HTTP fronts share the dispatcher; this smoke proves the
        # threaded ctor accepts the wiring and serves the empty payloads
        from filodb_tpu.http.server import FiloHttpServer
        srv = FiloHttpServer({}, port=0, rule_managers={}).start()
        try:
            doc = self._get(srv.port, "/api/v1/rules")
            assert doc == {"status": "success", "data": {"groups": []}}
            doc = self._get(srv.port, "/api/v1/alerts")
            assert doc == {"status": "success", "data": {"alerts": []}}
        finally:
            srv.stop()


class TestModelValidation:
    def test_load_groups_happy_path(self):
        groups = load_groups({"groups": [
            {"name": "g1", "interval": "2m", "rules": [
                {"record": "job:x:avg", "expr": "avg(x)",
                 "labels": {"team": "core"}},
                {"alert": "XHigh", "expr": "avg(x) > 1", "for": "5m",
                 "annotations": {"summary": "x too high"}},
            ]}]}, "timeseries")
        assert len(groups) == 1
        g = groups[0]
        assert g.interval_ms == 120_000 and g.dataset == "timeseries"
        rec, al = g.rules
        assert isinstance(rec, RecordingRule)
        assert dict(rec.labels) == {"team": "core"}
        assert isinstance(al, AlertingRule) and al.for_ms == 300_000

    @pytest.mark.parametrize("block", [
        {"groups": [{"name": "g", "rules": [
            {"expr": "x"}]}]},                       # neither record/alert
        {"groups": [{"name": "g", "rules": [
            {"record": "a", "alert": "b", "expr": "x"}]}]},  # both
        {"groups": [{"name": "g", "rules": [
            {"record": "1bad", "expr": "x"}]}]},     # invalid name
        {"groups": [{"name": "g", "rules": [
            {"record": "a::b", "expr": "x"}]}]},     # reserved ::
        {"groups": [{"name": "g", "rules": [
            {"record": "ALERTS", "expr": "x"}]}]},   # reserved name
        {"groups": [{"name": "g", "rules": [
            {"record": "a", "expr": "x", "for": "5m"}]}]},  # for on record
        {"groups": [{"name": "g", "rules": [
            {"alert": "A", "expr": "x",
             "labels": {"alertstate": "no"}}]}]},    # reserved label
        {"groups": [{"name": "g", "rules": [
            {"alert": "A", "expr": "x",
             "labels": {"_group_": "no"}}]}]},       # reserved scope stamp
        {"groups": [{"name": 'g"x', "rules": []}]},  # lexer-breaking group
        {"groups": [{"name": "g", "rules": [
            {"alert": 'A{bad="l"}', "expr": "x"}]}]},  # lexer-breaking alert
        {"groups": [{"name": "g", "interval": "500ms", "rules": []}]},
        {"groups": [{"name": "g", "rules": []},
                    {"name": "g", "rules": []}]},    # duplicate group
        {"groups": [{"name": "g", "rules": [
            {"record": "a", "expr": "x"},
            {"record": "a", "expr": "y"}]}]},        # duplicate rule
    ])
    def test_load_groups_rejects(self, block):
        with pytest.raises(ValueError):
            load_groups(block, "timeseries")
