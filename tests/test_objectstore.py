"""Object-store durable tier tests: segment format, write-behind upload,
manifest recovery, compaction, CRC tripwires, retries under injected faults,
and key-prefix split scans (the token-range analog).

Counterpart of the Cassandra tier specs (reference
``cassandra/src/test/scala/filodb.cassandra/columnstore/
CassandraColumnStoreSpec.scala``) plus the ``getScanSplits`` parallel-scan
contract (``CassandraColumnStore.scala:52``).
"""

import json
import threading

import numpy as np
import pytest

from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.store.api import PartKeyRecord
from filodb_tpu.core.store.localstore import _pk_blob
from filodb_tpu.core.store.objectstore import (
    CorruptSegmentError,
    ObjectStoreColumnStore,
    ObjectStoreError,
    ObjectStoreMetaStore,
    _canon_query,
    crc32c,
    open_object_store,
    parse_segment,
)
from filodb_tpu.core.store.remotestore import split_of
from filodb_tpu.memory.chunk import Chunk
from filodb_tpu.testing.fake_s3 import FakeS3, S3TransientError

DS = "timeseries"


def _pk(i: int) -> PartKey:
    return PartKey.create("gauge", {"_metric_": "heap_usage",
                                    "_ws_": "demo", "_ns_": f"app-{i}"})


def _chunk(cid: int, n: int = 10, t0: int = 1000) -> Chunk:
    ts = np.arange(t0, t0 + n * 1000, 1000, dtype=np.int64)
    vals = np.arange(n, dtype=np.float64) + cid
    return Chunk(cid, n, int(ts[0]), int(ts[-1]),
                 [ts.tobytes(), vals.tobytes()])


def _mk(client=None, **kw) -> ObjectStoreColumnStore:
    return ObjectStoreColumnStore(client or FakeS3(), **kw)


class TestCrc32c:
    def test_reference_vector(self):
        # RFC 3720 Castagnoli check value
        assert crc32c(b"123456789") == 0xE3069283

    def test_incremental(self):
        assert crc32c(b"6789", crc32c(b"12345")) == crc32c(b"123456789")


class TestFakeS3:
    def test_put_get_range_list_delete(self):
        s3 = FakeS3()
        s3.put_object("a/b", b"hello world")
        assert s3.get_object("a/b") == b"hello world"
        assert s3.get_object("a/b", start=6, length=5) == b"world"
        s3.put_object("a/c", b"x")
        assert s3.list_objects("a/") == ["a/b", "a/c"]
        s3.delete_object("a/b")
        assert s3.list_objects("a/") == ["a/c"]
        with pytest.raises(KeyError):
            s3.get_object("a/b")

    def test_dir_backed_persists(self, tmp_path):
        FakeS3(root=str(tmp_path)).put_object("k", b"v")
        assert FakeS3(root=str(tmp_path)).get_object("k") == b"v"

    def test_fault_injection(self):
        s3 = FakeS3()
        s3.inject("put", times=2, exc=S3TransientError("boom"))
        with pytest.raises(S3TransientError):
            s3.put_object("k", b"v")
        with pytest.raises(S3TransientError):
            s3.put_object("k", b"v")
        s3.put_object("k", b"v")  # third attempt succeeds
        assert s3.get_object("k") == b"v"


class TestSegmentFormat:
    def test_roundtrip_and_manifest(self):
        cs = _mk()
        pk = _pk(0)
        chunks = [_chunk(1), _chunk(2, t0=20_000)]
        cs.write_chunks(DS, 0, pk, chunks, ingestion_time=111)
        cs.write_part_keys(DS, 0, [PartKeyRecord(pk, 1000, 29_000)])
        cs.flush()
        back = cs.read_chunks(DS, 0, pk, 0, 2**62)
        assert [c.id for c in back] == [1, 2]
        # byte-exact payload roundtrip (test chunks carry raw vectors)
        assert list(back[0].vectors) == list(chunks[0].vectors)
        np.testing.assert_array_equal(
            np.frombuffer(back[0].vectors[1], np.float64),
            np.arange(10.0) + 1)
        man = json.loads(
            cs.client.get_object(f"filodb/{DS}/shard-0/manifest.json"))
        assert len(man["segments"]) >= 1
        seg_key = man["segments"][0]["key"]
        entries = parse_segment(cs.client.get_object(seg_key), seg_key)
        assert any(e[0] == "chunk" for e in entries)
        cs.close()

    def test_idempotent_rewrite_dedups(self):
        cs = _mk()
        pk = _pk(0)
        cs.write_chunks(DS, 0, pk, [_chunk(1)], ingestion_time=1)
        cs.write_chunks(DS, 0, pk, [_chunk(1)], ingestion_time=1)
        cs.flush()
        assert len(cs.read_chunks(DS, 0, pk, 0, 2**62)) == 1
        cs.close()

    def test_cold_recovery(self, tmp_path):
        s3root = str(tmp_path / "s3")
        cs = _mk(FakeS3(root=s3root))
        meta = ObjectStoreMetaStore(cs)
        pks = [_pk(i) for i in range(5)]
        for i, pk in enumerate(pks):
            cs.write_chunks(DS, 0, pk, [_chunk(i + 1)], ingestion_time=i)
        cs.write_part_keys(DS, 0, [PartKeyRecord(pk, 1000, 10_000)
                                   for pk in pks])
        meta.write_checkpoint(DS, 0, 0, 42)
        cs.close()

        cs2 = _mk(FakeS3(root=s3root))
        meta2 = ObjectStoreMetaStore(cs2)
        assert {r.part_key for r in cs2.scan_part_keys(DS, 0)} == set(pks)
        for i, pk in enumerate(pks):
            back = cs2.read_chunks(DS, 0, pk, 0, 2**62)
            assert [c.id for c in back] == [i + 1]
        assert meta2.read_checkpoints(DS, 0) == {0: 42}
        scanned = dict(cs2.scan_chunks_by_ingestion_time(DS, 0, 0, 3))
        assert set(scanned) == set(pks[:3])
        cs2.close()

    def test_delete_tombstone_durable(self, tmp_path):
        s3root = str(tmp_path / "s3")
        cs = _mk(FakeS3(root=s3root))
        pk0, pk1 = _pk(0), _pk(1)
        for pk in (pk0, pk1):
            cs.write_chunks(DS, 0, pk, [_chunk(1)], ingestion_time=1)
        cs.write_part_keys(DS, 0, [PartKeyRecord(pk0, 0, 1),
                                   PartKeyRecord(pk1, 0, 1)])
        cs.delete_part_keys(DS, 0, [pk0])
        cs.close()
        cs2 = _mk(FakeS3(root=s3root))
        assert [r.part_key for r in cs2.scan_part_keys(DS, 0)] == [pk1]
        assert cs2.read_chunks(DS, 0, pk0, 0, 2**62) == []
        cs2.close()

    def test_index_snapshot_roundtrip(self, tmp_path):
        s3root = str(tmp_path / "s3")
        cs = _mk(FakeS3(root=s3root))
        cs.write_index_snapshot(DS, 0, b"snapshot-bytes")
        cs.close()
        cs2 = _mk(FakeS3(root=s3root))
        assert cs2.read_index_snapshot(DS, 0) == b"snapshot-bytes"
        assert cs2.read_index_snapshot(DS, 1) is None
        cs2.close()


class TestWriteBehind:
    def test_checkpoint_never_ahead_of_data(self):
        """A checkpoint object must not become visible remotely before the
        segments it covers — otherwise a crash loses an acked flush."""
        s3 = FakeS3()
        order = []
        real_put = s3.put_object

        def spy_put(key, data):
            order.append(key)
            real_put(key, data)
        s3.put_object = spy_put
        cs = _mk(s3)
        meta = ObjectStoreMetaStore(cs)
        pk = _pk(0)
        cs.write_chunks(DS, 0, pk, [_chunk(1)], ingestion_time=1)
        meta.write_checkpoint(DS, 0, 0, 99)
        cs.flush()
        seg_idx = [i for i, k in enumerate(order) if k.endswith(".seg")]
        ckpt_idx = [i for i, k in enumerate(order)
                    if k.endswith("checkpoints.json")]
        assert seg_idx and ckpt_idx
        assert max(seg_idx) < min(ckpt_idx)
        cs.close()

    def test_upload_retries_never_lose_acked_flush(self, tmp_path):
        s3 = FakeS3(root=str(tmp_path / "s3"))
        s3.inject("put", times=3, exc=S3TransientError("503"))
        cs = _mk(s3, retry_policy=None)
        pk = _pk(0)
        cs.write_chunks(DS, 0, pk, [_chunk(1)], ingestion_time=1)
        cs.write_part_keys(DS, 0, [PartKeyRecord(pk, 1000, 10_000)])
        cs.flush()   # drains despite 3 injected faults
        assert cs.upload_errors() == []
        cs.close()
        from filodb_tpu.core.store.objectstore import RETRIES
        assert RETRIES.value >= 3
        cs2 = _mk(FakeS3(root=str(tmp_path / "s3")))
        assert len(cs2.read_chunks(DS, 0, pk, 0, 2**62)) == 1
        cs2.close()

    def test_fatal_upload_failure_parks_checkpoint_and_flush_raises(
            self, tmp_path):
        """A non-transient segment upload failure (S3 403/400 analog)
        must not let the checkpoint FIFO-queued behind it become visible
        remotely, and flush() must surface the loss instead of acking
        it — otherwise crash recovery trusts the checkpoint and the
        acked flush is silently lost."""
        s3 = FakeS3(root=str(tmp_path / "s3"))
        s3.inject("put", times=1, exc=ObjectStoreError("403 AccessDenied"))
        cs = _mk(s3)
        meta = ObjectStoreMetaStore(cs)
        pk = _pk(0)
        cs.write_chunks(DS, 0, pk, [_chunk(1)], ingestion_time=1)
        meta.write_checkpoint(DS, 0, 0, 99)
        with pytest.raises(ObjectStoreError):
            cs.flush()
        assert cs.upload_errors()
        # neither the segment nor the checkpoint behind it landed
        keys = s3.list_objects("")
        assert not any(k.endswith(".seg") for k in keys)
        assert not any(k.endswith("checkpoints.json") for k in keys)
        with pytest.raises(ObjectStoreError):
            cs.close()
        # recovery sees the pre-failure remote state: no checkpoint to
        # trust, so WAL replay re-covers the whole gap
        cs2 = _mk(FakeS3(root=str(tmp_path / "s3")))
        assert ObjectStoreMetaStore(cs2).read_checkpoints(DS, 0) == {}
        assert cs2.read_chunks(DS, 0, pk, 0, 2**62) == []
        cs2.close()

    def test_fatal_failure_in_one_shard_spares_others(self):
        s3 = FakeS3()
        cs = _mk(s3)
        meta = ObjectStoreMetaStore(cs)
        pk = _pk(0)
        cs.write_chunks(DS, 0, pk, [_chunk(1)], ingestion_time=1)
        s3.inject("put", times=1, exc=ObjectStoreError("403"))
        meta.write_checkpoint(DS, 0, 0, 7)    # shard 0 segment put fails
        cs.write_chunks(DS, 1, pk, [_chunk(1)], ingestion_time=1)
        meta.write_checkpoint(DS, 1, 0, 8)    # shard 1 is unaffected
        with pytest.raises(ObjectStoreError):
            cs.flush()
        keys = s3.list_objects("")
        assert any("shard-1" in k and k.endswith("checkpoints.json")
                   for k in keys)
        assert not any("shard-0" in k and k.endswith("checkpoints.json")
                       for k in keys)

    def test_read_your_writes_before_upload(self):
        """Pending/open segments serve reads from memory — no GETs."""
        s3 = FakeS3(latency_s=0)
        cs = _mk(s3)
        pk = _pk(0)
        cs.write_chunks(DS, 0, pk, [_chunk(1)], ingestion_time=1)
        gets_before = s3.op_counts.get("get", 0)
        assert len(cs.read_chunks(DS, 0, pk, 0, 2**62)) == 1
        assert s3.op_counts.get("get", 0) == gets_before
        cs.close()

    def test_multipart_for_large_segments(self):
        s3 = FakeS3()
        cs = _mk(s3, segment_target_bytes=1 << 20,
                 multipart_threshold=64 * 1024)
        pk = _pk(0)
        big = [_chunk(i + 1, n=4000, t0=i * 10_000_000) for i in range(4)]
        cs.write_chunks(DS, 0, pk, big, ingestion_time=1)
        cs.flush()
        assert s3.op_counts.get("multipart", 0) >= 3  # create+parts+complete
        back = cs.read_chunks(DS, 0, pk, 0, 2**62)
        assert [c.id for c in back] == [1, 2, 3, 4]
        cs.close()


class TestIntegrityTripwire:
    def test_flipped_byte_raises_never_wrong_results(self):
        from filodb_tpu.core.store.objectstore import CORRUPT
        s3 = FakeS3()
        cs = _mk(s3)
        pk = _pk(0)
        cs.write_chunks(DS, 0, pk, [_chunk(1)], ingestion_time=1)
        cs.flush()
        seg_key = next(k for k in s3.list_objects("") if k.endswith(".seg"))
        # flip a payload byte (past the entry header region)
        s3.corrupt(seg_key, offset=len(s3.get_object(seg_key)) // 2)
        before = CORRUPT.value
        # drop in-memory buffers so the read goes to the object
        cs2 = _mk(s3)
        with pytest.raises(CorruptSegmentError):
            cs2.read_chunks(DS, 0, pk, 0, 2**62)
        assert CORRUPT.value > before
        cs2.close()
        cs.close()

    def test_corrupt_segment_fails_recovery_scan(self, tmp_path):
        s3 = FakeS3(root=str(tmp_path / "s3"))
        cs = _mk(s3)
        cs.write_chunks(DS, 0, _pk(0), [_chunk(1)], ingestion_time=1)
        cs.close()
        seg_key = next(k for k in s3.list_objects("") if k.endswith(".seg"))
        s3.corrupt(seg_key, offset=10)
        cs2 = _mk(FakeS3(root=str(tmp_path / "s3")))
        with pytest.raises(CorruptSegmentError):
            cs2.scan_part_keys(DS, 0)
        cs2.close()


class TestCompaction:
    def test_small_segments_merge_and_survive_recovery(self, tmp_path):
        from filodb_tpu.core.store.objectstore import COMPACTIONS
        s3 = FakeS3(root=str(tmp_path / "s3"))
        cs = _mk(s3, bucket_count=1, compact_min_segments=4,
                 auto_compact=False)
        pk = _pk(0)
        for i in range(8):  # 8 tiny segments in one bucket
            cs.write_chunks(DS, 0, pk, [_chunk(i + 1)], ingestion_time=i)
            cs.flush()
        segs_before = [k for k in s3.list_objects("") if k.endswith(".seg")]
        assert len(segs_before) == 8
        before = COMPACTIONS.value
        assert cs.compact(DS, 0) >= 1
        cs.flush()
        assert COMPACTIONS.value > before
        segs_after = [k for k in s3.list_objects("") if k.endswith(".seg")]
        assert len(segs_after) < len(segs_before)
        # reads still correct post-compaction, in-process and after restart
        assert [c.id for c in cs.read_chunks(DS, 0, pk, 0, 2**62)] == \
            list(range(1, 9))
        cs.close()
        cs2 = _mk(FakeS3(root=str(tmp_path / "s3")))
        assert [c.id for c in cs2.read_chunks(DS, 0, pk, 0, 2**62)] == \
            list(range(1, 9))
        cs2.close()

    def test_stale_refs_after_compaction_swap_re_resolve(self):
        """Refs snapshotted before a compaction swaps the index must be
        re-resolved against the fresh index, not KeyError on the
        vanished segment seq (read/compaction race)."""
        cs = _mk(bucket_count=1, auto_compact=False)
        pk = _pk(0)
        for i in range(4):
            cs.write_chunks(DS, 0, pk, [_chunk(i + 1)], ingestion_time=i)
            cs.flush()
        st = cs._state(DS, 0)
        with cs._lock:
            stale = sorted(st.chunks[pk].values(), key=lambda r: r.chunk_id)
        assert cs.compact(DS, 0) >= 1   # swaps the index, deletes olds
        payloads = cs._fetch_refs(DS, 0, st, pk, stale)
        assert sorted(payloads) == [1, 2, 3, 4]
        cs.close()

    def test_compaction_drops_tombstoned_entries(self):
        s3 = FakeS3()
        cs = _mk(s3, bucket_count=1, auto_compact=False)
        pk0, pk1 = _pk(0), _pk(1)
        for pk in (pk0, pk1):
            cs.write_chunks(DS, 0, pk, [_chunk(1)], ingestion_time=1)
            cs.flush()
        cs.delete_part_keys(DS, 0, [pk0])
        cs.flush()
        cs.compact(DS, 0)
        cs.flush()
        live = set()
        for k in s3.list_objects(""):
            if k.endswith(".seg"):
                for e in parse_segment(s3.get_object(k), k):
                    live.add(e[1])
        assert _pk_blob(pk0) not in live
        assert _pk_blob(pk1) in live
        cs.close()


class TestSplitScans:
    def _fill(self, cs, n=32):
        pks = [_pk(i) for i in range(n)]
        for i, pk in enumerate(pks):
            cs.write_chunks(DS, 0, pk, [_chunk(1)], ingestion_time=i)
        cs.write_part_keys(DS, 0, [PartKeyRecord(pk, 0, 1) for pk in pks])
        cs.flush()
        return pks

    def test_partition_disjoint_and_complete(self):
        cs = _mk(bucket_count=8)
        pks = self._fill(cs)
        n_splits = 4
        seen = []
        for s in range(n_splits):
            part = cs.scan_part_keys_split(DS, 0, s, n_splits)
            for r in part:
                assert split_of(_pk_blob(r.part_key), n_splits) == s
            seen.extend(r.part_key for r in part)
        assert sorted(map(str, seen)) == sorted(map(str, pks))
        assert len(seen) == len(set(seen))
        # ingestion-time split scan unions to the full scan too
        full = dict(cs.scan_chunks_by_ingestion_time(DS, 0, 0, 2**62))
        union = {}
        for s in range(n_splits):
            union.update(cs.scan_chunks_by_ingestion_time_split(
                DS, 0, 0, 2**62, s, n_splits))
        assert set(union) == set(full)
        cs.close()

    def test_restrict_to_split_skips_foreign_buckets(self, tmp_path):
        """A split-restricted reader must only GET its own bucket prefixes —
        that's what makes fan-out cheap (the token-range analog)."""
        s3 = FakeS3(root=str(tmp_path / "s3"))
        cs = _mk(s3, bucket_count=8)
        self._fill(cs)
        cs.close()

        s3b = FakeS3(root=str(tmp_path / "s3"))
        reader = _mk(s3b, bucket_count=8)
        reader.restrict_to_split(0, 4)
        part = reader.scan_part_keys_split(DS, 0, 0, 4)
        assert part
        # every loaded segment belongs to split-0 buckets
        for info in reader._states[(DS, 0)].segments.values():
            assert info.bucket % 4 == 0
        reader.close()

    def test_split_view_is_read_only(self, tmp_path):
        """A split view's index holds a filtered segment set; any write
        would republish the manifest from it and permanently drop the
        foreign buckets' segments — so every write entry point raises."""
        s3root = str(tmp_path / "s3")
        cs = _mk(FakeS3(root=s3root), bucket_count=8)
        self._fill(cs)
        cs.close()
        reader = _mk(FakeS3(root=s3root), bucket_count=8)
        reader.restrict_to_split(0, 4)
        pk = _pk(0)
        with pytest.raises(ObjectStoreError):
            reader.write_chunks(DS, 0, pk, [_chunk(9)], ingestion_time=9)
        with pytest.raises(ObjectStoreError):
            reader.write_part_keys(DS, 0, [PartKeyRecord(pk, 0, 1)])
        with pytest.raises(ObjectStoreError):
            reader.delete_part_keys(DS, 0, [pk])
        with pytest.raises(ObjectStoreError):
            reader.write_index_snapshot(DS, 0, b"x")
        with pytest.raises(ObjectStoreError):
            reader.truncate(DS)
        with pytest.raises(ObjectStoreError):
            reader.compact(DS, 0)
        with pytest.raises(ObjectStoreError):
            ObjectStoreMetaStore(reader).write_checkpoint(DS, 0, 0, 1)
        # reads still work, and the full store is untouched
        assert reader.scan_part_keys_split(DS, 0, 0, 4)
        reader.close()
        full = _mk(FakeS3(root=s3root), bucket_count=8)
        assert len(full.scan_part_keys(DS, 0)) == 32
        full.close()

    def test_repair_jobs_fan_out_over_splits(self):
        from filodb_tpu.core.store.repair import PartitionKeysCopier
        src, dst = _mk(bucket_count=8), _mk(bucket_count=8)
        pks = self._fill(src)
        copier = PartitionKeysCopier(src, dst, DS, num_shards=1,
                                     n_splits=4)
        copier.run()
        dst.flush()
        assert {str(r.part_key) for r in dst.scan_part_keys(DS, 0)} == \
            {str(pk) for pk in pks}
        src.close()
        dst.close()


class TestConcurrency:
    def test_parallel_writers_one_shard(self):
        cs = _mk()
        pks = [_pk(i) for i in range(8)]

        def w(i):
            for j in range(5):
                cs.write_chunks(DS, 0, pks[i], [_chunk(j + 1)],
                                ingestion_time=j)
        threads = [threading.Thread(target=w, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        cs.flush()
        for pk in pks:
            assert [c.id for c in cs.read_chunks(DS, 0, pk, 0, 2**62)] == \
                [1, 2, 3, 4, 5]
        cs.close()


class TestSigV4:
    def test_canonical_query_sorted_and_slash_encoded(self):
        # AWS SigV4: params sorted by key, '/' in values %2F-encoded —
        # an unsorted or verbatim query signs a different string than
        # the service canonicalizes → SignatureDoesNotMatch
        q = _canon_query({"prefix": "demo/timeseries/shard-0/",
                          "list-type": "2",
                          "continuation-token": "a+b/c"})
        assert q == ("continuation-token=a%2Bb%2Fc&list-type=2"
                     "&prefix=demo%2Ftimeseries%2Fshard-0%2F")
        assert _canon_query({}) == ""
        assert _canon_query(None) == ""

    def test_signed_list_uses_canonical_query(self, monkeypatch):
        from filodb_tpu.core.store.objectstore import HttpS3Client
        client = HttpS3Client("http://s3.local", access_key="AK",
                              secret_key="SK")
        seen = []

        class _Resp:
            def __enter__(self):
                return self

            def __exit__(self, *a):
                return False

            def read(self):
                return (b"<ListBucketResult>"
                        b"<IsTruncated>false</IsTruncated>"
                        b"</ListBucketResult>")

        def fake_urlopen(req, timeout=None):
            seen.append(req)
            return _Resp()

        import urllib.request
        monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
        client.list_objects("bucket/demo/timeseries/")
        (req,) = seen
        # the URL carries the same canonical (sorted, %2F-encoded) query
        # that was signed
        assert req.full_url.endswith(
            "/bucket?list-type=2&prefix=demo%2Ftimeseries%2F")
        assert req.get_header("Authorization", "").startswith(
            "AWS4-HMAC-SHA256")


class TestFactory:
    def test_open_object_store_local_fake(self, tmp_path):
        cs, meta = open_object_store({"endpoint": None}, str(tmp_path))
        assert isinstance(cs, ObjectStoreColumnStore)
        assert isinstance(meta, ObjectStoreMetaStore)
        cs.write_chunks(DS, 0, _pk(0), [_chunk(1)], ingestion_time=1)
        cs.close()
        assert (tmp_path / "objectstore").exists()

    def test_open_object_store_http_endpoint(self, tmp_path):
        from filodb_tpu.core.store.objectstore import HttpS3Client
        cs, meta = open_object_store(
            {"endpoint": "http://127.0.0.1:1", "bucket": "b"},
            str(tmp_path))
        assert isinstance(cs.client, HttpS3Client)
        cs.close()
