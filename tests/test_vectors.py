"""Round-trip + selection tests for the full column vector codec family.

Coverage model: the reference's vector specs
(``memory/src/test/scala/filodb.memory/format/vectors/IntBinaryVectorTest.scala``,
``LongVectorTest.scala``, ``UTF8VectorTest.scala``, ``DoubleVectorTest.scala``,
ConstVector cases in ``NativeVectorTest.scala``) — minimal-nbits int packing,
const collapse, raw-vs-dict UTF8, and multi-column schema chunks.
"""

import numpy as np
import pytest

from filodb_tpu.core.schemas import Column, ColumnType, DataSchema, Schema
from filodb_tpu.memory import codecs
from filodb_tpu.memory.chunk import encode_chunk


class TestPackedInt:
    def test_round_trip_widths(self):
        rng = np.random.default_rng(7)
        for hi in (1, 2, 8, 200, 60_000, 2**31, 2**40):
            v = rng.integers(0, hi, size=137, dtype=np.int64)
            out = codecs.decode_packed_int(codecs.encode_packed_int(v))
            np.testing.assert_array_equal(out, v)

    def test_const_collapses_to_width0(self):
        v = np.full(1000, 123456789, dtype=np.int64)
        enc = codecs.encode_packed_int(v)
        # header only: ConstVector analog
        assert len(enc) == 14
        np.testing.assert_array_equal(codecs.decode_packed_int(enc), v)

    def test_minimal_nbits_selection(self):
        # values 0/1 -> 1 bit per value
        v = (np.arange(800) % 2).astype(np.int64)
        enc = codecs.encode_packed_int(v)
        assert len(enc) <= 14 + 100  # 800 bits = 100 bytes payload
        np.testing.assert_array_equal(codecs.decode_packed_int(enc), v)
        # values 0..15 -> 4 bits
        v4 = (np.arange(800) % 16).astype(np.int64)
        enc4 = codecs.encode_packed_int(v4)
        assert len(enc4) <= 14 + 400
        np.testing.assert_array_equal(codecs.decode_packed_int(enc4), v4)

    def test_frame_of_reference_large_base(self):
        # large base, tiny spread: should pack at sub-byte width
        v = 10**17 + (np.arange(100) % 4).astype(np.int64)
        enc = codecs.encode_packed_int(v)
        assert len(enc) <= 14 + 25
        np.testing.assert_array_equal(codecs.decode_packed_int(enc), v)

    def test_negative_values(self):
        v = np.array([-5, -1, 0, 3, -5, 2], dtype=np.int64)
        np.testing.assert_array_equal(
            codecs.decode_packed_int(codecs.encode_packed_int(v)), v)

    def test_int64_extremes(self):
        v = np.array([np.iinfo(np.int64).min, 0, np.iinfo(np.int64).max - 1],
                     dtype=np.int64)
        np.testing.assert_array_equal(
            codecs.decode_packed_int(codecs.encode_packed_int(v)), v)

    def test_empty(self):
        out = codecs.decode_packed_int(
            codecs.encode_packed_int(np.array([], np.int64)))
        assert len(out) == 0

    def test_odd_lengths_subbyte(self):
        for n in (1, 3, 7, 9, 15):
            v = (np.arange(n) % 2).astype(np.int64)
            np.testing.assert_array_equal(
                codecs.decode_packed_int(codecs.encode_packed_int(v)), v)

    def test_encode_int_picks_best(self):
        # monotone ramp: delta-delta collapses to const-slope (header only);
        # random small ints: frame-of-reference wins
        ramp = np.arange(0, 10_000, 10, dtype=np.int64)
        enc = codecs.encode_int(ramp)
        assert enc[0] == codecs.CODEC_DELTA_DELTA_CONST
        rng = np.random.default_rng(3)
        rnd = rng.integers(0, 16, size=1000, dtype=np.int64)
        enc2 = codecs.encode_int(rnd)
        np.testing.assert_array_equal(codecs.decode_any(enc2), rnd)
        assert len(enc2) < 1000  # must beat raw int64 by 8x+


class TestConstDouble:
    def test_round_trip(self):
        enc = codecs.encode_const_double(2.75, 42)
        out = codecs.decode_const_double(enc)
        assert out.shape == (42,)
        assert (out == 2.75).all()

    def test_encode_double_selects_const(self):
        v = np.full(500, -1.5)
        enc = codecs.encode_double(v)
        assert enc[0] == codecs.CODEC_CONST_DOUBLE
        assert len(enc) == 13
        np.testing.assert_array_equal(codecs.decode_any(enc), v)

    def test_encode_double_nan_const(self):
        v = np.full(10, np.nan)
        enc = codecs.encode_double(v)
        assert enc[0] == codecs.CODEC_CONST_DOUBLE
        assert np.isnan(codecs.decode_any(enc)).all()

    def test_encode_double_varying_uses_xor(self):
        v = np.array([1.0, 2.0, 3.0])
        enc = codecs.encode_double(v)
        assert enc[0] == codecs.CODEC_XOR_DOUBLE
        np.testing.assert_array_equal(codecs.decode_any(enc), v)


class TestUTF8Vector:
    def test_round_trip(self):
        vals = ["alpha", "beta", "", "汉字", "x" * 300]
        assert codecs.decode_utf8(codecs.encode_utf8(vals)) == vals

    def test_empty_vector(self):
        assert codecs.decode_utf8(codecs.encode_utf8([])) == []

    def test_high_cardinality_selects_raw(self):
        vals = [f"series-{i}" for i in range(100)]
        enc = codecs.encode_string(vals)
        assert enc[0] == codecs.CODEC_UTF8
        assert codecs.decode_any(enc) == vals

    def test_low_cardinality_selects_dict(self):
        vals = ["up", "down"] * 50
        enc = codecs.encode_string(vals)
        assert enc[0] == codecs.CODEC_DICT_STRING_LP
        assert codecs.decode_any(enc) == vals


class TestMapVector:
    def test_round_trip(self):
        vals = [{"app": "api", "dc": "east"},
                {"app": "api", "dc": "west"},
                {},
                {"app": "api", "dc": "east"}]
        out = codecs.decode_map(codecs.encode_map(vals))
        assert out == vals

    def test_none_rows_become_empty(self):
        out = codecs.decode_map(codecs.encode_map([None, {"a": "1"}]))
        assert out == [{}, {"a": "1"}]

    def test_repeating_maps_dict_compress(self):
        row = {"kubernetes_namespace": "prod", "app": "gateway", "zone": "b"}
        vals = [dict(row) for _ in range(1000)]
        enc = codecs.encode_map(vals)
        # dictionary: ~one blob + packed codes, far below per-row encoding
        assert len(enc) < 800
        assert codecs.decode_any(enc) == vals

    def test_unicode_keys_values(self):
        vals = [{"ключ": "значение", "k": "汉"}]
        assert codecs.decode_map(codecs.encode_map(vals)) == vals


MULTI = Schema(DataSchema(
    "multi",
    (Column("timestamp", ColumnType.TIMESTAMP),
     Column("count", ColumnType.LONG),
     Column("flag", ColumnType.INT),
     Column("value", ColumnType.DOUBLE),
     Column("msg", ColumnType.STRING),
     Column("tags", ColumnType.MAP)),
    value_column=3,
))


class TestMultiColumnChunk:
    def test_full_schema_round_trip(self):
        n = 50
        ts = np.arange(n, dtype=np.int64) * 1000
        counts = np.arange(n, dtype=np.int64) * 3
        flags = (np.arange(n) % 2).astype(np.int64)
        vals = np.sin(np.arange(n) / 5.0)
        msgs = [f"event {i % 5}" for i in range(n)]
        tags = [{"host": f"h{i % 3}"} for i in range(n)]
        chunk = encode_chunk(MULTI, ts, [counts, flags, vals, msgs, tags])
        np.testing.assert_array_equal(chunk.decode_column(0), ts)
        np.testing.assert_array_equal(chunk.decode_column(1), counts)
        np.testing.assert_array_equal(chunk.decode_column(2), flags)
        np.testing.assert_allclose(chunk.decode_column(3), vals)
        assert chunk.decode_column(4) == msgs
        assert chunk.decode_column(5) == tags

    def test_serialized_chunk_survives_wire(self):
        from filodb_tpu.memory.chunk import Chunk
        n = 10
        ts = np.arange(n, dtype=np.int64)
        chunk = encode_chunk(MULTI, ts, [
            np.zeros(n, np.int64), np.ones(n, np.int64),
            np.full(n, 7.0), ["a"] * n, [{"k": "v"}] * n])
        back = Chunk.deserialize(chunk.serialize())
        assert back.decode_column(4) == ["a"] * n
        assert back.decode_column(5) == [{"k": "v"}] * n
        np.testing.assert_array_equal(back.decode_column(3), np.full(n, 7.0))


class TestPartitionIngestMultiColumn:
    def test_ingest_and_read_string_map_columns(self):
        from filodb_tpu.core.memstore.partition import TimeSeriesPartition
        from filodb_tpu.core.partkey import PartKey
        part = TimeSeriesPartition(
            0, PartKey.create("multi", {"_metric_": "events"}), MULTI,
            max_chunk_size=8)
        for i in range(20):  # crosses chunk boundaries
            part.ingest(i * 1000, (i, i % 2, float(i), f"m{i % 3}",
                                   {"n": str(i % 2)}))
        assert part.num_samples == 20
        ts, vals = part.read_samples(0, 10**9, col=3)
        np.testing.assert_array_equal(vals, np.arange(20, dtype=float))
        ts, msgs = part.read_samples(0, 10**9, col=4)
        assert list(msgs) == [f"m{i % 3}" for i in range(20)]
        ts, tags = part.read_samples(0, 10**9, col=5)
        assert list(tags) == [{"n": str(i % 2)} for i in range(20)]
