"""Wire codec + transport hardening tests.

Reference counterpart: the Kryo serializer registration
(``client/Serializer.scala:23-64``) — a closed class registry. Unlike Kryo
-over-Akka, the transport also enforces a shared-secret handshake and frame
caps (VERDICT r1 hardening items).
"""

import struct

import numpy as np
import pytest

from filodb_tpu.coordinator.remote import (
    PlanExecutorServer,
    RemotePlanDispatcher,
)
from filodb_tpu.coordinator.wire import MAX_FRAME, decode, encode


class TestWireCodec:
    def test_primitives(self):
        for v in (None, True, False, 0, -5, 2**40, 1.5, "héllo", b"\x00ab",
                  [1, "a"], (1, (2, 3)), {"k": [1.0]}, frozenset({"x", "y"})):
            assert decode(encode(v)) == v

    def test_ndarrays(self):
        for a in (np.arange(5), np.zeros((2, 3), np.float32),
                  np.array([], np.int64), np.ones((2, 2, 2), bool)):
            b = decode(encode(a))
            assert b.dtype == a.dtype and b.shape == a.shape
            np.testing.assert_array_equal(a, b)

    def test_unknown_class_rejected_on_decode(self):
        # forge an object frame naming a class outside the registry
        name = b"OsSystemPwner"
        forged = b"O" + struct.pack("<I", len(name)) + name + \
            struct.pack("<H", 0)
        with pytest.raises(ValueError, match="unknown wire class"):
            decode(forged)

    def test_unregistered_class_rejected_on_encode(self):
        class NotRegistered:
            pass
        with pytest.raises(TypeError, match="not wire-serializable"):
            encode(NotRegistered())

    def test_exec_plan_round_trip(self):
        from filodb_tpu.core.filters import ColumnFilter, Equals
        from filodb_tpu.query.exec.plan import SelectRawPartitionsExec
        from filodb_tpu.query.exec.transformers import PeriodicSamplesMapper
        plan = SelectRawPartitionsExec(
            shard=1, filters=(ColumnFilter("_metric_", Equals("m")),),
            chunk_start=5, chunk_end=10,
            transformers=[PeriodicSamplesMapper(start=5, step=1, end=10,
                                                window=2, function="rate")])
        p2 = decode(encode(plan))
        assert repr(p2) == repr(plan)
        assert p2.transformers[0].function == "rate"


class TestTransportHardening:
    def test_auth_required_when_secret_set(self):
        srv = PlanExecutorServer(None, secret="s3cret").start()
        try:
            d = RemotePlanDispatcher("127.0.0.1", srv.port)
            # no auth (env secret unset on the client side): server rejects
            with pytest.raises((ConnectionError, RuntimeError, OSError)):
                d.call("ping")
        finally:
            srv.stop()

    def test_auth_succeeds_with_secret(self, monkeypatch):
        monkeypatch.setenv("FILODB_CLUSTER_SECRET", "topsecret")
        srv = PlanExecutorServer(None).start()  # picks up env secret
        try:
            d = RemotePlanDispatcher("127.0.0.1", srv.port)
            d._drop_conn()  # force a fresh (authenticated) connection
            assert d.ping()
        finally:
            srv.stop()
            d._drop_conn()

    def test_wrong_secret_rejected(self, monkeypatch):
        srv = PlanExecutorServer(None, secret="right").start()
        monkeypatch.setenv("FILODB_CLUSTER_SECRET", "wrong")
        try:
            d = RemotePlanDispatcher("127.0.0.1", srv.port)
            d._drop_conn()
            assert not d.ping()  # auth rejected → no pong
        finally:
            srv.stop()
            d._drop_conn()

    def test_oversized_frame_rejected(self):
        srv = PlanExecutorServer(None).start()
        try:
            import socket
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            s.sendall(struct.pack("<I", MAX_FRAME + 1))
            # server drops the connection without reading the body
            s.settimeout(2)
            assert s.recv(4) == b""
            s.close()
        finally:
            srv.stop()

    def test_truncated_frame_rejected(self):
        b = encode("hello world")
        with pytest.raises(ValueError, match="truncated"):
            decode(b[:-4])

    def test_trailing_bytes_rejected(self):
        with pytest.raises(ValueError, match="trailing"):
            decode(encode(1) + b"XX")

    def test_stateful_dispatcher_rejected_at_encode(self):
        from filodb_tpu.coordinator.cluster import Node, NodeDispatcher
        nd = NodeDispatcher(Node("n", None))
        with pytest.raises(TypeError, match="no wire fields"):
            encode(nd)

    def test_preauth_frame_cap(self):
        import socket
        from filodb_tpu.coordinator.remote import AUTH_FRAME_CAP
        srv = PlanExecutorServer(None, secret="s").start()
        try:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            s.sendall(struct.pack("<I", AUTH_FRAME_CAP + 1))
            s.settimeout(2)
            assert s.recv(4) == b""  # dropped before reading the body
            s.close()
        finally:
            srv.stop()

    def test_no_pickle_on_the_wire(self):
        # the encoded execute message must not contain pickle opcodes
        from filodb_tpu.query.model import QueryContext
        b = encode(("execute", "ds", None, QueryContext()))
        assert not b.startswith(b"\x80")
        assert b"\x80\x05" not in b


class TestLegacyContainerGate:
    def test_v1_pickle_rejected_by_default(self, monkeypatch):
        import pickle, struct as _s
        from filodb_tpu.core.record import RecordContainer
        monkeypatch.delenv("FILODB_ALLOW_LEGACY_WAL", raising=False)
        payload = pickle.dumps([("gauge", (("_metric_", "old"),), 1, (1.0,))])
        legacy = _s.pack("<BI", 1, len(payload)) + payload
        with pytest.raises(ValueError, match="legacy v1"):
            RecordContainer.deserialize(legacy)

    def test_v1_allowed_when_opted_in(self, monkeypatch):
        import pickle, struct as _s
        from filodb_tpu.core.record import RecordContainer
        monkeypatch.setenv("FILODB_ALLOW_LEGACY_WAL", "1")
        payload = pickle.dumps([("gauge", (("_metric_", "old"),), 1, (1.0,))])
        legacy = _s.pack("<BI", 1, len(payload)) + payload
        c = RecordContainer.deserialize(legacy)
        assert list(c)[0].timestamp == 1
