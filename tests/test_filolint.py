"""filolint static-analysis suite (filodb_tpu/analysis/).

Two layers:

- fixture tests: each pass against small known-bad / known-good
  sources written into a temp tree, including the PR 7
  blocking-evaluation-under-lock regression shape;
- the repo gate: ``run_all`` over THIS repo must produce no finding
  outside ``conf/filolint_baseline.json``, and no baseline entry may
  be stale or unjustified. This is the tier-1 enforcement point.
"""

import json
import os
import textwrap

import pytest

from filodb_tpu.analysis import (
    AnalysisContext,
    Baseline,
    Finding,
    run_all,
)
from filodb_tpu.analysis import cli, hotpath, lockdiscipline, parity
from filodb_tpu.analysis.model import suppressed

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO_ROOT, "conf", "filolint_baseline.json")


def write_tree(root, files):
    for rel, src in files.items():
        path = os.path.join(root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(textwrap.dedent(src))
    return str(root)


def codes(findings):
    return sorted(f.code for f in findings)


def run_pass(tmp_path, mod, files):
    root = write_tree(tmp_path, files)
    ctx = AnalysisContext.build(root)
    assert not ctx.errors, ctx.errors
    return mod.run(ctx)


# --------------------------------------------------------------------------
# LD101 blocking-under-lock

class TestLockDiscipline:
    def test_sleep_under_lock_flagged(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading, time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad(self):
                    with self._lock:
                        time.sleep(1)
            """})
        assert codes(out) == ["LD101"]
        assert "time.sleep" in out[0].message
        assert out[0].symbol == "C.bad"

    def test_pr7_regression_shape_query_under_lock(self, tmp_path):
        # the PR 7 priority inversion: rule evaluation under the state
        # lock, stalling lock-free readers behind a slow query
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class Manager:
                def __init__(self, svc):
                    self._lock = threading.Lock()
                    self.svc = svc

                def tick(self):
                    with self._lock:
                        return self.svc.query_range("expr", 0, 60, 600)
            """})
        assert codes(out) == ["LD101"]
        assert "query_range" in out[0].detail

    def test_transitive_self_call_chain(self, tmp_path):
        # blocking two hops away: with lock -> self.a() -> self.b() ->
        # sock.recv(); the closure expansion must surface the chain
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self, sock):
                    self._lock = threading.Lock()
                    self.sock = sock

                def outer(self):
                    with self._lock:
                        self.a()

                def a(self):
                    return self.b()

                def b(self):
                    return self.sock.recv(4096)
            """})
        assert codes(out) == ["LD101"]
        assert "a.b" in out[0].detail and "recv" in out[0].detail

    def test_blocking_outside_lock_is_fine(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading, time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def fine(self):
                    with self._lock:
                        x = 1
                    time.sleep(1)
                    return x
            """})
        assert out == []

    def test_condition_wait_exempts_own_lock(self, tmp_path):
        # cond.wait() releases the condition's lock while waiting — the
        # canonical producer/consumer shape must not be flagged
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cond = threading.Condition(self._lock)

                def wait_ready(self):
                    with self._cond:
                        self._cond.wait()
            """})
        assert out == []

    def test_nested_def_has_its_own_lock_scope(self, tmp_path):
        # a worker closure defined under a lock runs on its own thread:
        # the held stack must not leak into it
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading, time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def start(self):
                    with self._lock:
                        def worker():
                            time.sleep(1)
                        self.t = threading.Thread(target=worker)
            """})
        assert codes(out) == []

    def test_dict_get_is_not_a_queue_get(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.d = {}

                def fine(self, k):
                    with self._lock:
                        return self.d.get(k)
            """})
        assert out == []

    def test_queue_get_under_lock_flagged(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import queue, threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue()

                def bad(self):
                    with self._lock:
                        return self._q.get()
            """})
        assert codes(out) == ["LD101"]


# --------------------------------------------------------------------------
# LD102 lock-order cycles

class TestLockOrder:
    def test_opposite_orders_make_a_cycle(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._b:
                        with self._a:
                            pass
            """})
        assert codes(out) == ["LD102"]
        assert "C._a" in out[0].detail and "C._b" in out[0].detail

    def test_consistent_order_is_fine(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._a:
                        with self._b:
                            pass
            """})
        assert out == []

    def test_cycle_through_self_call(self, tmp_path):
        # one() holds A and calls helper() which takes B; two() nests A
        # under B directly — the deferred-call edges must close the loop
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        self.helper()

                def helper(self):
                    with self._b:
                        pass

                def two(self):
                    with self._b:
                        with self._a:
                            pass
            """})
        assert codes(out) == ["LD102"]


# --------------------------------------------------------------------------
# LD103 mixed-guard attribute stores

class TestMixedGuard:
    def test_mixed_stores_flagged(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def guarded(self):
                    with self._lock:
                        self.n += 1

                def unguarded(self):
                    self.n = 0
            """})
        assert codes(out) == ["LD103"]
        assert out[0].detail == "n"

    def test_init_stores_do_not_count(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def guarded(self):
                    with self._lock:
                        self.n += 1
            """})
        assert out == []

    def test_locked_suffix_convention_counts_as_guarded(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def guarded(self):
                    with self._lock:
                        self._bump_locked()

                def _bump_locked(self):
                    self.n += 1
            """})
        assert out == []


# --------------------------------------------------------------------------
# parity pass

WIRE_FIXTURE = """
    def _build_registry():
        registry = {}
        for cls in (Frame, Ghost):
            registry[cls.__name__] = cls
        for base in (Plan,):
            pass
        return registry
    """

SCRAPE_FIXTURE = """
    NAMES = [
        "filodb_good_total",
        "filodb_lazy_total",
        "filodb_phantom_total",
    ]
    """


class TestParity:
    def run(self, tmp_path, files):
        files.setdefault("filodb_tpu/coordinator/wire.py", WIRE_FIXTURE)
        files.setdefault("tests/test_metrics_scrape.py", SCRAPE_FIXTURE)
        return run_pass(tmp_path, parity, files)

    def test_unregistered_nested_dataclass(self, tmp_path):
        out = self.run(tmp_path, {"filodb_tpu/model.py": """
            from dataclasses import dataclass

            @dataclass
            class Inner:
                x: int

            @dataclass
            class Frame:
                inner: Inner

            class Ghost:
                pass

            class Plan:
                pass
            """})
        pr201 = [f for f in out if f.code == "PR201"]
        assert [f.detail for f in pr201] == ["Inner"]

    def test_stale_registry_name(self, tmp_path):
        # Ghost is named in the registry but no class defines it
        out = self.run(tmp_path, {"filodb_tpu/model.py": """
            from dataclasses import dataclass

            @dataclass
            class Frame:
                x: int

            class Plan:
                pass
            """})
        pr202 = [f for f in out if f.code == "PR202"]
        assert [f.detail for f in pr202] == ["Ghost"]

    def test_subclass_walk_registers_children(self, tmp_path):
        # SubPlan rides through the `for base in (Plan,)` walk: fields
        # referencing it from a registered class are fine
        out = self.run(tmp_path, {"filodb_tpu/model.py": """
            from dataclasses import dataclass

            class Plan:
                pass

            @dataclass
            class SubPlan(Plan):
                x: int

            @dataclass
            class Frame:
                plan: SubPlan

            class Ghost:
                pass
            """})
        assert [f for f in out if f.code == "PR201"] == []

    def test_wire_fields_must_be_registered(self, tmp_path):
        out = self.run(tmp_path, {"filodb_tpu/model.py": """
            class Frame:
                pass

            class Ghost:
                pass

            class Plan:
                pass

            class Orphan:
                __wire_fields__ = ("x",)
            """})
        pr201 = [f for f in out if f.code == "PR201"]
        assert [f.detail for f in pr201] == ["Orphan"]

    def test_metric_parity(self, tmp_path):
        out = self.run(tmp_path, {"filodb_tpu/metrics_mod.py": """
            from filodb_tpu.utils.metrics import Counter, GaugeFn

            good = Counter("filodb_good")
            uncovered = Counter("filodb_uncovered")
            ratio = GaugeFn("filodb_ratio", lambda: None)

            def lazy():
                return Counter("filodb_lazy")
            """,
            "filodb_tpu/model.py": """
            from dataclasses import dataclass

            @dataclass
            class Frame:
                x: int

            class Ghost:
                pass

            class Plan:
                pass
            """})
        # uncovered: module-level, not asserted -> PR203
        pr203 = [f for f in out if f.code == "PR203"]
        assert [f.detail for f in pr203] == ["filodb_uncovered_total"]
        # phantom: asserted, nothing produces it -> PR204; lazy counts
        # as a producer, GaugeFn is exempt from PR203
        pr204 = [f for f in out if f.code == "PR204"]
        assert [f.detail for f in pr204] == ["filodb_phantom_total"]

    def test_prom_charset(self, tmp_path):
        out = self.run(tmp_path, {"filodb_tpu/metrics_mod.py": """
            from filodb_tpu.utils.metrics import Counter

            def lazy():
                return Counter("filodb bad-name")
            """,
            "filodb_tpu/model.py": """
            from dataclasses import dataclass

            @dataclass
            class Frame:
                x: int

            class Ghost:
                pass

            class Plan:
                pass
            """})
        pr205 = [f for f in out if f.code == "PR205"]
        assert [f.detail for f in pr205] == ["filodb bad-name"]


# --------------------------------------------------------------------------
# hot-path pass

class TestHotPath:
    def test_host_sync_and_clock_in_kernel(self, tmp_path):
        out = run_pass(tmp_path, hotpath, {
            "filodb_tpu/query/engine/k.py": """
            import time
            import jax
            import numpy as np

            @jax.jit
            def kernel(x, meta):
                t = time.time()
                v = x.item()
                a = np.asarray(meta.steps)
                return v + t + float(meta.window)
            """})
        assert codes(out) == ["HP301", "HP301", "HP301", "HP302"]

    def test_nested_def_inherits_kernel_scope(self, tmp_path):
        out = run_pass(tmp_path, hotpath, {
            "filodb_tpu/query/engine/k.py": """
            import jax

            @jax.jit
            def kernel(x):
                def inner(y):
                    return y.item()
                return inner(x)
            """})
        assert codes(out) == ["HP301"]
        assert out[0].symbol == "kernel.inner"

    def test_pallas_kernel_detected(self, tmp_path):
        out = run_pass(tmp_path, hotpath, {
            "filodb_tpu/query/engine/k.py": """
            from jax.experimental import pallas as pl

            def body(ref, o_ref):
                o_ref[...] = float(ref[...])

            def launch(x):
                return pl.pallas_call(body, out_shape=x)(x)
            """})
        assert codes(out) == ["HP301"]

    def test_non_kernel_and_non_engine_ignored(self, tmp_path):
        out = run_pass(tmp_path, hotpath, {
            "filodb_tpu/query/engine/k.py": """
            def plain(x):
                return x.item()
            """,
            "filodb_tpu/coordinator/c.py": """
            import jax

            @jax.jit
            def kernel(x):
                return x.item()
            """})
        assert out == []


# --------------------------------------------------------------------------
# model: suppression, baseline, CLI

class TestModel:
    def test_inline_suppression(self, tmp_path):
        root = write_tree(tmp_path, {"filodb_tpu/m.py": """
            import threading, time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad(self):
                    with self._lock:
                        time.sleep(1)  # filolint: disable=LD101
            """})
        out = run_all(root, passes=[lockdiscipline])
        assert out == []

    def test_suppression_is_code_scoped(self):
        lines = ["x = 1  # filolint: disable=LD101"]
        assert suppressed(lines, 1, "LD101")
        assert not suppressed(lines, 1, "LD103")
        assert suppressed(["y  # filolint: disable=all"], 1, "HP302")

    def test_key_is_line_free(self):
        a = Finding("LD101", "p.py", 10, "C.m", "d", "msg")
        b = Finding("LD101", "p.py", 99, "C.m", "d", "msg")
        assert a.key == b.key

    def test_baseline_diff_and_update(self, tmp_path):
        f1 = Finding("LD101", "p.py", 1, "C.m", "d1", "m1")
        f2 = Finding("LD101", "p.py", 2, "C.m", "d2", "m2")
        bl = Baseline()
        bl.update([f1])
        bl.entries[f1.key]["justification"] = "intentional"
        new, stale = bl.diff([f1, f2])
        assert [f.key for f in new] == [f2.key]
        assert stale == []
        new, stale = bl.diff([f2])
        assert [e["key"] for e in stale] == [f1.key]
        # update keeps the human-written justification
        bl.update([f1, f2])
        assert bl.entries[f1.key]["justification"] == "intentional"
        assert "TODO" in bl.entries[f2.key]["justification"]
        path = str(tmp_path / "bl.json")
        bl.save(path)
        assert Baseline.load(path).entries == bl.entries

    def test_cli_gate_roundtrip(self, tmp_path, capsys):
        root = write_tree(tmp_path, {"filodb_tpu/m.py": """
            import threading, time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad(self):
                    with self._lock:
                        time.sleep(1)
            """})
        bl = str(tmp_path / "baseline.json")
        assert cli.main(["--root", root, "--baseline", bl]) == 1
        assert cli.main(["--root", root, "--baseline", bl,
                         "--update-baseline"]) == 0
        assert cli.main(["--root", root, "--baseline", bl]) == 0
        out = json.loads(json.dumps(json.load(open(bl))))
        assert out["entries"][0]["code"] == "LD101"
        capsys.readouterr()

    def test_cli_parse_error_exits_2(self, tmp_path, capsys):
        root = write_tree(tmp_path,
                          {"filodb_tpu/bad.py": "def broken(:\n"})
        assert cli.main(["--root", root]) == 2
        capsys.readouterr()


# --------------------------------------------------------------------------
# the repo gate (tier-1 enforcement)

class TestRepoGate:
    def test_repo_has_no_unbaselined_findings(self):
        findings = run_all(REPO_ROOT)
        bl = Baseline.load(BASELINE)
        new, stale = bl.diff(findings)
        assert not new, "new filolint findings (fix or baseline with " \
            "justification):\n" + "\n".join(f.render() for f in new)
        assert not stale, "stale baseline entries (remove them):\n" + \
            "\n".join(e["key"] for e in stale)

    def test_repo_parses_clean(self):
        ctx = AnalysisContext.build(REPO_ROOT)
        assert ctx.errors == []

    def test_every_baseline_entry_is_justified(self):
        bl = Baseline.load(BASELINE)
        assert bl.entries, "baseline should exist and be non-empty"
        unjustified = [k for k, e in bl.entries.items()
                       if not e.get("justification")
                       or "TODO" in e["justification"]]
        assert not unjustified, unjustified
