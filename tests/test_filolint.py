"""filolint static-analysis suite (filodb_tpu/analysis/).

Two layers:

- fixture tests: each pass against small known-bad / known-good
  sources written into a temp tree, including the PR 7
  blocking-evaluation-under-lock regression shape;
- the repo gate: ``run_all`` over THIS repo must produce no finding
  outside ``conf/filolint_baseline.json``, and no baseline entry may
  be stale or unjustified. This is the tier-1 enforcement point.
"""

import json
import os
import textwrap

import pytest

from filodb_tpu.analysis import (
    AnalysisContext,
    Baseline,
    Finding,
    run_all,
)
from filodb_tpu.analysis import (
    chokepoint,
    cli,
    decisionparity,
    hotpath,
    lifecycle,
    lockdiscipline,
    parity,
)
from filodb_tpu.analysis.model import suppressed

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO_ROOT, "conf", "filolint_baseline.json")


def write_tree(root, files):
    for rel, src in files.items():
        path = os.path.join(root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(textwrap.dedent(src))
    return str(root)


def codes(findings):
    return sorted(f.code for f in findings)


def run_pass(tmp_path, mod, files):
    root = write_tree(tmp_path, files)
    ctx = AnalysisContext.build(root)
    assert not ctx.errors, ctx.errors
    return mod.run(ctx)


# --------------------------------------------------------------------------
# LD101 blocking-under-lock

class TestLockDiscipline:
    def test_sleep_under_lock_flagged(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading, time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad(self):
                    with self._lock:
                        time.sleep(1)
            """})
        assert codes(out) == ["LD101"]
        assert "time.sleep" in out[0].message
        assert out[0].symbol == "C.bad"

    def test_pr7_regression_shape_query_under_lock(self, tmp_path):
        # the PR 7 priority inversion: rule evaluation under the state
        # lock, stalling lock-free readers behind a slow query
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class Manager:
                def __init__(self, svc):
                    self._lock = threading.Lock()
                    self.svc = svc

                def tick(self):
                    with self._lock:
                        return self.svc.query_range("expr", 0, 60, 600)
            """})
        assert codes(out) == ["LD101"]
        assert "query_range" in out[0].detail

    def test_transitive_self_call_chain(self, tmp_path):
        # blocking two hops away: with lock -> self.a() -> self.b() ->
        # sock.recv(); the closure expansion must surface the chain
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self, sock):
                    self._lock = threading.Lock()
                    self.sock = sock

                def outer(self):
                    with self._lock:
                        self.a()

                def a(self):
                    return self.b()

                def b(self):
                    return self.sock.recv(4096)
            """})
        assert codes(out) == ["LD101"]
        assert "a.b" in out[0].detail and "recv" in out[0].detail

    def test_blocking_outside_lock_is_fine(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading, time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def fine(self):
                    with self._lock:
                        x = 1
                    time.sleep(1)
                    return x
            """})
        assert out == []

    def test_condition_wait_exempts_own_lock(self, tmp_path):
        # cond.wait() releases the condition's lock while waiting — the
        # canonical producer/consumer shape must not be flagged
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cond = threading.Condition(self._lock)

                def wait_ready(self):
                    with self._cond:
                        self._cond.wait()
            """})
        assert out == []

    def test_nested_def_has_its_own_lock_scope(self, tmp_path):
        # a worker closure defined under a lock runs on its own thread:
        # the held stack must not leak into it
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading, time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def start(self):
                    with self._lock:
                        def worker():
                            time.sleep(1)
                        self.t = threading.Thread(target=worker)
            """})
        assert codes(out) == []

    def test_dict_get_is_not_a_queue_get(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.d = {}

                def fine(self, k):
                    with self._lock:
                        return self.d.get(k)
            """})
        assert out == []

    def test_queue_get_under_lock_flagged(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import queue, threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue()

                def bad(self):
                    with self._lock:
                        return self._q.get()
            """})
        assert codes(out) == ["LD101"]


# --------------------------------------------------------------------------
# LD102 lock-order cycles

class TestLockOrder:
    def test_opposite_orders_make_a_cycle(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._b:
                        with self._a:
                            pass
            """})
        assert codes(out) == ["LD102"]
        assert "C._a" in out[0].detail and "C._b" in out[0].detail

    def test_consistent_order_is_fine(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._a:
                        with self._b:
                            pass
            """})
        assert out == []

    def test_cycle_through_self_call(self, tmp_path):
        # one() holds A and calls helper() which takes B; two() nests A
        # under B directly — the deferred-call edges must close the loop
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        self.helper()

                def helper(self):
                    with self._b:
                        pass

                def two(self):
                    with self._b:
                        with self._a:
                            pass
            """})
        assert codes(out) == ["LD102"]


# --------------------------------------------------------------------------
# LD103 mixed-guard attribute stores

class TestMixedGuard:
    def test_mixed_stores_flagged(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def guarded(self):
                    with self._lock:
                        self.n += 1

                def unguarded(self):
                    self.n = 0
            """})
        assert codes(out) == ["LD103"]
        assert out[0].detail == "n"

    def test_init_stores_do_not_count(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def guarded(self):
                    with self._lock:
                        self.n += 1
            """})
        assert out == []

    def test_locked_suffix_convention_counts_as_guarded(self, tmp_path):
        out = run_pass(tmp_path, lockdiscipline, {"filodb_tpu/m.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def guarded(self):
                    with self._lock:
                        self._bump_locked()

                def _bump_locked(self):
                    self.n += 1
            """})
        assert out == []


# --------------------------------------------------------------------------
# parity pass

WIRE_FIXTURE = """
    def _build_registry():
        registry = {}
        for cls in (Frame, Ghost):
            registry[cls.__name__] = cls
        for base in (Plan,):
            pass
        return registry
    """

SCRAPE_FIXTURE = """
    NAMES = [
        "filodb_good_total",
        "filodb_lazy_total",
        "filodb_phantom_total",
    ]
    """


class TestParity:
    def run(self, tmp_path, files):
        files.setdefault("filodb_tpu/coordinator/wire.py", WIRE_FIXTURE)
        files.setdefault("tests/test_metrics_scrape.py", SCRAPE_FIXTURE)
        return run_pass(tmp_path, parity, files)

    def test_unregistered_nested_dataclass(self, tmp_path):
        out = self.run(tmp_path, {"filodb_tpu/model.py": """
            from dataclasses import dataclass

            @dataclass
            class Inner:
                x: int

            @dataclass
            class Frame:
                inner: Inner

            class Ghost:
                pass

            class Plan:
                pass
            """})
        pr201 = [f for f in out if f.code == "PR201"]
        assert [f.detail for f in pr201] == ["Inner"]

    def test_stale_registry_name(self, tmp_path):
        # Ghost is named in the registry but no class defines it
        out = self.run(tmp_path, {"filodb_tpu/model.py": """
            from dataclasses import dataclass

            @dataclass
            class Frame:
                x: int

            class Plan:
                pass
            """})
        pr202 = [f for f in out if f.code == "PR202"]
        assert [f.detail for f in pr202] == ["Ghost"]

    def test_subclass_walk_registers_children(self, tmp_path):
        # SubPlan rides through the `for base in (Plan,)` walk: fields
        # referencing it from a registered class are fine
        out = self.run(tmp_path, {"filodb_tpu/model.py": """
            from dataclasses import dataclass

            class Plan:
                pass

            @dataclass
            class SubPlan(Plan):
                x: int

            @dataclass
            class Frame:
                plan: SubPlan

            class Ghost:
                pass
            """})
        assert [f for f in out if f.code == "PR201"] == []

    def test_wire_fields_must_be_registered(self, tmp_path):
        out = self.run(tmp_path, {"filodb_tpu/model.py": """
            class Frame:
                pass

            class Ghost:
                pass

            class Plan:
                pass

            class Orphan:
                __wire_fields__ = ("x",)
            """})
        pr201 = [f for f in out if f.code == "PR201"]
        assert [f.detail for f in pr201] == ["Orphan"]

    def test_metric_parity(self, tmp_path):
        out = self.run(tmp_path, {"filodb_tpu/metrics_mod.py": """
            from filodb_tpu.utils.metrics import Counter, GaugeFn

            good = Counter("filodb_good")
            uncovered = Counter("filodb_uncovered")
            ratio = GaugeFn("filodb_ratio", lambda: None)

            def lazy():
                return Counter("filodb_lazy")
            """,
            "filodb_tpu/model.py": """
            from dataclasses import dataclass

            @dataclass
            class Frame:
                x: int

            class Ghost:
                pass

            class Plan:
                pass
            """})
        # uncovered: module-level, not asserted -> PR203
        pr203 = [f for f in out if f.code == "PR203"]
        assert [f.detail for f in pr203] == ["filodb_uncovered_total"]
        # phantom: asserted, nothing produces it -> PR204; lazy counts
        # as a producer, GaugeFn is exempt from PR203
        pr204 = [f for f in out if f.code == "PR204"]
        assert [f.detail for f in pr204] == ["filodb_phantom_total"]

    def test_pyramid_families_exempt_from_nothing(self, tmp_path):
        # filodb_pyramid_* carries the zero-payload accounting: the lazy
        # exemption PR203 grants does NOT apply (PR207 still fires)
        out = self.run(tmp_path, {"filodb_tpu/metrics_mod.py": """
            from filodb_tpu.utils.metrics import Counter

            good = Counter("filodb_good")

            def lazy():
                return Counter("filodb_pyramid_ghost")

            def lazy2():
                return Counter("filodb_lazy")

            def lazy3():
                return Counter("filodb_phantom")
            """,
            "filodb_tpu/model.py": """
            from dataclasses import dataclass

            @dataclass
            class Frame:
                x: int

            class Ghost:
                pass

            class Plan:
                pass
            """})
        pr207 = [f for f in out if f.code == "PR207"]
        assert [f.detail for f in pr207] == ["filodb_pyramid_ghost_total"]
        # and the plain lazy counter stays exempt from PR203
        assert [f for f in out if f.code == "PR203"] == []

    def test_prom_charset(self, tmp_path):
        out = self.run(tmp_path, {"filodb_tpu/metrics_mod.py": """
            from filodb_tpu.utils.metrics import Counter

            def lazy():
                return Counter("filodb bad-name")
            """,
            "filodb_tpu/model.py": """
            from dataclasses import dataclass

            @dataclass
            class Frame:
                x: int

            class Ghost:
                pass

            class Plan:
                pass
            """})
        pr205 = [f for f in out if f.code == "PR205"]
        assert [f.detail for f in pr205] == ["filodb bad-name"]


# --------------------------------------------------------------------------
# hot-path pass

class TestHotPath:
    def test_host_sync_and_clock_in_kernel(self, tmp_path):
        out = run_pass(tmp_path, hotpath, {
            "filodb_tpu/query/engine/k.py": """
            import time
            import jax
            import numpy as np

            @jax.jit
            def kernel(x, meta):
                t = time.time()
                v = x.item()
                a = np.asarray(meta.steps)
                return v + t + float(meta.window)
            """})
        assert codes(out) == ["HP301", "HP301", "HP301", "HP302"]

    def test_nested_def_inherits_kernel_scope(self, tmp_path):
        out = run_pass(tmp_path, hotpath, {
            "filodb_tpu/query/engine/k.py": """
            import jax

            @jax.jit
            def kernel(x):
                def inner(y):
                    return y.item()
                return inner(x)
            """})
        assert codes(out) == ["HP301"]
        assert out[0].symbol == "kernel.inner"

    def test_pallas_kernel_detected(self, tmp_path):
        out = run_pass(tmp_path, hotpath, {
            "filodb_tpu/query/engine/k.py": """
            from jax.experimental import pallas as pl

            def body(ref, o_ref):
                o_ref[...] = float(ref[...])

            def launch(x):
                return pl.pallas_call(body, out_shape=x)(x)
            """})
        assert codes(out) == ["HP301"]

    def test_non_kernel_and_non_engine_ignored(self, tmp_path):
        out = run_pass(tmp_path, hotpath, {
            "filodb_tpu/query/engine/k.py": """
            def plain(x):
                return x.item()
            """,
            "filodb_tpu/coordinator/c.py": """
            import jax

            @jax.jit
            def kernel(x):
                return x.item()
            """})
        assert out == []

    def test_shard_map_wrapped_kernel_in_parallel(self, tmp_path):
        """The dist_query factory idiom: an undecorated closure becomes a
        kernel by being the first argument of shard_map/_shard_map — and
        parallel/ is in scope alongside query/engine/."""
        out = run_pass(tmp_path, hotpath, {
            "filodb_tpu/parallel/d.py": """
            import jax
            from jax.experimental.shard_map import shard_map

            def make_step(mesh):
                def step(ts, vals):
                    def kernel(ts_l, vals_l):
                        return vals_l.sum() + float(ts_l.shape)
                    return _shard_map(kernel, mesh=mesh, in_specs=(),
                                      out_specs=())(ts, vals)
                return jax.jit(step)
            """})
        assert codes(out) == ["HP301"]
        assert out[0].symbol == "make_step.step.kernel"

    def test_jit_call_form_wrapped_kernel(self, tmp_path):
        """``jit(fn)`` call form (no decorator) marks ``fn`` a kernel;
        the jitted wrapper's own body is scanned too."""
        out = run_pass(tmp_path, hotpath, {
            "filodb_tpu/parallel/j.py": """
            import time
            from jax import jit

            def prep(vals):
                t = time.time()
                return vals + t

            prep_jitted = jit(prep)
            """})
        assert codes(out) == ["HP302"]
        assert out[0].symbol == "prep"


# --------------------------------------------------------------------------
# RL4xx resource lifecycle

class TestLifecycle:
    def test_rl401_leak_on_exception_narrow_except(self, tmp_path):
        # the remote.py postmortem shape: a checked-out socket crossing
        # raising calls with only a narrow transport-error handler —
        # any other exception class leaks the fd out of the pool
        out = run_pass(tmp_path, lifecycle, {"filodb_tpu/m.py": """
            class D:
                def roundtrip(self, pool, key, msg):
                    sock = pool.checkout(key)
                    try:
                        sock.sendall(msg)
                        resp = sock.recv(4096)
                    except (ConnectionError, OSError):
                        sock.close()
                        raise
                    pool.checkin(key, sock)
                    return resp
            """})
        assert codes(out) == ["RL401"]
        assert "sock" in out[0].detail

    def test_rl401_broad_except_is_protection(self, tmp_path):
        out = run_pass(tmp_path, lifecycle, {"filodb_tpu/m.py": """
            class D:
                def roundtrip(self, pool, key, msg):
                    sock = pool.checkout(key)
                    try:
                        sock.sendall(msg)
                        resp = sock.recv(4096)
                    except BaseException:
                        sock.close()
                        raise
                    pool.checkin(key, sock)
                    return resp
            """})
        assert out == []

    def test_rl401_finally_is_protection(self, tmp_path):
        out = run_pass(tmp_path, lifecycle, {"filodb_tpu/m.py": """
            import socket

            def fetch(host, msg):
                s = socket.create_connection((host, 80))
                try:
                    s.sendall(msg)
                    return s.recv(4096)
                finally:
                    s.close()
            """})
        assert out == []

    def test_rl402_leak_through_helper(self, tmp_path):
        # the acquisition is hidden in a local helper whose summary
        # says "returns a fresh socket"; the caller never releases it
        out = run_pass(tmp_path, lifecycle, {"filodb_tpu/m.py": """
            import socket

            class D:
                def _dial(self):
                    s = socket.create_connection(("h", 80))
                    return s

                def ping(self):
                    sock = self._dial()
                    sock.sendall(b"ping")
            """})
        assert "RL402" in codes(out)
        assert any("self._dial()" in f.detail for f in out)

    def test_release_through_helper_is_clean(self, tmp_path):
        # ...and a release hidden in a helper counts as a release
        out = run_pass(tmp_path, lifecycle, {"filodb_tpu/m.py": """
            import socket

            def _close_quietly(sock):
                try:
                    sock.close()
                except OSError:
                    pass

            def probe(host):
                s = socket.create_connection((host, 80))
                try:
                    s.sendall(b"hi")
                finally:
                    _close_quietly(s)
            """})
        assert out == []

    def test_ownership_transfer_silences(self, tmp_path):
        # storing the socket on self transfers ownership out of the
        # function — constructor caching, not a leak
        out = run_pass(tmp_path, lifecycle, {"filodb_tpu/m.py": """
            import socket

            class Conn:
                def connect(self, host):
                    s = socket.create_connection((host, 80))
                    self._sock = s
                    return self._sock
            """})
        assert out == []

    def test_rl403_thread_not_joined(self, tmp_path):
        out = run_pass(tmp_path, lifecycle, {"filodb_tpu/m.py": """
            import threading

            def fire(work):
                t = threading.Thread(target=work)
                t.start()
            """})
        assert codes(out) == ["RL403"]

    def test_rl403_daemon_or_joined_clean(self, tmp_path):
        out = run_pass(tmp_path, lifecycle, {"filodb_tpu/m.py": """
            import threading

            def daemonized(work):
                t = threading.Thread(target=work, daemon=True)
                t.start()

            def awaited(work):
                t = threading.Thread(target=work)
                t.start()
                t.join()
            """})
        assert out == []

    def test_rl403_self_thread_joined_elsewhere_in_class(self, tmp_path):
        out = run_pass(tmp_path, lifecycle, {"filodb_tpu/m.py": """
            import threading

            class Worker:
                def start(self):
                    self._t = threading.Thread(target=self.run)
                    self._t.start()

                def stop(self):
                    self._t.join()

            class Leaky:
                def start(self):
                    self._t = threading.Thread(target=self.run)
                    self._t.start()
            """})
        assert codes(out) == ["RL403"]
        assert out[0].symbol.startswith("Leaky")

    def test_rl404_ack_outside_finally(self, tmp_path):
        out = run_pass(tmp_path, lifecycle, {"filodb_tpu/m.py": """
            class W:
                def drain_bad(self):
                    item = self._q.get()
                    self.handle(item)
                    self._q.task_done()

                def drain_good(self):
                    item = self._q.get()
                    try:
                        self.handle(item)
                    finally:
                        self._q.task_done()
            """})
        assert codes(out) == ["RL404"]
        assert out[0].symbol == "W.drain_bad"


# --------------------------------------------------------------------------
# CP5xx choke points

class TestChokepoint:
    def test_cp501_deadline_dropped_at_new_call_site(self, tmp_path):
        # a NEW dispatcher subclass that blocks on the network without
        # consulting any deadline — the invariant PR 1 review restored
        # by hand
        out = run_pass(tmp_path, chokepoint, {"filodb_tpu/m.py": """
            class GoodDispatcher(PlanDispatcher):
                def dispatch(self, plan, ctx):
                    ctx.deadline.check()
                    return self._sock.recv(4096)

            class BadDispatcher(PlanDispatcher):
                def dispatch(self, plan, ctx):
                    return self._sock.recv(4096)
            """})
        assert codes(out) == ["CP501"]
        assert out[0].symbol == "BadDispatcher.dispatch"

    def test_cp501_closure_sees_helper_deadline(self, tmp_path):
        # the deadline reference may live in a self-call helper
        out = run_pass(tmp_path, chokepoint, {"filodb_tpu/m.py": """
            class D(PlanDispatcher):
                def dispatch(self, plan, ctx):
                    return self._roundtrip(plan, ctx)

                def _roundtrip(self, plan, ctx):
                    self._sock.settimeout(ctx.deadline.remaining())
                    return self._sock.recv(4096)
            """})
        assert out == []

    def test_cp502_dispatch_outside_admission(self, tmp_path):
        out = run_pass(tmp_path, chokepoint, {
            "filodb_tpu/coordinator/m.py": """
            class Svc:
                def run_bad(self, plan, ctx):
                    return plan.dispatcher.dispatch(plan, ctx)

                def run_good(self, plan, ctx):
                    with governor().admit(cost=2):
                        return plan.dispatcher.dispatch(plan, ctx)
            """})
        assert codes(out) == ["CP502"]
        assert out[0].symbol == "Svc.run_bad"

    def test_cp502_plan_tree_internals_exempt(self, tmp_path):
        # below the gate, dispatch recursion is already admitted
        out = run_pass(tmp_path, chokepoint, {
            "filodb_tpu/query/exec/m.py": """
            class Node:
                def execute(self, ctx):
                    return self.child.dispatcher.dispatch(self.child, ctx)
            """})
        assert out == []

    def test_cp503_direct_bookkeeping(self, tmp_path):
        out = run_pass(tmp_path, chokepoint, {
            "filodb_tpu/coordinator/m.py": """
            def flaky(peer):
                breaker_for(peer).record_failure()
            """,
            "filodb_tpu/utils/resilience.py": """
            class CircuitBreaker:
                def ok(self):
                    self.record_success()
            """})
        assert codes(out) == ["CP503"]
        assert out[0].path == "filodb_tpu/coordinator/m.py"

    def test_cp503_force_open_exempt(self, tmp_path):
        # a failure-detector verdict, not a call outcome
        out = run_pass(tmp_path, chokepoint, {
            "filodb_tpu/coordinator/m.py": """
            def member_lost(peer):
                breaker_for(peer).force_open()
            """})
        assert out == []

    def test_cp504_double_outcome_one_path(self, tmp_path):
        out = run_pass(tmp_path, chokepoint, {
            "filodb_tpu/coordinator/m.py": """
            def call(breaker, req):
                with breaker.calling() as out:
                    resp = send(req)
                    out.success()
                    out.success()
                    return resp
            """})
        assert codes(out) == ["CP504"]

    def test_cp504_alternative_paths_clean(self, tmp_path):
        # the remote_exec shape: each handler is its own path, one
        # outcome per path
        out = run_pass(tmp_path, chokepoint, {
            "filodb_tpu/coordinator/m.py": """
            def call(breaker, req):
                with breaker.calling() as out:
                    try:
                        resp = send(req)
                    except HTTPError:
                        out.success()
                        raise
                    except DecodeError:
                        out.failure()
                        raise
                    return resp
            """})
        assert out == []


# --------------------------------------------------------------------------
# DC601 adaptive-decision settle parity

class TestDecisionParity:
    def test_unsettled_decide_flagged(self, tmp_path):
        out = run_pass(tmp_path, decisionparity, {"filodb_tpu/m.py": """
            def route(model, sig):
                d = model.decide("sidecar", sig, ("a", "b"), "a")
                return "x"
            """})
        assert codes(out) == ["DC601"]

    def test_unsettled_classify_flagged(self, tmp_path):
        out = run_pass(tmp_path, decisionparity, {"filodb_tpu/m.py": """
            def classed(model, sig):
                d = model.classify("admit", sig, 0.05, "cheap",
                                   "expensive", "cheap")
                return d.arm == "cheap"
            """})
        # returning d.arm counts as a return hand-off of d — so settle
        # the bare comparison case by NOT binding d in the return
        assert codes(out) == []
        out = run_pass(tmp_path, decisionparity, {"filodb_tpu/m.py": """
            def classed(model, sig):
                d = model.classify("admit", sig, 0.05, "cheap",
                                   "expensive", "cheap")
                arm = d.arm
                return "ok"
            """})
        assert codes(out) == ["DC601"]

    def test_record_actual_settles(self, tmp_path):
        out = run_pass(tmp_path, decisionparity, {"filodb_tpu/m.py": """
            def route(model, sig, elapsed):
                d = model.decide("paging", sig, ("exact", "wide"), "exact")
                model.record_actual(d, elapsed)
                return d.arm
            """})
        assert out == []

    def test_defer_settles(self, tmp_path):
        out = run_pass(tmp_path, decisionparity, {"filodb_tpu/m.py": """
            def route(model, ctx, sig):
                d = model.decide("sidecar", sig, ("a", "b"), "a")
                model.defer(ctx, d)
                return d.arm == "a"
            """})
        assert out == []

    def test_return_hand_off_settles(self, tmp_path):
        # the lane-router shape: the decision rides out in a tuple and
        # the caller owns the settle
        out = run_pass(tmp_path, decisionparity, {"filodb_tpu/m.py": """
            def shared_decision(model, lanes, lane, sig):
                d = model.decide("lane", sig, tuple(lanes), lane)
                return d.arm, d, model
            """})
        assert out == []

    def test_closure_checked_independently(self, tmp_path):
        # a settle in the enclosing function does not excuse a decide
        # trapped inside a closure that never settles
        out = run_pass(tmp_path, decisionparity, {"filodb_tpu/m.py": """
            def outer(model, sig, elapsed):
                def inner():
                    d = model.decide("sidecar", sig, ("a", "b"), "a")
                    return "x"
                other = model.decide("paging", sig, ("a", "b"), "a")
                model.record_actual(other, elapsed)
                return inner
            """})
        assert codes(out) == ["DC601"]
        assert out[0].symbol == "outer.inner"

    def test_cost_model_module_exempt(self, tmp_path):
        out = run_pass(tmp_path, decisionparity, {
            "filodb_tpu/query/cost_model.py": """
            def helper(self, sig):
                d = self.decide("sidecar", sig, ("a", "b"), "a")
                return "x"
            """})
        assert out == []

    def test_inline_suppression(self, tmp_path):
        root = write_tree(tmp_path, {"filodb_tpu/m.py": """
            def route(model, sig):
                d = model.decide("sidecar", sig, ("a", "b"), "a")  # filolint: disable=DC601
                return "x"
            """})
        assert run_all(root, passes=[decisionparity]) == []


# --------------------------------------------------------------------------
# model: suppression, baseline, CLI

class TestModel:
    def test_inline_suppression(self, tmp_path):
        root = write_tree(tmp_path, {"filodb_tpu/m.py": """
            import threading, time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad(self):
                    with self._lock:
                        time.sleep(1)  # filolint: disable=LD101
            """})
        out = run_all(root, passes=[lockdiscipline])
        assert out == []

    def test_suppression_is_code_scoped(self):
        lines = ["x = 1  # filolint: disable=LD101"]
        assert suppressed(lines, 1, "LD101")
        assert not suppressed(lines, 1, "LD103")
        assert suppressed(["y  # filolint: disable=all"], 1, "HP302")

    def test_key_is_line_free(self):
        a = Finding("LD101", "p.py", 10, "C.m", "d", "msg")
        b = Finding("LD101", "p.py", 99, "C.m", "d", "msg")
        assert a.key == b.key

    def test_baseline_diff_and_update(self, tmp_path):
        f1 = Finding("LD101", "p.py", 1, "C.m", "d1", "m1")
        f2 = Finding("LD101", "p.py", 2, "C.m", "d2", "m2")
        bl = Baseline()
        bl.update([f1])
        bl.entries[f1.key]["justification"] = "intentional"
        new, stale = bl.diff([f1, f2])
        assert [f.key for f in new] == [f2.key]
        assert stale == []
        new, stale = bl.diff([f2])
        assert [e["key"] for e in stale] == [f1.key]
        # update keeps the human-written justification
        bl.update([f1, f2])
        assert bl.entries[f1.key]["justification"] == "intentional"
        assert "TODO" in bl.entries[f2.key]["justification"]
        path = str(tmp_path / "bl.json")
        bl.save(path)
        assert Baseline.load(path).entries == bl.entries

    def test_cli_gate_roundtrip(self, tmp_path, capsys):
        root = write_tree(tmp_path, {"filodb_tpu/m.py": """
            import threading, time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad(self):
                    with self._lock:
                        time.sleep(1)
            """})
        bl = str(tmp_path / "baseline.json")
        assert cli.main(["--root", root, "--baseline", bl]) == 1
        assert cli.main(["--root", root, "--baseline", bl,
                         "--update-baseline"]) == 0
        assert cli.main(["--root", root, "--baseline", bl]) == 0
        out = json.loads(json.dumps(json.load(open(bl))))
        assert out["entries"][0]["code"] == "LD101"
        capsys.readouterr()

    def test_cli_parse_error_exits_2(self, tmp_path, capsys):
        root = write_tree(tmp_path,
                          {"filodb_tpu/bad.py": "def broken(:\n"})
        assert cli.main(["--root", root]) == 2
        capsys.readouterr()

    def test_cli_sarif_output(self, tmp_path, capsys):
        root = write_tree(tmp_path, {"filodb_tpu/m.py": """
            import threading, time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad(self):
                    with self._lock:
                        time.sleep(1)
            """})
        bl = str(tmp_path / "baseline.json")
        assert cli.main(["--root", root, "--baseline", bl,
                         "--format", "sarif"]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        assert run["tool"]["driver"]["name"] == "filolint"
        # the minimal tree also trips the parity placeholders (PR202/4)
        (res,) = [r for r in run["results"] if r["ruleId"] == "LD101"]
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "filodb_tpu/m.py"
        assert loc["region"]["startLine"] > 0
        # line-free key rides along for CI result matching
        assert res["partialFingerprints"]["filolintKey"].startswith(
            "LD101:")
        assert any(r["id"] == "LD101"
                   for r in run["tool"]["driver"]["rules"])

    def test_cli_changed_only_filters_to_diff_scope(self, tmp_path,
                                                    capsys):
        import subprocess

        root = write_tree(tmp_path, {
            "filodb_tpu/clean.py": "X = 1\n",
            "filodb_tpu/m.py": """
            import threading, time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad(self):
                    with self._lock:
                        time.sleep(1)
            """})
        bl = str(tmp_path / "baseline.json")

        def git(*a):
            subprocess.run(["git", *a], cwd=root, check=True,
                           capture_output=True)

        git("init", "-q")
        git("-c", "user.email=t@t", "-c", "user.name=t",
            "commit", "-q", "--allow-empty", "-m", "seed")
        git("add", "-A")
        git("-c", "user.email=t@t", "-c", "user.name=t",
            "commit", "-q", "-m", "base")
        # nothing changed vs HEAD -> the LD101 in m.py is out of scope
        assert cli.main(["--root", root, "--baseline", bl,
                         "--changed-only"]) == 0
        capsys.readouterr()
        # touch m.py -> back in scope
        with open(os.path.join(root, "filodb_tpu", "m.py"), "a") as f:
            f.write("\n")
        assert cli.main(["--root", root, "--baseline", bl,
                         "--changed-only"]) == 1
        capsys.readouterr()

    def test_changed_only_dependent_closure(self, tmp_path):
        # helper.py changed -> caller.py (which imports it) is in scope
        root = write_tree(tmp_path, {
            "filodb_tpu/__init__.py": "",
            "filodb_tpu/helper.py": "def f():\n    return 1\n",
            "filodb_tpu/caller.py":
                "from filodb_tpu.helper import f\n",
            "filodb_tpu/unrelated.py": "Y = 2\n",
        })
        ctx = AnalysisContext.build(root)
        scope = cli._dependent_closure(
            ctx, {"filodb_tpu/helper.py"})
        assert "filodb_tpu/caller.py" in scope
        assert "filodb_tpu/unrelated.py" not in scope


# --------------------------------------------------------------------------
# the repo gate (tier-1 enforcement)

class TestRepoGate:
    def test_repo_has_no_unbaselined_findings(self):
        findings = run_all(REPO_ROOT)
        bl = Baseline.load(BASELINE)
        new, stale = bl.diff(findings)
        assert not new, "new filolint findings (fix or baseline with " \
            "justification):\n" + "\n".join(f.render() for f in new)
        assert not stale, "stale baseline entries (remove them):\n" + \
            "\n".join(e["key"] for e in stale)

    def test_repo_parses_clean(self):
        ctx = AnalysisContext.build(REPO_ROOT)
        assert ctx.errors == []

    def test_every_baseline_entry_is_justified(self):
        bl = Baseline.load(BASELINE)
        assert bl.entries, "baseline should exist and be non-empty"
        unjustified = [k for k, e in bl.entries.items()
                       if not e.get("justification")
                       or "TODO" in e["justification"]]
        assert not unjustified, unjustified
