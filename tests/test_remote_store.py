"""Remote ColumnStore: chunk-server protocol + scan splits + ODP/repair.

The second, networked store implementation behind the same API (reference:
``CassandraColumnStore`` with ``getScanSplits`` token ranges). Crash
recovery over this store runs in test_durability (parameterized); this
module covers the protocol surface, split scans and the repair/ODP jobs.
"""

import numpy as np
import pytest

from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.memstore.partition import TimeSeriesPartition
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.core.store.api import PartKeyRecord
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.core.store.remotestore import (
    ChunkStoreServer,
    RemoteColumnStore,
    RemoteMetaStore,
    StoreOpError,
    split_of,
)
from filodb_tpu.testing.data import (
    gauge_stream,
    machine_metrics_series,
)

START = 1_600_000_000


@pytest.fixture
def server(tmp_path):
    srv = ChunkStoreServer(root=str(tmp_path / "store")).start()
    yield srv
    srv.shutdown()


@pytest.fixture
def cs(server):
    store = RemoteColumnStore("127.0.0.1", server.port)
    yield store
    store.close()


@pytest.fixture
def meta(server):
    store = RemoteMetaStore("127.0.0.1", server.port)
    yield store
    store.close()


def _chunks_for(key, n=100, chunk=50):
    part = TimeSeriesPartition(0, key, DEFAULT_SCHEMAS["gauge"],
                               max_chunk_size=chunk)
    for i in range(n):
        part.ingest((START + i) * 1000, (float(i),))
    return part.make_flush_chunks()


class TestProtocol:
    def test_chunks_round_trip(self, cs):
        key = machine_metrics_series(1)[0]
        chunks = _chunks_for(key)
        cs.write_chunks("ds", 0, key, chunks, ingestion_time=777)
        back = cs.read_chunks("ds", 0, key, 0, 2**62)
        assert [c.id for c in back] == [c.id for c in chunks]
        ts = np.concatenate([c.decode_column(0) for c in back])
        assert len(ts) == 100
        # idempotent rewrite
        cs.write_chunks("ds", 0, key, chunks, ingestion_time=777)
        assert len(cs.read_chunks("ds", 0, key, 0, 2**62)) == len(chunks)

    def test_part_keys_upsert_and_scan(self, cs):
        keys = machine_metrics_series(5)
        cs.write_part_keys("ds", 0, [PartKeyRecord(k, 100, 200)
                                     for k in keys])
        cs.write_part_keys("ds", 0, [PartKeyRecord(keys[0], 150, 999)])
        recs = {r.part_key: r for r in cs.scan_part_keys("ds", 0)}
        assert len(recs) == 5
        assert recs[keys[0]].start_time == 100
        assert recs[keys[0]].end_time == 999

    def test_ingestion_time_scan(self, cs):
        key = machine_metrics_series(1)[0]
        cs.write_chunks("ds", 0, key, _chunks_for(key), ingestion_time=500)
        got = list(cs.scan_chunks_by_ingestion_time("ds", 0, 0, 1000))
        assert len(got) == 1 and got[0][0] == key
        assert len(got[0][1]) == 2
        assert not list(cs.scan_chunks_by_ingestion_time("ds", 0, 1000,
                                                         2000))

    def test_max_persisted_ts(self, cs):
        key = machine_metrics_series(1)[0]
        cs.write_chunks("ds", 0, key, _chunks_for(key), ingestion_time=1)
        floors = cs.max_persisted_ts("ds", 0)
        assert floors[key] == (START + 99) * 1000

    def test_tokens_and_since_scans(self, cs):
        keys = machine_metrics_series(3)
        cs.write_part_keys("ds", 0, [PartKeyRecord(keys[0], 1, 2)])
        ct, pt = cs.update_tokens("ds", 0)
        cs.write_part_keys("ds", 0, [PartKeyRecord(keys[1], 3, 4),
                                     PartKeyRecord(keys[2], 5, 6)])
        newer = cs.scan_part_keys_since("ds", 0, pt)
        assert {r.part_key for r in newer} == {keys[1], keys[2]}

    def test_index_snapshot(self, cs):
        assert cs.read_index_snapshot("ds", 0) is None
        cs.write_index_snapshot("ds", 0, b"snapshot-bytes")
        assert cs.read_index_snapshot("ds", 0) == b"snapshot-bytes"

    def test_checkpoints(self, meta):
        meta.write_checkpoint("ds", 0, 0, 41)
        meta.write_checkpoint("ds", 0, 1, 77)
        meta.write_checkpoint("ds", 0, 0, 42)
        assert meta.read_checkpoints("ds", 0) == {0: 42, 1: 77}

    def test_delete_part_keys(self, cs):
        keys = machine_metrics_series(2)
        for k in keys:
            cs.write_chunks("ds", 0, k, _chunks_for(k), ingestion_time=1)
        cs.write_part_keys("ds", 0, [PartKeyRecord(k, 1, 2) for k in keys])
        cs.delete_part_keys("ds", 0, [keys[0]])
        assert {r.part_key for r in cs.scan_part_keys("ds", 0)} == {keys[1]}
        assert cs.read_chunks("ds", 0, keys[0], 0, 2**62) == []

    def test_truncate(self, cs):
        key = machine_metrics_series(1)[0]
        cs.write_chunks("ds", 0, key, _chunks_for(key), ingestion_time=1)
        cs.truncate("ds")
        assert cs.read_chunks("ds", 0, key, 0, 2**62) == []

    def test_bad_dataset_name_rejected(self, cs):
        with pytest.raises(StoreOpError):
            cs.scan_part_keys("../escape", 0)
        with pytest.raises(StoreOpError):
            cs.scan_part_keys("ds", -4)


class TestScanSplits:
    def test_splits_partition_the_keyspace(self, cs):
        keys = machine_metrics_series(64)
        cs.write_part_keys("ds", 0, [PartKeyRecord(k, 1, 2) for k in keys])
        n_splits = 4
        parts = [cs.scan_part_keys_split("ds", 0, i, n_splits)
                 for i in range(n_splits)]
        # disjoint and complete
        seen = [r.part_key for p in parts for r in p]
        assert len(seen) == len(set(seen)) == 64
        # more than one split actually carries keys (hash spreads)
        assert sum(1 for p in parts if p) >= 2

    def test_split_matches_local_default_impl(self, cs, tmp_path):
        from filodb_tpu.core.store.localstore import (
            LocalDiskColumnStore,
            _pk_blob,
        )
        keys = machine_metrics_series(32)
        recs = [PartKeyRecord(k, 1, 2) for k in keys]
        cs.write_part_keys("ds", 0, recs)
        local = LocalDiskColumnStore(str(tmp_path / "local"))
        local.write_part_keys("ds", 0, recs)
        for i in range(3):
            remote_keys = {r.part_key
                           for r in cs.scan_part_keys_split("ds", 0, i, 3)}
            local_keys = {r.part_key
                          for r in local.scan_part_keys_split("ds", 0, i, 3)}
            assert remote_keys == local_keys
        local.close()

    def test_parallel_split_scan_threads(self, cs):
        from concurrent.futures import ThreadPoolExecutor
        keys = machine_metrics_series(48)
        cs.write_part_keys("ds", 0, [PartKeyRecord(k, 1, 2) for k in keys])
        with ThreadPoolExecutor(max_workers=6) as ex:
            parts = list(ex.map(
                lambda i: cs.scan_part_keys_split("ds", 0, i, 6), range(6)))
        assert sum(len(p) for p in parts) == 48


class TestMemstoreOverRemote:
    def _build(self, server):
        cs = RemoteColumnStore("127.0.0.1", server.port)
        meta = RemoteMetaStore("127.0.0.1", server.port)
        ms = TimeSeriesMemStore(cs, meta)
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=50,
                                              groups_per_shard=2))
        return ms

    def test_flush_and_odp_through_remote(self, server):
        ms = self._build(server)
        shard = ms.get_shard("timeseries", 0)
        keys = machine_metrics_series(4)
        for sd in gauge_stream(keys, 150, start_ms=START * 1000, batch=50):
            shard.ingest(sd)
        shard.flush_all()
        # evict persisted chunks; reads must page them back over the wire
        for pid in range(shard.num_partitions):
            shard.evict_partition_chunks(pid)
        from filodb_tpu.core.memstore.odp import page_partitions
        parts = [shard.partition(pid) for pid in
                 shard.lookup_partitions([], 0, 2**62)]
        extra = page_partitions(shard, parts, START * 1000, 2**62,
                                shard.odp_cache)
        assert extra  # chunks came back from the remote store
        ts, vals = parts[0].read_samples(
            START * 1000, 2**62,
            extra_chunks=extra.get(parts[0].part_id))
        assert len(ts) == 150

    def test_repair_jobs_over_remote(self, server):
        from filodb_tpu.core.store.api import InMemoryColumnStore
        from filodb_tpu.core.store.repair import ChunkCopier
        ms = self._build(server)
        shard = ms.get_shard("timeseries", 0)
        keys = machine_metrics_series(3)
        for sd in gauge_stream(keys, 100, start_ms=START * 1000, batch=50):
            shard.ingest(sd)
        shard.flush_all()
        dst = InMemoryColumnStore()
        stats = ChunkCopier(shard.column_store, dst, "timeseries",
                            1).run(0, 2**62)
        assert stats["partitions"] >= 3
        for k in keys:
            assert dst.read_chunks("timeseries", 0, k, 0, 2**62)


def test_split_of_stability():
    # split assignment must be stable across processes (pure crc32)
    assert split_of(b"some-part-key", 4) == split_of(b"some-part-key", 4)
    spread = {split_of(f"k{i}".encode(), 8) for i in range(100)}
    assert len(spread) >= 6


class TestStandaloneRemoteStore:
    def test_server_with_remote_durability_tier(self, tmp_path):
        """Node A serves its column store over TCP; node B runs with
        store_remote pointing at A — flush + restart recovery go over the
        wire (the CassandraColumnStore deployment shape)."""
        import json as _json
        import socket as _socket
        import time as _time

        from filodb_tpu.config import ServerConfig
        from filodb_tpu.standalone import FiloServer

        srv_store = ChunkStoreServer(root=str(tmp_path / "tier")).start()
        try:
            cfg_path = tmp_path / "server.json"
            cfg_path.write_text(_json.dumps({
                "node_name": "b", "data_dir": str(tmp_path / "b"),
                "http_port": 0, "gateway_port": 0,
                "store_remote": f"127.0.0.1:{srv_store.port}",
                "datasets": {"timeseries": {
                    "num_shards": 1, "spread": 0,
                    "store": {"max_chunk_size": 20,
                              "groups_per_shard": 1}}},
            }))
            cfg = ServerConfig.load(str(cfg_path))
            with _socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                gport = s.getsockname()[1]
            object.__setattr__(cfg, "gateway_port", gport)
            node = FiloServer(cfg).start()
            try:
                with _socket.create_connection(("127.0.0.1", gport)) as s:
                    for i in range(50):
                        ts_ns = (START + i * 10) * 1_000_000_000
                        s.sendall(f"remote_m,host=h1,_ws_=demo,_ns_=App-0 "
                                  f"value={i} {ts_ns}\n".encode())
                deadline = _time.monotonic() + 10
                shard = node.memstore.get_shard("timeseries", 0)
                while _time.monotonic() < deadline \
                        and shard.stats.rows_ingested.value < 50:
                    node.gateway.sink.flush()
                    _time.sleep(0.2)
                shard.flush_all()
            finally:
                node.shutdown()
            # chunks landed in the remote tier, not node-local sqlite
            probe = RemoteColumnStore("127.0.0.1", srv_store.port)
            recs = probe.scan_part_keys("timeseries", 0)
            assert len(recs) == 1
            chunks = probe.read_chunks("timeseries", 0, recs[0].part_key,
                                       0, 2**62)
            assert chunks and sum(c.num_rows for c in chunks) >= 20
            probe.close()
        finally:
            srv_store.shutdown()
