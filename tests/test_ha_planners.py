"""HA / federation planner tests.

Mirrors reference ``HighAvailabilityPlannerSpec``,
``ShardKeyRegexPlannerSpec``, ``SinglePartitionPlannerSpec``,
``LogicalPlanParserSpec``: routing around failures via a live replica
server, regex shard-key fan-out, and PromQL reconstruction round-trips.
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.ha_planner import (
    HighAvailabilityPlanner,
    MultiPartitionPlanner,
    PartitionLocationProvider,
    ShardKeyRegexPlanner,
    SinglePartitionPlanner,
    StaticFailureProvider,
    TimeRange,
)
from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.http.server import FiloHttpServer
from filodb_tpu.promql.parser import TimeStepParams, parse_query
from filodb_tpu.query.exec.plan import ExecContext, StitchRvsExec
from filodb_tpu.query.logical_parser import to_promql
from filodb_tpu.testing.data import gauge_stream, machine_metrics_series

START = 1_600_000_000


class TestLogicalPlanParser:
    """Round-trip: parse → render → parse again gives the same plan."""

    CASES = [
        'heap_usage{_ws_="demo",_ns_="App-1"}',
        'rate(http_requests_total{_ws_="d",_ns_="n"}[5m])',
        'sum(rate(m[5m]))',
        'sum by (job) (rate(m[1m]))',
        'topk(5, sum by (app) (rate(cpu[1m])))',
        'histogram_quantile(0.99, sum(rate(lat[5m])) by (le))',
        '(sum(rate(a[1m])) / sum(rate(b[1m])))',
        'quantile_over_time(0.9, m[10m])',
        'predict_linear(m[30m], 3600)',
        'absent(m{job="x"})',
        'label_replace(m, "d", "$1", "s", "(.*)")',
        'max_over_time(rate(m[1m])[30m:1m])',
        'scalar(sum(m))',
        'vector(5)',
        '(m > bool 5)',
        '(a and b)',
        'count_values("version", build_info)',
    ]

    @pytest.mark.parametrize("query", CASES)
    def test_round_trip(self, query):
        params = TimeStepParams(START, 60, START + 3600)
        p1 = parse_query(query, params)
        text = to_promql(p1)
        p2 = parse_query(text, params)
        assert p1 == p2, f"{query} -> {text}"


def _mk_service(n_series=6, ns="App-1", nss=None):
    ms = TimeSeriesMemStore()
    for s in range(4):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=100))
    for one_ns in (nss or [ns]):
        keys = machine_metrics_series(n_series, ns=one_ns)
        ingest_routed(ms, "timeseries",
                      gauge_stream(keys, 400, start_ms=START * 1000), 4, 1)
    return QueryService(ms, "timeseries", 4, spread=1)


class TestHighAvailabilityPlanner:
    def test_no_failures_stays_local(self):
        svc = _mk_service()
        planner = HighAvailabilityPlanner(
            "timeseries", svc.planner, StaticFailureProvider([]),
            "http://127.0.0.1:1/promql/timeseries")
        plan = parse_query("sum(heap_usage)",
                           TimeStepParams(START, 60, START + 1200))
        ep = planner.materialize(plan)
        assert not isinstance(ep, StitchRvsExec)
        ctx = ExecContext(svc.memstore, "timeseries")
        assert ep.dispatcher.dispatch(ep, ctx).result.num_series == 1

    def test_failure_routes_to_replica(self):
        # replica = a live HTTP server over an identical dataset
        replica_svc = _mk_service()
        http = FiloHttpServer({"timeseries": replica_svc}, port=0).start()
        try:
            local_svc = _mk_service()
            fail_start = (START + 600) * 1000
            fail_end = (START + 1200) * 1000
            planner = HighAvailabilityPlanner(
                "timeseries", local_svc.planner,
                StaticFailureProvider([TimeRange(fail_start, fail_end)]),
                f"http://127.0.0.1:{http.port}/promql/timeseries")
            plan = parse_query(
                'sum(sum_over_time(heap_usage{_ws_="demo",_ns_="App-1"}[2m]))',
                TimeStepParams(START + 300, 60, START + 2400))
            ep = planner.materialize(plan)
            assert isinstance(ep, StitchRvsExec)
            reprs = repr(ep.tree_str())
            assert "PromQlRemoteExec" in reprs
            ctx = ExecContext(local_svc.memstore, "timeseries")
            result = ep.dispatcher.dispatch(ep, ctx).result
            # compare against a pure local run (data identical on both sides)
            direct = local_svc.query_range(
                'sum(sum_over_time(heap_usage{_ws_="demo",_ns_="App-1"}[2m]))',
                START + 300, 60, START + 2400).result
            assert result.num_steps == direct.num_steps
            np.testing.assert_allclose(result.values, direct.values,
                                       rtol=1e-6, equal_nan=True)
        finally:
            http.stop()


class TestShardKeyRegexPlanner:
    def test_fanout_sum(self):
        svc = _mk_service(nss=["App-0", "App-1", "App-2"])

        def matcher(filters):
            return [{"_ws_": "demo", "_ns_": f"App-{i}"} for i in range(3)]

        planner = ShardKeyRegexPlanner(svc.planner, matcher)
        plan = parse_query('sum(heap_usage{_ws_="demo",_ns_=~"App.*"})',
                           TimeStepParams(START + 300, 300, START + 900))
        ep = planner.materialize(plan)
        ctx = ExecContext(svc.memstore, "timeseries")
        result = ep.dispatcher.dispatch(ep, ctx).result
        assert result.num_series == 1
        # equals sum over all 18 series
        direct = svc.query_range('sum({__name__="heap_usage"})',
                                 START + 300, 300, START + 900).result
        np.testing.assert_allclose(result.values, direct.values, rtol=1e-9)

    def test_fanout_avg_not_pushed_down(self):
        svc = _mk_service(nss=["App-0", "App-1"])

        def matcher(filters):
            return [{"_ws_": "demo", "_ns_": f"App-{i}"} for i in range(2)]

        planner = ShardKeyRegexPlanner(svc.planner, matcher)
        plan = parse_query('avg(heap_usage{_ws_="demo",_ns_=~"App.*"})',
                           TimeStepParams(START + 300, 300, START + 900))
        ep = planner.materialize(plan)
        ctx = ExecContext(svc.memstore, "timeseries")
        result = ep.dispatcher.dispatch(ep, ctx).result
        direct = svc.query_range('avg({__name__="heap_usage"})',
                                 START + 300, 300, START + 900).result
        np.testing.assert_allclose(result.values, direct.values, rtol=1e-9)

    def test_no_regex_passthrough(self):
        svc = _mk_service()
        planner = ShardKeyRegexPlanner(svc.planner, lambda f: [])
        plan = parse_query('sum(heap_usage{_ws_="demo",_ns_="App-1"})',
                           TimeStepParams(START + 300, 300, START + 900))
        ep = planner.materialize(plan)
        ctx = ExecContext(svc.memstore, "timeseries")
        assert ep.dispatcher.dispatch(ep, ctx).result.num_series == 1


class TestSingleAndMultiPartition:
    def test_single_partition_selector(self):
        svc = _mk_service()
        chosen = []

        class Probe(SingleClusterPlanner):
            def materialize(self, plan, q=None):
                chosen.append(self.dataset)
                return super().materialize(plan, q)

        p_raw = Probe("timeseries", 4, 1)
        p_ds = Probe("other", 4, 1)
        planner = SinglePartitionPlanner(
            planners={"raw": p_raw, "ds": p_ds},
            select=lambda plan: "raw", default="raw")
        plan = parse_query("heap_usage",
                           TimeStepParams(START, 300, START + 600))
        planner.materialize(plan)
        assert chosen == ["timeseries"]

    def test_multipartition_local(self):
        svc = _mk_service()

        class Loc(PartitionLocationProvider):
            def partition_of(self, shard_key):
                return "local"

            def endpoint_of(self, partition):
                return "http://nowhere"

        planner = MultiPartitionPlanner(Loc(), "local", svc.planner)
        plan = parse_query('sum(heap_usage{_ws_="demo",_ns_="App-1"})',
                           TimeStepParams(START, 300, START + 600))
        ep = planner.materialize(plan)
        ctx = ExecContext(svc.memstore, "timeseries")
        assert ep.dispatcher.dispatch(ep, ctx).result.num_series == 1

    def test_multipartition_remote_plan(self):
        svc = _mk_service()

        class Loc(PartitionLocationProvider):
            def partition_of(self, shard_key):
                return "other-cluster"

            def endpoint_of(self, partition):
                return "http://replica:8080/promql/timeseries"

        planner = MultiPartitionPlanner(Loc(), "local", svc.planner)
        plan = parse_query('sum(heap_usage{_ws_="demo",_ns_="App-1"})',
                           TimeStepParams(START, 300, START + 600))
        ep = planner.materialize(plan)
        from filodb_tpu.query.exec.remote_exec import PromQlRemoteExec
        assert isinstance(ep, PromQlRemoteExec)
        assert "sum" in ep.promql
