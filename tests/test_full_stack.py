"""Full-stack capstone: one server with EVERYTHING on — device-page decode,
streaming downsampling, gateway ingestion, WAL persistence + segmented
retention, query via the client API — then a restart recovery.

The closest single-test analog of running the whole reference stack
(FiloServer + Kafka + Cassandra + downsampler) end to end.
"""

import json
import socket
import time

import numpy as np
import pytest

from filodb_tpu.client import FiloClient
from filodb_tpu.config import ServerConfig
from filodb_tpu.standalone import FiloServer

START = 1_600_000_000


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture
def cfg_path(tmp_path):
    p = tmp_path / "server.json"
    p.write_text(json.dumps({
        "node_name": "full-stack",
        "data_dir": str(tmp_path / "data"),
        "http_port": 0,
        "gateway_port": _free_port(),
        "datasets": {"timeseries": {
            "num_shards": 2, "spread": 1,
            "store": {"max_chunk_size": 60, "groups_per_shard": 2,
                      "flush_interval_ms": 400, "device_pages": True,
                      "retention_ms": 10**15},
            "downsample": {"streaming": True, "resolutions_ms": [300000],
                           "schedule_s": 3600,
                           "raw_retention_ms": 10**15}}},
    }))
    return str(p)


def test_everything_on(cfg_path, tmp_path):
    srv = FiloServer(ServerConfig.load(cfg_path)).start()
    try:
        client = FiloClient(port=srv.http.port)
        assert client.health()

        # 1. ingest 40 min of gauges + counters for 6 hosts via the gateway
        with socket.create_connection(("127.0.0.1",
                                       srv.gateway.port)) as s:
            for i in range(240):
                ts_ns = (START + i * 10) * 1_000_000_000
                for h in range(6):
                    s.sendall(
                        f"cpu,host=h{h},_ws_=demo,_ns_=full "
                        f"value={40 + h + (i % 5)} {ts_ns}\n".encode())
                    s.sendall(
                        f"reqs,host=h{h},_ws_=demo,_ns_=full "
                        f"counter={i * (h + 2)} {ts_ns}\n".encode())
        srv.gateway.sink.flush()

        # 2. wait until ingested, then query through the device-page path
        deadline = time.monotonic() + 20
        ok = False
        while time.monotonic() < deadline:
            res = client.query_range("count(cpu)", START + 2390,
                                     START + 2390, 60)
            if res and float(res[0]["values"][0][1]) == 6:
                ok = True
                break
            time.sleep(0.2)
        assert ok, "gauges not fully ingested"

        labels, values, steps = client.query_range_matrix(
            "sum(rate(reqs[5m]))", START + 600, START + 2300, 60)
        assert values.shape[0] == 1
        finite = values[np.isfinite(values)]
        # sum of per-host slopes: sum((h+2)/10) = 2.7/sec
        np.testing.assert_allclose(np.median(finite), 2.7, rtol=0.05)

        # 3. streaming downsample rollups materialized and flushed
        flush_deadline = time.monotonic() + 20
        ds_ok = False
        while time.monotonic() < flush_deadline:
            try:
                n = sum(srv.memstore.get_shard("timeseries_ds_5m", s)
                        .num_partitions for s in range(2))
                if n >= 6:
                    ds_ok = True
                    break
            except KeyError:
                pass
            time.sleep(0.3)
        assert ds_ok, "streaming rollups missing"

        # 4. chunks + checkpoints persisted (flush scheduler ran)
        persist_deadline = time.monotonic() + 25
        persisted = 0
        while time.monotonic() < persist_deadline:
            persisted = sum(
                len(srv.column_store.scan_part_keys("timeseries", s))
                for s in range(2))
            if persisted >= 12:
                break
            time.sleep(0.3)
        assert persisted >= 12  # 6 cpu + 6 reqs series

        topk = client.query("topk(2, cpu)", START + 2390)
        assert len(topk) == 2
    finally:
        srv.shutdown()

    # 5. restart on the same data dir: WAL replay + index bootstrap restore
    srv2 = FiloServer(ServerConfig.load(cfg_path)).start()
    try:
        client = FiloClient(port=srv2.http.port)
        deadline = time.monotonic() + 20
        n = 0
        while time.monotonic() < deadline:
            res = client.query_range("count_over_time(cpu[40m])",
                                     START + 2395, START + 2395, 60)
            if res:
                n = sum(float(s["values"][0][1]) for s in res)
                if n == 6 * 240:
                    break
            time.sleep(0.3)
        assert n == 6 * 240, f"recovery incomplete: {n}"
    finally:
        srv2.shutdown()
