"""Native (C++) codec parity + arena tests.

The native tier mirrors the reference's off-heap layer (UnsafeUtils/jffi,
NibblePack.scala, BlockManager.scala); these tests pin byte-identical output
against the pure-python reference implementation.
"""

import numpy as np
import pytest

from filodb_tpu.memory import native
from filodb_tpu.memory.nibblepack import (
    nibble_pack_py,
    nibble_unpack_py,
)

pytestmark = pytest.mark.skipif(native.get_lib() is None,
                                reason="native toolchain unavailable")


class TestNativeNibblePack:
    def cases(self):
        rng = np.random.default_rng(9)
        yield np.zeros(100, np.uint64)
        yield np.arange(1, 100, dtype=np.uint64)
        yield rng.integers(0, 2**63, 1000, dtype=np.uint64)
        yield rng.integers(0, 16, 777, dtype=np.uint64)
        yield np.array([2**64 - 1, 0, 1, 0xFFF0, 0x1000], np.uint64)
        yield (rng.integers(0, 2**40, 64, dtype=np.uint64) << np.uint64(12))
        yield np.array([], np.uint64)

    def test_pack_byte_identical(self):
        for v in self.cases():
            assert native.nibble_pack_native(v) == nibble_pack_py(v)

    def test_unpack_round_trip(self):
        for v in self.cases():
            packed = nibble_pack_py(v)
            out = native.nibble_unpack_native(packed, len(v))
            np.testing.assert_array_equal(out, v)

    def test_unpack_python_packed_native(self):
        v = np.random.default_rng(1).integers(0, 2**50, 333, dtype=np.uint64)
        packed = native.nibble_pack_native(v)
        np.testing.assert_array_equal(nibble_unpack_py(packed, len(v)), v)

    def test_truncated_stream_raises(self):
        v = np.arange(100, dtype=np.uint64) * 1000
        packed = nibble_pack_py(v)
        with pytest.raises(ValueError):
            native.nibble_unpack_native(packed[: len(packed) // 2], 100)


class TestNativeXor:
    def test_round_trip(self):
        v = np.random.default_rng(2).normal(size=500)
        enc = native.xor_encode_native(v)
        out = native.xor_decode_native(enc)
        np.testing.assert_array_equal(out, v)

    def test_matches_numpy(self):
        v = np.array([1.5, 1.5, 2.25, -0.5, np.nan, 0.0])
        enc = native.xor_encode_native(v)
        bits = v.view(np.uint64)
        prev = np.concatenate([[np.uint64(0)], bits[:-1]])
        np.testing.assert_array_equal(enc, bits ^ prev)


class TestArena:
    def test_alloc_write_read(self):
        arena = native.NativeArena(block_size=4096)
        b = arena.alloc_block(owner=7)
        off = arena.block_alloc(b, 100)
        assert off == 0
        arena.write(b, off, b"hello world")
        assert arena.read(b, off, 11) == b"hello world"
        off2 = arena.block_alloc(b, 50)
        assert off2 == 104  # 8-byte aligned bump
        arena.close()

    def test_block_full(self):
        arena = native.NativeArena(block_size=4096)
        b = arena.alloc_block(owner=1)
        assert arena.block_alloc(b, 4000) == 0
        assert arena.block_alloc(b, 200) == -1  # full
        assert arena.block_remaining(b) == 4096 - 4000
        arena.close()

    def test_reclaim_and_reuse(self):
        arena = native.NativeArena(block_size=4096)
        for _ in range(5):
            arena.alloc_block(owner=1)
        arena.alloc_block(owner=2)
        stats = arena.stats
        assert stats["allocated_blocks"] == 6
        assert stats["bytes_in_use"] == 6 * 4096
        assert arena.reclaim_owner(1) == 5
        assert arena.stats["bytes_in_use"] == 4096
        # reclaimed blocks are reused, not re-allocated
        for _ in range(5):
            arena.alloc_block(owner=3)
        assert arena.stats["allocated_blocks"] == 6
        arena.close()
