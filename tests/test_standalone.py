"""Standalone server + CLI smoke tests (reference: FiloServer boot +
filo-cli commands)."""

import json
import socket
import time
import urllib.request

import pytest

from filodb_tpu.cli import main as cli_main
from filodb_tpu.config import ServerConfig
from filodb_tpu.standalone import FiloServer

START = 1_600_000_000


@pytest.fixture
def server(tmp_path):
    cfg_path = tmp_path / "server.json"
    cfg_path.write_text(json.dumps({
        "node_name": "test-node",
        "data_dir": str(tmp_path / "data"),
        "http_port": 0,
        "gateway_port": 0,
        "datasets": {"timeseries": {
            "num_shards": 2, "spread": 1,
            "store": {"max_chunk_size": 100, "groups_per_shard": 2}}},
    }))
    cfg = ServerConfig.load(str(cfg_path))
    # enable gateway on an ephemeral port
    object.__setattr__(cfg, "gateway_port", _free_port())
    srv = FiloServer(cfg).start()
    yield srv, tmp_path
    srv.shutdown()


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestFiloServer:
    def test_ingest_via_gateway_then_query(self, server):
        srv, tmp_path = server
        with socket.create_connection(("127.0.0.1",
                                       srv.gateway.port)) as s:
            for i in range(120):
                ts_ns = (START + i * 10) * 1_000_000_000
                s.sendall(f"cpu_usage,host=h1,_ws_=demo,_ns_=App-0 "
                          f"value={50 + i % 7} {ts_ns}\n".encode())
        # wait for the ingestion workers to drain the log
        deadline = time.monotonic() + 10
        got = 0
        while time.monotonic() < deadline:
            srv.gateway.sink.flush()
            code, body = _get(srv.http.port,
                              "/promql/timeseries/api/v1/query_range",
                              query="count_over_time(cpu_usage[10m])",
                              start=START + 1200, end=START + 1200, step=60)
            res = body["data"]["result"]
            if res and float(res[0]["values"][0][1]) >= 59:
                got = float(res[0]["values"][0][1])
                break
            time.sleep(0.1)
        assert got == 59.0  # 10m window @10s, left-exclusive

    def test_health_and_status(self, server):
        srv, _ = server
        code, body = _get(srv.http.port, "/__health")
        assert body["status"] == "healthy"
        code, body = _get(srv.http.port, "/api/v1/cluster/timeseries/status")
        assert len(body["data"]) == 2

    def test_restart_recovers_from_wal(self, server):
        srv, tmp_path = server
        with socket.create_connection(("127.0.0.1", srv.gateway.port)) as s:
            for i in range(50):
                ts_ns = (START + i * 10) * 1_000_000_000
                s.sendall(f"mem_usage,_ws_=demo,_ns_=App-0 value={i} "
                          f"{ts_ns}\n".encode())
        time.sleep(0.3)
        srv.gateway.sink.flush()
        time.sleep(0.3)
        srv.shutdown()
        # restart on the same data dir: WAL replay restores the data
        cfg = ServerConfig.load(None)
        object.__setattr__(cfg, "data_dir", str(tmp_path / "data"))
        object.__setattr__(cfg, "http_port", 0)
        cfg.datasets = {k: v for k, v in cfg.datasets.items()}
        srv2 = FiloServer(cfg).start()
        try:
            deadline = time.monotonic() + 10
            n = 0
            while time.monotonic() < deadline:
                code, body = _get(
                    srv2.http.port, "/promql/timeseries/api/v1/query_range",
                    query="count_over_time(mem_usage[10m])",
                    start=START + 500, end=START + 500, step=60)
                res = body["data"]["result"]
                if res:
                    n = float(res[0]["values"][0][1])
                    if n == 50:
                        break
                time.sleep(0.1)
            assert n == 50.0
        finally:
            srv2.shutdown()


class TestCli:
    def test_importcsv_and_promql(self, tmp_path, capsys):
        csv_path = tmp_path / "data.csv"
        lines = []
        for i in range(100):
            lines.append(f"{(START + i * 10) * 1000},{i * 1.5},"
                         f"host=h1,_ws_=demo,_ns_=App-0")
        csv_path.write_text("\n".join(lines))
        data_dir = str(tmp_path / "clidata")
        cli_main(["--data-dir", data_dir, "--num-shards", "2", "importcsv",
                  str(csv_path), "--metric", "cli_metric"])
        out = capsys.readouterr().out
        assert "imported 100 samples" in out
        cli_main(["--data-dir", data_dir, "--num-shards", "2", "promql",
                  "max_over_time(cli_metric[20m])",
                  "--start", str(START + 990), "--end", str(START + 990)])
        out = capsys.readouterr().out
        body = json.loads(out)
        assert body["data"]["result"]
        assert float(body["data"]["result"][0]["values"][0][1]) == 99 * 1.5
        cli_main(["--data-dir", data_dir, "--num-shards", "2", "list"])
        out = capsys.readouterr().out
        assert "total partitions: 1" in out
        cli_main(["--data-dir", data_dir, "--num-shards", "2",
                  "decodechunks", "--verbose"])
        out = capsys.readouterr().out
        assert "chunks" in out


def _get(port, path, **params):
    import urllib.parse
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    with urllib.request.urlopen(url) as r:
        return r.status, json.loads(r.read())


class TestTopkCard:
    def test_topkcard(self, tmp_path, capsys):
        csv_path = tmp_path / "d.csv"
        csv_path.write_text("\n".join(
            f"{(START + i * 10) * 1000},{i},host=h{i % 3},_ws_=demo,_ns_=App-0"
            for i in range(30)))
        data_dir = str(tmp_path / "cd")
        cli_main(["--data-dir", data_dir, "--num-shards", "2", "importcsv",
                  str(csv_path), "--metric", "card_metric"])
        capsys.readouterr()
        cli_main(["--data-dir", data_dir, "--num-shards", "2", "topkcard",
                  "--prefix", "demo"])
        out = capsys.readouterr().out
        assert "App-0" in out and "series=3" in out


class TestServerDownsampling:
    def test_downsample_plane_boots(self, tmp_path):
        import time as _time
        cfg_path = tmp_path / "ds.json"
        cfg_path.write_text(json.dumps({
            "node_name": "ds-node", "data_dir": str(tmp_path / "d"),
            "http_port": 0, "gateway_port": 0,
            "datasets": {"timeseries": {
                "num_shards": 2, "spread": 1,
                "store": {"max_chunk_size": 50, "groups_per_shard": 2},
                "downsample": {"resolutions_ms": [300000],
                               "schedule_s": 1,
                               "raw_retention_ms": 3600000}}},
        }))
        srv = FiloServer(ServerConfig.load(str(cfg_path))).start()
        try:
            from filodb_tpu.coordinator.longtime_planner import (
                LongTimeRangePlanner,
            )
            svc = srv.http.services["timeseries"]
            assert isinstance(svc.planner, LongTimeRangePlanner)
            # feed data via the WAL, flush, let the job produce ds chunks
            from filodb_tpu.coordinator.ingestion import route_container
            from filodb_tpu.testing.data import (
                gauge_stream,
                machine_metrics_series,
            )
            keys = machine_metrics_series(2)
            for sd in gauge_stream(keys, 120, start_ms=START * 1000):
                for shard, cont in route_container(sd.container, 2,
                                                   1).items():
                    srv.logs[("timeseries", shard)].append(cont)
            deadline = _time.monotonic() + 15
            got = 0
            while _time.monotonic() < deadline:
                for node in srv.cluster.nodes.values():
                    for s in node.owned_shards("timeseries"):
                        node.memstore.get_shard("timeseries", s).flush_all()
                recs = sum(
                    len(srv.column_store.scan_part_keys(
                        "timeseries_ds_5m", s)) for s in range(2))
                if recs >= 2:
                    got = recs
                    break
                _time.sleep(0.5)
            assert got >= 2  # downsampler produced ds part keys
        finally:
            srv.shutdown()
