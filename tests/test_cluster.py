"""Cluster tests: shard assignment, multi-node scatter-gather queries, TCP
plan shipping, node failure → reassignment → recovery.

Mirrors the reference's coordinator specs + multi-jvm cluster specs
(``ShardManagerSpec``, ``ClusterRecoverySpec``, ``NodeClusterSpec``) — nodes
here are in-process (own memstores) sharing the column store + log, with the
same recovery semantics; plan shipping additionally runs over real TCP.
"""

import time

import numpy as np
import pytest

from filodb_tpu.coordinator.cluster import FilodbCluster, Node
from filodb_tpu.coordinator.ingestion import route_container
from filodb_tpu.coordinator.remote import PlanExecutorServer, RemotePlanDispatcher
from filodb_tpu.coordinator.shard_manager import ShardManager
from filodb_tpu.coordinator.shardmapper import ShardMapper, ShardStatus
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.record import SomeData
from filodb_tpu.core.store.api import InMemoryColumnStore, InMemoryMetaStore
from filodb_tpu.core.store.config import IngestionConfig, StoreConfig
from filodb_tpu.kafka.log import InMemoryLog
from filodb_tpu.testing.data import gauge_stream, machine_metrics_series

START = 1_600_000_000
NUM_SHARDS = 4


class TestShardManager:
    def test_assignment_balanced(self):
        sm = ShardManager("ds", 8, min_num_nodes=2)
        sm.add_member("n1")
        sm.add_member("n2")
        assert len(sm.mapper.shards_of("n1")) == 4
        assert len(sm.mapper.shards_of("n2")) == 4
        assert sm.mapper.unassigned_shards() == []

    def test_member_removed_reassigns(self):
        sm = ShardManager("ds", 8, min_num_nodes=2)
        for n in ("n1", "n2", "n3"):
            sm.add_member(n)
        # n1/n2 filled to the min-num-nodes cap (4 each); n3 idle standby
        assert len(sm.mapper.shards_of("n1")) == 4
        assert len(sm.mapper.shards_of("n3")) == 0
        evs = sm.remove_member("n1")
        down = [e for e in evs if e.status == ShardStatus.DOWN]
        assert len(down) == 4
        # the standby absorbs the lost shards
        assert sm.mapper.unassigned_shards() == []
        assert len(sm.mapper.shards_of("n2")) == 4
        assert len(sm.mapper.shards_of("n3")) == 4

    def test_subscriber_resync(self):
        sm = ShardManager("ds", 4)
        sm.add_member("n1")
        seen = []
        sm.subscribe(lambda ev: seen.append(ev))
        assert len(seen) == 4  # replay of current state

    def test_min_nodes_gate(self):
        sm = ShardManager("ds", 4, min_num_nodes=2)
        sm.add_member("n1")
        sm.add_member("n2")
        sm.remove_member("n2")
        # only one node left (< min): shards stay down
        assert len(sm.mapper.shards_of("n1")) <= 4


def _mk_cluster(shared_cs, shared_meta, names):
    cluster = FilodbCluster()
    for n in names:
        cluster.join(Node(n, TimeSeriesMemStore(shared_cs, shared_meta)))
    return cluster


def _publish(logs, stream, num_shards, spread=1):
    for sd in stream:
        for shard, cont in route_container(sd.container, num_shards,
                                           spread).items():
            logs[shard].append(cont)


@pytest.fixture
def cluster_env():
    cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
    logs = {s: InMemoryLog() for s in range(NUM_SHARDS)}
    keys = machine_metrics_series(12, ns="App-3")
    _publish(logs, gauge_stream(keys, 240, start_ms=START * 1000), NUM_SHARDS)
    cluster = _mk_cluster(cs, meta, ["node-a", "node-b", "node-c"])
    config = IngestionConfig("timeseries", NUM_SHARDS, min_num_nodes=2,
                             store=StoreConfig(max_chunk_size=60,
                                               groups_per_shard=2))
    cluster.setup_dataset(config, logs)
    assert cluster.wait_active("timeseries", 10)
    yield cluster, logs, keys, cs, meta
    cluster.stop()


class TestClusterQuery:
    def test_scatter_gather_across_nodes(self, cluster_env):
        cluster, logs, keys, *_ = cluster_env
        # both nodes own shards
        assert cluster.nodes["node-a"].owned_shards("timeseries")
        assert cluster.nodes["node-b"].owned_shards("timeseries")
        svc = cluster.query_service("timeseries", spread=1)
        r = svc.query_range('count(heap_usage{_ns_="App-3"})',
                            START + 600, 60, START + 2000)
        assert r.result.num_series == 1
        np.testing.assert_array_equal(r.result.values[0], 12.0)

    def test_query_all_series_found(self, cluster_env):
        cluster, *_ = cluster_env
        svc = cluster.query_service("timeseries", spread=1)
        r = svc.query_range('heap_usage{_ns_="App-3"}',
                            START + 600, 300, START + 1500)
        assert r.result.num_series == 12

    def test_node_kill_reassign_recover(self, cluster_env):
        cluster, logs, keys, cs, meta = cluster_env
        svc = cluster.query_service("timeseries", spread=1)
        r1 = svc.query_range('sum(heap_usage{_ns_="App-3"})',
                             START + 600, 300, START + 1500)
        # flush so the checkpoint/recovery path has data to skip
        for node in cluster.nodes.values():
            for shard in node.owned_shards("timeseries"):
                node.memstore.get_shard("timeseries", shard).flush_all()
        # kill node-b; failure detector reassigns; survivors recover from the
        # shared column store + log (checkpointed replay)
        cluster.start_failure_detector()
        killed_shards = cluster.nodes["node-b"].owned_shards("timeseries")
        assert killed_shards
        cluster.nodes["node-b"].kill()
        t0 = time.monotonic()
        while time.monotonic() - t0 < 10:
            if ("node-b" not in cluster.nodes
                    and cluster.wait_active("timeseries", 0.05)):
                break
            time.sleep(0.02)
        assert "node-b" not in cluster.nodes
        owned_now = (cluster.nodes["node-a"].owned_shards("timeseries")
                     + cluster.nodes["node-c"].owned_shards("timeseries"))
        assert sorted(owned_now) == list(range(NUM_SHARDS))
        svc2 = cluster.query_service("timeseries", spread=1)
        r2 = svc2.query_range('sum(heap_usage{_ns_="App-3"})',
                              START + 600, 300, START + 1500)
        np.testing.assert_allclose(r2.result.values, r1.result.values,
                                   rtol=1e-9)


class TestRemoteDispatch:
    def test_tcp_plan_shipping(self):
        from filodb_tpu.coordinator.ingestion import ingest_routed
        from filodb_tpu.coordinator.planner import SingleClusterPlanner
        from filodb_tpu.promql.parser import TimeStepParams, parse_query
        from filodb_tpu.query.exec.plan import ExecContext

        # "remote" node with the data
        ms_remote = TimeSeriesMemStore()
        for s in range(2):
            ms_remote.setup("timeseries", s, StoreConfig(max_chunk_size=60))
        keys = machine_metrics_series(6)
        ingest_routed(ms_remote, "timeseries",
                      gauge_stream(keys, 120, start_ms=START * 1000), 2, 1)
        server = PlanExecutorServer(ms_remote).start()
        try:
            # local planner ships every leaf over TCP
            disp = RemotePlanDispatcher("127.0.0.1", server.port)
            assert disp.ping()
            planner = SingleClusterPlanner(
                "timeseries", 2, spread=1,
                dispatcher_for_shard=lambda s: disp)
            plan = parse_query("sum(heap_usage)",
                               TimeStepParams(START + 300, 60, START + 1000))
            ep = planner.materialize(plan)
            ms_local = TimeSeriesMemStore()  # empty: all data is remote
            ctx = ExecContext(ms_local, "timeseries")
            result = ep.dispatcher.dispatch(ep, ctx).result
            assert result.num_series == 1
            assert np.isfinite(result.values).all()
        finally:
            server.stop()

    def test_remote_error_propagates(self):
        ms = TimeSeriesMemStore()
        server = PlanExecutorServer(ms).start()
        try:
            from filodb_tpu.query.exec.plan import (
                ExecContext,
                SelectRawPartitionsExec,
            )
            disp = RemotePlanDispatcher("127.0.0.1", server.port)
            # missing shard → remote raises → surfaced locally
            leaf = SelectRawPartitionsExec(shard=9, filters=(),
                                           chunk_start=0, chunk_end=1)
            with pytest.raises(RuntimeError, match="remote execution failed"):
                disp.dispatch(leaf, ExecContext(None, "timeseries"))
        finally:
            server.stop()

    def test_ping_dead_server(self):
        disp = RemotePlanDispatcher("127.0.0.1", 1, timeout=0.3)
        assert not disp.ping()


class TestFlushScheduler:
    def test_scheduled_flush_persists_chunks(self):
        import time as _time
        cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
        cluster = FilodbCluster()
        node = Node("n1", TimeSeriesMemStore(cs, meta), flush_tick_s=0.05)
        cluster.join(node)
        logs = {0: InMemoryLog(), 1: InMemoryLog()}
        keys = machine_metrics_series(4)
        _publish(logs, gauge_stream(keys, 120, start_ms=START * 1000), 2)
        config = IngestionConfig(
            "timeseries", 2,
            store=StoreConfig(max_chunk_size=30, groups_per_shard=2))
        cluster.setup_dataset(config, logs)
        assert cluster.wait_active("timeseries", 5)
        # scheduler flushes groups on its own; sealed chunks reach the store
        deadline = _time.monotonic() + 10
        total = 0
        while _time.monotonic() < deadline:
            total = sum(len(cs.read_chunks("timeseries", s, k, 0, 2**62))
                        for s in range(2) for k in keys)
            if total >= 4 * 3:  # 120 samples / 30 per chunk per series
                break
            _time.sleep(0.1)
        assert total >= 4 * 3
        # checkpoints advanced too
        cps = {}
        for s in range(2):
            cps.update(meta.read_checkpoints("timeseries", s))
        assert cps
        cluster.stop()
