"""Distributed (shard × time mesh) query tests on the virtual 8-device CPU
mesh: the sharded sum(rate()) must match the single-device kernel exactly.

Counterpart of the reference's multi-jvm distributed query tests
(``coordinator/src/multi-jvm/...``) — here distribution is an SPMD program, so
"multi-node" correctness is exercised by sharding over virtual devices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from filodb_tpu.parallel.dist_query import (
    make_distributed_sum_rate,
    pad_for_mesh,
)
from filodb_tpu.query.engine import kernels
from filodb_tpu.query.engine.aggregations import aggregate
from filodb_tpu.query.engine.batch import TS_PAD


def make_series(P=12, S=200, seed=0, resets=True):
    rng = np.random.default_rng(seed)
    ts = np.full((P, S), TS_PAD, np.int32)
    vals = np.zeros((P, S), np.float64)
    counts = np.zeros(P, np.int32)
    for p in range(P):
        n = int(rng.integers(S // 2, S))
        t = np.cumsum(rng.integers(5_000, 15_000, n))
        v = np.cumsum(rng.integers(0, 20, n)).astype(float)
        if resets and n > 50:
            r = int(rng.integers(20, n - 10))
            v[r:] -= v[r]
        ts[p, :n] = t
        vals[p, :n] = v
        counts[p] = n
    return ts, vals, counts


@pytest.fixture(scope="module")
def mesh():
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    return Mesh(devs, ("shard", "time"))


class TestDistributedSumRate:
    def test_matches_single_device(self, mesh):
        P, S = 12, 200
        ts, vals, counts = make_series(P, S)
        gids = np.arange(P, dtype=np.int32) % 3
        steps = np.arange(600_000, 1_500_000, 60_000, dtype=np.int32)
        window = np.int32(300_000)

        # single-device reference
        rate = np.asarray(kernels.range_eval(
            "rate", jnp.asarray(ts), jnp.asarray(vals), jnp.asarray(counts),
            jnp.asarray(steps), jnp.asarray(window)))
        expect = np.asarray(aggregate("sum", jnp.asarray(rate),
                                      jnp.asarray(gids), 3))

        # distributed
        ts_p, vals_p, valid, gid_p = pad_for_mesh(ts, vals, counts, gids, mesh)
        fn = make_distributed_sum_rate(mesh, 3)
        out = np.asarray(fn(jnp.asarray(ts_p), jnp.asarray(vals_p),
                            jnp.asarray(valid), jnp.asarray(gid_p),
                            jnp.asarray(steps), jnp.asarray(window)))
        np.testing.assert_allclose(out, expect, rtol=1e-9, atol=1e-12,
                                   equal_nan=True)

    def test_boundary_resets_handled(self, mesh):
        # counters that reset exactly around time-block boundaries
        P, S = 4, 160
        ts = np.full((P, S), TS_PAD, np.int32)
        vals = np.zeros((P, S), np.float64)
        counts = np.full(P, S, np.int32)
        for p in range(P):
            t = np.arange(S, dtype=np.int64) * 10_000 + 10_000
            v = np.cumsum(np.ones(S)) * (p + 1)
            # reset at the exact S/2 boundary (where the time axis splits)
            v[S // 2:] -= v[S // 2]
            ts[p] = t
            vals[p] = v
        gids = np.zeros(P, np.int32)
        steps = np.array([900_000, 1_200_000], dtype=np.int32)
        window = np.int32(600_000)

        rate = np.asarray(kernels.range_eval(
            "rate", jnp.asarray(ts), jnp.asarray(vals), jnp.asarray(counts),
            jnp.asarray(steps), jnp.asarray(window)))
        expect = np.asarray(aggregate("sum", jnp.asarray(rate),
                                      jnp.asarray(gids), 1))
        ts_p, vals_p, valid, gid_p = pad_for_mesh(ts, vals, counts, gids, mesh)
        fn = make_distributed_sum_rate(mesh, 1)
        out = np.asarray(fn(jnp.asarray(ts_p), jnp.asarray(vals_p),
                            jnp.asarray(valid), jnp.asarray(gid_p),
                            jnp.asarray(steps), jnp.asarray(window)))
        np.testing.assert_allclose(out, expect, rtol=1e-9, equal_nan=True)

    def test_empty_groups_nan(self, mesh):
        P, S = 4, 64
        ts, vals, counts = make_series(P, S, seed=5)
        gids = np.zeros(P, np.int32)
        steps = np.array([10], dtype=np.int32)  # before any data
        window = np.int32(5)
        ts_p, vals_p, valid, gid_p = pad_for_mesh(ts, vals, counts, gids, mesh)
        fn = make_distributed_sum_rate(mesh, 2)
        out = np.asarray(fn(jnp.asarray(ts_p), jnp.asarray(vals_p),
                            jnp.asarray(valid), jnp.asarray(gid_p),
                            jnp.asarray(steps), jnp.asarray(window)))
        assert np.isnan(out).all()


class TestDistributedRangeAggFamily:
    @pytest.mark.parametrize("fn,agg", [
        ("sum_over_time", "sum"), ("count_over_time", "sum"),
        ("avg_over_time", "avg"), ("min_over_time", "min"),
        ("max_over_time", "max"), ("last_over_time", "sum"),
    ])
    def test_matches_single_device(self, mesh, fn, agg):
        from filodb_tpu.parallel.dist_query import make_distributed_range_agg

        P_, S = 8, 128
        ts, vals, counts = make_series(P_, S, seed=11, resets=False)
        gids = np.arange(P_, dtype=np.int32) % 2
        steps = np.arange(400_000, 1_000_000, 60_000, dtype=np.int32)
        window = np.int32(300_000)
        per_series = np.asarray(kernels.range_eval(
            fn, jnp.asarray(ts), jnp.asarray(vals), jnp.asarray(counts),
            jnp.asarray(steps), jnp.asarray(window)))
        expect = np.asarray(aggregate(agg, jnp.asarray(per_series),
                                      jnp.asarray(gids), 2))
        ts_p, vals_p, valid, gid_p = pad_for_mesh(ts, vals, counts, gids,
                                                  mesh)
        f = make_distributed_range_agg(mesh, fn, 2, agg)
        out = np.asarray(f(jnp.asarray(ts_p), jnp.asarray(vals_p),
                           jnp.asarray(valid), jnp.asarray(gid_p),
                           jnp.asarray(steps), jnp.asarray(window)))
        np.testing.assert_allclose(out, expect, rtol=1e-9, atol=1e-12,
                                   equal_nan=True, err_msg=f"{fn}/{agg}")


class TestRingVariant:
    def test_ring_matches_gather(self, mesh):
        from filodb_tpu.parallel.dist_query import (
            make_distributed_sum_rate_ring,
        )

        P_, S = 12, 200
        ts, vals, counts = make_series(P_, S, seed=21)
        gids = np.arange(P_, dtype=np.int32) % 3
        steps = np.arange(600_000, 1_500_000, 60_000, dtype=np.int32)
        window = np.int32(300_000)
        ts_p, vals_p, valid, gid_p = pad_for_mesh(ts, vals, counts, gids,
                                                  mesh)
        gather_fn = make_distributed_sum_rate(mesh, 3)
        ring_fn = make_distributed_sum_rate_ring(mesh, 3)
        a = np.asarray(gather_fn(jnp.asarray(ts_p), jnp.asarray(vals_p),
                                 jnp.asarray(valid), jnp.asarray(gid_p),
                                 jnp.asarray(steps), jnp.asarray(window)))
        b = np.asarray(ring_fn(jnp.asarray(ts_p), jnp.asarray(vals_p),
                               jnp.asarray(valid), jnp.asarray(gid_p),
                               jnp.asarray(steps), jnp.asarray(window)))
        np.testing.assert_allclose(b, a, rtol=1e-9, atol=1e-12,
                                   equal_nan=True)

    def test_ring_extrapolation_sensitive(self, mesh):
        """First sample arrives late (time-block 0 empty for some series):
        extrapolation depends on the true global t_first — a zero-polluted
        ring combine would diverge here."""
        from filodb_tpu.parallel.dist_query import (
            make_distributed_sum_rate_ring,
        )

        P_, S = 8, 128
        ts = np.full((P_, S), TS_PAD, np.int32)
        vals = np.zeros((P_, S), np.float64)
        counts = np.zeros(P_, np.int32)
        rng = np.random.default_rng(33)
        for p in range(P_):
            n = 40  # few samples, all landing in the SECOND time block
            t0 = 900_000 + p * 1000
            ts[p, :n] = t0 + np.arange(n) * 10_000
            vals[p, :n] = np.cumsum(rng.integers(1, 10, n)).astype(float)
            counts[p] = n
        gids = np.zeros(P_, np.int32)
        steps = np.array([1_400_000, 1_500_000], dtype=np.int32)
        window = np.int32(900_000)  # window start long before first sample
        rate = np.asarray(kernels.range_eval(
            "rate", jnp.asarray(ts), jnp.asarray(vals), jnp.asarray(counts),
            jnp.asarray(steps), jnp.asarray(window)))
        expect = np.asarray(aggregate("sum", jnp.asarray(rate),
                                      jnp.asarray(gids), 1))
        ts_p, vals_p, valid, gid_p = pad_for_mesh(ts, vals, counts, gids,
                                                  mesh)
        ring_fn = make_distributed_sum_rate_ring(mesh, 1)
        out = np.asarray(ring_fn(jnp.asarray(ts_p), jnp.asarray(vals_p),
                                 jnp.asarray(valid), jnp.asarray(gid_p),
                                 jnp.asarray(steps), jnp.asarray(window)))
        np.testing.assert_allclose(out, expect, rtol=1e-9, equal_nan=True)
