"""Regex filter rewriting (FastRegexMatcher analog) + index parity.

Reference leans on Lucene's regex automata (``PartKeyLuceneIndex.scala:455``);
here literal/alternation/prefix analysis rewrites regex filters into
postings lookups and narrowed scans. Every rewrite must return EXACTLY the
ids of the naive full value scan, on both the native and pure-Python index
tiers.
"""

import os

import pytest

from filodb_tpu.core.filters import (
    ColumnFilter,
    Equals,
    EqualsRegex,
    NotEqualsRegex,
    regex_plan,
)
from filodb_tpu.core.memstore.index import FrozenLabel, PartKeyIndex
from filodb_tpu.core.partkey import PartKey


class TestRegexPlan:
    def test_literal(self):
        assert regex_plan("api") == ("literal", "api")
        assert regex_plan("api-server_1") == ("literal", "api-server_1")

    def test_alternation_of_literals(self):
        assert regex_plan("a|b|c") == ("alts", ["a", "b", "c"])
        assert regex_plan("up|down") == ("alts", ["up", "down"])

    def test_nested_alternation_not_alts(self):
        kind, _ = regex_plan("a(b|c)")
        assert kind == "prefix"
        kind, _ = regex_plan("(a|b)c")
        assert kind == "scan"

    def test_prefix_extraction(self):
        assert regex_plan("api-.*") == ("prefix", "api-")
        assert regex_plan("i5.*") == ("prefix", "i5")
        # the char before a quantifier is NOT part of the fixed prefix
        assert regex_plan("abc*") == ("prefix", "ab")
        assert regex_plan("abc?d") == ("prefix", "ab")

    def test_no_prefix_scan(self):
        assert regex_plan(".*foo") == ("scan", None)
        assert regex_plan("[ab]x") == ("scan", None)

    def test_escapes_stay_conservative(self):
        # "\." could be a literal dot, but we don't claim it
        kind, _ = regex_plan(r"a\.b")
        assert kind == "prefix"
        assert regex_plan(r"a\.b")[1] == "a"
        assert regex_plan(r"a|b\|c") == ("scan", None) \
            or regex_plan(r"a|b\|c")[0] == "scan"


class TestFrozenPrefixRange:
    def build(self, values):
        pairs = [(v.encode(), [i]) for i, v in enumerate(values)]
        return FrozenLabel.build(pairs), sorted(v.encode() for v in values)

    def test_basic_range(self):
        fr, svals = self.build(["apple", "apricot", "banana", "cherry",
                                "ap", "apz"])
        lo, hi = fr.prefix_range(b"ap")
        got = [fr.value(vi) for vi in range(lo, hi)]
        assert got == [v for v in svals if v.startswith(b"ap")]

    def test_no_match(self):
        fr, _ = self.build(["a", "b", "c"])
        lo, hi = fr.prefix_range(b"zz")
        assert lo == hi

    def test_prefix_with_0xff_suffix(self):
        fr, svals = self.build(["a\xffb", "a\xffc", "b"])
        lo, hi = fr.prefix_range("a\xff".encode())
        got = [fr.value(vi) for vi in range(lo, hi)]
        assert got == [v for v in svals
                       if v.startswith("a\xff".encode())]

    def test_full_table(self):
        fr, svals = self.build([f"v{i:03d}" for i in range(50)])
        lo, hi = fr.prefix_range(b"v")
        assert (lo, hi) == (0, 50)
        lo, hi = fr.prefix_range(b"v01")
        assert hi - lo == 10


def _build_index(native: bool):
    if not native:
        os.environ["FILODB_NO_NATIVE_INDEX"] = "1"
    try:
        idx = PartKeyIndex()
    finally:
        os.environ.pop("FILODB_NO_NATIVE_INDEX", None)
    for i in range(400):
        key = PartKey.create("gauge", {
            "_metric_": f"m{i % 4}", "app": f"app-{i % 10}",
            "instance": f"inst{i:03d}"})
        idx.add_part_key(i, key, start_time=0, end_time=10**15)
    return idx


@pytest.mark.parametrize("native", [True, False])
class TestIndexRegexParity:
    """Rewritten paths must match a naive full-scan reference result."""

    def _naive(self, idx, col, flt):
        import re
        rx = re.compile(f"^(?:{flt.pattern})$")
        out = set()
        for pid in range(400):
            k = idx.part_key(pid)
            if k is None:
                continue
            v = k.label_map.get(col)
            if v is not None and rx.match(v):
                out.add(pid)
        return out

    def _query(self, idx, col, pattern, extra_eq=None):
        filters = [ColumnFilter(col, EqualsRegex(pattern))]
        if extra_eq:
            filters.append(ColumnFilter(*extra_eq))
        return set(idx.part_ids_from_filters(filters, 0, 2**62))

    def test_literal_rewrite(self, native):
        idx = _build_index(native)
        assert self._query(idx, "app", "app-3") == \
            self._naive(idx, "app", EqualsRegex("app-3"))

    def test_alts_rewrite(self, native):
        idx = _build_index(native)
        got = self._query(idx, "app", "app-1|app-5|app-9")
        assert got == self._naive(idx, "app", EqualsRegex("app-1|app-5|app-9"))
        assert len(got) == 120

    def test_prefix_rewrite(self, native):
        idx = _build_index(native)
        got = self._query(idx, "instance", "inst01.*")
        assert got == self._naive(idx, "instance", EqualsRegex("inst01.*"))
        assert len(got) == 10

    def test_scan_fallback(self, native):
        idx = _build_index(native)
        got = self._query(idx, "instance", ".*5")
        assert got == self._naive(idx, "instance", EqualsRegex(".*5"))

    def test_regex_with_equals_combo(self, native):
        idx = _build_index(native)
        got = self._query(idx, "instance", "inst0.*",
                          extra_eq=("app", Equals("app-7")))
        naive = self._naive(idx, "instance", EqualsRegex("inst0.*"))
        eq = {pid for pid in range(400)
              if idx.part_key(pid).label_map.get("app") == "app-7"}
        assert got == naive & eq

    def test_regex_only_query(self, native):
        idx = _build_index(native)
        got = self._query(idx, "app", "app-[02].*")
        assert got == self._naive(idx, "app", EqualsRegex("app-[02].*"))

    def test_time_bounds_respected(self, native):
        idx = _build_index(native)
        idx.update_end_time(5, 100)  # pid 5 ended long ago
        filters = [ColumnFilter("app", EqualsRegex("app-5"))]
        got = set(idx.part_ids_from_filters(filters, 200, 2**62))
        assert 5 not in got
        assert 15 in got

    def test_not_regex_unchanged(self, native):
        idx = _build_index(native)
        filters = [ColumnFilter("app", NotEqualsRegex("app-[0-8]"))]
        got = set(idx.part_ids_from_filters(filters, 0, 2**62))
        assert got == {pid for pid in range(400)
                       if idx.part_key(pid).label_map["app"] == "app-9"}


class TestCharClassSoundness:
    """Review regression: metachars inside character classes must not
    desync the alternation splitter (verified query-dropping bug)."""

    def test_class_hides_alternation(self):
        assert regex_plan("a[(]x|y") == ("scan", None)
        assert regex_plan("x[]]|y") == ("scan", None)
        assert regex_plan("a[^]]b|c") == ("scan", None)

    @pytest.mark.parametrize("native", [True, False])
    def test_query_results_not_dropped(self, native):
        if not native:
            os.environ["FILODB_NO_NATIVE_INDEX"] = "1"
        try:
            idx = PartKeyIndex()
        finally:
            os.environ.pop("FILODB_NO_NATIVE_INDEX", None)
        for pid, app in enumerate(["a(x", "y", "zz"]):
            idx.add_part_key(pid, PartKey.create("gauge", {
                "_metric_": "m", "app": app}), 0, 10**15)
        got = set(idx.part_ids_from_filters(
            [ColumnFilter("app", EqualsRegex("a[(]x|y"))], 0, 2**62))
        assert got == {0, 1}
