"""Networked ingest log (reference KafkaIngestionStream contract: one log
partition == one shard, containers over the network, no shared FS)."""

import pytest

from filodb_tpu.kafka.log_server import LogServer, RemoteLog
from filodb_tpu.testing.data import gauge_stream, machine_metrics_series


@pytest.fixture
def server(tmp_path):
    srv = LogServer(str(tmp_path / "broker")).start()
    yield srv
    srv.stop()


def containers(n, start_ms=0):
    keys = machine_metrics_series(1)
    return [sd.container for sd in gauge_stream(keys, n, batch=1,
                                                start_ms=start_ms)]


class TestRemoteLog:
    def test_append_read_round_trip(self, server):
        lg = RemoteLog("127.0.0.1", server.port, "ds", 0)
        for i, c in enumerate(containers(10)):
            assert lg.append(c) == i
        assert lg.latest_offset == 9
        entries = list(lg.read_from(0))
        assert [e.offset for e in entries] == list(range(10))
        # records parse back into real containers
        recs = list(entries[0].container)
        assert recs[0].timestamp == 0
        lg.close()

    def test_partition_isolation(self, server):
        l0 = RemoteLog("127.0.0.1", server.port, "ds", 0)
        l1 = RemoteLog("127.0.0.1", server.port, "ds", 1)
        for c in containers(3):
            l0.append(c)
        assert l1.latest_offset == -1
        assert list(l1.read_from(0)) == []
        l0.close()
        l1.close()

    def test_tail_from_offset_and_batching(self, server):
        lg = RemoteLog("127.0.0.1", server.port, "ds", 0, read_batch=4)
        for c in containers(11):
            lg.append(c)
        assert [e.offset for e in lg.read_from(5)] == [5, 6, 7, 8, 9, 10]
        lg.close()

    def test_durability_across_server_restart(self, server, tmp_path):
        lg = RemoteLog("127.0.0.1", server.port, "ds", 0)
        for c in containers(6):
            lg.append(c)
        lg.close()
        server.stop()
        srv2 = LogServer(str(tmp_path / "broker")).start()
        lg2 = RemoteLog("127.0.0.1", srv2.port, "ds", 0)
        assert lg2.latest_offset == 5
        assert len(list(lg2.read_from(0))) == 6
        # truncation + offset alignment work remotely
        assert lg2.truncate_before(10) == 0  # single segment retained
        lg2.align_after(100)
        c = containers(1, start_ms=10**9)[0]
        assert lg2.append(c) == 101
        lg2.close()
        srv2.stop()

    def test_auth_required(self, tmp_path, monkeypatch):
        srv = LogServer(str(tmp_path / "b2"), secret="brokersecret").start()
        try:
            lg = RemoteLog("127.0.0.1", srv.port, "ds", 0)
            with pytest.raises((ConnectionError, RuntimeError, OSError)):
                lg.append(containers(1)[0])
            monkeypatch.setenv("FILODB_CLUSTER_SECRET", "brokersecret")
            lg2 = RemoteLog("127.0.0.1", srv.port, "ds", 0)
            assert lg2.append(containers(1)[0]) == 0
            lg2.close()
        finally:
            srv.stop()


class TestClusterOverNetworkedLog:
    def test_gateway_and_owner_without_shared_fs(self, tmp_path):
        """Full in-process cluster against a broker: gateway sink produces
        to the log server; the shard's ingest worker tails it remotely."""
        from filodb_tpu.coordinator.cluster import FilodbCluster, Node
        from filodb_tpu.coordinator.query_service import QueryService
        from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
        from filodb_tpu.core.store.api import (
            InMemoryColumnStore,
            InMemoryMetaStore,
        )
        from filodb_tpu.core.store.config import IngestionConfig, StoreConfig
        from filodb_tpu.gateway.server import ContainerSink

        srv = LogServer(str(tmp_path / "broker")).start()
        try:
            num_shards = 2
            ms = TimeSeriesMemStore(InMemoryColumnStore(),
                                    InMemoryMetaStore())
            node = Node("n0", ms)
            cluster = FilodbCluster()
            cluster.join(node)
            logs = {s: RemoteLog("127.0.0.1", srv.port, "ts", s)
                    for s in range(num_shards)}
            cfg = IngestionConfig(dataset="ts", num_shards=num_shards,
                                  store=StoreConfig(max_chunk_size=100,
                                                    groups_per_shard=2))
            cluster.setup_dataset(cfg, logs)
            # the gateway produces through ITS OWN remote handles
            sink_logs = {s: RemoteLog("127.0.0.1", srv.port, "ts", s)
                         for s in range(num_shards)}
            sink = ContainerSink(sink_logs, num_shards, spread=1)
            from filodb_tpu.gateway.influx import parse_influx_line
            for i in range(50):
                for app in ("a", "b", "c"):
                    sink.add(parse_influx_line(
                        f"m_net,app={app} value={i} "
                        f"{(1_600_000_000 + i * 10) * 10**9}"))
            sink.flush()
            import time
            svc = QueryService(ms, "ts", num_shards, spread=1)
            for _ in range(100):
                r = svc.query_instant("count(m_net)", 1_600_000_000 + 500)
                if r.result.num_series and r.result.values[0, 0] == 3:
                    break
                time.sleep(0.05)
            assert r.result.values[0, 0] == 3
            total = sum(p.num_samples
                        for s in ms.shards_for("ts")
                        for p in s.partitions if p is not None)
            assert total == 150
        finally:
            node.kill()
            srv.stop()


class TestWireValidation:
    """Wire-supplied dataset/shard become filesystem path components; the
    broker must reject anything that could escape its root (ADVICE r2)."""

    def test_path_traversal_dataset_rejected(self, server, tmp_path):
        from filodb_tpu.kafka.log_server import LogOpError
        lg = RemoteLog("127.0.0.1", server.port, "../../evil", 0)
        with pytest.raises(LogOpError, match="invalid dataset"):
            lg.append(containers(1)[0])
        # nothing escaped the broker root
        assert not (tmp_path / "evil").exists()
        lg.close()

    def test_bad_shard_types_rejected(self, server):
        from filodb_tpu.kafka.log_server import LogOpError
        for bad in ("0/../..", -1, 10**9, True):
            lg = RemoteLog("127.0.0.1", server.port, "ds", bad)
            with pytest.raises(LogOpError, match="invalid shard"):
                lg.latest_offset
            lg.close()

    def test_slash_and_dot_names_rejected(self, server):
        from filodb_tpu.kafka.log_server import LogOpError
        for bad in ("a/b", "..", ".", "", "x" * 200):
            lg = RemoteLog("127.0.0.1", server.port, bad, 0)
            with pytest.raises(LogOpError, match="invalid dataset"):
                lg.latest_offset
            lg.close()

    def test_server_error_is_log_op_error_not_transport(self, server):
        """Deterministic server-side errors raise LogOpError (a RuntimeError
        subclass), so retry loops can distinguish them from transport
        failures and stop spinning (ADVICE r2 low)."""
        from filodb_tpu.kafka.log_server import LogOpError
        lg = RemoteLog("127.0.0.1", server.port, "../../x", 3)
        try:
            lg.latest_offset
        except LogOpError as e:
            assert isinstance(e, RuntimeError)
        else:
            raise AssertionError("expected LogOpError")
        lg.close()

    def test_valid_names_still_work(self, server):
        lg = RemoteLog("127.0.0.1", server.port, "prod-metrics_v2.1", 42)
        assert lg.append(containers(1)[0]) == 0
        assert lg.latest_offset == 0
        lg.close()

    def test_newline_dataset_rejected(self, server):
        from filodb_tpu.kafka.log_server import LogOpError
        lg = RemoteLog("127.0.0.1", server.port, "evil\n", 0)
        with pytest.raises(LogOpError, match="invalid dataset"):
            lg.latest_offset
        lg.close()

    def test_read_batch_capped(self, server):
        """A huge max_n must not make the broker materialize the whole log
        in one reply."""
        from filodb_tpu.kafka.log_server import MAX_READ_BATCH
        lg = RemoteLog("127.0.0.1", server.port, "ds", 7)
        for c in containers(3):
            lg.append(c)
        batch = lg._call("read", "ds", 7, 0, 10**18)
        assert len(batch) == 3  # served, but the cap bounds any reply
        assert MAX_READ_BATCH >= 256  # sane floor for real tailing
        assert lg._call("read", "ds", 7, 0, -5) == []
        from filodb_tpu.kafka.log_server import LogOpError
        with pytest.raises(LogOpError, match="invalid read"):
            lg._call("read", "ds", 7, "zero", 10)
        lg.close()

    def test_client_read_batch_clamped_to_server_cap(self, server):
        """A client read_batch above the broker cap must not break
        end-of-log detection (short-batch sentinel)."""
        from filodb_tpu.kafka.log_server import MAX_READ_BATCH
        lg = RemoteLog("127.0.0.1", server.port, "ds", 9,
                       read_batch=MAX_READ_BATCH * 2)
        assert lg.read_batch == MAX_READ_BATCH
        for c in containers(5):
            lg.append(c)
        assert len(list(lg.read_from(0))) == 5
        lg.close()
