"""Chaos suite: fault-injection tests for the distributed query path.

Kills real executors and arms :class:`FaultInjector` faults at the
instrumented sites to exercise partial scatter-gather, breaker skips, retry
exhaustion and deadline enforcement. Deterministic: retries are configured
with zero backoff and deadlines run on injected clocks — no wall-clock
sleeps.
"""

import pytest

from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.coordinator.remote import (
    PlanExecutorServer,
    RemotePlanDispatcher,
    _pool,
    reset_pool,
)
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.promql.parser import TimeStepParams, parse_query
from filodb_tpu.query.exec.plan import (
    ExecContext,
    SelectRawPartitionsExec,
)
from filodb_tpu.testing.data import gauge_stream, machine_metrics_series
from filodb_tpu.utils import resilience
from filodb_tpu.utils.resilience import (
    CircuitOpenError,
    Deadline,
    DeadlineExceeded,
    FaultInjector,
    ResilienceConfig,
    breaker_for,
    reset_breakers,
)

pytestmark = pytest.mark.chaos

START = 1_600_000_000
NUM_SHARDS = 4


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def now(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(autouse=True)
def _clean():
    FaultInjector.reset()
    reset_breakers()
    reset_pool()
    # fail-fast posture: no backoff sleeps, short dials
    resilience.configure(retry_max_attempts=1, retry_base_backoff_s=0.0,
                         retry_max_backoff_s=0.0)
    yield
    FaultInjector.reset()
    reset_breakers()
    reset_pool()
    resilience._config = ResilienceConfig()


@pytest.fixture
def scatter_env():
    """4 remote executors (one per shard) behind one populated memstore;
    the planner ships each shard's leaf to its own executor."""
    ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=60))
    keys = machine_metrics_series(8)
    ingest_routed(ms, "timeseries",
                  gauge_stream(keys, 120, start_ms=START * 1000),
                  NUM_SHARDS, 2)
    servers = [PlanExecutorServer(ms).start() for _ in range(NUM_SHARDS)]
    disps = {s: RemotePlanDispatcher("127.0.0.1", servers[s].port,
                                     timeout=2.0)
             for s in range(NUM_SHARDS)}
    planner = SingleClusterPlanner(
        "timeseries", NUM_SHARDS, spread=2,
        dispatcher_for_shard=lambda s: disps[s])
    yield servers, disps, planner
    for srv in servers:
        srv.stop()


def _materialize(planner):
    plan = parse_query("sum(heap_usage)",
                       TimeStepParams(START + 300, 60, START + 1000))
    return planner.materialize(plan)


def _execute(ep):
    ctx = ExecContext(TimeSeriesMemStore(), "timeseries",
                      deadline=Deadline.after(30.0))
    return ep.dispatcher.dispatch(ep, ctx)


class TestPartialScatterGather:
    def test_all_executors_up_is_complete(self, scatter_env):
        _, _, planner = scatter_env
        result = _execute(_materialize(planner))
        assert not result.partial
        assert result.warnings == []
        assert result.result.num_series == 1

    def test_one_killed_executor_yields_partial(self, scatter_env):
        servers, _, planner = scatter_env
        servers[2].stop()  # shard 2's executor dies before the scatter
        result = _execute(_materialize(planner))
        assert result.partial
        assert len(result.warnings) == 1
        # the warning names the lost shards
        assert "shards [2]" in result.warnings[0]
        assert result.result.num_series == 1  # 3 of 4 shards still answer

    def test_failures_above_threshold_fail_query(self, scatter_env):
        servers, _, planner = scatter_env
        for s in (0, 1, 3):
            servers[s].stop()  # 3/4 lost > 0.5 threshold
        with pytest.raises(ConnectionError,
                           match="scatter-gather children failed"):
            _execute(_materialize(planner))

    def test_allow_partial_off_fails_on_first_loss(self, scatter_env):
        servers, _, planner = scatter_env
        servers[2].stop()
        resilience.configure(allow_partial=False)
        with pytest.raises((ConnectionError, OSError)):
            _execute(_materialize(planner))

    def test_injected_child_fault_names_shard(self, scatter_env):
        _, _, planner = scatter_env
        # exact match: the site also fires for enclosing subtrees that span
        # every shard — only the single-shard leaf child should die
        FaultInjector.arm("gather.child", error=ConnectionError, times=1,
                          match=lambda ctx: ctx["shards"] == [1])
        result = _execute(_materialize(planner))
        assert result.partial
        assert "shards [1]" in result.warnings[0]

    def test_deadline_exceeded_is_never_partial(self, scatter_env):
        _, _, planner = scatter_env
        clk = FakeClock()
        # one slow child burns the whole deadline; the query must FAIL with
        # a timeout, not degrade to a partial result
        FaultInjector.arm("gather.child", delay_s=100.0, times=1,
                          sleep=clk.advance,
                          match=lambda ctx: ctx["shards"] == [0])
        ep = _materialize(planner)
        ctx = ExecContext(TimeSeriesMemStore(), "timeseries",
                          deadline=Deadline.after(30.0, clock=clk.now))
        with pytest.raises(DeadlineExceeded):
            ep.dispatcher.dispatch(ep, ctx)


class TestBreakerIntegration:
    def test_open_breaker_peer_is_skipped(self, scatter_env):
        _, disps, planner = scatter_env
        breaker_for(disps[3].peer).force_open()
        result = _execute(_materialize(planner))
        assert result.partial
        assert "CircuitOpenError" in result.warnings[0]
        assert "shards [3]" in result.warnings[0]

    def test_repeated_failures_open_breaker(self, scatter_env):
        servers, disps, planner = scatter_env
        resilience.configure(breaker_failure_threshold=2)
        servers[1].stop()
        ep = _materialize(planner)
        _execute(ep)  # failure 1 for shard 1's peer
        _execute(ep)  # failure 2 → breaker opens
        assert breaker_for(disps[1].peer).is_open
        # next query skips the peer without dialing: the dispatch site
        # never fires for the open peer
        fault = FaultInjector.arm("remote.dispatch")  # counts, no error
        result = _execute(ep)
        assert result.partial
        assert fault.fired == NUM_SHARDS - 1  # all but the open peer

    def test_dispatch_to_open_breaker_raises_without_dial(self):
        disp = RemotePlanDispatcher("127.0.0.1", 1)  # nothing listens
        breaker_for(disp.peer).force_open()
        connects = FaultInjector.arm("remote.connect")
        leaf = SelectRawPartitionsExec(shard=0, filters=(), chunk_start=0,
                                       chunk_end=1)
        with pytest.raises(CircuitOpenError):
            disp.dispatch(leaf, ExecContext(None, "timeseries"))
        assert connects.fired == 0

    def test_deadline_expiry_is_not_a_breaker_failure(self):
        """Regression: a burst of tight-deadline queries must not open a
        healthy peer's breaker — the deadline expires before dialing."""
        resilience.configure(breaker_failure_threshold=1)
        disp = RemotePlanDispatcher("127.0.0.1", 1)
        clk = FakeClock()
        leaf = SelectRawPartitionsExec(shard=0, filters=(), chunk_start=0,
                                       chunk_end=1)
        ctx = ExecContext(None, "timeseries",
                          deadline=Deadline.after(1.0, clock=clk.now))
        clk.advance(2.0)
        with pytest.raises(DeadlineExceeded):
            disp.dispatch(leaf, ctx)
        assert breaker_for(disp.peer).state == "closed"


class TestRetryBehavior:
    def test_retry_exhausts_budget_and_fails(self):
        resilience.configure(retry_max_attempts=3)
        before = resilience._retries_total.value
        fault = FaultInjector.arm("remote.dispatch", error=ConnectionError)
        disp = RemotePlanDispatcher("127.0.0.1", 1)
        leaf = SelectRawPartitionsExec(shard=0, filters=(), chunk_start=0,
                                       chunk_end=1)
        with pytest.raises(ConnectionError):
            disp.dispatch(leaf, ExecContext(None, "timeseries"))
        assert fault.fired == 3  # initial attempt + 2 retries
        assert resilience._retries_total.value == before + 2

    def test_stale_pooled_socket_retries_on_fresh_connection(self,
                                                             scatter_env):
        servers, disps, planner = scatter_env
        resilience.configure(retry_max_attempts=2)
        disp = disps[0]

        def leaves(p):
            cs = p.children()
            return [p] if not cs else [x for c in cs for x in leaves(c)]

        leaf = next(x for x in leaves(_materialize(planner))
                    if x.dispatcher is disp)
        assert disp.ping()  # pools a socket
        # the peer restarted: the pooled socket is dead but not yet noticed
        for sock in _pool._idle[(disp.host, disp.port)]:
            sock.close()
        result = disp.dispatch(leaf, ExecContext(None, "timeseries"))
        assert result.result is not None  # transparently redialed


class TestRemoteStoreFaults:
    @pytest.fixture
    def store_env(self, tmp_path):
        from filodb_tpu.core.store.remotestore import ChunkStoreServer
        srv = ChunkStoreServer(root=str(tmp_path)).start()
        yield srv
        srv.shutdown()

    def test_stale_pooled_socket_retries(self, store_env):
        from filodb_tpu.core.store.remotestore import _RemoteConn
        conn = _RemoteConn("127.0.0.1", store_env.port)
        assert conn.call("ping") is True
        conn._sock.close()  # server restarted under us
        assert conn.call("ping") is True  # one retry on a fresh socket

    def test_injected_fault_consumed_by_retry(self, store_env):
        from filodb_tpu.core.store.remotestore import _RemoteConn
        conn = _RemoteConn("127.0.0.1", store_env.port)
        assert conn.call("ping") is True  # pool a socket first
        fault = FaultInjector.arm("store.call", error=ConnectionError,
                                  times=1)
        assert conn.call("ping") is True  # fault hits, fresh-socket retry
        assert fault.fired == 1

    def test_persistent_failure_opens_breaker(self, store_env):
        from filodb_tpu.core.store.remotestore import _RemoteConn
        resilience.configure(breaker_failure_threshold=2)
        conn = _RemoteConn("127.0.0.1", store_env.port)
        FaultInjector.arm("store.call", error=ConnectionError)
        for _ in range(2):
            with pytest.raises(ConnectionError):
                conn.call("ping")
        assert breaker_for(conn.peer).is_open
        with pytest.raises(CircuitOpenError):
            conn.call("ping")


class TestPromQlRemoteFaults:
    def _plan(self):
        from filodb_tpu.query.exec.remote_exec import PromQlRemoteExec
        return PromQlRemoteExec(endpoint="http://127.0.0.1:1/promql/ts",
                                promql="up", start=0, step=60_000,
                                end=60_000, timeout_s=0.5)

    def test_unreachable_endpoint_tagged_connection_error(self):
        p = self._plan()
        FaultInjector.arm("promql.remote", error=ConnectionError)
        with pytest.raises(ConnectionError,
                           match=r"remote query to http://127\.0\.0\.1:1"):
            p.do_execute(ExecContext(None, "timeseries"))

    def test_repeated_failures_open_endpoint_breaker(self):
        p = self._plan()
        resilience.configure(breaker_failure_threshold=2)
        FaultInjector.arm("promql.remote", error=ConnectionError)
        for _ in range(2):
            with pytest.raises(ConnectionError):
                p.do_execute(ExecContext(None, "timeseries"))
        with pytest.raises(CircuitOpenError):
            p.do_execute(ExecContext(None, "timeseries"))

    def test_exhausted_deadline_fails_before_dialing(self):
        p = self._plan()
        clk = FakeClock()
        fired = FaultInjector.arm("promql.remote")
        ctx = ExecContext(None, "timeseries",
                          deadline=Deadline.after(1.0, clock=clk.now))
        clk.advance(2.0)
        with pytest.raises(DeadlineExceeded):
            p.do_execute(ctx)
        assert fired.fired == 0

    def test_http_error_probe_closes_breaker(self):
        """Regression: an HTTP error status during the half-open probe
        means the peer ANSWERED — the breaker must close, not wedge
        half-open forever."""
        import urllib.error
        from filodb_tpu.utils.resilience import RemoteQueryError
        resilience.configure(breaker_reset_s=0.0)
        p = self._plan()
        b = breaker_for(p.endpoint)
        b.force_open()  # reset 0s → half-open on the next call
        FaultInjector.arm("promql.remote",
                          error=urllib.error.HTTPError(
                              p.endpoint, 503, "unavailable", None, None))
        with pytest.raises(RemoteQueryError, match="HTTP 503"):
            p.do_execute(ExecContext(None, "timeseries"))
        assert b.state == "closed"
        # and subsequent calls are admitted (would raise CircuitOpenError
        # if the probe slot had wedged)
        FaultInjector.reset()
        FaultInjector.arm("promql.remote", error=ConnectionError)
        with pytest.raises(ConnectionError):
            p.do_execute(ExecContext(None, "timeseries"))
