"""Consul seed discovery vs a protocol-level fake agent (reference
``akka-bootstrapper/ConsulClient.scala`` + Consul seed strategy)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from filodb_tpu.coordinator.bootstrap import ConsulDiscovery


class FakeConsulAgent:
    """In-memory Consul agent speaking the /v1 HTTP API subset the
    bootstrapper uses: service register/deregister + health listing."""

    def __init__(self):
        self.services: dict[str, dict] = {}
        agent = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, body=b""):
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_PUT(self):
                ln = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(ln)
                if self.path == "/v1/agent/service/register":
                    svc = json.loads(body)
                    agent.services[svc["ID"]] = svc
                    return self._send(200)
                if self.path.startswith("/v1/agent/service/deregister/"):
                    sid = self.path.rsplit("/", 1)[1]
                    agent.services.pop(sid, None)
                    return self._send(200)
                return self._send(404)

            def do_GET(self):
                if self.path.startswith("/v1/health/service/"):
                    name = self.path.split("/")[4].split("?")[0]
                    entries = [
                        {"Node": {"Address": s["Address"]},
                         "Service": {"ID": s["ID"], "Service": s["Name"],
                                     "Address": s["Address"],
                                     "Port": s["Port"]}}
                        for s in agent.services.values()
                        if s["Name"] == name]
                    return self._send(200, json.dumps(entries).encode())
                return self._send(404)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture
def agent():
    a = FakeConsulAgent().start()
    yield a
    a.stop()


class TestConsulDiscovery:
    def test_register_discover_deregister(self, agent):
        d = ConsulDiscovery(port=agent.port, service_name="filodb")
        assert d.discover() == []
        d.register("node-a", "10.0.0.1", 2552)
        d.register("node-b", "10.0.0.2", 2552)
        assert d.discover() == [("10.0.0.1", 2552), ("10.0.0.2", 2552)]
        d.deregister("node-a")
        assert d.discover() == [("10.0.0.2", 2552)]

    def test_other_services_filtered(self, agent):
        d = ConsulDiscovery(port=agent.port, service_name="filodb")
        d.register("me", "10.0.0.9", 2552)
        other = ConsulDiscovery(port=agent.port, service_name="unrelated")
        other.register("them", "10.0.0.8", 9999)
        assert d.discover() == [("10.0.0.9", 2552)]

    def test_deterministic_seed_order(self, agent):
        d = ConsulDiscovery(port=agent.port, service_name="filodb")
        for i in (3, 1, 2):
            d.register(f"n{i}", f"10.0.0.{i}", 2552)
        assert d.discover() == [("10.0.0.1", 2552), ("10.0.0.2", 2552),
                                ("10.0.0.3", 2552)]

    def test_unreachable_agent_yields_no_seeds(self):
        d = ConsulDiscovery(port=1, service_name="filodb", timeout=0.3)
        assert d.discover() == []
