"""Multi-process mesh runtime: descriptor shipping, N-process × 1-CPU-
device byte-identity against the single-process engines, cold-model
parity with ``FILODB_MULTIPROC=0``, and worker-loss degradation.

Real process isolation, real TCP — the CI face of the cluster-scale
SPMD path (doc/mesh_engine.md §multi-process). Workers are seeded with
``filodb_tpu.testing.mesh_store:build_store`` (content-hashed shard
routing ⇒ every process derives identical per-shard data), so the root
process's in-memory store doubles as the ground truth.
"""

import os
import time

import numpy as np
import pytest

from filodb_tpu.coordinator.mesh_cluster import (
    _M_PROC_DISPATCH,
    _M_PROC_FALLBACK,
    LoweredDescriptor,
    MeshClusterRuntime,
)
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.coordinator.wire import decode, encode
from filodb_tpu.parallel.mesh_engine import MeshQueryEngine, make_query_mesh
from filodb_tpu.parallel.multiproc import MeshWorkerSupervisor
from filodb_tpu.promql.parser import TimeStepParams, parse_query
from filodb_tpu.testing import mesh_store

START = mesh_store.START_MS // 1000
PARAMS = TimeStepParams(START + 600, 60, START + 1500)
SEED = "filodb_tpu.testing.mesh_store:build_store"

QUERIES = [
    'sum(rate(http_requests_total[10m]))',
    'sum by (job) (rate(http_requests_total[5m]))',
    'sum(rate(http_requests_total{job="job-1"}[10m])) by (instance)',
    'avg(rate(http_requests_total[10m]))',
]


def _plan(query, params=PARAMS):
    return parse_query(query, params)


def _baseline_engine():
    # the same 1-device mesh shape each worker runs, so padded baseline
    # rows contribute exact +0.0 and bitwise comparison is meaningful
    return MeshQueryEngine(mesh=make_query_mesh(n_devices=1))


def assert_bitwise(a, b):
    assert [str(k) for k in a.keys] == [str(k) for k in b.keys]
    np.testing.assert_array_equal(a.steps_ms, b.steps_ms)
    assert np.asarray(a.values).tobytes() == np.asarray(b.values).tobytes()


# --------------------------------------------------------------------------
# descriptor wire round-trip (no processes)


class TestDescriptorWire:
    def _descriptor(self):
        eng = _baseline_engine()
        low = eng._lower(_plan(QUERIES[1]))
        assert low is not None
        return LoweredDescriptor.from_lowered(low, "timeseries"), low

    def test_registered_on_the_wire(self):
        from filodb_tpu.coordinator.wire import registry
        assert "LoweredDescriptor" in registry()
        assert "MeshWorkerClient" in registry()

    def test_roundtrip_is_identity(self):
        desc, _ = self._descriptor()
        back = decode(encode(desc))
        assert back == desc
        assert back.signature == desc.signature

    def test_to_lowered_reproduces_plan(self):
        desc, low = self._descriptor()
        back = decode(encode(desc)).to_lowered()
        assert back == low

    def test_strip_agg_for_worker_execution(self):
        # workers run the agg-stripped form: raw per-series windows with
        # the metric label kept, reduction happens at the root
        desc, _ = self._descriptor()
        w = decode(encode(desc)).to_lowered(strip_agg=True)
        assert w.agg is None and w.by == () and w.without == ()
        assert w.keep_metric and w.post == ()


# --------------------------------------------------------------------------
# spawned cluster: byte-identity, service routing, cold-model parity


@pytest.fixture(scope="module")
def cluster():
    store = mesh_store.build_store()
    sup = MeshWorkerSupervisor(dataset=mesh_store.DATASET,
                               num_shards=mesh_store.NUM_SHARDS,
                               workers=2, seed=SEED)
    sup.spawn()
    try:
        sup.wait_ready(timeout_s=120.0)
        rt = MeshClusterRuntime(store, mesh_store.DATASET,
                                mesh_store.NUM_SHARDS, sup.slices)
        yield store, sup, rt
        rt.shutdown()
    finally:
        sup.stop()


class TestMultiprocByteIdentity:
    @pytest.mark.parametrize("query", QUERIES)
    def test_matches_single_process_mesh(self, cluster, query):
        store, _, rt = cluster
        got = rt.execute_plan(_plan(query))
        assert got is not None, f"multiproc fell back: {query}"
        want = _baseline_engine().execute(store, mesh_store.DATASET,
                                          _plan(query))
        assert_bitwise(got, want)

    def test_matches_exec_path(self, cluster):
        # same tolerance contract the single-process mesh engine holds
        # against the scatter-gather exec path (test_mesh_engine idiom)
        store, _, rt = cluster
        exec_svc = QueryService(store, mesh_store.DATASET,
                                mesh_store.NUM_SHARDS, spread=1)
        for query in QUERIES[:2]:
            got = rt.execute_plan(_plan(query))
            re = exec_svc.query_range(query, START + 600, 60, START + 1500)
            e = re.result
            assert sorted(map(str, e.keys)) == sorted(map(str, got.keys))
            oe = np.argsort([str(k) for k in e.keys])
            og = np.argsort([str(k) for k in got.keys])
            np.testing.assert_allclose(
                np.asarray(got.values)[og], np.asarray(e.values)[oe],
                rtol=1e-6, atol=1e-9, equal_nan=True)

    def test_worker_status_reports_slices(self, cluster):
        _, sup, rt = cluster
        st = rt.status()
        assert len(st["workers"]) == 2
        ranges = sorted(tuple(w["shards"]) for w in st["workers"])
        assert ranges == [(0, 2), (2, 4)]
        for w in st["workers"]:
            assert w["reachable"]
            assert w["devices"] == 1

    def test_service_routes_through_multiproc(self, cluster):
        store, _, rt = cluster
        svc = QueryService(store, mesh_store.DATASET, mesh_store.NUM_SHARDS,
                           spread=1, engine="mesh")
        ref = QueryService(store, mesh_store.DATASET, mesh_store.NUM_SHARDS,
                           spread=1, engine="mesh")
        svc.mesh_cluster = rt
        before = _M_PROC_DISPATCH["ok"].value
        for query in QUERIES:
            a = svc.query_range(query, START + 600, 60, START + 1500)
            # bitwise against the 1-device engine shape the workers run
            want = _baseline_engine().execute(store, mesh_store.DATASET,
                                              _plan(query))
            assert np.asarray(a.result.values).tobytes() == \
                np.asarray(want.values).tobytes()
            assert not a.partial
            # the service's own (8-virtual-device) engine agrees to f64
            # rounding: reduction tree shape differs across mesh widths
            b = ref.query_range(query, START + 600, 60, START + 1500)
            np.testing.assert_allclose(
                np.asarray(a.result.values), np.asarray(b.result.values),
                rtol=1e-12, atol=1e-12, equal_nan=True)
        assert _M_PROC_DISPATCH["ok"].value >= before + len(QUERIES)

    def test_disabled_env_cold_parity(self, cluster, monkeypatch):
        # FILODB_MULTIPROC=0 must reproduce the single-process engine
        # bit-for-bit: the runtime declines, the fallback counter bumps,
        # and the service result is the engine's own answer
        store, _, rt = cluster
        monkeypatch.setenv("FILODB_MULTIPROC", "0")
        before = _M_PROC_FALLBACK["disabled"].value
        assert rt.execute_plan(_plan(QUERIES[0])) is None
        assert _M_PROC_FALLBACK["disabled"].value == before + 1
        svc = QueryService(store, mesh_store.DATASET, mesh_store.NUM_SHARDS,
                           spread=1, engine="mesh")
        svc.mesh_cluster = rt
        ref = QueryService(store, mesh_store.DATASET, mesh_store.NUM_SHARDS,
                           spread=1, engine="mesh")
        got = svc.query_range(QUERIES[0], START + 600, 60, START + 1500)
        want = ref.query_range(QUERIES[0], START + 600, 60, START + 1500)
        assert np.asarray(got.result.values).tobytes() == \
            np.asarray(want.result.values).tobytes()


# --------------------------------------------------------------------------
# chaos: worker loss degrades to the single-process path, never wrong


def test_worker_loss_degrades_to_fallback():
    store = mesh_store.build_store()
    sup = MeshWorkerSupervisor(dataset=mesh_store.DATASET,
                               num_shards=mesh_store.NUM_SHARDS,
                               workers=2, seed=SEED)
    sup.spawn()
    try:
        sup.wait_ready(timeout_s=120.0)
        rt = MeshClusterRuntime(store, mesh_store.DATASET,
                                mesh_store.NUM_SHARDS, sup.slices,
                                timeout=5.0)
        plan = _plan(QUERIES[0])
        healthy = rt.execute_plan(plan)
        assert healthy is not None

        sup.procs[0].kill()
        sup.procs[0].wait(timeout=10)
        before = _M_PROC_FALLBACK["worker"].value
        assert rt.execute_plan(plan) is None
        assert _M_PROC_FALLBACK["worker"].value == before + 1

        # the service path serves the same answer through the fallback:
        # bitwise vs a service that never had the runtime, and within f64
        # rounding of the healthy multiproc result (the fallback engine's
        # wider mesh changes the reduction tree, never the answer)
        svc = QueryService(store, mesh_store.DATASET, mesh_store.NUM_SHARDS,
                           spread=1, engine="mesh")
        svc.mesh_cluster = rt
        ref = QueryService(store, mesh_store.DATASET, mesh_store.NUM_SHARDS,
                           spread=1, engine="mesh")
        got = svc.query_range(QUERIES[0], START + 600, 60, START + 1500)
        want = ref.query_range(QUERIES[0], START + 600, 60, START + 1500)
        assert np.asarray(got.result.values).tobytes() == \
            np.asarray(want.result.values).tobytes()
        np.testing.assert_allclose(
            np.asarray(got.result.values), np.asarray(healthy.values),
            rtol=1e-12, atol=1e-12, equal_nan=True)
    finally:
        sup.stop()


def test_supervisor_slices_tile_the_shard_space():
    sup = MeshWorkerSupervisor(dataset="timeseries", num_shards=10,
                               workers=3, seed=SEED)
    spans = [r for _, _, r in sup.slices]
    assert spans[0][0] == 0 and spans[-1][1] == 10
    for (a, b), (c, d) in zip(spans, spans[1:]):
        assert b == c and a < b
    with pytest.raises(ValueError):
        MeshClusterRuntime(None, "timeseries", 10,
                           [("h", 1, (0, 4)), ("h", 2, (5, 10))])
