"""Adaptive overload protection: the admission gate (bounded concurrency +
deadline-aware wait queue), scan-time query budgets (partial vs error
degrade, identical local and remote), the memory-pressure watchdog state
machine, HTTP 503/Retry-After encoding on both fronts, gateway ingest
shedding under CRITICAL, and the routed cardinality-quota path."""

import json
import threading
import time

import numpy as np
import pytest

from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.coordinator.remote import (
    PlanExecutorServer,
    RemotePlanDispatcher,
    reset_pool,
)
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.query.model import QueryContext, QueryLimitExceeded
from filodb_tpu.testing.data import gauge_stream, machine_metrics_series
from filodb_tpu.utils import governor as gov
from filodb_tpu.utils.resilience import Deadline, reset_breakers

NUM_SHARDS = 4
START = 1_600_000_000
QS = START + 100
QE = START + 2000
STEP = 60


@pytest.fixture(autouse=True)
def fresh_governor():
    """Tests share the process-global governor: isolate every test."""
    gov.reset()
    yield
    gov.reset()


def build_store():
    ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=100,
                                              groups_per_shard=4))
    ingest_routed(ms, "timeseries",
                  gauge_stream(machine_metrics_series(10, ns="App-2"), 240,
                               start_ms=START * 1000, interval_ms=10_000,
                               seed=11),
                  NUM_SHARDS, spread=1)
    return ms


@pytest.fixture(scope="module")
def store():
    return build_store()


@pytest.fixture
def svc(store):
    s = QueryService(store, "timeseries", NUM_SHARDS, spread=1)
    s.result_cache = None  # budgets asserted against the engine directly
    return s


def assert_equivalent(a, b, rtol=2e-5):
    m0, m1 = a.result, b.result
    i0 = {k: i for i, k in enumerate(m0.keys)}
    i1 = {k: i for i, k in enumerate(m1.keys)}
    assert set(i0) == set(i1), set(i0) ^ set(i1)
    for k, i in i0.items():
        x = np.asarray(m0.values[i])
        y = np.asarray(m1.values[i1[k]])
        assert np.array_equal(np.isnan(x), np.isnan(y)), k
        assert np.allclose(x, y, rtol=rtol, atol=1e-9, equal_nan=True), k


def _hold_slot(g):
    """Occupy one admission slot from another thread; returns (release,
    thread) once the slot is definitely held."""
    held, release = threading.Event(), threading.Event()

    def occupant():
        with g.admit():
            held.set()
            release.wait(timeout=30)

    t = threading.Thread(target=occupant, daemon=True)
    t.start()
    assert held.wait(timeout=5)
    return release, t


# ---------------------------------------------------------------------------
# admission gate


class TestAdmissionGate:
    def test_admit_and_release(self):
        g = gov.governor()
        before = gov._admitted.value
        with g.admit():
            assert g.inflight == 1
        assert g.inflight == 0
        assert gov._admitted.value == before + 1

    def test_waiter_admitted_when_slot_frees(self):
        gov.configure(admission_capacity=1)
        g = gov.governor()
        release, t = _hold_slot(g)
        got = threading.Event()

        def waiter():
            with g.admit():
                got.set()

        w = threading.Thread(target=waiter, daemon=True)
        w.start()
        time.sleep(0.1)
        assert not got.is_set()  # queued behind the occupant
        release.set()
        assert got.wait(timeout=5)
        t.join(timeout=5)
        w.join(timeout=5)
        assert g.inflight == 0

    def test_shed_when_deadline_cannot_be_met(self):
        gov.configure(admission_capacity=1, retry_after_s=2.0)
        g = gov.governor()
        release, t = _hold_slot(g)
        try:
            t0 = time.monotonic()
            with pytest.raises(gov.QueryRejected) as ei:
                with g.admit(deadline=Deadline.after(0.3)):
                    pass
            assert time.monotonic() - t0 < 2.0  # shed promptly, no hang
            assert ei.value.reason == "deadline"
            assert ei.value.retry_after_s == 2.0
            assert gov._rejected["deadline"].value >= 1
        finally:
            release.set()
            t.join(timeout=5)

    def test_shed_on_max_queue_wait(self):
        gov.configure(admission_capacity=1, max_queue_wait_s=0.2)
        g = gov.governor()
        release, t = _hold_slot(g)
        try:
            with pytest.raises(gov.QueryRejected) as ei:
                with g.admit():  # no deadline: bounded by max_queue_wait_s
                    pass
            assert ei.value.reason == "capacity"
        finally:
            release.set()
            t.join(timeout=5)

    def test_queue_full_sheds_immediately(self):
        gov.configure(admission_capacity=1, admission_queue_limit=0)
        g = gov.governor()
        release, t = _hold_slot(g)
        try:
            t0 = time.monotonic()
            with pytest.raises(gov.QueryRejected) as ei:
                with g.admit():
                    pass
            assert time.monotonic() - t0 < 0.5  # no queue slot -> no wait
            assert ei.value.reason == "queue_full"
        finally:
            release.set()
            t.join(timeout=5)

    def test_critical_sheds_expensive_admits_cheap(self):
        g = gov.governor()
        g.set_state(gov.CRITICAL)
        with pytest.raises(gov.QueryRejected) as ei:
            with g.admit(cost=gov.EXPENSIVE):
                pass
        assert ei.value.reason == "critical"
        assert gov._rejected["critical"].value >= 1
        with g.admit(cost=gov.CHEAP):  # instant/metadata stays alive
            assert g.inflight == 1

    def test_degraded_capacity_shrinks(self):
        gov.configure(admission_capacity=8, degraded_capacity_factor=0.5)
        g = gov.governor()
        assert g.capacity() == 8
        before = gov._transitions[gov.DEGRADED].value
        assert g.set_state(gov.DEGRADED)
        assert g.capacity() == 4
        assert not g.set_state(gov.DEGRADED)  # idempotent, not a transition
        assert gov._transitions[gov.DEGRADED].value == before + 1
        g.set_state(gov.OK)
        assert g.capacity() == 8


# ---------------------------------------------------------------------------
# memory watchdog


class TestMemoryWatchdog:
    def test_threshold_state_machine(self):
        g = gov.governor()
        level = {"v": 0.1}
        fired = []
        w = gov.MemoryWatchdog(gov=g, interval_s=999.0)
        w.add_source("fake", lambda: level["v"])
        w.on_degraded.append(lambda s: fired.append(s))

        assert w.sample() == gov.OK
        level["v"] = 0.80
        assert w.sample() == gov.DEGRADED
        level["v"] = 0.95
        assert w.sample() == gov.CRITICAL
        assert fired == [gov.DEGRADED, gov.CRITICAL]  # upward edges only
        level["v"] = 0.10
        assert w.sample() == gov.OK
        assert fired == [gov.DEGRADED, gov.CRITICAL]  # recovery is silent

    def test_broken_and_torn_down_sources_are_skipped(self):
        w = gov.MemoryWatchdog(gov=gov.governor(), interval_s=999.0)
        w.add_source("gone", lambda: None)
        w.add_source("broken", lambda: 1 / 0)
        w.add_source("live", lambda: 0.4)
        assert w.utilization() == pytest.approx(0.4)

    def test_background_thread_drives_state_and_stop_resets(self):
        g = gov.governor()
        level = {"v": 0.99}
        w = gov.MemoryWatchdog(gov=g, interval_s=0.02)
        w.add_source("fake", lambda: level["v"])
        w.start()
        try:
            deadline = time.monotonic() + 5
            while g.state != gov.CRITICAL and time.monotonic() < deadline:
                time.sleep(0.02)
            assert g.state == gov.CRITICAL
        finally:
            w.stop()
        assert g.state == gov.OK  # stop never strands pressure


# ---------------------------------------------------------------------------
# admission wired through QueryService


class TestServiceAdmission:
    def test_query_shed_then_recovers(self, svc):
        gov.configure(admission_capacity=1, max_queue_wait_s=0.2,
                      retry_after_s=3.0)
        g = gov.governor()
        release, t = _hold_slot(g)
        try:
            with pytest.raises(gov.QueryRejected) as ei:
                svc.query_range("heap_usage", QS, STEP, QE)
            assert ei.value.retry_after_s == 3.0
        finally:
            release.set()
            t.join(timeout=5)
        # slot freed: the very same query is admitted and completes
        r = svc.query_range("heap_usage", QS, STEP, QE)
        assert r.result.num_series == 10
        assert not r.partial

    def test_instant_query_survives_critical(self, svc):
        gov.governor().set_state(gov.CRITICAL)
        with pytest.raises(gov.QueryRejected):
            svc.query_range("heap_usage", QS, STEP, QE)  # range: expensive
        r = svc.query_range("heap_usage", QE, 0, QE)  # instant: cheap
        assert r.result.num_series >= 1


# ---------------------------------------------------------------------------
# scan-time query budgets


class TestQueryBudget:
    def _qc(self, **limits):
        qc = QueryContext()
        qc.planner_params.budget = gov.QueryBudget(**limits)
        return qc

    def test_samples_budget_partial(self, svc):
        r = svc.query_range("heap_usage", QS, STEP, QE,
                            self._qc(max_samples_scanned=50))
        assert r.partial
        assert any("budget" in w for w in r.warnings)
        full = svc.query_range("heap_usage", QS, STEP, QE)
        assert not full.partial and not full.warnings

    def test_samples_budget_error_mode(self, svc):
        with pytest.raises(QueryLimitExceeded):
            svc.query_range("heap_usage", QS, STEP, QE,
                            self._qc(max_samples_scanned=50,
                                     degrade="error"))

    def test_default_budget_from_config(self, svc):
        """Config-level limits attach a budget without the caller opting
        in; unlimited config (the default) attaches none."""
        before = gov._budget_exceeded.value
        gov.configure(max_samples_scanned=50)
        r = svc.query_range("heap_usage", QS, STEP, QE)
        assert r.partial
        assert gov._budget_exceeded.value > before
        assert gov.default_budget().max_samples_scanned == 50
        gov.configure(max_samples_scanned=0)
        assert gov.default_budget() is None

    def test_result_bytes_budget_truncates(self, svc):
        full = svc.query_range("heap_usage", QS, STEP, QE)
        assert full.result.num_series == 10
        limit = int(full.result.values.nbytes * 0.4)
        r = svc.query_range("heap_usage", QS, STEP, QE,
                            self._qc(max_result_bytes=limit))
        assert r.partial
        assert 0 < r.result.num_series < 10
        # what survives is real data: a subset of the full answer
        assert set(r.result.keys) <= set(full.result.keys)

    def test_group_cardinality_budget(self, svc):
        svc.planner.agg_pushdown = "off"  # root-side map/reduce path
        try:
            full = svc.query_range("sum(heap_usage) by (host)",
                                   QS, STEP, QE)
            assert full.result.num_series > 3
            r = svc.query_range("sum(heap_usage) by (host)", QS, STEP, QE,
                                self._qc(max_group_cardinality=3))
            assert r.partial
            assert 0 < r.result.num_series <= 3
            assert set(r.result.keys) <= set(full.result.keys)
        finally:
            svc.planner.agg_pushdown = "auto"


# ---------------------------------------------------------------------------
# budgets over the wire: remote leaves degrade exactly like local ones


class TestRemoteBudgetEquivalence:
    def test_budget_partial_same_local_and_remote(self, store):
        reset_breakers()
        reset_pool()
        srv = PlanExecutorServer(store).start()
        try:
            disp = RemotePlanDispatcher("127.0.0.1", srv.port)
            local = QueryService(store, "timeseries", NUM_SHARDS, spread=1)
            remote = QueryService(store, "timeseries", NUM_SHARDS, spread=1)
            local.result_cache = remote.result_cache = None
            remote.planner.dispatcher_for_shard = lambda s: disp

            def qc():
                c = QueryContext()
                c.planner_params.budget = gov.QueryBudget(
                    max_samples_scanned=40)
                return c

            a = local.query_range("heap_usage", QS, STEP, QE, qc())
            b = remote.query_range("heap_usage", QS, STEP, QE, qc())
            assert a.partial and b.partial
            assert any("budget" in w for w in a.warnings)
            # the budget rode PlannerParams over the wire: remote leaves
            # breach at the same leaf-local counts, so the flagged result
            # is indistinguishable from the in-process one
            assert set(a.warnings) == set(b.warnings)
            assert_equivalent(a, b)
        finally:
            srv.stop()
            reset_pool()


# ---------------------------------------------------------------------------
# HTTP encoding: 503 + Retry-After with distinct errorTypes, both fronts


class _RaisingSvc:
    def __init__(self, exc):
        self.exc = exc

    def query_range(self, *a, **k):
        raise self.exc


class _FakeApp:
    def __init__(self, svc):
        self.services = {"timeseries": svc}
        self.response_cache = None
        self.shard_maps = {}
        self.cluster = None

    def batched(self, svc):
        return svc


RANGE_URL = ("/promql/timeseries/api/v1/query_range?"
             "query=up&start=0&end=100&step=10")


class TestHttpOverloadEncoding:
    def _handle(self, exc):
        from filodb_tpu.http.server import HttpDispatcher
        return HttpDispatcher(_FakeApp(_RaisingSvc(exc))).handle(
            "GET", RANGE_URL)

    def test_rejected_is_503_unavailable_with_retry_after(self):
        code, headers, body = self._handle(
            gov.QueryRejected("shed", retry_after_s=2.4))
        assert code == 503
        assert headers["Retry-After"] == "2"
        assert json.loads(body)["errorType"] == "unavailable"

    def test_deadline_is_503_timeout(self):
        from filodb_tpu.utils.resilience import DeadlineExceeded
        code, headers, body = self._handle(DeadlineExceeded("too slow"))
        assert code == 503
        assert "Retry-After" in headers
        assert json.loads(body)["errorType"] == "timeout"

    def test_retry_after_rounding_and_default(self):
        from filodb_tpu.http.server import retry_after_headers
        assert retry_after_headers(0.2) == {"Retry-After": "1"}  # floor 1s
        assert retry_after_headers(7.6) == {"Retry-After": "8"}
        gov.configure(retry_after_s=3.0)
        assert retry_after_headers() == {"Retry-After": "3"}

    def _fast_single(self, exc):
        from filodb_tpu.http.fastserver import FastHttpServer, _HotReq
        fs = FastHttpServer.__new__(FastHttpServer)  # encoder only, no IO
        req = _HotReq(None, 0, _RaisingSvc(exc), "range", ("up", 0, 10, 100))
        return fs._run_single(req)

    def test_fastserver_rejected_is_503_unavailable(self):
        code, headers, body = self._fast_single(
            gov.QueryRejected("shed", retry_after_s=5.0))
        assert code == 503
        assert headers["Retry-After"] == "5"
        assert json.loads(body)["errorType"] == "unavailable"

    def test_fastserver_deadline_is_503_timeout(self):
        from filodb_tpu.utils.resilience import DeadlineExceeded
        code, headers, body = self._fast_single(DeadlineExceeded("too slow"))
        assert code == 503
        assert "Retry-After" in headers
        assert json.loads(body)["errorType"] == "timeout"

    def test_fastserver_knows_shed_status_lines(self):
        from filodb_tpu.http.fastserver import _STATUS
        assert 429 in _STATUS and 503 in _STATUS


# ---------------------------------------------------------------------------
# gateway ingest shedding under CRITICAL


class TestGatewayShedding:
    def _records(self, n, tag="h"):
        from filodb_tpu.gateway.influx import parse_influx_line
        recs = []
        for i in range(n):
            recs.extend(parse_influx_line(
                f"heap_usage,host={tag}{i} value=1.0",
                {"_ws_": "demo", "_ns_": "App-0"}, now_ms=START * 1000))
        return recs

    def test_critical_sheds_instead_of_blocking(self):
        from filodb_tpu.gateway import server as gw
        sink = gw.ContainerSink({}, num_shards=1, spread=0,
                                flush_every=4, max_pending=4)
        for r in self._records(4):  # buffer at the brim...
            sink._pending.add(r)
        sink._flushing = True       # ...with a drain pinned in flight
        gov.governor().set_state(gov.CRITICAL)
        before = gw.records_shed.value
        t0 = time.perf_counter()
        sink.add(self._records(2, tag="x"))
        assert time.perf_counter() - t0 < 1.0  # shed, not the 5s block
        assert gw.records_shed.value == before + 2

    def test_queue_depth_gauge_renders(self):
        from filodb_tpu.gateway import server as gw
        from filodb_tpu.utils.metrics import render_prometheus
        sink = gw.ContainerSink({}, num_shards=1, spread=0)
        for r in self._records(3):
            sink._pending.add(r)
        text = render_prometheus()
        assert "gateway_queue_depth 3" in text


# ---------------------------------------------------------------------------
# cardinality quota end-to-end: routed ingest past the quota error


class TestCardinalityQuotaEndToEnd:
    def test_routed_ingest_past_quota(self):
        n_shards = 2
        ms = TimeSeriesMemStore()
        for s in range(n_shards):
            sh = ms.setup("quota_ds", s, StoreConfig(max_chunk_size=50))
            sh.cardinality.set_quota(["demo", "App-0"], 2)
        hot = machine_metrics_series(8, metric="hot_metric")  # ns App-0
        ok = machine_metrics_series(4, metric="ok_metric", ns="App-1")
        ingest_routed(ms, "quota_ds",
                      gauge_stream(hot + ok, 30, start_ms=START * 1000,
                                   interval_ms=10_000, seed=3),
                      n_shards, spread=0)

        shards = ms.shards_for("quota_ds")
        app0 = sum(sh.cardinality.cardinality(["demo", "App-0"]).active_ts
                   for sh in shards)
        app1 = sum(sh.cardinality.cardinality(["demo", "App-1"]).active_ts
                   for sh in shards)
        dropped = sum(sh.stats.quota_dropped.value for sh in shards)
        assert app0 <= 2 * n_shards < 8  # offending namespace is capped
        assert app1 == 4                 # neighbours are untouched
        assert dropped > 0               # every rejection is counted

        # ingestion continued past the quota errors: admitted series are
        # fully queryable end to end
        svc = QueryService(ms, "quota_ds", n_shards, spread=0)
        r = svc.query_range("ok_metric", START + 100, 60, START + 280)
        assert r.result.num_series == 4
        hot_r = svc.query_range("hot_metric", START + 100, 60, START + 280)
        assert 0 < hot_r.result.num_series <= 2 * n_shards
