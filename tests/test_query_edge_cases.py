"""Query-engine edge cases: NaN/staleness, sparse series, chunk boundaries,
offsets, instant queries, multi-schema stores.

Mirrors the reference's edge-case coverage in
``query/src/test/scala/filodb/query/exec`` specs (NaN handling, chunk
boundary windows, counter correction across chunks).
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.record import IngestRecord, RecordContainer, SomeData
from filodb_tpu.core.store.config import StoreConfig

START = 1_600_000_000


def mk_store(max_chunk=50):
    ms = TimeSeriesMemStore()
    ms.setup("timeseries", 0, StoreConfig(max_chunk_size=max_chunk))
    return ms


def ingest(ms, key, samples):
    c = RecordContainer()
    for ts, v in samples:
        c.add(IngestRecord(key, ts, (v,)))
    ms.ingest("timeseries", 0, SomeData(c, 0))


def gauge_key(metric="m", **labels):
    return PartKey.create("gauge", {"_metric_": metric, "_ws_": "w",
                                    "_ns_": "n", **labels})


class TestNaNStaleness:
    def test_nan_samples_are_gaps(self):
        ms = mk_store()
        key = gauge_key()
        samples = [((START + i * 10) * 1000,
                    np.nan if 20 <= i < 40 else float(i))
                   for i in range(60)]
        ingest(ms, key, samples)
        svc = QueryService(ms, "timeseries", 1, spread=0)
        # count_over_time excludes NaN (stale) samples
        r = svc.query_range("count_over_time(m[10m])", START + 595, 60,
                            START + 595).result
        assert r.values[0, 0] == 40.0  # 60 - 20 NaN
        # instant selector: during the NaN gap the last valid sample (i=19)
        # is still within 5m staleness at i=25
        r2 = svc.query_range("m", START + 250, 60, START + 250).result
        assert r2.values[0, 0] == 19.0

    def test_fully_nan_series_dropped(self):
        ms = mk_store()
        ingest(ms, gauge_key("allnan"),
               [((START + i * 10) * 1000, np.nan) for i in range(10)])
        svc = QueryService(ms, "timeseries", 1, spread=0)
        r = svc.query_range("allnan", START, 60, START + 100).result
        assert r.compact().num_series == 0


class TestChunkBoundaries:
    def test_window_spanning_many_chunks(self):
        # chunk size 50 → 8 chunks; window covers all of them
        ms = mk_store(max_chunk=50)
        key = gauge_key()
        ingest(ms, key, [((START + i * 10) * 1000, float(i))
                         for i in range(400)])
        svc = QueryService(ms, "timeseries", 1, spread=0)
        r = svc.query_range("sum_over_time(m[2h])", START + 3995, 60,
                            START + 3995).result
        np.testing.assert_allclose(r.values[0, 0], sum(range(400)))

    def test_counter_reset_at_chunk_boundary(self):
        ms = mk_store(max_chunk=50)
        key = PartKey.create("prom-counter", {"_metric_": "c", "_ws_": "w",
                                              "_ns_": "n"})
        vals = list(np.arange(50) * 10.0) + list(np.arange(50) * 7.0)
        c = RecordContainer()
        for i, v in enumerate(vals):
            c.add(IngestRecord(key, (START + i * 10) * 1000, (v,)))
        ms.ingest("timeseries", 0, SomeData(c, 0))
        svc = QueryService(ms, "timeseries", 1, spread=0)
        r = svc.query_range("increase(c[10m])", START + 895, 60,
                            START + 895).result
        # window (295, 895]: samples i=30..89; reset at i=50 (490 -> 0)
        # corrected increase = (490 - 300) + (39*7 - 0)
        expect_raw = (490.0 - 300.0) + 39 * 7.0
        # extrapolation scales it; just sanity-bound the result
        assert expect_raw * 0.9 < r.values[0, 0] < expect_raw * 1.15

    def test_sparse_vs_dense_batching(self):
        # series with very different sample counts batch correctly
        ms = mk_store()
        ingest(ms, gauge_key(instance="dense"),
               [((START + i * 10) * 1000, 1.0) for i in range(300)])
        ingest(ms, gauge_key(instance="sparse"),
               [((START + i * 600) * 1000, 2.0) for i in range(5)])
        svc = QueryService(ms, "timeseries", 1, spread=0)
        r = svc.query_range("sum_over_time(m[50m])", START + 2995, 60,
                            START + 2995).result
        by_inst = {k.label_map["instance"]: r.values[i, 0]
                   for i, k in enumerate(r.keys)}
        assert by_inst["dense"] == 300.0
        assert by_inst["sparse"] == 2.0 * 5


class TestOffsets:
    def test_offset_shifts_data(self):
        ms = mk_store()
        ingest(ms, gauge_key(), [((START + i * 10) * 1000, float(i))
                                 for i in range(200)])
        svc = QueryService(ms, "timeseries", 1, spread=0)
        r_now = svc.query_range("max_over_time(m[5m])", START + 1995, 60,
                                START + 1995).result
        r_off = svc.query_range("max_over_time(m[5m] offset 10m)",
                                START + 2595, 60, START + 2595).result
        np.testing.assert_allclose(r_off.values, r_now.values)

    def test_offset_instant_selector(self):
        ms = mk_store()
        ingest(ms, gauge_key(), [((START + i * 10) * 1000, float(i))
                                 for i in range(100)])
        svc = QueryService(ms, "timeseries", 1, spread=0)
        r = svc.query_range("m offset 5m", START + 800, 60, START + 800)
        assert r.result.values[0, 0] == 50.0  # sample at +500s


class TestMultiSchema:
    def test_gauge_and_counter_same_query(self):
        ms = mk_store()
        ingest(ms, gauge_key("shared_name"),
               [((START + i * 10) * 1000, 5.0) for i in range(50)])
        ckey = PartKey.create("prom-counter",
                              {"_metric_": "shared_name", "_ws_": "w",
                               "_ns_": "n", "kind": "counter"})
        c = RecordContainer()
        for i in range(50):
            c.add(IngestRecord(ckey, (START + i * 10) * 1000, (float(i),)))
        ms.ingest("timeseries", 0, SomeData(c, 0))
        svc = QueryService(ms, "timeseries", 1, spread=0)
        r = svc.query_range("shared_name", START + 400, 60, START + 400)
        assert r.result.num_series == 2  # both schemas matched


class TestInstantQuery:
    def test_instant_vector(self):
        ms = mk_store()
        ingest(ms, gauge_key(), [((START + i * 10) * 1000, float(i))
                                 for i in range(100)])
        svc = QueryService(ms, "timeseries", 1, spread=0)
        r = svc.query_instant("sum(m)", START + 500)
        assert r.result.num_steps == 1
        assert r.result.values[0, 0] == 50.0


class TestQueryGuardrails:
    def test_max_query_matches(self):
        from filodb_tpu.query.model import QueryLimitExceeded
        ms = TimeSeriesMemStore()
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=50,
                                              max_query_matches=3))
        for i in range(5):
            ingest(ms, gauge_key(instance=str(i)),
                   [((START + j * 10) * 1000, 1.0) for j in range(5)])
        svc = QueryService(ms, "timeseries", 1, spread=0)
        with pytest.raises(QueryLimitExceeded, match="matches 5 series"):
            svc.query_range("m", START + 40, 60, START + 40)

    def test_configurable_lookback(self):
        ms = mk_store()
        ingest(ms, gauge_key(), [((START + i * 10) * 1000, float(i))
                                 for i in range(10)])
        # default 5m lookback finds the stale sample 200s later
        svc = QueryService(ms, "timeseries", 1, spread=0)
        r = svc.query_range("m", START + 300, 60, START + 300).result
        assert r.values[0, 0] == 9.0
        # 60s lookback does not
        svc_short = QueryService(ms, "timeseries", 1, spread=0,
                                 lookback_ms=60_000)
        r2 = svc_short.query_range("m", START + 300, 60, START + 300).result
        assert r2.compact().num_series == 0
