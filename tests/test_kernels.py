"""Range-function kernel semantics tests.

Validates the jitted kernels against a naive per-window numpy implementation
of Prometheus semantics (the reference pins the same behaviors in
``query/src/test/scala/filodb/query/exec/rangefn/RateFunctionsSpec.scala`` and
``AggrOverTimeFunctionsSpec.scala``: counter correction, extrapolation,
NaN/no-sample handling).
"""

import numpy as np
import pytest

from filodb_tpu.query.engine import kernels
from filodb_tpu.query.engine.aggregations import (
    aggregate,
    histogram_quantile,
    quantile_across,
    topk_mask,
)
from filodb_tpu.query.engine.batch import TS_PAD


def make_batch(series: list[tuple[np.ndarray, np.ndarray]]):
    """series: list of (ts_ms int64 ascending, values float64)."""
    P = len(series)
    S = max(len(t) for t, _ in series)
    S = max(8, 1 << (S - 1).bit_length())
    ts = np.full((P, S), TS_PAD, np.int32)
    vals = np.full((P, S), np.nan, np.float64)
    counts = np.zeros(P, np.int32)
    for i, (t, v) in enumerate(series):
        n = len(t)
        counts[i] = n
        ts[i, :n] = t
        vals[i, :n] = v
    return ts, vals, counts


# ---- naive reference implementations (straight from promql definitions) ----

def naive_window(t, v, t_end, window):
    m = (t > t_end - window) & (t <= t_end)
    return t[m], v[m]


def naive_rate(t, v, t_end, window, is_rate=True, is_counter=True):
    wt, wv = naive_window(t, v, t_end, window)
    if len(wt) < 2:
        return np.nan
    corrected = wv.copy().astype(float)
    if is_counter:
        corr = 0.0
        for i in range(1, len(wv)):
            if wv[i] < wv[i - 1]:
                corr += wv[i - 1]
            corrected[i] = wv[i] + corr
    result = corrected[-1] - corrected[0]
    t_first, t_last = wt[0] / 1000.0, wt[-1] / 1000.0
    range_start, range_end = (t_end - window) / 1000.0, t_end / 1000.0
    sampled = t_last - t_first
    avg_dur = sampled / (len(wt) - 1)
    dur_start = t_first - range_start
    dur_end = range_end - t_last
    if is_counter and result > 0 and wv[0] >= 0:
        dur_zero = sampled * wv[0] / result
        dur_start = min(dur_start, dur_zero)
    threshold = avg_dur * 1.1
    extend = sampled
    extend += dur_start if dur_start < threshold else avg_dur / 2
    extend += dur_end if dur_end < threshold else avg_dur / 2
    result *= extend / sampled
    if is_rate:
        result /= window / 1000.0
    return result


def run(fn, series, steps_ms, window_ms, **kw):
    ts, vals, counts = make_batch(series)
    import jax.numpy as jnp
    out = kernels.range_eval(fn, jnp.asarray(ts), jnp.asarray(vals),
                             jnp.asarray(counts),
                             jnp.asarray(steps_ms, jnp.int32),
                             jnp.asarray(window_ms, jnp.int32), **kw)
    return np.asarray(out)


def regular_series(n=100, interval=10_000, start=0, seed=0):
    rng = np.random.default_rng(seed)
    t = start + np.arange(n, dtype=np.int64) * interval
    v = rng.normal(50, 10, n)
    return t, v


class TestOverTimeFns:
    def setup_method(self):
        self.t, self.v = regular_series()
        self.steps = np.arange(300_000, 1_000_000, 60_000, dtype=np.int64)
        self.window = 300_000

    def _check(self, fn, naive):
        out = run(fn, [(self.t, self.v)], self.steps, self.window)[0]
        for k, te in enumerate(self.steps):
            wt, wv = naive_window(self.t, self.v, te, self.window)
            expect = naive(wt, wv) if len(wt) else np.nan
            np.testing.assert_allclose(out[k], expect, rtol=1e-9,
                                       err_msg=f"{fn} at step {k}")

    def test_sum_over_time(self):
        self._check("sum_over_time", lambda t, v: v.sum())

    def test_avg_over_time(self):
        self._check("avg_over_time", lambda t, v: v.mean())

    def test_count_over_time(self):
        self._check("count_over_time", lambda t, v: float(len(v)))

    def test_min_over_time(self):
        self._check("min_over_time", lambda t, v: v.min())

    def test_max_over_time(self):
        self._check("max_over_time", lambda t, v: v.max())

    def test_stddev_over_time(self):
        self._check("stddev_over_time", lambda t, v: v.std())

    def test_stdvar_over_time(self):
        self._check("stdvar_over_time", lambda t, v: v.var())

    def test_last_over_time(self):
        self._check("last_over_time", lambda t, v: v[-1])

    def test_empty_window_is_nan(self):
        steps = np.array([10_000_000], dtype=np.int64)  # far past data
        out = run("sum_over_time", [(self.t, self.v)], steps, self.window)
        assert np.isnan(out[0, 0])

    def test_irregular_timestamps(self):
        rng = np.random.default_rng(3)
        t = np.cumsum(rng.integers(1000, 30_000, 80)).astype(np.int64)
        v = rng.normal(size=80)
        out = run("sum_over_time", [(t, v)], self.steps, self.window)[0]
        for k, te in enumerate(self.steps):
            _, wv = naive_window(t, v, te, self.window)
            expect = wv.sum() if len(wv) else np.nan
            np.testing.assert_allclose(out[k], expect, rtol=1e-9)

    def test_multiple_series_batched(self):
        series = [regular_series(seed=s, n=50 + s * 10) for s in range(7)]
        out = run("max_over_time", series, self.steps, self.window)
        for p, (t, v) in enumerate(series):
            for k, te in enumerate(self.steps):
                _, wv = naive_window(t, v, te, self.window)
                expect = wv.max() if len(wv) else np.nan
                np.testing.assert_allclose(out[p, k], expect, rtol=1e-9)


class TestRateFamily:
    def counter(self, n=100, resets=(40, 77)):
        rng = np.random.default_rng(1)
        t = np.arange(n, dtype=np.int64) * 10_000
        incr = rng.integers(0, 20, n).astype(float)
        v = np.cumsum(incr)
        for r in resets:
            v[r:] -= v[r]  # counter reset to 0 at index r
        return t, np.maximum(v, 0.0)

    def test_rate_no_reset(self):
        t = np.arange(100, dtype=np.int64) * 10_000
        v = np.arange(100, dtype=np.float64) * 5  # steady 0.5/sec
        steps = np.array([500_000, 700_000], dtype=np.int64)
        out = run("rate", [(t, v)], steps, 300_000)[0]
        np.testing.assert_allclose(out, 0.5, rtol=1e-6)

    def test_rate_matches_promql_with_resets(self):
        t, v = self.counter()
        steps = np.arange(300_000, 990_000, 55_000, dtype=np.int64)
        out = run("rate", [(t, v)], steps, 300_000)[0]
        for k, te in enumerate(steps):
            expect = naive_rate(t, v, te, 300_000, is_rate=True)
            np.testing.assert_allclose(out[k], expect, rtol=1e-9,
                                       err_msg=f"step {te}")

    def test_increase(self):
        t, v = self.counter()
        steps = np.array([400_000, 750_000], dtype=np.int64)
        out = run("increase", [(t, v)], steps, 300_000)[0]
        for k, te in enumerate(steps):
            expect = naive_rate(t, v, te, 300_000, is_rate=False)
            np.testing.assert_allclose(out[k], expect, rtol=1e-9)

    def test_delta_gauge(self):
        t, v = regular_series(seed=5)
        steps = np.array([500_000], dtype=np.int64)
        out = run("delta", [(t, v)], steps, 300_000)[0]
        expect = naive_rate(t, v, 500_000, 300_000, is_rate=False,
                            is_counter=False)
        np.testing.assert_allclose(out[0], expect, rtol=1e-9)

    def test_rate_single_sample_nan(self):
        t = np.array([100_000], dtype=np.int64)
        v = np.array([5.0])
        out = run("rate", [(t, v)], np.array([150_000], np.int64), 300_000)
        assert np.isnan(out[0, 0])

    def test_irate(self):
        t, v = self.counter(resets=())
        steps = np.array([505_000], dtype=np.int64)
        out = run("irate", [(t, v)], steps, 300_000)[0]
        expect = (v[50] - v[49]) / 10.0
        np.testing.assert_allclose(out[0], expect, rtol=1e-9)

    def test_idelta(self):
        t, v = regular_series()
        steps = np.array([505_000], dtype=np.int64)
        out = run("idelta", [(t, v)], steps, 300_000)[0]
        np.testing.assert_allclose(out[0], v[50] - v[49], rtol=1e-9)

    def test_resets_and_changes(self):
        t, v = self.counter()
        steps = np.array([990_000], dtype=np.int64)
        window = 1_000_000  # covers every sample incl. t=0
        out_r = run("resets", [(t, v)], steps, window)[0]
        naive_resets = sum(1 for i in range(1, len(v)) if v[i] < v[i - 1])
        np.testing.assert_allclose(out_r[0], naive_resets)
        out_c = run("changes", [(t, v)], steps, window)[0]
        naive_changes = sum(1 for i in range(1, len(v)) if v[i] != v[i - 1])
        np.testing.assert_allclose(out_c[0], naive_changes)

    def test_deriv(self):
        # exact line: slope recovered exactly
        t = np.arange(60, dtype=np.int64) * 10_000
        v = 3.0 + 0.25 * (t / 1000.0)
        steps = np.array([400_000, 590_000], dtype=np.int64)
        out = run("deriv", [(t, v)], steps, 300_000)[0]
        np.testing.assert_allclose(out, 0.25, rtol=1e-6)


class TestQuantileHoltWinters:
    def test_quantile_over_time(self):
        t, v = regular_series()
        steps = np.arange(300_000, 900_000, 60_000, dtype=np.int64)
        import jax.numpy as jnp
        ts, vals, counts = make_batch([(t, v)])
        out = np.asarray(kernels.quantile_over_time(
            0.9, jnp.asarray(ts), jnp.asarray(vals), jnp.asarray(counts),
            jnp.asarray(steps, jnp.int32), jnp.asarray(300_000, jnp.int32)))[0]
        for k, te in enumerate(steps):
            _, wv = naive_window(t, v, te, 300_000)
            expect = np.quantile(wv, 0.9) if len(wv) else np.nan
            np.testing.assert_allclose(out[k], expect, rtol=1e-9)

    def test_holt_winters_smoke(self):
        t = np.arange(100, dtype=np.int64) * 10_000
        v = np.linspace(0, 100, 100)  # trending line: hw tracks it closely
        steps = np.array([800_000], dtype=np.int64)
        import jax.numpy as jnp
        ts, vals, counts = make_batch([(t, v)])
        out = np.asarray(kernels.holt_winters(
            0.5, 0.3, jnp.asarray(ts), jnp.asarray(vals), jnp.asarray(counts),
            jnp.asarray(steps, jnp.int32), jnp.asarray(300_000, jnp.int32)))[0]
        # smoothed value should be near the last window sample
        assert abs(out[0] - 80.0) < 5.0


class TestAggregations:
    def test_sum_avg_count_by_group(self):
        rng = np.random.default_rng(0)
        vals = rng.normal(size=(6, 4))
        vals[2, 1] = np.nan
        gid = np.array([0, 0, 0, 1, 1, 1], np.int32)
        import jax.numpy as jnp
        s = np.asarray(aggregate("sum", jnp.asarray(vals), jnp.asarray(gid), 2))
        expect0 = np.nansum(vals[:3], axis=0)
        np.testing.assert_allclose(s[0], expect0, rtol=1e-9)
        a = np.asarray(aggregate("avg", jnp.asarray(vals), jnp.asarray(gid), 2))
        np.testing.assert_allclose(a[1], vals[3:].mean(axis=0), rtol=1e-9)
        c = np.asarray(aggregate("count", jnp.asarray(vals), jnp.asarray(gid), 2))
        assert c[0, 1] == 2.0  # NaN excluded

    def test_min_max_stddev(self):
        vals = np.array([[1.0, 5.0], [3.0, np.nan], [2.0, 4.0]])
        gid = np.zeros(3, np.int32)
        import jax.numpy as jnp
        assert np.asarray(aggregate("min", jnp.asarray(vals),
                                    jnp.asarray(gid), 1))[0, 0] == 1.0
        assert np.asarray(aggregate("max", jnp.asarray(vals),
                                    jnp.asarray(gid), 1))[0, 1] == 5.0
        sd = np.asarray(aggregate("stddev", jnp.asarray(vals),
                                  jnp.asarray(gid), 1))
        np.testing.assert_allclose(sd[0, 0], np.std([1, 3, 2]), rtol=1e-9)

    def test_topk(self):
        vals = np.array([[10.0], [30.0], [20.0], [5.0]])
        gid = np.zeros(4, np.int32)
        import jax.numpy as jnp
        mask = np.asarray(topk_mask(jnp.asarray(vals), jnp.asarray(gid), 1, 2))
        assert mask[:, 0].tolist() == [False, True, True, False]

    def test_bottomk(self):
        vals = np.array([[10.0], [30.0], [20.0], [5.0]])
        gid = np.zeros(4, np.int32)
        import jax.numpy as jnp
        mask = np.asarray(topk_mask(jnp.asarray(vals), jnp.asarray(gid), 1, 2,
                                    bottom=True))
        assert mask[:, 0].tolist() == [True, False, False, True]

    def test_quantile_across(self):
        vals = np.array([[1.0], [2.0], [3.0], [4.0]])
        gid = np.zeros(4, np.int32)
        import jax.numpy as jnp
        q = np.asarray(quantile_across(0.5, jnp.asarray(vals),
                                       jnp.asarray(gid), 1))
        np.testing.assert_allclose(q[0, 0], 2.5)


class TestHistogramQuantile:
    def test_simple(self):
        import jax.numpy as jnp
        les = jnp.asarray([1.0, 2.0, 4.0, np.inf])
        h = jnp.asarray([[10.0, 20.0, 30.0, 30.0]])  # cumulative counts
        out = np.asarray(histogram_quantile(0.5, h, les))
        # rank = 15 → bucket (1,2]: 1 + (15-10)/(20-10) * 1 = 1.5
        np.testing.assert_allclose(out[0], 1.5, rtol=1e-9)

    def test_highest_bucket_clamps(self):
        import jax.numpy as jnp
        les = jnp.asarray([1.0, 2.0, np.inf])
        h = jnp.asarray([[0.0, 0.0, 10.0]])
        out = np.asarray(histogram_quantile(0.99, h, les))
        np.testing.assert_allclose(out[0], 2.0)

    def test_empty_is_nan(self):
        import jax.numpy as jnp
        les = jnp.asarray([1.0, np.inf])
        h = jnp.asarray([[0.0, 0.0]])
        assert np.isnan(np.asarray(histogram_quantile(0.5, h, les))[0])


class TestPreCorrectedLaneParity:
    """The pre-corrected/rebased f32-precision lane must be numerically
    identical (in f64 test mode) to the legacy in-kernel correction path —
    including the extrapolate-to-zero clamp, which needs each window's RAW
    first sample (a reset right before the window start must still bind
    the clamp)."""

    def _args(self):
        # one series with a counter reset at t=70s; window at 105s has its
        # first sample AFTER the reset (raw first = 2, corrected = 1102)
        ts = np.array([[0, 10, 20, 30, 40, 70, 80, 90]], np.int32) * 1000
        vals = np.array([[0, 400, 800, 1000, 1100, 2, 52, 102]], np.float64)
        counts = np.array([8], np.int32)
        steps = np.array([105_000], np.int32)
        window = np.int32(60_000)
        return ts, vals, counts, steps, window

    def test_rate_clamp_survives_rebasing(self):
        from filodb_tpu.query.engine import kernels
        from filodb_tpu.query.engine.batch import SeriesBatch

        ts, vals, counts, steps, window = self._args()
        legacy = np.asarray(kernels.range_eval(
            "rate", ts, vals, counts, steps, window, counter=True))
        batch = SeriesBatch(0, ts, vals, counts, [0])
        ts_d, reb, cnt_d, raw_d = batch.delta_arrays(counter=True)
        lane = np.asarray(kernels.range_eval(
            "rate", ts_d, reb, cnt_d, steps, window, counter=True,
            pre_corrected=True, raw=raw_d))
        np.testing.assert_allclose(lane, legacy, rtol=1e-12)
        # the clamp actually binds here (guards against the heuristic
        # silently degrading to no-clamp)
        unclamped = np.asarray(kernels.range_eval(
            "rate", ts_d, reb, cnt_d, steps, window, counter=True,
            pre_corrected=True))
        assert not np.allclose(unclamped, legacy, rtol=1e-6)

    def test_idelta_keeps_raw_negative_delta_across_reset(self):
        """idelta is defined on raw samples: the step straddling a counter
        reset reports the negative raw diff (Prometheus semantics) — the
        rebase-only lane must preserve that."""
        from filodb_tpu.query.engine import kernels
        from filodb_tpu.query.engine.batch import SeriesBatch

        ts, vals, counts, steps, window = self._args()
        legacy = np.asarray(kernels.range_eval(
            "idelta", ts, vals, counts,
            np.array([75_000], np.int32), window))
        batch = SeriesBatch(0, ts, vals, counts, [0])
        ts_d, reb, cnt_d, _ = batch.delta_arrays(counter=False)
        lane = np.asarray(kernels.range_eval(
            "idelta", ts_d, reb, cnt_d, np.array([75_000], np.int32),
            window, pre_corrected=True))
        np.testing.assert_allclose(lane, legacy, rtol=1e-12)
        assert legacy[0, 0] == -1098.0  # 2 - 1100: raw negative delta
