"""Tiered federation tests: one PromQL query across memstore, the
downsample tier, and object-store history.

Covers the ``route_tiers`` seam semantics (every step in exactly one
tier, lookback satisfied across seams), the ``ColdTierStore`` ODP read
path over a real ``ObjectStoreColumnStore``, federated-vs-all-raw
equivalence with both seams in range, chaos (object-store latency and
fault injection → partial + warning, never wrong data), per-tier
``QueryStats`` attribution, governor cost classing, result-cache warm
behavior, and the ``/api/v1/status/tiers`` route on both HTTP fronts.
"""

import json
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.coordinator.tiered_planner import (
    TieredPlanner,
    build_tiered_planner,
)
from filodb_tpu.core.downsample import (
    DownsampledTimeSeriesStore,
    DownsamplerJob,
)
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.api import InMemoryColumnStore, InMemoryMetaStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.core.store.objectstore import ObjectStoreColumnStore
from filodb_tpu.promql.parser import TimeStepParams, parse_query
from filodb_tpu.query.exec.plan import ExecContext, StitchRvsExec
from filodb_tpu.query.federation import (
    DOWNSAMPLE,
    MEMSTORE,
    OBJECTSTORE,
    ColdTierStore,
    TierRange,
    route_tiers,
)
from filodb_tpu.testing.data import (
    counter_series,
    counter_stream,
    gauge_stream,
    machine_metrics_series,
)
from filodb_tpu.testing.fake_s3 import FakeS3, S3TransientError
from filodb_tpu.utils.resilience import RetryPolicy

START = 1_600_000_000
RES = 300_000  # 5m

# fixture timeline (seconds past START): data covers [0, +6000); the
# memstore tier floor sits at +4000 and the raw (object-store) floor at
# +2000 — queries over [+900, +5400] cross BOTH seams
NOW = (START + 6000) * 1000
MEM_FLOOR = (START + 4000) * 1000
RAW_FLOOR = (START + 2000) * 1000


def _grid(start, step, end):
    return list(range(start, end + 1, step))


def _steps(ranges, step):
    out = []
    for r in ranges:
        out.extend(_grid(r.start, step, r.end))
    return out


class TestRouteTiers:
    def test_all_memstore(self):
        assert route_tiers(100, 10, 200, 30, mem_floor=50,
                           raw_floor=0) == [TierRange(MEMSTORE, 100, 200)]

    def test_all_objectstore(self):
        assert route_tiers(100, 10, 200, 30, mem_floor=10_000,
                           raw_floor=0) == [TierRange(OBJECTSTORE, 100, 200)]

    def test_all_downsample(self):
        assert route_tiers(100, 10, 200, 30, mem_floor=10_000,
                           raw_floor=5_000) == [TierRange(DOWNSAMPLE,
                                                          100, 200)]

    def test_three_way_split(self):
        rs = route_tiers(0, 10, 100, 5, mem_floor=50, raw_floor=20)
        assert rs == [TierRange(DOWNSAMPLE, 0, 20),
                      TierRange(OBJECTSTORE, 30, 50),
                      TierRange(MEMSTORE, 60, 100)]

    def test_coverage_disjoint_exhaustive(self):
        """Every grid step lands in exactly one tier for a sweep of
        floor/lookback/step alignments (the seam property)."""
        start, end = 1000, 2000
        for step in (7, 10, 100):
            for lookback in (0, 3, step, 250):
                for mem_floor in (900, 1203, 1500, 2500):
                    for raw_floor in (None, 800, 1100, 1490):
                        rs = route_tiers(start, step, end, lookback,
                                         mem_floor, raw_floor)
                        got = _steps(rs, step)
                        assert got == _grid(start, step, end), (
                            step, lookback, mem_floor, raw_floor, rs)
                        # tiers appear oldest-first, at most once each
                        order = [r.tier for r in rs]
                        assert order == sorted(
                            order, key=[DOWNSAMPLE, OBJECTSTORE,
                                        MEMSTORE].index)
                        assert len(set(order)) == len(order)

    def test_exact_boundary_step_goes_to_newer_tier(self):
        """A step whose window starts EXACTLY on the tier floor is
        covered by that tier (>= semantics) — the off-by-one a naive
        ``>`` comparison would get wrong."""
        # step 100 at t=500 with lookback 200 → window [300, 500]
        rs = route_tiers(300, 100, 700, 200, mem_floor=300, raw_floor=0)
        assert rs == [TierRange(MEMSTORE, 500, 700)] or rs[-1].start == 500
        # one ms deeper floor pushes the boundary one full step newer
        rs2 = route_tiers(300, 100, 700, 200, mem_floor=301, raw_floor=0)
        assert rs2[-1] == TierRange(MEMSTORE, 600, 700)
        assert rs2[0] == TierRange(OBJECTSTORE, 300, 500)

    def test_lookback_satisfied_across_seams(self):
        """No tier is asked for a step whose lookback window reaches
        below that tier's data floor."""
        rs = route_tiers(0, 10, 1000, 35, mem_floor=500, raw_floor=100)
        for r in rs:
            floor = {MEMSTORE: 500, OBJECTSTORE: 100,
                     DOWNSAMPLE: -(2**62)}[r.tier]
            assert r.start - 35 >= floor

    def test_mem_floor_clamped_to_raw_floor(self):
        """Misconfiguration (memory retention longer than durable raw
        retention) must not double-route steps to ds AND memstore."""
        rs = route_tiers(0, 10, 100, 0, mem_floor=20, raw_floor=50)
        assert _steps(rs, 10) == _grid(0, 10, 100)
        assert [r.tier for r in rs] == [DOWNSAMPLE, MEMSTORE]

    def test_no_ds_tier_when_raw_floor_none(self):
        rs = route_tiers(0, 10, 100, 0, mem_floor=50, raw_floor=None)
        assert [r.tier for r in rs] == [OBJECTSTORE, MEMSTORE]


def build_env(cs=None, num_shards=2, n_samples=600, counter=False,
              read_cs=None):
    """Memstore + flushed column store + series keys. ``read_cs`` (for
    object-store backends) is a separate store instance over the same
    bucket, so cold-tier reads exercise real ranged GETs instead of the
    writer's in-memory buffers."""
    cs = cs if cs is not None else InMemoryColumnStore()
    ms = TimeSeriesMemStore(cs, InMemoryMetaStore())
    for s in range(num_shards):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=120,
                                              groups_per_shard=2))
    if counter:
        keys = counter_series(4)
        stream = counter_stream(keys, n_samples, start_ms=START * 1000,
                                seed=7)
    else:
        keys = machine_metrics_series(6)
        stream = gauge_stream(keys, n_samples, start_ms=START * 1000)
    ingest_routed(ms, "timeseries", stream, num_shards, spread=0)
    ms.flush_all("timeseries")
    flush = getattr(cs, "flush", None)
    if flush is not None:
        flush()
    return ms, cs, keys


def build_planner(ms, cs, num_shards=2, with_ds=True, read_cs=None,
                  **kw):
    raw_planner = SingleClusterPlanner("timeseries", num_shards, spread=0)
    ds_planner = None
    raw_retention = None
    if with_ds:
        DownsamplerJob(cs, "timeseries", num_shards,
                       resolutions_ms=(RES,)).run(0, 2**62)
        ds_store = DownsampledTimeSeriesStore(cs, "timeseries", RES,
                                              num_shards)
        ds_planner = SingleClusterPlanner("timeseries", num_shards,
                                          spread=0, store=ds_store)
        raw_retention = NOW - RAW_FLOOR
    return build_tiered_planner(
        raw_planner, read_cs if read_cs is not None else cs, "timeseries",
        num_shards, mem_retention_ms=NOW - MEM_FLOOR,
        raw_retention_ms=raw_retention, ds_planner=ds_planner,
        now_ms=lambda: NOW, **kw)


def run(ms, planner, promql, start, step, end, ctx=None):
    plan = parse_query(promql, TimeStepParams(start, step, end))
    ep = planner.materialize(plan)
    ctx = ctx or ExecContext(ms, "timeseries")
    return ep.dispatcher.dispatch(ep, ctx), ep, ctx


class TestColdTierStore:
    def test_reads_match_memstore(self):
        """The cold facade pages the SAME raw chunks the memstore holds —
        per-partition samples must match exactly."""
        ms, cs, keys = build_env(num_shards=1)
        cold = ColdTierStore(cs, "timeseries", 1)
        sh = cold.get_shard("timeseries", 0)
        hot = ms.get_shard("timeseries", 0)
        from filodb_tpu.core.filters import ColumnFilter, Equals
        f = [ColumnFilter("_metric_", Equals("heap_usage"))]
        pids = sh.lookup_partitions(f, 0, 2**62)
        assert len(pids) == 6
        hot_pids = hot.lookup_partitions(f, 0, 2**62)
        hot_by_key = {hot.partition(p).part_key: p for p in hot_pids}
        for pid in pids:
            part = sh.partition(pid)
            ts, vals = part.read_samples(0, 2**62)
            hts, hvals = hot.partition(
                hot_by_key[part.part_key]).read_samples(0, 2**62)
            np.testing.assert_array_equal(ts, hts)
            np.testing.assert_array_equal(vals, hvals)
            assert part.chunks_read > 0

    def test_odp_cache_serves_covered_repeat(self):
        ms, cs, keys = build_env(num_shards=1)
        cold = ColdTierStore(cs, "timeseries", 1)
        sh = cold.get_shard("timeseries", 0)
        pid = sh.lookup_partitions([], 0, 2**62)[0]
        part = sh.partition(pid)
        part.read_samples(0, 2**62)
        paged = sh.stats.chunks_paged_in.value
        assert paged > 0 and len(sh.odp_cache) == paged
        part.read_samples(0, 2**62)  # covered repeat: no new paging
        assert sh.stats.chunks_paged_in.value == paged
        cold.clear_caches()
        assert cold.cache_chunks() == 0


class TestFederatedEquivalence:
    def test_two_tier_exact_match(self):
        """memstore + objectstore read IDENTICAL raw chunks → federated
        result must equal the all-raw control bit-for-bit."""
        ms, cs, keys = build_env()
        planner = build_planner(ms, cs, with_ds=False)
        raw = SingleClusterPlanner("timeseries", 2, spread=0)
        q = "max_over_time(heap_usage[10m])"
        r, ep, ctx = run(ms, planner, q, START + 900, 300, START + 5400)
        assert isinstance(ep, StitchRvsExec)
        ctl, _, _ = run(ms, raw, q, START + 900, 300, START + 5400)
        assert r.result.num_series == ctl.result.num_series == 6
        np.testing.assert_array_equal(r.result.steps_ms,
                                      ctl.result.steps_ms)
        ctl_vals = ctl.result.values[_row_order(ctl.result, r.result)]
        np.testing.assert_allclose(r.result.values, ctl_vals,
                                   equal_nan=True)

    def test_three_tier_sum_rate_within_tolerance(self):
        """sum(rate(counter[15m])) spanning all three tiers matches the
        all-raw control: exact on the raw tiers, rollup tolerance on the
        downsample portion, and no dropped/duplicated steps at either
        seam."""
        ms, cs, keys = build_env(counter=True)
        planner = build_planner(ms, cs, with_ds=True)
        raw = SingleClusterPlanner("timeseries", 2, spread=0)
        q = "sum(rate(http_requests_total[15m]))"
        start, step, end = START + 1200, 300, START + 5400
        r, ep, ctx = run(ms, planner, q, start, step, end)
        ctl, _, _ = run(ms, raw, q, start, step, end)
        fed, control = r.result, ctl.result
        assert fed.num_series == control.num_series == 1
        # seam integrity: the full grid, strictly increasing, no dupes
        expected = np.arange(start * 1000, end * 1000 + 1, step * 1000)
        np.testing.assert_array_equal(fed.steps_ms, expected)
        assert (np.diff(fed.steps_ms) > 0).all()
        # every step the control answers, the federated result answers
        m = np.isfinite(control.values)
        assert np.isfinite(fed.values[m]).all()
        # raw-backed steps (objectstore + memstore tiers) agree exactly
        raw_steps = fed.steps_ms >= RAW_FLOOR + 15 * 60 * 1000
        np.testing.assert_allclose(fed.values[:, raw_steps],
                                   control.values[:, raw_steps],
                                   rtol=1e-9, equal_nan=True)
        # downsampled steps agree within the repo-wide rollup tolerance
        mm = m & np.isfinite(fed.values)
        ratio = fed.values[mm] / control.values[mm]
        assert 0.5 < np.median(ratio) < 2.0
        assert set(ctx.stats.tiers) == {MEMSTORE, OBJECTSTORE, DOWNSAMPLE}

    def test_hot_path_untouched(self):
        """A query fully inside memstore retention materializes through
        the raw planner directly — no TierExec, no stitch."""
        ms, cs, keys = build_env()
        planner = build_planner(ms, cs, with_ds=False)
        q = "max_over_time(heap_usage[5m])"
        r, ep, ctx = run(ms, planner, q, START + 4500, 300, START + 5400)
        assert not isinstance(ep, StitchRvsExec)
        assert "TierExec" not in repr(ep)
        assert r.result.num_series == 6
        assert not ctx.stats.tiers


def _row_order(a, b):
    """Index array reordering ``a``'s rows to ``b``'s key order."""
    pos = {k: i for i, k in enumerate(a.keys)}
    return np.array([pos[k] for k in b.keys], dtype=np.int64)


def _objectstore_env(tmp_path, **kw):
    """Writer + independent reader over one FakeS3 root: cold-tier reads
    go through real ranged GETs, not the writer's write-behind buffers."""
    s3root = str(tmp_path / "s3")
    s3 = FakeS3(root=s3root)
    cs = ObjectStoreColumnStore(s3)
    ms, _, keys = build_env(cs=cs)
    read_s3 = FakeS3(root=s3root)
    read_cs = ObjectStoreColumnStore(
        read_s3, read_retry_policy=RetryPolicy(max_attempts=2,
                                               base_backoff_s=0.01,
                                               max_backoff_s=0.05))
    planner = build_planner(ms, cs, with_ds=False, read_cs=read_cs, **kw)
    return ms, planner, read_s3, read_cs


Q_SPAN = ("max_over_time(heap_usage[10m])", START + 900, 300, START + 5400)


class TestChaos:
    def test_objectstore_latency_slow_but_correct(self, tmp_path):
        ms, planner, s3, _ = _objectstore_env(tmp_path)
        s3.latency_s = 0.01
        r, ep, ctx = run(ms, planner, *Q_SPAN)
        ctl, _, _ = run(ms, SingleClusterPlanner("timeseries", 2, spread=0),
                        *Q_SPAN)
        assert not r.partial
        ctl_vals = ctl.result.values[_row_order(ctl.result, r.result)]
        np.testing.assert_allclose(r.result.values, ctl_vals,
                                   equal_nan=True)

    def test_objectstore_fault_partial_plus_warning(self, tmp_path):
        """A cold tier lost to transport faults degrades to partial +
        warning; the steps that ARE answered match the control — never
        wrong data."""
        ms, planner, s3, _ = _objectstore_env(tmp_path)
        s3.inject("get", times=100,
                  exc=S3TransientError("injected outage"))
        r, ep, ctx = run(ms, planner, *Q_SPAN)
        assert r.partial
        assert any("lost" in w for w in r.warnings)
        ctl, _, _ = run(ms, SingleClusterPlanner("timeseries", 2, spread=0),
                        *Q_SPAN)
        fed, control = r.result, ctl.result
        # the lost cold tier's steps are absent; the surviving steps are
        # a suffix of the control grid and must match it exactly
        assert fed.num_steps > 0  # memstore tier still answered
        cols = np.searchsorted(control.steps_ms, fed.steps_ms)
        np.testing.assert_array_equal(control.steps_ms[cols], fed.steps_ms)
        ctl_vals = control.values[_row_order(control, fed)][:, cols]
        both = np.isfinite(fed.values) & np.isfinite(ctl_vals)
        assert both.any()
        np.testing.assert_allclose(fed.values[both], ctl_vals[both])

    def test_corrupt_segment_errors_never_wrong_data(self, tmp_path):
        from filodb_tpu.core.store.objectstore import CorruptSegmentError
        ms, planner, s3, read_cs = _objectstore_env(tmp_path)
        for key in s3.list_objects(""):
            if key.endswith(".seg"):
                s3.corrupt(key,
                           offset=len(s3.get_object(key)) // 2)
        with pytest.raises(CorruptSegmentError):
            run(ms, planner, *Q_SPAN)


class TestPerTierStats:
    def test_stats_all_reports_per_tier_buckets(self, tmp_path):
        ms, planner, s3, _ = _objectstore_env(tmp_path)
        svc = QueryService(ms, "timeseries", 2, spread=0)
        svc.planner = planner
        qr = svc.query_range(*Q_SPAN)
        tiers = qr.stats.tiers
        assert set(tiers) == {MEMSTORE, OBJECTSTORE}
        for t, b in tiers.items():
            assert b["subqueries"] == 1
            assert b["series"] > 0 and b["chunks"] > 0
            assert b["wallMs"] > 0
        # cold bytes moved over the (fake) wire; hot tier read memory
        assert tiers[OBJECTSTORE]["bytes"] > 0
        assert tiers[MEMSTORE]["bytes"] == 0
        assert tiers[OBJECTSTORE]["decodeMs"] >= 0
        # ?stats=all JSON face
        from filodb_tpu.http.promjson import _stats_json
        doc = _stats_json(qr, full=True)
        assert set(doc["tiers"]) == {MEMSTORE, OBJECTSTORE}
        assert doc["tiers"][OBJECTSTORE]["bytes"] > 0
        json.dumps(doc)  # serializable as-is

    def test_federation_counters_move(self):
        from filodb_tpu.query.federation import fed_queries, fed_sub_memstore
        ms, cs, keys = build_env()
        planner = build_planner(ms, cs, with_ds=False)
        q0, s0 = fed_queries.value, fed_sub_memstore.value
        run(ms, planner, *Q_SPAN)
        assert fed_queries.value == q0 + 1
        assert fed_sub_memstore.value == s0 + 1


class TestGovernorClassing:
    def test_cold_queries_classed_expensive(self):
        from filodb_tpu.utils.governor import EXPENSIVE
        ms, cs, keys = build_env()
        planner = build_planner(ms, cs, with_ds=False)
        cold_plan = parse_query("heap_usage",
                                TimeStepParams(START + 900, 300,
                                               START + 5400))
        hot_plan = parse_query("heap_usage",
                               TimeStepParams(START + 4500, 60,
                                              START + 4500))
        assert planner.cost_hint(cold_plan) == EXPENSIVE
        assert planner.cost_hint(hot_plan) is None
        assert not planner.mem_only(cold_plan)
        assert planner.mem_only(hot_plan)

    def test_query_service_uses_cost_hint_and_mem_only(self):
        """The service consults the planner for admission cost AND mesh
        eligibility, so straddling queries never serve raw-only data
        through the mesh bypass."""
        ms, cs, keys = build_env()
        svc = QueryService(ms, "timeseries", 2, spread=0)
        svc.planner = build_planner(ms, cs, with_ds=False)
        cold_plan = parse_query("heap_usage",
                                TimeStepParams(START + 900, 300,
                                               START + 5400))
        assert not svc._planner_mem_only(cold_plan)
        qr = svc.query_range("max_over_time(heap_usage[10m])",
                             START + 900, 300, START + 5400)
        assert set(qr.stats.tiers) == {MEMSTORE, OBJECTSTORE}

    def test_longtime_planner_hooks(self):
        from filodb_tpu.coordinator.longtime_planner import (
            LongTimeRangePlanner,
        )
        from filodb_tpu.utils.governor import EXPENSIVE
        p = LongTimeRangePlanner(
            SingleClusterPlanner("timeseries", 1, spread=0),
            SingleClusterPlanner("timeseries", 1, spread=0),
            raw_retention_ms=NOW - RAW_FLOOR, now_ms=lambda: NOW)
        cold = parse_query("heap_usage", TimeStepParams(START + 900, 300,
                                                        START + 5400))
        hot = parse_query("heap_usage", TimeStepParams(START + 4500, 60,
                                                       START + 4500))
        assert not p.mem_only(cold) and p.mem_only(hot)
        assert p.cost_hint(cold) == EXPENSIVE and p.cost_hint(hot) is None


class TestResultCacheComposition:
    def test_warm_repeat_reads_no_objectstore_bytes(self, tmp_path):
        """Second identical federated query settles from the extent
        cache: strictly fewer object-store GETs (zero) than the cold
        run, identical answer."""
        ms, planner, s3, _ = _objectstore_env(tmp_path)
        svc = QueryService(ms, "timeseries", 2, spread=0,
                           result_cache={"enabled": True,
                                         "extent_steps": 8})
        svc.planner = planner
        r1 = svc.query_range(*Q_SPAN)
        gets_cold = s3.op_counts.get("get", 0)
        assert gets_cold > 0
        r2 = svc.query_range(*Q_SPAN)
        gets_warm = s3.op_counts.get("get", 0) - gets_cold
        assert gets_warm == 0
        np.testing.assert_allclose(
            r2.result.values,
            r1.result.values[_row_order(r1.result, r2.result)],
            equal_nan=True)
        # the caching wrapper must not flatten the expanded stats: the
        # per-tier buckets and hit/miss counters survive extent assembly
        assert OBJECTSTORE in r1.stats.tiers
        assert r1.stats.cache_misses > 0
        assert r2.stats.cache_hits > 0

    def test_version_token_invalidates_on_tier_growth(self):
        ms, cs, keys = build_env()
        planner = build_planner(ms, cs, with_ds=False)
        t0 = planner.version_token()
        # cold index bootstraps lazily: a refresh discovers the flushed
        # part keys and bumps the token → cached extents re-key
        for sh in planner.cold_planner.store.shards_for("timeseries"):
            sh.refresh_index()
        assert planner.version_token() > t0


@pytest.fixture(scope="module", params=["threaded", "fast"])
def fed_server(request):
    """Federated dataset behind BOTH HTTP fronts."""
    ms, cs, keys = build_env()
    svc = QueryService(ms, "timeseries", 2, spread=0)
    svc.planner = build_planner(ms, cs, with_ds=True)
    from filodb_tpu.http.server import FiloHttpServer
    if request.param == "fast":
        from filodb_tpu.http.fastserver import FastHttpServer
        srv = FastHttpServer({"timeseries": svc}, port=0).start()
    else:
        srv = FiloHttpServer({"timeseries": svc}, port=0).start()
    yield srv
    srv.stop()


def _get(srv, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{srv.port}{path}" + (f"?{qs}" if qs else "")
    with urllib.request.urlopen(url) as r:
        return r.status, json.loads(r.read())


class TestStatusTiersRoute:
    def test_tiers_route_both_fronts(self, fed_server):
        status, body = _get(fed_server, "/api/v1/status/tiers",
                            dataset="timeseries")
        assert status == 200 and body["status"] == "success"
        doc = body["data"]["timeseries"]
        assert doc["federated"] is True
        assert doc["memFloorMs"] == MEM_FLOOR
        assert doc["rawFloorMs"] == RAW_FLOOR
        by_tier = {t["tier"]: t for t in doc["tiers"]}
        assert set(by_tier) == {MEMSTORE, OBJECTSTORE, DOWNSAMPLE}
        assert by_tier[MEMSTORE]["series"] == 6
        assert by_tier[OBJECTSTORE]["series"] == 6
        assert by_tier[DOWNSAMPLE]["series"] == 6
        assert by_tier[DOWNSAMPLE]["resolutionMs"] == RES
        assert by_tier[OBJECTSTORE]["ceilMs"] == MEM_FLOOR
        assert by_tier[OBJECTSTORE]["floorMs"] == RAW_FLOOR

    def test_stats_all_over_http(self, fed_server):
        status, body = _get(
            fed_server, "/promql/timeseries/api/v1/query_range",
            query="max_over_time(heap_usage[10m])", start=START + 900,
            step=300, end=START + 5400, stats="all")
        assert status == 200
        tiers = body["queryStats"]["tiers"]
        assert set(tiers) == {MEMSTORE, OBJECTSTORE, DOWNSAMPLE}
        for b in tiers.values():
            assert b["subqueries"] >= 1

    def test_cli_tiers(self, fed_server, capsys):
        from filodb_tpu.cli import main
        rc = main(["--host", f"127.0.0.1:{fed_server.port}",
                   "--dataset", "timeseries", "tiers"])
        assert not rc
        out = capsys.readouterr().out
        assert "federated=True" in out
        for tier in (MEMSTORE, OBJECTSTORE, DOWNSAMPLE):
            assert tier in out

    def test_cli_tiers_json(self, fed_server, capsys):
        from filodb_tpu.cli import main
        rc = main(["--host", f"127.0.0.1:{fed_server.port}",
                   "--dataset", "timeseries", "tiers", "--json"])
        assert not rc
        doc = json.loads(capsys.readouterr().out)
        assert doc["federated"] is True


class TestTieredPlannerUnit:
    def test_timeless_plans_route_raw(self):
        """Plans with no periodic grid (raw chunk export) bypass tier
        routing entirely and go to the raw planner."""
        ms, cs, keys = build_env(num_shards=1)
        planner = build_planner(ms, cs, num_shards=1, with_ds=False)
        from filodb_tpu.core.filters import ColumnFilter, Equals
        from filodb_tpu.query import logical as lp
        raw = lp.RawSeries(
            (ColumnFilter("_metric_", Equals("heap_usage")),),
            START * 1000, (START + 6000) * 1000)
        ep = planner.materialize(raw)
        assert "TierExec" not in repr(ep)  # no times → raw fan-out

    def test_standalone_wires_tiered_planner_on_optin(self, tmp_path):
        """FiloServer swaps in a TieredPlanner only when the operator
        sets an explicit memstore horizon; without one the planner stays
        untouched (synthetic-old-timestamp data would otherwise route to
        a cold tier that has not been uploaded yet)."""
        from filodb_tpu.config import ServerConfig
        from filodb_tpu.standalone import FiloServer
        base = {"node_name": "fed-node", "http_port": 0, "gateway_port": 0,
                "datasets": {"timeseries": {
                    "num_shards": 1,
                    "store": {"max_chunk_size": 50}}}}
        cfg = dict(base, data_dir=str(tmp_path / "a"),
                   federation={"mem_retention_ms": 10**15})
        p = tmp_path / "fed.json"
        p.write_text(json.dumps(cfg))
        srv = FiloServer(ServerConfig.load(str(p))).start()
        try:
            assert isinstance(srv.http.services["timeseries"].planner,
                              TieredPlanner)
        finally:
            srv.shutdown()
        cfg2 = dict(base, data_dir=str(tmp_path / "b"))
        p2 = tmp_path / "nofed.json"
        p2.write_text(json.dumps(cfg2))
        srv2 = FiloServer(ServerConfig.load(str(p2))).start()
        try:
            assert not isinstance(srv2.http.services["timeseries"].planner,
                                  TieredPlanner)
        finally:
            srv2.shutdown()

    def test_single_cold_range_skips_stitch(self):
        ms, cs, keys = build_env(num_shards=1)
        planner = build_planner(ms, cs, num_shards=1, with_ds=False)
        q = "max_over_time(heap_usage[10m])"
        r, ep, ctx = run(ms, planner, q, START + 900, 300, START + 2400)
        assert "TierExec" in repr(ep) and not isinstance(ep, StitchRvsExec)
        assert r.result.num_series == 6
        assert set(ctx.stats.tiers) == {OBJECTSTORE}
