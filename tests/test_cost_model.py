"""Trace-driven adaptive planner (PR 18): the online cost model behind
every either/or planning decision.

Covers the estimator (EWMA + reservoir, LRU bound), the decide/classify
routing contract, the deferred-settle plumbing, metastore persistence
(restart survival), cold-start static parity (below ``min_samples`` —
and under ``FILODB_ADAPTIVE=0`` — every site reproduces the static
heuristic bit-for-bit), predicted-cost result-cache admission under
byte pressure, the governor's live Retry-After provider, and the
``/api/v1/debug/costmodel`` endpoint on both HTTP fronts.
"""

import json
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.coordinator import adaptive_planner as ap
from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.core.store.localstore import LocalDiskMetaStore
from filodb_tpu.query import cost_model as cm
from filodb_tpu.query.cost_model import CostModel, Decision
from filodb_tpu.query.model import RangeVectorKey, StepMatrix
from filodb_tpu.query.result_cache import ResultCache, ResultCacheConfig
from filodb_tpu.testing.data import gauge_stream, machine_metrics_series
from filodb_tpu.utils import governor as gov

START = 1_600_000_000


# --------------------------------------------------------------------------
# estimator

class TestEstimator:
    def test_ewma_warm_up_then_smooth(self):
        m = CostModel(min_samples=2)
        m.observe("paging", "s", "exact", 1.0)
        assert m.estimate("paging", "s", "exact") is None  # n=1 < 2
        m.observe("paging", "s", "exact", 3.0)
        # first two samples replace (PR 14 _LaneCost semantics)
        assert m.estimate("paging", "s", "exact") == 3.0
        m.observe("paging", "s", "exact", 13.0)
        assert m.estimate("paging", "s", "exact") == pytest.approx(
            3.0 + 0.3 * (13.0 - 3.0))

    def test_percentiles_from_reservoir(self):
        m = CostModel(min_samples=1)
        for v in range(1, 11):
            m.observe("admit", "class:expensive", "wall", float(v))
        assert m.percentile("admit", "class:expensive", "wall", 0.5) \
            == pytest.approx(5.0)
        assert m.percentile("admit", "class:expensive", "wall", 0.9) \
            == pytest.approx(9.0)
        assert m.percentile("admit", "missing", "wall", 0.9) is None

    def test_signature_table_is_lru_bounded(self):
        m = CostModel(min_samples=1)
        m.max_signatures = 4  # constructor clamps to >=16; pin for the test
        for i in range(8):
            m.observe("paging", f"sig{i}", "exact", 0.01)
        assert len(m._stats) == 4
        # newest signatures survive
        assert ("paging", "sig7") in m._stats
        assert ("paging", "sig0") not in m._stats

    def test_signature_key_is_stable_not_hash_randomized(self):
        # persisted signatures must survive interpreter restarts, so
        # non-string signatures hash with blake2b, never Python hash()
        assert cm.signature_key("short:sig") == "short:sig"
        k = cm.signature_key(("a", 17))
        assert k == cm.signature_key(("a", 17))
        assert len(k) == 16


# --------------------------------------------------------------------------
# decide / classify contract

class TestDecide:
    def test_cold_model_returns_static_arm(self):
        m = CostModel()
        for site in cm.SITES:
            d = m.decide(site, "sig", ("a", "b"), static_arm="b")
            assert (d.arm, d.source) == ("b", "static")

    def test_warm_model_routes_to_cheaper_arm(self):
        m = CostModel(min_samples=2)
        for _ in range(3):
            m.observe("sidecar", "s", "sidecar", 0.001)
            m.observe("sidecar", "s", "decode", 0.5)
        d = m.decide("sidecar", "s", ("sidecar", "decode"),
                     static_arm="decode")
        assert (d.arm, d.source) == ("sidecar", "model")
        assert d.predicted == pytest.approx(0.001)

    def test_one_cold_arm_pins_static_when_require_all(self):
        # natural traffic only settles the taken arm; require_all keeps
        # the model from flipping on one-sided evidence
        m = CostModel(min_samples=2)
        for _ in range(5):
            m.observe("sidecar", "s", "decode", 0.5)
        d = m.decide("sidecar", "s", ("sidecar", "decode"),
                     static_arm="decode")
        assert (d.arm, d.source) == ("decode", "static")

    def test_require_all_false_keeps_min_over_known(self):
        # the lane router's PR 14 semantics: route by whatever is warm
        m = CostModel(min_samples=2)
        for _ in range(3):
            m.observe("lane", "b4", "device", 0.002)
        d = m.decide("lane", "b4", ("device", "single", "host"),
                     static_arm="host", require_all=False)
        assert (d.arm, d.source) == ("device", "model")

    def test_env_kill_switch_pins_static(self, monkeypatch):
        monkeypatch.setenv("FILODB_ADAPTIVE", "0")
        m = CostModel(min_samples=1)
        m.observe("sidecar", "s", "sidecar", 0.001)
        m.observe("sidecar", "s", "decode", 0.5)
        d = m.decide("sidecar", "s", ("sidecar", "decode"),
                     static_arm="decode")
        assert (d.arm, d.source) == ("decode", "static")

    def test_override_wins_over_warm_model(self):
        m = CostModel(min_samples=1)
        m.observe("sidecar", "s", "sidecar", 9.0)
        m.observe("sidecar", "s", "decode", 0.1)
        d = m.decide("sidecar", "s", ("sidecar", "decode"),
                     static_arm="decode", override="sidecar")
        assert (d.arm, d.source) == ("sidecar", "override")

    def test_classify_threshold_and_wall_settle(self):
        m = CostModel(min_samples=2)
        d = m.classify("admit", "class", 0.05, below_arm="cheap",
                       above_arm="expensive", static_arm="expensive")
        assert (d.arm, d.source) == ("expensive", "static")
        for _ in range(3):
            m.observe("admit", "class", "wall", 0.001)
        d = m.classify("admit", "class", 0.05, below_arm="cheap",
                       above_arm="expensive", static_arm="expensive")
        assert (d.arm, d.source) == ("cheap", "model")
        # settles under the wall arm regardless of the chosen class
        m.record_actual(d, 0.002)
        assert m.samples("admit", "class", "wall") == 4


# --------------------------------------------------------------------------
# deferred settle

class _Carrier:
    pass


class TestDeferredSettle:
    def test_defer_then_settle_feeds_taken_arm(self):
        m = CostModel(min_samples=1)
        carrier = _Carrier()
        d = m.decide("sidecar", "s", ("sidecar", "decode"),
                     static_arm="sidecar")
        m.defer(carrier, d)
        CostModel.settle_deferred(carrier, 0.25)
        assert m.samples("sidecar", "s", "sidecar") == 1
        assert m.estimate("sidecar", "s", "sidecar") == pytest.approx(0.25)
        # list drained: a second settle is a no-op
        CostModel.settle_deferred(carrier, 9.9)
        assert m.samples("sidecar", "s", "sidecar") == 1

    def test_relabel_on_bypass_settles_fallback_arm(self):
        # mid-fold _Bypass: the sidecar arm never ran to completion, so
        # the wall time must land under "decode" with no calibration hit
        m = CostModel(min_samples=1)
        m.observe("sidecar", "s", "sidecar", 0.001)
        m.observe("sidecar", "s", "decode", 0.001)
        carrier = _Carrier()
        d = m.decide("sidecar", "s", ("sidecar", "decode"),
                     static_arm="decode")
        m.defer(carrier, d)
        CostModel.relabel_deferred(carrier, "sidecar", "decode")
        CostModel.settle_deferred(carrier, 0.5)
        assert m.samples("sidecar", "s", "decode") == 2
        assert m.samples("sidecar", "s", "sidecar") == 1

    def test_calibration_error_tracks_prediction_quality(self):
        m = CostModel(min_samples=1)
        for _ in range(3):
            m.observe("paging", "s", "exact", 0.1)
        d = m.decide("paging", "s", ("exact",), static_arm="exact",
                     require_all=False)
        assert d.source == "model"
        m.record_actual(d, 0.1)
        assert m.calibration()["paging"] == pytest.approx(0.0, abs=1e-6)
        ring = m.recent()
        assert ring and ring[-1]["site"] == "paging"


# --------------------------------------------------------------------------
# persistence (satellite 3): restart survival via the metastore

class TestPersistence:
    def _warm(self, m):
        for _ in range(10):
            m.observe("sidecar", "fold:pw1024", "sidecar", 0.002)
            m.observe("sidecar", "fold:pw1024", "decode", 0.4)

    def test_bytes_round_trip_preserves_routing(self):
        m = CostModel(dataset="ds", min_samples=2)
        self._warm(m)
        fresh = CostModel(dataset="ds", min_samples=2)
        assert fresh.from_bytes(m.to_bytes())
        d = fresh.decide("sidecar", "fold:pw1024", ("sidecar", "decode"),
                         static_arm="decode")
        assert (d.arm, d.source) == ("sidecar", "model")
        assert fresh.estimate("sidecar", "fold:pw1024", "decode") \
            == m.estimate("sidecar", "fold:pw1024", "decode")
        assert fresh.percentile("sidecar", "fold:pw1024", "decode", 0.9) \
            == m.percentile("sidecar", "fold:pw1024", "decode", 0.9)

    def test_restart_survival_via_local_meta_store(self, tmp_path):
        meta = LocalDiskMetaStore(str(tmp_path))
        m = CostModel(dataset="timeseries", min_samples=2)
        self._warm(m)
        m.save(meta)
        # "restart": a brand-new process-level model for the dataset
        reborn = CostModel(dataset="timeseries", min_samples=2)
        assert reborn.load(meta)
        d = reborn.decide("sidecar", "fold:pw1024", ("sidecar", "decode"),
                          static_arm="decode")
        assert (d.arm, d.source) == ("sidecar", "model")

    def test_load_missing_blob_is_clean_cold_start(self, tmp_path):
        meta = LocalDiskMetaStore(str(tmp_path))
        m = CostModel(dataset="never-saved")
        assert not m.load(meta)
        d = m.decide("sidecar", "s", ("a", "b"), static_arm="b")
        assert (d.arm, d.source) == ("b", "static")

    def test_corrupt_blob_is_clean_cold_start(self):
        m = CostModel(dataset="ds")
        assert not m.from_bytes(b"not json at all")
        assert len(m._stats) == 0

    def test_install_and_persist_lifecycle(self, tmp_path):
        meta = LocalDiskMetaStore(str(tmp_path))
        m = ap.install("timeseries", meta, {"min_samples": 2})
        self._warm(m)
        ap.persist("timeseries", meta)
        cm.reset_models()
        m2 = ap.install("timeseries", meta, {"min_samples": 2})
        d = m2.decide("sidecar", "fold:pw1024", ("sidecar", "decode"),
                      static_arm="decode")
        assert (d.arm, d.source) == ("sidecar", "model")


# --------------------------------------------------------------------------
# cold-start static parity (satellite 3): below min_samples and with the
# kill switch, the adaptive path reproduces the static plan bit-for-bit

NUM_SHARDS = 2


@pytest.fixture(scope="module")
def store():
    ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=64))
    keys = machine_metrics_series(6)
    ingest_routed(ms, "timeseries",
                  gauge_stream(keys, 600, start_ms=START * 1000,
                               interval_ms=10_000, seed=3),
                  NUM_SHARDS, spread=1)
    return ms


class TestColdStartParity:
    QUERIES = [
        "avg_over_time(heap_usage[3m])",
        "sum(avg_over_time(heap_usage[5m]))",
        "quantile_over_time(0.9, heap_usage[5m])",
    ]

    def _run_all(self, store):
        svc = QueryService(store, "timeseries", NUM_SHARDS, spread=1)
        out = []
        for q in self.QUERIES:
            r = svc.query_range(q, START + 600, 60, START + 4000)
            out.append((r.result.num_series,
                        np.asarray(r.result.values).tobytes()))
        return out

    def test_cold_adaptive_matches_disabled_bit_for_bit(
            self, store, monkeypatch):
        monkeypatch.setenv("FILODB_ADAPTIVE", "0")
        static = self._run_all(store)
        cm.reset_models()
        monkeypatch.setenv("FILODB_ADAPTIVE", "1")
        adaptive = self._run_all(store)
        for (ns, sb), (na, ab) in zip(static, adaptive):
            assert ns == na
            assert sb == ab

    def test_cold_queries_never_depart_from_static(self, store):
        # every decision the cold run made must carry source="static"
        # (or "override"); nothing routes by model before warm-up
        self._run_all(store)
        for model in cm.models().values():
            for row in model.recent():
                assert row.get("source", "static") != "model"


# --------------------------------------------------------------------------
# result-cache admission under byte pressure (satellite 1)

def _matrix(steps=64, series=2, seed=0):
    rng = np.random.default_rng(seed)
    keys = [RangeVectorKey.of({"k": f"s{seed}-{i}"}) for i in range(series)]
    return StepMatrix(keys, rng.random((series, steps)),
                      np.arange(steps, dtype=np.int64) * 60_000)


class TestCacheByteArbitration:
    def test_decode_extent_outlives_pyramid_served_extent(self):
        one = _matrix(seed=1)
        nbytes = int(one.values.nbytes) + int(one.steps_ms.nbytes)
        c = ResultCache(ResultCacheConfig(max_bytes=int(nbytes * 3.5)))
        c._put(("cheap-old",), None, _matrix(seed=1), cheap=True)
        c._put(("costly-old",), None, _matrix(seed=2), cheap=False)
        c._put(("cheap-new",), None, _matrix(seed=3), cheap=True)
        # budget forces one eviction: strict LRU would evict costly-old
        # (oldest is cheap-old... ) — cheap entries must go first
        c._put(("costly-new",), None, _matrix(seed=4), cheap=False)
        with c._lock:
            keys = set(c._lru)
        assert ("costly-old",) in keys, \
            "expensive-to-recompute extent was evicted before cheap ones"
        assert ("cheap-old",) not in keys
        assert c.nbytes <= c.config.max_bytes

    def test_cheap_exhausted_falls_back_to_lru(self):
        one = _matrix(seed=1)
        nbytes = int(one.values.nbytes) + int(one.steps_ms.nbytes)
        c = ResultCache(ResultCacheConfig(max_bytes=int(nbytes * 2.5)))
        c._put(("a",), None, _matrix(seed=1), cheap=False)
        c._put(("b",), None, _matrix(seed=2), cheap=False)
        c._put(("c",), None, _matrix(seed=3), cheap=False)
        with c._lock:
            keys = list(c._lru)
        assert ("a",) not in keys  # plain LRU once no cheap entry exists

    def test_reinsert_clears_cheap_bit(self):
        c = ResultCache(ResultCacheConfig(max_bytes=1 << 20))
        c._put(("k",), None, _matrix(seed=1), cheap=True)
        assert ("k",) in c._cheap
        c._put(("k",), None, _matrix(seed=1), cheap=False)
        assert ("k",) not in c._cheap


# --------------------------------------------------------------------------
# governor Retry-After from live percentiles

class TestRetryAfter:
    def teardown_method(self):
        gov.reset()

    def test_provider_none_falls_back_to_static(self):
        assert gov._advised_retry_after("capacity", 1.0) == 1.0
        gov.set_retry_after_provider(lambda reason: None)
        assert gov._advised_retry_after("capacity", 1.0) == 1.0

    def test_provider_exception_falls_back(self):
        def boom(reason):
            raise RuntimeError("no")
        gov.set_retry_after_provider(boom)
        assert gov._advised_retry_after("capacity", 1.0) == 1.0

    def test_provider_value_clamped(self):
        gov.set_retry_after_provider(lambda reason: 500.0)
        assert gov._advised_retry_after("capacity", 1.0) == 60.0
        gov.set_retry_after_provider(lambda reason: 0.0001)
        assert gov._advised_retry_after("capacity", 1.0) == 0.05

    def test_live_percentile_flows_from_settled_queries(self):
        m = cm.model_for("timeseries")
        m.configure(min_samples=1)
        for v in (0.2, 0.4, 0.6, 0.8, 1.0):
            m.observe("admit", f"class:{gov.EXPENSIVE}", "wall", v)
        advised = ap.retry_after_provider("capacity")
        assert advised == pytest.approx(1.0)  # p90 of the reservoir
        assert ap.retry_after_provider("rules") is None  # cold class

    def test_reset_clears_provider(self):
        gov.set_retry_after_provider(lambda reason: 2.0)
        gov.reset()
        assert gov._advised_retry_after("capacity", 1.0) == 1.0


# --------------------------------------------------------------------------
# /api/v1/debug/costmodel on both HTTP fronts (satellite 2)

@pytest.fixture(params=["threaded", "fast"])
def server(request, store):
    svc = QueryService(store, "timeseries", NUM_SHARDS, spread=1)
    if request.param == "fast":
        from filodb_tpu.http.fastserver import FastHttpServer
        srv = FastHttpServer({"timeseries": svc}, port=0).start()
    else:
        from filodb_tpu.http.server import FiloHttpServer
        srv = FiloHttpServer({"timeseries": svc}, port=0).start()
    yield srv
    srv.stop()


def _get(server, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{server.port}{path}" + (f"?{qs}" if qs else "")
    with urllib.request.urlopen(url) as r:
        return r.status, json.loads(r.read())


class TestCostModelEndpoint:
    def test_debug_costmodel_snapshot(self, server):
        m = cm.model_for("timeseries")
        for _ in range(3):
            m.observe("sidecar", "fold:pw512", "sidecar", 0.002)
        code, body = _get(server,
                          "/promql/timeseries/api/v1/debug/costmodel")
        assert code == 200 and body["status"] == "success"
        snap = body["data"]
        assert snap["dataset"] == "timeseries"
        assert snap["signatures"] >= 1
        rows = snap["estimates"]
        assert any(r["site"] == "sidecar" and r["arm"] == "sidecar"
                   and r["n"] == 3 for r in rows)
        assert {"p50_s", "p90_s", "warm", "estimate_s"} <= set(rows[0])

    def test_debug_costmodel_limit(self, server):
        m = cm.model_for("timeseries")
        for i in range(5):
            m.observe("paging", f"page:span{i}", "exact", 0.01)
        code, body = _get(server,
                          "/promql/timeseries/api/v1/debug/costmodel",
                          limit=2)
        assert code == 200
        assert len(body["data"]["estimates"]) == 2
