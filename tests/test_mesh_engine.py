"""Mesh query engine parity: PromQL → planner → (shard × time) device mesh.

The mesh path (``parallel/mesh_engine.py``) must return byte-comparable
results to the scatter-gather exec path for every supported plan shape, on
the virtual 8-device CPU mesh (conftest sets
``--xla_force_host_platform_device_count=8``). Reference boundary replaced:
``query/src/main/scala/filodb/query/exec/ExecPlan.scala:41`` scatter-gather.
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.testing.data import (
    counter_series,
    counter_stream,
    gauge_stream,
    machine_metrics_series,
)

START = 1_600_000_000
NUM_SHARDS = 4


def build_store(kind="counter", n_series=24, n_samples=240):
    ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=100,
                                              groups_per_shard=4))
    if kind == "counter":
        keys = counter_series(n_series, metric="http_requests_total")
        stream = counter_stream(keys, n_samples, start_ms=START * 1000,
                                interval_ms=10_000, seed=3)
    else:
        keys = machine_metrics_series(n_series, metric="gauge_metric")
        stream = gauge_stream(keys, n_samples, start_ms=START * 1000,
                              interval_ms=10_000, seed=3)
    ingest_routed(ms, "timeseries", stream, NUM_SHARDS, spread=1)
    return ms


def services(ms):
    exec_svc = QueryService(ms, "timeseries", NUM_SHARDS, spread=1)
    mesh_svc = QueryService(ms, "timeseries", NUM_SHARDS, spread=1,
                            engine="mesh")
    return exec_svc, mesh_svc


def assert_same(r_exec, r_mesh):
    e, m = r_exec.result, r_mesh.result
    assert sorted(map(str, e.keys)) == sorted(map(str, m.keys))
    np.testing.assert_array_equal(e.steps_ms, m.steps_ms)
    order_e = np.argsort([str(k) for k in e.keys])
    order_m = np.argsort([str(k) for k in m.keys])
    np.testing.assert_allclose(e.values[order_e], m.values[order_m],
                               rtol=1e-6, atol=1e-9, equal_nan=True)


class TestMeshParity:
    @pytest.fixture(scope="class")
    def counter_store(self):
        return build_store("counter")

    @pytest.fixture(scope="class")
    def gauge_store(self):
        return build_store("gauge")

    def q(self, svc, query):
        return svc.query_range(query, START + 600, 60, START + 1800)

    def test_sum_rate_global(self, counter_store):
        e, m = services(counter_store)
        query = 'sum(rate(http_requests_total[5m]))'
        assert_same(self.q(e, query), self.q(m, query))

    def test_sum_rate_by_labels(self, counter_store):
        e, m = services(counter_store)
        query = 'sum(rate(http_requests_total[5m])) by (_ns_)'
        assert_same(self.q(e, query), self.q(m, query))

    def test_sum_rate_with_filters(self, counter_store):
        e, m = services(counter_store)
        query = 'sum(rate(http_requests_total{_ns_="App-0"}[2m])) by (instance)'
        assert_same(self.q(e, query), self.q(m, query))

    @pytest.mark.parametrize("fn", ["sum_over_time", "count_over_time",
                                    "avg_over_time", "min_over_time",
                                    "max_over_time", "last_over_time"])
    @pytest.mark.parametrize("agg", ["sum", "avg", "count", "min", "max"])
    def test_agg_fn_matrix(self, gauge_store, fn, agg):
        e, m = services(gauge_store)
        query = f'{agg}({fn}(gauge_metric[3m])) by (_ns_)'
        assert_same(self.q(e, query), self.q(m, query))

    def test_by_metric_label_groups_on_nothing(self, counter_store):
        # exec drops the metric label from range-fn output keys before
        # grouping; by (_metric_) must therefore collapse to one group
        e, m = services(counter_store)
        query = 'sum(rate(http_requests_total[5m])) by (_metric_)'
        re, rm = self.q(e, query), self.q(m, query)
        assert_same(re, rm)
        assert rm.result.num_series == 1
        assert rm.result.keys[0].labels == ()

    def test_sample_limit_enforced_on_mesh_path(self, counter_store):
        from filodb_tpu.query.model import (
            PlannerParams,
            QueryContext,
            QueryLimitExceeded,
        )
        _, m = services(counter_store)
        qctx = QueryContext(planner_params=PlannerParams(
            enforce_sample_limit=True, sample_limit=3))
        with pytest.raises(QueryLimitExceeded):
            m.query_range('sum(rate(http_requests_total[5m])) by (instance)',
                          START + 600, 60, START + 1800, qcontext=qctx)

    def test_instant_query(self, counter_store):
        e, m = services(counter_store)
        query = 'sum(rate(http_requests_total[5m])) by (_ns_)'
        re = e.query_instant(query, START + 1200)
        rm = m.query_instant(query, START + 1200)
        assert_same(re, rm)

    def test_empty_selector(self, counter_store):
        e, m = services(counter_store)
        query = 'sum(rate(no_such_metric[5m]))'
        re, rm = self.q(e, query), self.q(m, query)
        assert re.result.num_series == rm.result.num_series == 0

    def test_mesh_used_not_fallback(self, counter_store):
        _, m = services(counter_store)
        plan_hits = []
        orig = m.mesh_engine.execute

        def spy(*a, **kw):
            out = orig(*a, **kw)
            plan_hits.append(out is not None)
            return out

        m.mesh_engine.execute = spy
        self.q(m, 'sum(rate(http_requests_total[5m])) by (_ns_)')
        assert plan_hits == [True]

    def test_unsupported_shapes_fall_back(self, counter_store):
        _, m = services(counter_store)
        # offset / unsupported fn / binary join: exec path answers them
        for query in [
            'sum(rate(http_requests_total[5m] offset 1m))',
            'sum(deriv(http_requests_total[5m]))',
            'topk(2, rate(http_requests_total[5m]))',
            'rate(http_requests_total[5m])',
        ]:
            r = self.q(m, query)
            assert r is not None  # executes via fallback without raising

    def test_mesh_skipped_when_shards_partial(self):
        # a coordinator facade in a multi-node cluster holds only its own
        # shards; the mesh must not serve partial data
        ms = TimeSeriesMemStore()
        for s in range(2):  # only 2 of 4 shards local
            ms.setup("timeseries", s, StoreConfig())
        svc = QueryService(ms, "timeseries", num_shards=4, spread=1,
                           engine="mesh")
        assert not svc._mesh_eligible()
        called = []
        svc.mesh_engine.execute = lambda *a, **kw: called.append(1)
        # the exec fallback needs remote dispatchers for the missing shards
        # (not wired in this test); the point is the mesh never engages
        with pytest.raises(KeyError):
            svc.query_range('sum(rate(x[5m]))', START, 60, START + 600)
        assert not called

    def test_topk_wrapper_on_mesh(self, counter_store):
        e, m = services(counter_store)
        query = 'topk(2, sum(rate(http_requests_total[5m])) by (instance))'
        re, rm = self.q(e, query), self.q(m, query)
        assert_same(re, rm)
        # the mesh path actually engaged (not the exec fallback)
        hits = []
        orig = m.mesh_engine.execute
        m.mesh_engine.execute = lambda *a, **kw: (hits.append(1),
                                                  orig(*a, **kw))[1]
        self.q(m, query)
        assert hits

    def test_ring_variant_parity(self, counter_store):
        from filodb_tpu.parallel.mesh_engine import MeshQueryEngine
        e, m = services(counter_store)
        m.mesh_engine = MeshQueryEngine(variant="ring")
        query = 'sum(rate(http_requests_total[5m])) by (_ns_)'
        assert_same(self.q(e, query), self.q(m, query))


class TestMeshWidenedCoverage:
    """Round-3 widened plan family (VERDICT r2 #4): offsets, without,
    raw/un-aggregated selectors, instant-selector staleness, more range fns
    and agg ops, instant-fn/scalar post-transforms, and batched multi-query
    execution."""

    @pytest.fixture(scope="class")
    def counter_store(self):
        return build_store("counter")

    @pytest.fixture(scope="class")
    def gauge_store(self):
        return build_store("gauge")

    def q(self, svc, query):
        return svc.query_range(query, START + 600, 60, START + 1800)

    def _mesh_engaged(self, m, query):
        eng = m.mesh_engine
        calls = []
        orig = eng.execute
        eng.execute = lambda *a, **kw: (calls.append(1), orig(*a, **kw))[1]
        try:
            self.q(m, query)
        finally:
            eng.execute = orig
        return bool(calls)

    @pytest.mark.parametrize("query", [
        'sum(rate(http_requests_total[5m] offset 2m))',
        'sum(rate(http_requests_total[5m] offset 2m)) by (_ns_)',
        'avg(increase(http_requests_total[5m]))',
        'sum(delta(http_requests_total[5m]))',
    ])
    def test_offsets_and_counter_family(self, counter_store, query):
        e, m = services(counter_store)
        assert_same(self.q(e, query), self.q(m, query))
        assert self._mesh_engaged(m, query)

    @pytest.mark.parametrize("query", [
        'sum(sum_over_time(gauge_metric[3m])) without (instance)',
        'stddev(max_over_time(gauge_metric[3m])) by (_ns_)',
        'stdvar(avg_over_time(gauge_metric[3m]))',
        'group(last_over_time(gauge_metric[3m])) by (_ns_)',
        'sum(present_over_time(gauge_metric[3m]))',
        'avg(stddev_over_time(gauge_metric[3m])) by (_ns_)',
        'max(stdvar_over_time(gauge_metric[3m]))',
    ])
    def test_without_and_new_fns_aggs(self, gauge_store, query):
        e, m = services(gauge_store)
        assert_same(self.q(e, query), self.q(m, query))
        assert self._mesh_engaged(m, query)

    @pytest.mark.parametrize("query", [
        'http_requests_total',                  # raw instant selector
        'http_requests_total{_ns_="App-0"}',
        'rate(http_requests_total[5m])',        # un-aggregated range fn
        'max_over_time(http_requests_total[4m])',
    ])
    def test_per_series_outputs(self, counter_store, query):
        e, m = services(counter_store)
        assert_same(self.q(e, query), self.q(m, query))
        assert self._mesh_engaged(m, query)

    @pytest.mark.parametrize("query", [
        'abs(sum(rate(http_requests_total[5m])) by (_ns_))',
        'clamp_max(sum(rate(http_requests_total[5m])), 0.5)',
        'sqrt(avg(rate(http_requests_total[5m])))',
        '2 * sum(rate(http_requests_total[5m])) by (_ns_)',
        'sum(rate(http_requests_total[5m])) by (_ns_) > 0.2',
        'sum(rate(http_requests_total[5m])) by (_ns_) > bool 0.2',
        'topk(2, rate(http_requests_total[5m]))',
    ])
    def test_post_transforms(self, counter_store, query):
        e, m = services(counter_store)
        assert_same(self.q(e, query), self.q(m, query))
        assert self._mesh_engaged(m, query)

    def test_execute_many_batches_one_program(self, counter_store):
        # distinct step grids, same signature → one kernel call, sliced back
        e, m = services(counter_store)
        eng = m.mesh_engine
        query = 'sum(rate(http_requests_total[5m])) by (_ns_)'
        ranges = [(START + 600 + 120 * i, 60, START + 1500 + 60 * i)
                  for i in range(5)]
        qs = [(query, s, st, en) for (s, st, en) in ranges]
        lowered_calls = []
        orig = eng.execute_lowered_many
        eng.execute_lowered_many = lambda lows, *a, **kw: (
            lowered_calls.append(len(lows)), orig(lows, *a, **kw))[1]
        rm = m.query_range_many(qs)
        eng.execute_lowered_many = orig
        assert lowered_calls == [5]  # one program for the whole group
        for (s, st, en), r in zip(ranges, rm):
            re = e.query_range(query, s, st, en)
            assert_same(re, r)

    def test_execute_many_mixed_support(self, counter_store):
        # unsupported member of the batch falls back to the exec path
        e, m = services(counter_store)
        query_ok = 'sum(rate(http_requests_total[5m]))'
        query_fb = 'sum(deriv(http_requests_total[5m]))'
        qs = [(query_ok, START + 600, 60, START + 1800),
              (query_fb, START + 600, 60, START + 1800)]
        rm = m.query_range_many(qs)
        for (qq, s, st, en), r in zip(qs, rm):
            assert_same(e.query_range(qq, s, st, en), r)

    def test_hit_rate_accounting(self, counter_store):
        _, m = services(counter_store)
        self.q(m, 'sum(rate(http_requests_total[5m]))')
        self.q(m, 'sum(deriv(http_requests_total[5m]))')
        eng = m.mesh_engine
        assert eng.hits >= 1 and eng.misses >= 1
        assert 0.0 < eng.hit_rate < 1.0


class TestMeshODP:
    """Cold data must reach the mesh path via on-demand paging, exactly as
    it reaches the exec path (regression: after a restart, replayed shards
    hold only post-checkpoint tails — the mesh engine returned NaN for all
    flushed history until it learned to call ``page_partitions``)."""

    def test_mesh_reads_evicted_chunks(self, tmp_path):
        from filodb_tpu.core.store.localstore import (
            LocalDiskColumnStore,
            LocalDiskMetaStore,
        )

        cs = LocalDiskColumnStore(str(tmp_path / "data"))
        meta = LocalDiskMetaStore(str(tmp_path / "data"))
        ms = TimeSeriesMemStore(cs, meta)
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=50,
                                              groups_per_shard=4))
        keys = machine_metrics_series(4)
        shard = ms.get_shard("timeseries", 0)
        for sd in gauge_stream(keys, 300, start_ms=START * 1000):
            shard.ingest(sd)
        shard.flush_all(ingestion_time=1)
        assert sum(shard.evict_partition_chunks(p.part_id)
                   for p in shard.partitions if p) > 0

        exec_svc = QueryService(ms, "timeseries", 1, spread=0)
        mesh_svc = QueryService(ms, "timeseries", 1, spread=0, engine="mesh")
        q = 'count_over_time(heap_usage[55m])'
        re = exec_svc.query_range(q, START + 3000, 60, START + 3000)
        rm = mesh_svc.query_range(q, START + 3000, 60, START + 3000)
        assert_same(re, rm)
        assert rm.result.num_series == 4
        np.testing.assert_array_equal(np.asarray(rm.result.values)[:, 0],
                                      300.0)


def build_hist_store(n_series=8, n_samples=240):
    from filodb_tpu.testing.data import histogram_series, histogram_stream
    ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=100,
                                              groups_per_shard=4))
    keys = histogram_series(n_series, metric="http_req_latency")
    stream = histogram_stream(keys, n_samples, start_ms=START * 1000,
                              interval_ms=10_000, seed=11)
    ingest_routed(ms, "timeseries", stream, NUM_SHARDS, spread=1)
    return ms


class TestMeshHistogram:
    """First-class histograms on the mesh path (VERDICT r3 #3): buckets
    flatten into the series axis; results must match the exec path."""

    @pytest.fixture(scope="class")
    def hist_store(self):
        return build_hist_store()

    def q(self, svc, query):
        return svc.query_range(query, START + 600, 60, START + 1800)

    def _mesh_must_handle(self, m_svc, query):
        eng = m_svc.mesh_engine
        hits0 = eng.hits
        r = self.q(m_svc, query)
        assert eng.hits > hits0, f"mesh engine fell back for {query}"
        return r

    def test_hist_quantile_sum_rate(self, hist_store):
        e, m = services(hist_store)
        query = ('histogram_quantile(0.9, '
                 'sum(rate(http_req_latency[5m])))')
        re = self.q(e, query)
        rm = self._mesh_must_handle(m, query)
        assert_same(re, rm)

    def test_hist_quantile_sum_rate_by_app(self, hist_store):
        e, m = services(hist_store)
        query = ('histogram_quantile(0.5, '
                 'sum(rate(http_req_latency[5m])) by (app))')
        assert_same(self.q(e, query), self._mesh_must_handle(m, query))

    def test_hist_sum_rate_raw_buckets(self, hist_store):
        # no quantile: result is a histogram matrix; still mesh-served
        e, m = services(hist_store)
        query = 'sum(rate(http_req_latency[5m])) by (app)'
        re, rm = self.q(e, query), self._mesh_must_handle(m, query)
        ev, mv = re.result, rm.result
        assert ev.is_histogram and mv.is_histogram
        assert_same(re, rm)

    def test_hist_per_series_rate(self, hist_store):
        e, m = services(hist_store)
        query = 'rate(http_req_latency[5m])'
        assert_same(self.q(e, query), self._mesh_must_handle(m, query))

    def test_hist_increase_quantile(self, hist_store):
        e, m = services(hist_store)
        query = ('histogram_quantile(0.99, '
                 'sum(increase(http_req_latency[10m])))')
        assert_same(self.q(e, query), self._mesh_must_handle(m, query))

    def test_hist_unsupported_agg_falls_back(self, hist_store):
        # min is not bucket-wise meaningful here; exec path must serve it
        e, m = services(hist_store)
        query = 'min(rate(http_req_latency[5m]))'
        assert_same(self.q(e, query), self.q(m, query))

    def test_unsupported_agg_after_cached_sum(self, hist_store):
        # regression: a hist batch cached under sum(...) must not satisfy a
        # later min(...) over the same selector via the cache-hit branch
        e, m = services(hist_store)
        q_sum = 'sum(rate(http_req_latency[5m]))'
        q_min = 'min(rate(http_req_latency[5m]))'
        self.q(m, q_sum)  # populate the batch cache
        assert_same(self.q(e, q_min), self.q(m, q_min))
