"""Broad PromQL conformance corpus.

Mirrors the breadth of the reference's 761-line ParserSpec
(``prometheus/src/test/scala/filodb/prometheus/parse/ParserSpec.scala``):
every query must parse; a representative subset also round-trips through
LogicalPlanParser; invalid queries must fail.
"""

import pytest

from filodb_tpu.promql.parser import ParseError, TimeStepParams, parse_query
from filodb_tpu.query.logical_parser import to_promql

P = TimeStepParams(1_600_000_000, 60, 1_600_003_600)

VALID = [
    # selectors
    'foo',
    'foo:bar:baz',                      # recording-rule style names
    'foo{}',
    'foo{bar="baz"}',
    'foo{bar="baz",quux!="nerf"}',
    '{__name__="metric"}',
    '{__name__=~"metric.*",job="j"}',
    'foo{instance=~"prod-.*"}',
    'foo{instance!~"dev-.*"}',
    'foo offset 5m',
    'foo offset 1h30m',
    'foo{a="b"} offset 1w',
    # literals
    '1',
    '2.5',
    '.5 * 4',
    '0x1F + 1',
    'Inf',
    'NaN',
    '-1 ^ 2',
    '5 % 2',
    # rate & friends
    'rate(foo[5m])',
    'rate(foo{bar="baz"}[1h])',
    'increase(foo[30m])',
    'delta(cpu_temp[2h])',
    'idelta(foo[5m])',
    'irate(foo[5m])',
    'resets(foo[1h])',
    'changes(foo[10m])',
    'deriv(foo[10m])',
    'predict_linear(foo[1h], 3600)',
    'holt_winters(foo[1d], 0.3, 0.1)',
    'rate(foo[5m] offset 1h)',
    # over_time family
    'avg_over_time(foo[5m])',
    'min_over_time(foo[5m])',
    'max_over_time(foo[5m])',
    'sum_over_time(foo[5m])',
    'count_over_time(foo[5m])',
    'stddev_over_time(foo[5m])',
    'stdvar_over_time(foo[5m])',
    'last_over_time(foo[5m])',
    'present_over_time(foo[5m])',
    'quantile_over_time(0.99, foo[5m])',
    'zscore(foo[5m])',
    'timestamp(foo)',
    # aggregations
    'sum(foo)',
    'min(foo)',
    'max(foo)',
    'avg(foo)',
    'count(foo)',
    'stddev(foo)',
    'stdvar(foo)',
    'group(foo)',
    'sum(foo) by (bar)',
    'sum by (bar) (foo)',
    'sum by (bar, baz) (foo)',
    'sum without (instance) (foo)',
    'sum(rate(foo[5m])) by (job)',
    'topk(5, foo)',
    'bottomk(3, sum(rate(foo[1m])) by (job))',
    'quantile(0.9, foo)',
    'count_values("version", build_info)',
    'sum by (job) (rate(foo[5m] offset 10m))',
    # binary ops & precedence
    'foo + bar',
    'foo - bar',
    'foo * bar',
    'foo / bar',
    'foo % bar',
    'foo ^ bar',
    'foo + bar * baz',
    '(foo + bar) * baz',
    'foo == bar',
    'foo != bar',
    'foo > bar',
    'foo >= bar',
    'foo < bar',
    'foo <= bar',
    'foo > bool 5',
    'foo == bool bar',
    'foo and bar',
    'foo or bar',
    'foo unless bar',
    'foo and bar or baz',
    'foo * on (job) bar',
    'foo * ignoring (instance) bar',
    'foo / on (job) group_left bar',
    'foo / on (job) group_left (extra) bar',
    'foo / ignoring (x) group_right bar',
    '2 * foo',
    'foo * 2',
    '2 < foo',
    'foo atan2 bar',
    '-foo',
    '1 + 2 * 3 - 4 / 2',
    'sum(a) / sum(b) * 100 > 5',
    # instant functions
    'abs(foo)',
    'ceil(foo)',
    'floor(foo)',
    'exp(foo)',
    'ln(foo)',
    'log2(foo)',
    'log10(foo)',
    'sqrt(foo)',
    'round(foo)',
    'round(foo, 0.5)',
    'clamp(foo, 0, 100)',
    'clamp_min(foo, 0)',
    'clamp_max(foo, 100)',
    'sgn(foo)',
    'sin(foo)', 'cos(foo)', 'tan(foo)', 'asin(foo)', 'acos(foo)',
    'atan(foo)', 'sinh(foo)', 'cosh(foo)', 'tanh(foo)',
    'deg(foo)', 'rad(foo)',
    'hour(foo)', 'minute(foo)', 'month(foo)', 'year(foo)',
    'day_of_month(foo)', 'day_of_week(foo)', 'day_of_year(foo)',
    'days_in_month(foo)',
    'histogram_quantile(0.9, rate(req_bucket[5m]))',
    'histogram_quantile(0.99, sum(rate(req_bucket[5m])) by (le))',
    # misc functions
    'absent(foo)',
    'absent(foo{job="x"})',
    'sort(foo)',
    'sort_desc(foo)',
    'label_replace(foo, "dst", "$1", "src", "(.+)")',
    'label_join(foo, "dst", "-", "a", "b")',
    'scalar(foo)',
    'vector(1)',
    'vector(time())',
    'time()',
    'scalar(foo) + 1',
    'foo * scalar(bar)',
    # subqueries
    'max_over_time(rate(foo[1m])[30m:1m])',
    'avg_over_time(foo[1h:5m])',
    'sum_over_time(sum(foo)[30m:5m])',
    'quantile_over_time(0.5, foo[1h:])',
    # nesting
    'sum(rate(foo{a="b"}[5m])) by (job) / sum(rate(bar[5m])) by (job)',
    'histogram_quantile(0.9, sum(rate(b[5m])) by (le, job))',
    'topk(3, sum(rate(a[1m])) by (x)) + on (x) bottomk(3, b)',
    'ceil(abs(sum(rate(foo[5m]))))',
    'clamp(sum by (a) (rate(m[5m])), 0, 10)',
    # step-multiple durations (filodb extension)
    'rate(foo[5i])',
    'sum_over_time(foo[2i])',
    # --- round-2 expansion toward ParserSpec breadth -------------------
    # selector spellings
    'foo{bar="baz", quux="nerf"}',
    'foo{bar="baz",}',
    "foo{bar='baz'}",
    'foo{bar=`baz`}',
    '{job="api", __name__="m"}',
    'foo{label="value with spaces"}',
    'foo{label="esc\\"aped"}',
    'foo{label="tab\\tnewline\\n"}',
    'foo{label=""}',
    'foo{label!=""}',
    'foo{label=~""}',
    'a_metric_with_a_very_long_name_0123456789',
    'nan_metric',
    'inf_metric',
    'foo{on="x"}',
    'foo{and="x"}',
    'foo{or="x"}',
    'foo{unless="x"}',
    'foo{group_left="x"}',
    'foo{bool="x"}',
    'foo{offset="x"}',
    # durations
    'foo offset 0s',
    'foo offset 30s',
    'foo offset 90m',
    'foo offset 2d',
    'foo offset 3w',
    'foo offset 1y',
    'rate(foo[90s])',
    'rate(foo[1h30m])',
    'rate(foo[1d1h])',
    'rate(foo[1w1d])',
    'avg_over_time(foo[2w])',
    'sum_over_time(foo[1y])',
    # @ modifier
    'foo @ 1609746000',
    'foo @ 1609746000.123',
    'foo offset 5m @ 1609746000',
    'foo @ 1609746000 offset 5m',
    'rate(foo[5m] @ 1609746000)',
    'sum(foo @ 1609746000)',
    'max_over_time(rate(foo[1m])[30m:1m] @ 1609746000)',
    # arithmetic with scalars on both sides
    '1 + foo',
    'foo - 1',
    '1 - foo',
    '10 / foo',
    'foo ^ 2 ^ 3',
    '2 ^ -1',
    '-(foo)',
    '-sum(foo)',
    '+foo',
    '(((foo)))',
    '((foo + bar))',
    # comparison + bool
    'foo != bool bar',
    'foo >= bool 0.5',
    'foo <= bool bar',
    'foo < bool 1e3',
    '1 == bool 1',
    # scientific / numeric literal forms
    '1e4',
    '1.5e-3',
    '2E5 * foo',
    '0.0001 + foo',
    # vector matching variants
    'foo + on (a, b) bar',
    'foo + ignoring (a, b) bar',
    'foo * on (a) group_left (c, d) bar',
    'foo * on (a) group_right (c) bar',
    'foo * on () bar',
    'foo and on (job) bar',
    'foo or on (job) bar',
    'foo unless on (job) bar',
    'foo and ignoring (x) bar',
    'foo or ignoring () bar',
    'a + on (x) b + on (y) c',
    # aggregation spellings
    'sum (foo)',
    'sum by () (foo)',
    'sum without () (foo)',
    'sum(foo)',
    'avg by (a) (rate(foo[5m]))',
    'count without (a, b) (foo)',
    'topk(1, foo)',
    'topk(10, rate(foo[1m]))',
    'bottomk(2, foo) by (job)',
    'topk(5, foo) without (instance)',
    'quantile(0.5, rate(foo[5m]))',
    'quantile(0.999, foo) by (le)',
    'count_values("code", http_requests)',
    'stddev by (job) (foo)',
    'stdvar without (x) (foo)',
    'group by (job) (foo)',
    # range + instant function nesting
    'rate(sum_metric_bucket[5m])',
    'irate(foo{job="x"}[30s])',
    'increase(foo[1i])',
    'resets(counter_total[1h])',
    'deriv(gauge_metric[10m])',
    'predict_linear(gauge_metric[1h], 14400)',
    'holt_winters(foo[10m], 0.5, 0.5)',
    'quantile_over_time(0.25, foo{a="b"}[10m])',
    'absent_over_time(foo[10m])',
    'present_over_time(foo{job="x"}[1h])',
    'avg_over_time(max_over_time(foo[5m])[30m:5m])',
    'ceil(rate(foo[5m]))',
    'abs(delta(gauge[1h]))',
    'sqrt(sum(foo))',
    'exp(ln(foo))',
    'clamp_min(clamp_max(foo, 10), 1)',
    'round(foo, 5)',
    'round(rate(foo[5m]), 0.001)',
    # histogram pipelines
    'histogram_quantile(0.5, req_bucket)',
    'histogram_quantile(0.95, sum by (le) (rate(req_bucket[5m])))',
    'histogram_quantile(0.9, sum(rate(b[5m])) without (instance))',
    'sum(histogram_quantile(0.99, rate(b[5m]))) by (job)',
    # label manipulation
    'label_replace(foo, "a", "$0", "b", ".*")',
    'label_replace(rate(foo[5m]), "x", "$1-$2", "y", "(.)-(.)")',
    'label_join(foo, "dst", ",", "a")',
    'label_join(foo, "dst", "", "a", "b", "c")',
    'sort(sum by (a) (foo))',
    'sort_desc(rate(foo[5m]))',
    # scalar/vector conversions
    'scalar(sum(foo))',
    'vector(0)',
    'vector(scalar(foo))',
    'scalar(foo) * scalar(bar)',
    'time() - foo',
    'foo - time()',
    'year()',
    'month()',
    'minute()',
    'hour()',
    # absent family
    'absent(foo{a="b", c="d"})',
    'absent(rate(foo[5m]))',
    'absent_over_time(foo{x="y"}[30m])',
    # subquery depth
    'max_over_time(rate(foo[1m])[1h:])',
    'min_over_time(rate(foo[1m])[1h:30s])',
    'avg_over_time(sum by (a) (rate(m[5m]))[30m:1m])',
    'sum_over_time(avg_over_time(foo[5m])[30m:5m])',
    'max_over_time(max_over_time(max_over_time(m[1m])[5m:1m])[15m:5m])',
    'rate(foo[5m:30s])',
    'last_over_time(foo[10m:1m])',
    'quantile_over_time(0.9, rate(foo[1m])[10m:1m])',
    'max_over_time(rate(foo[1m] offset 5m)[30m:1m])',
    'avg_over_time(foo[1h:5m] offset 30m)',
    # keyword-ish metric names
    'rate_total',
    'sum_total',
    'avg_metric',
    'min_max_gauge',
    'bool_metric',
    # deep expressions
    '(a + b) / (c + d)',
    '(a / b) or (c / d)',
    'a unless (b and c)',
    '((a or b) and c) unless d',
    'sum(rate(a[5m])) / sum(rate(b[5m])) > bool 0.1',
    'max(a) - min(a)',
    'avg(a) + stddev(a) * 2',
    'topk(5, a / b)',
    'sum(a) by (x, y) + on (x) group_left sum(b) by (x)',
    'histogram_quantile(0.99, sum(rate(lat_bucket{svc="s"}[5m])) by (le))'
    ' > 0.5',
    'clamp(a, 1, 2)',
    # comments & whitespace tolerance
    'foo # trailing comment',
    '  foo  +  bar  ',
    'sum(\n  rate(foo[5m])\n) by (job)',
]

INVALID = [
    '',
    '{}',
    'foo{',
    'foo}',
    'foo{bar}',
    'foo{bar=}',
    'foo{bar="baz"',
    'foo[5m]',              # bare range vector
    'rate(foo)',            # missing range
    'sum()',
    'topk(foo)',            # missing k
    'foo + ',
    'foo @ bar',
    '(foo',
    'foo[5m',
    'rate(foo[5m]) offset',
    'quantile_over_time(foo[5m])',
]

ROUND_TRIP_SKIP = {
    # bare-scalar folds and unary rewrites don't render back identically
    '1', '2.5', '.5 * 4', '0x1F + 1', 'Inf', 'NaN', '-1 ^ 2', '5 % 2',
    '1 + 2 * 3 - 4 / 2', '-foo', 'timestamp(foo)', 'foo{}',
    'quantile_over_time(0.5, foo[1h:])',
    # normalizations: quote style, __name__ promotion, float @ precision,
    # scalar folds, absent_over_time lowering
    'foo{bar=`baz`}', '{job="api", __name__="m"}',
    'foo @ 1609746000.123', '2 ^ -1', '1 == bool 1',
    'absent_over_time(foo[10m])', 'absent_over_time(foo{x="y"}[30m])',
}


class TestCorpus:
    @pytest.mark.parametrize("query", VALID)
    def test_parses(self, query):
        parse_query(query, P)

    @pytest.mark.parametrize("query", [q for q in VALID
                                       if q not in ROUND_TRIP_SKIP])
    def test_round_trip_stable(self, query):
        p1 = parse_query(query, P)
        try:
            text = to_promql(p1)
        except ValueError:
            pytest.skip("plan type not renderable")
        p2 = parse_query(text, P)
        assert p1 == p2, f"{query!r} -> {text!r}"

    @pytest.mark.parametrize("query", INVALID)
    def test_rejects(self, query):
        with pytest.raises(ParseError):
            parse_query(query, P)


# ---------------------------------------------------------------------------
# Plan-structure goldens (reference ParserSpec pins LogicalPlan toString for
# hundreds of queries; these pin the structural parse of representative
# shapes — selector filters, windows, offsets, grouping, joins, subqueries)

def _plan_str(p):
    import dataclasses
    name = type(p).__name__
    if not dataclasses.is_dataclass(p):
        return repr(p)
    parts = []
    for f in dataclasses.fields(p):
        v = getattr(p, f.name)
        if f.name in ("start", "end", "step", "range_start", "range_end"):
            continue  # absolute times vary with query params
        if dataclasses.is_dataclass(v) and not isinstance(v,
                                                          (int, float, str)):
            parts.append(f"{f.name}={_plan_str(v)}")
        elif isinstance(v, tuple) and v and dataclasses.is_dataclass(v[0]):
            parts.append(
                f"{f.name}=({','.join(_plan_str(x) for x in v)})")
        elif v not in (None, (), 0, "", False):
            parts.append(f"{f.name}={v!r}")
    return f"{name}({','.join(parts)})"


PLAN_GOLDENS = [
    ('sum(rate(http_requests_total{job="api"}[5m]))',
     "Aggregate(op='sum',vector=PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='http_requests_total')),ColumnFilter(column='job',filter=Equals(value='api'))),lookback=300000),window=300000,function='rate'))"),
    ('sum(rate(foo[5m])) by (job, instance)',
     "Aggregate(op='sum',vector=PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000),window=300000,function='rate'),by=('job', 'instance'))"),
    ('sum without (instance) (rate(foo[5m]))',
     "Aggregate(op='sum',vector=PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000),window=300000,function='rate'),without=('instance',))"),
    ('topk(5, sum(rate(foo[1m])) by (app))',
     "Aggregate(op='topk',vector=Aggregate(op='sum',vector=PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=60000),window=60000,function='rate'),by=('app',)),params=(5.0,))"),
    ('histogram_quantile(0.99, sum(rate(req_latency_bucket[5m])) by (le))',
     "ApplyInstantFunction(vector=Aggregate(op='sum',vector=PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='req_latency_bucket'))),lookback=300000),window=300000,function='rate'),by=('le',)),function='histogram_quantile',args=(0.99,))"),
    ('rate(foo[5m] offset 1h)',
     "PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000,offset=3600000),window=300000,function='rate',offset=3600000)"),
    ('foo offset 5m',
     "PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000,offset=300000),offset=300000)"),
    ('foo @ 1609746000',
     "PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000),at_ms=1609746000000)"),
    ('avg_over_time(foo[10m:1m])',
     "SubqueryWithWindowing(inner=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),function='avg_over_time',subquery_window=600000,subquery_step=60000)"),
    ('max_over_time(rate(foo[5m])[30m:5m])',
     "SubqueryWithWindowing(inner=PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000),window=300000,function='rate'),function='max_over_time',subquery_window=1800000,subquery_step=300000)"),
    ('foo / on (job) bar',
     "BinaryJoin(lhs=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),op='/',rhs=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='bar'))),lookback=300000)),cardinality='one-to-one',on=('job',))"),
    ('foo * ignoring (instance) group_left bar',
     "BinaryJoin(lhs=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),op='*',rhs=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='bar'))),lookback=300000)),cardinality='many-to-one',ignoring=('instance',))"),
    ('foo and bar',
     "BinaryJoin(lhs=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),op='and',rhs=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='bar'))),lookback=300000)),cardinality='many-to-many')"),
    ('foo unless on (x) bar',
     "BinaryJoin(lhs=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),op='unless',rhs=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='bar'))),lookback=300000)),cardinality='many-to-many',on=('x',))"),
    ('abs(foo)',
     "ApplyInstantFunction(vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),function='abs')"),
    ('clamp_max(foo, 10)',
     "ApplyInstantFunction(vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),function='clamp_max',args=(10.0,))"),
    ('label_replace(foo, "dst", "$1", "src", "(.*)")',
     "ApplyMiscellaneousFunction(vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),function='label_replace',args=('dst', '$1', 'src', '(.*)'))"),
    ('quantile(0.9, foo)',
     "Aggregate(op='quantile',vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),params=(0.9,))"),
    ('count_values("ver", foo)',
     "Aggregate(op='count_values',vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),params=('ver',))"),
    ('scalar(foo) * 2',
     "ScalarBinaryOperation(op='*',lhs=ScalarVaryingDoublePlan(vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),function='scalar'),rhs=2.0)"),
    ('vector(1)',
     'VectorPlan(scalar=ScalarFixedDoublePlan(value=1.0))'),
    ('time()',
     "ScalarTimeBasedPlan(function='time')"),
    ('predict_linear(foo[1h], 3600)',
     "PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=3600000),window=3600000,function='predict_linear',params=(3600.0,))"),
    ('-foo',
     "ScalarVectorBinaryOperation(op='*',scalar=ScalarFixedDoublePlan(value=-1.0),vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),scalar_is_lhs=True)"),
    ('foo > bool 2',
     "ScalarVectorBinaryOperation(op='>',scalar=ScalarFixedDoublePlan(value=2.0),vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),bool_mode=True)"),
    ('2 < foo',
     "ScalarVectorBinaryOperation(op='<',scalar=ScalarFixedDoublePlan(value=2.0),vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),scalar_is_lhs=True)"),
    ('absent(foo{job="x"})',
     "ApplyAbsentFunction(vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo')),ColumnFilter(column='job',filter=Equals(value='x'))),lookback=300000)),filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo')),ColumnFilter(column='job',filter=Equals(value='x'))))"),
    ('sort_desc(foo)',
     "ApplySortFunction(vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),descending=True)"),
    ('changes(foo[10m])',
     "PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=600000),window=600000,function='changes')"),
    ('resets(foo[1h])',
     "PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=3600000),window=3600000,function='resets')"),
    ('irate(foo[1m])',
     "PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=60000),window=60000,function='irate')"),
    ('delta(gauge[30m])',
     "PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='gauge'))),lookback=1800000),window=1800000,function='delta')"),
    ('idelta(gauge[5m])',
     "PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='gauge'))),lookback=300000),window=300000,function='idelta')"),
    ('stddev(foo) by (a)',
     "Aggregate(op='stddev',vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),by=('a',))"),
    ('stdvar(foo)',
     "Aggregate(op='stdvar',vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)))"),
    ('group(foo) by (ns)',
     "Aggregate(op='group',vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),by=('ns',))"),
    ('min_over_time(foo[5m])',
     "PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000),window=300000,function='min_over_time')"),
    ('quantile_over_time(0.5, foo[10m])',
     "PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=600000),window=600000,function='quantile_over_time',params=(0.5,))"),
    ('holt_winters(foo[1d], 0.3, 0.1)',
     "PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=86400000),window=86400000,function='holt_winters',params=(0.3, 0.1))"),
    ('timestamp(foo)',
     "PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000),window=300000,function='timestamp')"),
    ('day_of_week()',
     "ApplyInstantFunction(vector=VectorPlan(scalar=ScalarTimeBasedPlan(function='time')),function='day_of_week')"),
    ('hour(foo)',
     "ApplyInstantFunction(vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),function='hour')"),
    ('month(vector(1))',
     "ApplyInstantFunction(vector=VectorPlan(scalar=ScalarFixedDoublePlan(value=1.0)),function='month')"),
    ('http_requests_total::sum',
     "PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='http_requests_total'))),lookback=300000,column='sum'))"),
    ('foo[5m:30s]',
     "_Subquery(inner=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),window=300000)"),
    ('rate(foo{bar=~"b.+"}[5i])',
     "PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo')),ColumnFilter(column='bar',filter=EqualsRegex(pattern='b.+'))),lookback=300000),window=300000,function='rate')"),
    ('sum(rate(foo[5m])) / sum(rate(bar[5m]))',
     "BinaryJoin(lhs=Aggregate(op='sum',vector=PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000),window=300000,function='rate')),op='/',rhs=Aggregate(op='sum',vector=PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='bar'))),lookback=300000),window=300000,function='rate')),cardinality='one-to-one')"),
    ('ceil(avg(foo))',
     "ApplyInstantFunction(vector=Aggregate(op='avg',vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000))),function='ceil')"),
    ('exp(foo)',
     "ApplyInstantFunction(vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),function='exp')"),
    ('ln(foo)',
     "ApplyInstantFunction(vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),function='ln')"),
    ('log2(foo)',
     "ApplyInstantFunction(vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),function='log2')"),
    ('sqrt(foo)',
     "ApplyInstantFunction(vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),function='sqrt')"),
    ('floor(foo)',
     "ApplyInstantFunction(vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),function='floor')"),
    ('round(foo, 0.5)',
     "ApplyInstantFunction(vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),function='round',args=(0.5,))"),
    ('sgn(foo)',
     "ApplyInstantFunction(vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),function='sgn')"),
    ('deg(foo)',
     "ApplyInstantFunction(vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),function='deg')"),
    ('rad(foo)',
     "ApplyInstantFunction(vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),function='rad')"),
    ('last_over_time(foo[5m])',
     "PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000),window=300000,function='last_over_time')"),
    ('present_over_time(foo[5m])',
     "PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000),window=300000,function='present_over_time')"),
    ('count(up == 1)',
     "Aggregate(op='count',vector=ScalarVectorBinaryOperation(op='==',scalar=ScalarFixedDoublePlan(value=1.0),vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='up'))),lookback=300000))))"),
    ('avg(rate(foo[2m])) by (job)',
     "Aggregate(op='avg',vector=PeriodicSeriesWithWindowing(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=120000),window=120000,function='rate'),by=('job',))"),
    ('bottomk(3, foo)',
     "Aggregate(op='bottomk',vector=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),params=(3.0,))"),
    ('foo or vector(0)',
     "BinaryJoin(lhs=PeriodicSeries(raw=RawSeries(filters=(ColumnFilter(column='_metric_',filter=Equals(value='foo'))),lookback=300000)),op='or',rhs=VectorPlan(scalar=ScalarFixedDoublePlan()),cardinality='many-to-many')"),
]


EXTRA_INVALID = [
    # operator/grammar misuse (reference ParserSpec parseError coverage)
    'foo{bar=}', 'foo{bar', 'foo{=~"x"}', 'foo{bar!}',
    'rate(foo[5m)', 'rate(foo 5m])', 'rate(foo[5x])', 'rate(foo[])',
    'foo[5m] + bar', 'rate(foo)', 'sum()',
    'topk(foo)', 'quantile(foo)', 'clamp_max(foo)',
    'foo offset', 'foo offset bar', 'foo @ bar',
    'and foo', 'foo or', 'foo unless unless bar',
    'sum by (foo',  'sum by foo (x)',
    'histogram_quantile(, foo)',
    '(foo', 'foo)', '',
    'foo=~"b"', '1[5m]',
    'label_replace(foo)', 'vector()', 'scalar()',
]


class TestPlanStructure:
    @pytest.mark.parametrize("query,expected", PLAN_GOLDENS,
                             ids=[q for q, _ in PLAN_GOLDENS])
    def test_plan_structure(self, query, expected):
        assert _plan_str(parse_query(query, P)) == expected

    @pytest.mark.parametrize("query", EXTRA_INVALID)
    def test_extra_rejects(self, query):
        with pytest.raises(ParseError):
            parse_query(query, P)
