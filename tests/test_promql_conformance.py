"""Broad PromQL conformance corpus.

Mirrors the breadth of the reference's 761-line ParserSpec
(``prometheus/src/test/scala/filodb/prometheus/parse/ParserSpec.scala``):
every query must parse; a representative subset also round-trips through
LogicalPlanParser; invalid queries must fail.
"""

import pytest

from filodb_tpu.promql.parser import ParseError, TimeStepParams, parse_query
from filodb_tpu.query.logical_parser import to_promql

P = TimeStepParams(1_600_000_000, 60, 1_600_003_600)

VALID = [
    # selectors
    'foo',
    'foo:bar:baz',                      # recording-rule style names
    'foo{}',
    'foo{bar="baz"}',
    'foo{bar="baz",quux!="nerf"}',
    '{__name__="metric"}',
    '{__name__=~"metric.*",job="j"}',
    'foo{instance=~"prod-.*"}',
    'foo{instance!~"dev-.*"}',
    'foo offset 5m',
    'foo offset 1h30m',
    'foo{a="b"} offset 1w',
    # literals
    '1',
    '2.5',
    '.5 * 4',
    '0x1F + 1',
    'Inf',
    'NaN',
    '-1 ^ 2',
    '5 % 2',
    # rate & friends
    'rate(foo[5m])',
    'rate(foo{bar="baz"}[1h])',
    'increase(foo[30m])',
    'delta(cpu_temp[2h])',
    'idelta(foo[5m])',
    'irate(foo[5m])',
    'resets(foo[1h])',
    'changes(foo[10m])',
    'deriv(foo[10m])',
    'predict_linear(foo[1h], 3600)',
    'holt_winters(foo[1d], 0.3, 0.1)',
    'rate(foo[5m] offset 1h)',
    # over_time family
    'avg_over_time(foo[5m])',
    'min_over_time(foo[5m])',
    'max_over_time(foo[5m])',
    'sum_over_time(foo[5m])',
    'count_over_time(foo[5m])',
    'stddev_over_time(foo[5m])',
    'stdvar_over_time(foo[5m])',
    'last_over_time(foo[5m])',
    'present_over_time(foo[5m])',
    'quantile_over_time(0.99, foo[5m])',
    'zscore(foo[5m])',
    'timestamp(foo)',
    # aggregations
    'sum(foo)',
    'min(foo)',
    'max(foo)',
    'avg(foo)',
    'count(foo)',
    'stddev(foo)',
    'stdvar(foo)',
    'group(foo)',
    'sum(foo) by (bar)',
    'sum by (bar) (foo)',
    'sum by (bar, baz) (foo)',
    'sum without (instance) (foo)',
    'sum(rate(foo[5m])) by (job)',
    'topk(5, foo)',
    'bottomk(3, sum(rate(foo[1m])) by (job))',
    'quantile(0.9, foo)',
    'count_values("version", build_info)',
    'sum by (job) (rate(foo[5m] offset 10m))',
    # binary ops & precedence
    'foo + bar',
    'foo - bar',
    'foo * bar',
    'foo / bar',
    'foo % bar',
    'foo ^ bar',
    'foo + bar * baz',
    '(foo + bar) * baz',
    'foo == bar',
    'foo != bar',
    'foo > bar',
    'foo >= bar',
    'foo < bar',
    'foo <= bar',
    'foo > bool 5',
    'foo == bool bar',
    'foo and bar',
    'foo or bar',
    'foo unless bar',
    'foo and bar or baz',
    'foo * on (job) bar',
    'foo * ignoring (instance) bar',
    'foo / on (job) group_left bar',
    'foo / on (job) group_left (extra) bar',
    'foo / ignoring (x) group_right bar',
    '2 * foo',
    'foo * 2',
    '2 < foo',
    'foo atan2 bar',
    '-foo',
    '1 + 2 * 3 - 4 / 2',
    'sum(a) / sum(b) * 100 > 5',
    # instant functions
    'abs(foo)',
    'ceil(foo)',
    'floor(foo)',
    'exp(foo)',
    'ln(foo)',
    'log2(foo)',
    'log10(foo)',
    'sqrt(foo)',
    'round(foo)',
    'round(foo, 0.5)',
    'clamp(foo, 0, 100)',
    'clamp_min(foo, 0)',
    'clamp_max(foo, 100)',
    'sgn(foo)',
    'sin(foo)', 'cos(foo)', 'tan(foo)', 'asin(foo)', 'acos(foo)',
    'atan(foo)', 'sinh(foo)', 'cosh(foo)', 'tanh(foo)',
    'deg(foo)', 'rad(foo)',
    'hour(foo)', 'minute(foo)', 'month(foo)', 'year(foo)',
    'day_of_month(foo)', 'day_of_week(foo)', 'day_of_year(foo)',
    'days_in_month(foo)',
    'histogram_quantile(0.9, rate(req_bucket[5m]))',
    'histogram_quantile(0.99, sum(rate(req_bucket[5m])) by (le))',
    # misc functions
    'absent(foo)',
    'absent(foo{job="x"})',
    'sort(foo)',
    'sort_desc(foo)',
    'label_replace(foo, "dst", "$1", "src", "(.+)")',
    'label_join(foo, "dst", "-", "a", "b")',
    'scalar(foo)',
    'vector(1)',
    'vector(time())',
    'time()',
    'scalar(foo) + 1',
    'foo * scalar(bar)',
    # subqueries
    'max_over_time(rate(foo[1m])[30m:1m])',
    'avg_over_time(foo[1h:5m])',
    'sum_over_time(sum(foo)[30m:5m])',
    'quantile_over_time(0.5, foo[1h:])',
    # nesting
    'sum(rate(foo{a="b"}[5m])) by (job) / sum(rate(bar[5m])) by (job)',
    'histogram_quantile(0.9, sum(rate(b[5m])) by (le, job))',
    'topk(3, sum(rate(a[1m])) by (x)) + on (x) bottomk(3, b)',
    'ceil(abs(sum(rate(foo[5m]))))',
    'clamp(sum by (a) (rate(m[5m])), 0, scalar(max(cap)))'
    if False else 'clamp(sum by (a) (rate(m[5m])), 0, 10)',
    # step-multiple durations (filodb extension)
    'rate(foo[5i])',
    'sum_over_time(foo[2i])',
]

INVALID = [
    '',
    '{}',
    'foo{',
    'foo}',
    'foo{bar}',
    'foo{bar=}',
    'foo{bar="baz"',
    'foo[5m]',              # bare range vector
    'rate(foo)',            # missing range
    'sum()',
    'topk(foo)',            # missing k
    'foo + ',
    'foo @ bar',
    '(foo',
    'foo[5m',
    'rate(foo[5m]) offset',
    'quantile_over_time(foo[5m])',
]

ROUND_TRIP_SKIP = {
    # bare-scalar folds and unary rewrites don't render back identically
    '1', '2.5', '.5 * 4', '0x1F + 1', 'Inf', 'NaN', '-1 ^ 2', '5 % 2',
    '1 + 2 * 3 - 4 / 2', '-foo', 'timestamp(foo)', 'foo{}',
    'quantile_over_time(0.5, foo[1h:])',
}


class TestCorpus:
    @pytest.mark.parametrize("query", VALID)
    def test_parses(self, query):
        parse_query(query, P)

    @pytest.mark.parametrize("query", [q for q in VALID
                                       if q not in ROUND_TRIP_SKIP])
    def test_round_trip_stable(self, query):
        p1 = parse_query(query, P)
        try:
            text = to_promql(p1)
        except ValueError:
            pytest.skip("plan type not renderable")
        p2 = parse_query(text, P)
        assert p1 == p2, f"{query!r} -> {text!r}"

    @pytest.mark.parametrize("query", INVALID)
    def test_rejects(self, query):
        with pytest.raises(ParseError):
            parse_query(query, P)
