"""Shard-status ack/resync protocol (reference ``StatusActor.scala:41``):
sequenced event feed, implicit acks via poll offsets, gap-forced resync."""

from filodb_tpu.coordinator.bootstrap import ShardUpdateSubscriber
from filodb_tpu.coordinator.shard_manager import ShardManager
from filodb_tpu.coordinator.shardmapper import ShardStatus


class _LocalDispatcher:
    """Calls a ShardManager directly, shaped like the control transport."""

    def __init__(self, sm: ShardManager):
        self.sm = sm

    def call(self, kind, dataset, since_seq, epoch=None):
        assert kind == "shard_events"
        events, seq, resynced, ep = self.sm.events_since(since_seq, epoch)
        return ([(e.shard, e.status.name, e.node, e.progress)
                 for e in events], seq, resynced, ep)


class TestAckResync:
    def test_incremental_delivery_and_ack(self):
        sm = ShardManager("ds", 4)
        sub = ShardUpdateSubscriber("ds", 4, _LocalDispatcher(sm))
        sm.add_member("n0")
        assert sub.poll() == 4  # four ASSIGNED events
        assert sub.mapper.owners == sm.mapper.owners
        assert sub.poll() == 0  # acked: nothing new
        sm.shard_active(2, "n0")
        assert sub.poll() == 1
        assert sub.mapper.statuses[2] == ShardStatus.ACTIVE
        assert sub.resyncs == 0

    def test_gap_forces_resync(self):
        sm = ShardManager("ds", 4, event_log_cap=3)
        sub = ShardUpdateSubscriber("ds", 4, _LocalDispatcher(sm))
        sm.add_member("n0")
        # overflow the retained window before the subscriber polls
        for _ in range(5):
            sm.shard_active(0, "n0")
            sm.shard_active(1, "n0")
        applied = sub.poll()
        assert sub.resyncs == 1
        assert applied == 4  # full snapshot, one event per shard
        assert sub.mapper.owners == sm.mapper.owners
        assert sub.mapper.statuses[0] == ShardStatus.ACTIVE
        # back in step: subsequent polls are incremental again
        sm.shard_recovery(3, "n0", 50)
        assert sub.poll() == 1
        assert sub.resyncs == 1
        assert sub.mapper.statuses[3] == ShardStatus.RECOVERY

    def test_fresh_subscriber_gets_snapshot_or_log(self):
        sm = ShardManager("ds", 2)
        sm.add_member("a")
        sm.shard_active(0, "a")
        sub = ShardUpdateSubscriber("ds", 2, _LocalDispatcher(sm))
        sub.poll()
        assert sub.mapper.owners == sm.mapper.owners
        assert sub.mapper.statuses == sm.mapper.statuses

    def test_coordinator_restart_forces_resync(self):
        # follower's ack can be AHEAD after a coordinator restart resets the
        # sequence — must resync, not silently skip the fresh events
        sm1 = ShardManager("ds", 2)
        sub = ShardUpdateSubscriber("ds", 2, _LocalDispatcher(sm1))
        sm1.add_member("a")
        for _ in range(6):
            sm1.shard_active(0, "a")
        sub.poll()
        assert sub.last_seq > 0
        # coordinator restarts with fresh state
        sm2 = ShardManager("ds", 2)
        sm2.add_member("b")
        sub.dispatcher = _LocalDispatcher(sm2)
        sub.poll()
        assert sub.resyncs == 1
        assert sub.mapper.owners == sm2.mapper.owners

    def test_restart_with_plausible_seq_forces_resync(self):
        # the nastier restart case: the NEW coordinator has already emitted
        # >= since_seq events, so the ack is numerically inside the new
        # feed's range — neither 'behind' nor 'ahead' fires. The epoch
        # token must force the resync.
        sm1 = ShardManager("ds", 4)
        sub = ShardUpdateSubscriber("ds", 4, _LocalDispatcher(sm1))
        sm1.add_member("a")  # 4 events, seq = 4
        sub.poll()
        assert sub.last_seq == 4
        # restart: fresh manager immediately emits 4 events for a DIFFERENT
        # member, so its seq is also 4 — the stale ack looks current
        sm2 = ShardManager("ds", 4)
        sm2.add_member("b")
        assert sm2.epoch != sm1.epoch
        sub.dispatcher = _LocalDispatcher(sm2)
        sub.poll()
        assert sub.resyncs == 1
        assert sub.mapper.owners == sm2.mapper.owners
        assert sub.epoch == sm2.epoch
        # steady state after adopting the new epoch
        sm2.shard_active(0, "b")
        assert sub.poll() == 1
        assert sub.resyncs == 1

    def test_member_mirrors_coordinator_over_wire(self):
        """End to end over the real control transport."""
        from filodb_tpu.coordinator.remote import (
            PlanExecutorServer,
            RemotePlanDispatcher,
        )
        sm = ShardManager("ds", 4)
        sm.add_member("n0")

        def handler(dataset, since_seq, epoch=None):
            events, seq, resynced, ep = sm.events_since(since_seq, epoch)
            return ([(e.shard, e.status.name, e.node, e.progress)
                     for e in events], seq, resynced, ep)

        srv = PlanExecutorServer(None, extra_handlers={
            "shard_events": handler}).start()
        try:
            sub = ShardUpdateSubscriber(
                "ds", 4, RemotePlanDispatcher("127.0.0.1", srv.port))
            sub.poll()
            assert sub.mapper.owners == sm.mapper.owners
            sm.shard_active(1, "n0")
            sub.poll()
            assert sub.mapper.statuses[1] == ShardStatus.ACTIVE
        finally:
            srv.stop()
