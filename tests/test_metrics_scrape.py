"""Prometheus /metrics exposition breadth — reference shard-metric parity.

The reference names ~50 shard metrics in ``TimeSeriesShardStats``
(``TimeSeriesShard.scala:41-133``); this scrapes the standalone server after
ingest + flush + query traffic and asserts the named series are present
with per-shard dataset/shard tags.
"""

import json
import socket
import time
import urllib.request

import pytest

from filodb_tpu.config import ServerConfig
from filodb_tpu.standalone import FiloServer

START = 1_600_000_000

EXPECTED_NAMES = [
    # ingest
    "memstore_rows_ingested_total",
    "recovery_row_skipped_total",
    "memstore_data_dropped_total",
    "memstore_unknown_schema_dropped_total",
    "memstore_incompatible_containers_total",
    "memstore_offsets_not_recovered_total",
    "memstore_out_of_order_samples_total",
    "ingestion_clock_delay_ms",
    # partition lifecycle
    "memstore_partitions_created_total",
    "memstore_partitions_purged_total",
    "memstore_partitions_purged_index_total",
    "memstore_partitions_purge_time_ms_total",
    "memstore_partitions_evicted_total",
    "memstore_chunkids_evicted_total",
    "memstore_partitions_paged_restored_total",
    "memstore_eviction_stall_ns_total",
    "num_partitions",
    "memstore_timeseries_count",
    "num_ingesting_partitions",
    # encode / flush
    "memstore_samples_encoded_total",
    "memstore_encoded_bytes_allocated_total",
    "memstore_hist_encoded_bytes_total",
    "memstore_flushes_chunks_written_total",
    "memstore_flushes_success_total",
    "memstore_flushes_failed_total",
    "memstore_index_num_dirty_keys_flushed_total",
    "chunk_flush_task_latency_seconds_count",
    "memstore_downsample_records_created_total",
    # offsets
    "shard_offset_latest_inmemory",
    "shard_offset_flushed_latest",
    "shard_offset_flushed_earliest",
    # recovery
    "memstore_total_shard_recovery_time_ms",
    "memstore_index_recovery_partkeys_processed_total",
    # query
    "memstore_partitions_queried_total",
    "memstore_chunks_queried_total",
    "query_time_range_minutes_count",
    # chunk aggregate sidecars (query/engine/sidecar_lane.py,
    # memory/chunk.py) — registered at import time
    "filodb_sidecar_served_total",
    "filodb_sidecar_bypassed_total",
    "filodb_sidecar_backfilled_total",
    # ODP
    "chunks_paged_in_total",
    "memstore_partitions_paged_in_total",
    # bloom
    "evicted_pk_bloom_filter_queries_total",
    "evicted_pk_bloom_filter_fp_total",
    "evicted_pk_bloom_filter_approx_size",
    # live-state gauges
    "memstore_index_entries",
    "memstore_index_ram_bytes",
    "memstore_writebuffer_pool_size",
    "memstore_chunk_ram_bytes",
]

# extent result cache (filodb_tpu.query.result_cache) — registered the
# moment a cache-enabled service is built (standalone default-on)
RESULT_CACHE_NAMES = [
    "filodb_result_cache_hits_total",
    "filodb_result_cache_misses_total",
    "filodb_result_cache_partial_hits_total",
    "filodb_result_cache_evictions_total",
    "filodb_result_cache_bytes",
]

# distributed-aggregation pushdown + wire transport (coordinator/planner.py,
# coordinator/remote.py) — registered at import, standalone imports both
DIST_AGG_NAMES = [
    "filodb_agg_pushdown_applied_total",
    "filodb_agg_pushdown_bypassed_total",
    "filodb_remote_bytes_sent_total",
    "filodb_remote_bytes_received_total",
    "filodb_wire_frames_compressed_total",
    "filodb_wire_frames_raw_total",
    "filodb_wire_compress_bytes_in_total",
    "filodb_wire_compress_bytes_out_total",
]

# query-path resilience (coordinator/query_service.py, utils/resilience.py)
# — counters registered at import; found missing by the filolint
# metrics-parity pass (PR203), which now keeps these lists in step with
# the source tree
QUERY_RESILIENCE_NAMES = [
    "filodb_partial_results_total",
    "filodb_query_retries_total",
]

# overload protection (utils/governor.py, gateway/server.py) — gauges and
# counters pre-registered at import so families render before any shed
GOVERNOR_NAMES = [
    "filodb_governor_state",
    "filodb_governor_inflight",
    "filodb_governor_queue_depth",
    "filodb_governor_memory_utilization",
    "filodb_governor_admitted_total",
    "filodb_governor_rejected_total",
    "filodb_governor_transitions_total",
    "filodb_governor_budget_exceeded_total",
    "filodb_governor_queue_wait_seconds_bucket",
    "filodb_governor_queue_wait_seconds_count",
    "filodb_governor_queue_wait_seconds_sum",
    "gateway_queue_depth",
    "gateway_records_shed_total",
]


# live shard migration (coordinator/migration.py) — registered at import so
# dashboards see the families before any migration runs
MIGRATION_NAMES = [
    "filodb_shard_migrations_started_total",
    "filodb_shard_migrations_completed_total",
    "filodb_shard_migrations_aborted_total",
    "filodb_shard_migrations_resumed_total",
    "filodb_shard_migration_active",
    "filodb_shard_migration_phase",
    "filodb_shard_migration_lag",
    "filodb_shard_migration_seconds_bucket",
    "filodb_shard_migration_seconds_count",
    "filodb_shard_migration_seconds_sum",
]


# per-tenant isolation (utils/governor.py) — untagged family anchors
# pre-registered; runtime series carry {tenant=...} tags
TENANT_NAMES = [
    "filodb_tenant_inflight",
    "filodb_tenant_admitted_total",
    "filodb_tenant_rejected_total",
    "filodb_tenant_ingest_dropped_total",
    "filodb_tenant_series",
    "filodb_tenant_quota",
]


# standing queries (filodb_tpu/rules) — registered at import; standalone
# imports the package unconditionally, so the families render before (and
# whether or not) any rule group is configured
RULES_NAMES = [
    "filodb_rules_groups",
    "filodb_rules_watermark_lag_seconds",
    "filodb_rules_evals_total",
    "filodb_rules_eval_failures_total",
    "filodb_rules_evals_shed_total",
    "filodb_rules_steps_evaluated_total",
    "filodb_rules_steps_skipped_total",
    "filodb_rules_samples_written_total",
    "filodb_rules_eval_seconds_bucket",
    "filodb_rules_eval_seconds_count",
    "filodb_rules_eval_seconds_sum",
    "filodb_rules_last_eval_ts",
    "filodb_rules_unrecovered_groups",
]

ALERTS_NAMES = [
    "filodb_alerts_firing",
    "filodb_alerts_pending",
    "filodb_alerts_transitions_total",
    # notification egress (rules/notify.py): registered at import even
    # when no webhook is configured
    "filodb_alerts_notifications_total",
    "filodb_alerts_notification_failures_total",
    "filodb_alerts_notifications_dropped_total",
]


# distributed query tracing + slow-query flight recorder
# (utils/tracing.py) — stage histograms pre-registered at import from the
# whitelisted stage names; sampling/recorder counters too
TRACING_NAMES = [
    "filodb_query_stage_seconds_bucket",
    "filodb_query_stage_seconds_count",
    "filodb_query_stage_seconds_sum",
    "filodb_queries_sampled_total",
    "filodb_slow_queries_recorded_total",
]


# object-store durable tier (core/store/objectstore.py) — registered at
# import; standalone imports the module regardless of the configured backend
OBJECTSTORE_NAMES = [
    "filodb_objectstore_puts_total",
    "filodb_objectstore_gets_total",
    "filodb_objectstore_bytes_up_total",
    "filodb_objectstore_bytes_down_total",
    "filodb_objectstore_payload_bytes_down_total",
    "filodb_objectstore_retries_total",
    "filodb_objectstore_compactions_total",
    "filodb_objectstore_corrupt_total",
    "filodb_objectstore_queue_depth",
]


# aggregate pyramids (core/store/pyramid.py, query/engine/pyramid_lane.py)
# — registered when objectstore imports pyramid at boot; kept in step with
# the source tree by the filolint PR207 rule (no lazy/GaugeFn exemptions)
PYRAMID_NAMES = [
    "filodb_pyramid_objects_written_total",
    "filodb_pyramid_backfilled_total",
    "filodb_pyramid_served_total",
    "filodb_pyramid_fallback_total",
    "filodb_pyramid_nodes_total",
    "filodb_pyramid_bytes_down_total",
]


# ingest-path freshness + self-monitoring (utils/selfmon.py,
# utils/tracing.py, coordinator/cluster.py, core/memstore/shard.py) —
# kept in step with the source tree by the filolint PR206 rule, which
# (unlike PR203) exempts nothing: lag GaugeFns register at shard start
# and the fixture boots shards + drives ingest, so all families render
INGEST_OBS_NAMES = [
    "filodb_metric_scrape_errors_total",
    "filodb_ingest_slow_recorded_total",
    "filodb_ingest_lag_seconds",
    "filodb_ingest_offset_lag",
    "filodb_ingest_checkpoint_lag",
    "filodb_ingest_errors_total",
    "filodb_ingest_e2e_seconds_bucket",
    "filodb_ingest_e2e_seconds_count",
    "filodb_ingest_e2e_seconds_sum",
    "filodb_selfmon_ticks_total",
    "filodb_selfmon_errors_total",
    "filodb_selfmon_samples_total",
    "filodb_selfmon_series",
    "filodb_selfmon_tick_seconds_bucket",
    "filodb_selfmon_tick_seconds_count",
    "filodb_selfmon_tick_seconds_sum",
    "filodb_objectstore_oldest_task_age_seconds",
]


# tiered query federation (query/federation.py, core/memstore/odp.py) —
# counters registered when the HTTP front imports federation at boot; the
# ODP cache-size GaugeFn renders 0 before any cache instance exists
FEDERATION_NAMES = [
    "filodb_federation_queries_total",
    "filodb_federation_subqueries_total",
    "filodb_odp_cache_chunks",
    "odp_range_hits_total",
]


# continuous shard replication + hedged replica reads
# (coordinator/replication.py) — counters and untagged gauge anchors
# registered at import (standalone imports cluster → replication at boot),
# so the families render before any replica exists
REPLICATION_NAMES = [
    "filodb_replica_promotions_total",
    "filodb_replica_divergence_total",
    "filodb_replica_follower_reads_total",
    "filodb_replica_lag",
    "filodb_replica_watermark",
    "filodb_hedged_reads_total",
    "filodb_hedged_reads_won_total",
]


# mesh query engine (parallel/mesh_engine.py, parallel/adaptive.py) —
# plan recognition, split-vs-fused dispatch, device cache behavior,
# exec-path fallbacks, and adaptive lane routing; all registered at
# mesh_engine import (QueryService construction at boot)
MESH_NAMES = [
    "filodb_mesh_supported_total",
    "filodb_mesh_unsupported_total",
    "filodb_mesh_dispatch_total",
    "filodb_mesh_compile_cache_total",
    "filodb_mesh_batch_cache_total",
    "filodb_mesh_bounds_cache_total",
    "filodb_mesh_eval_cache_total",
    "filodb_mesh_fallback_total",
    "filodb_mesh_routed_total",
    "filodb_mesh_hit_rate",
]


# multi-process mesh runtime (coordinator/mesh_cluster.py) — descriptor
# dispatch outcomes, fallback reasons, live worker gauge, and root-side
# collective latency; registered at mesh_cluster import (pulled in by
# query_service at boot so families render before any worker spawns)
MESH_PROC_NAMES = [
    "filodb_mesh_proc_dispatch_total",
    "filodb_mesh_proc_fallback_total",
    "filodb_mesh_proc_workers",
    "filodb_mesh_proc_collective_seconds_bucket",
    "filodb_mesh_proc_collective_seconds_count",
    "filodb_mesh_proc_collective_seconds_sum",
]


# trace-driven adaptive planner (query/cost_model.py) — decision sources,
# settle counts, calibration error, signature-table occupancy; registered
# at cost_model import (QueryService admission path at boot)
COSTMODEL_NAMES = [
    "filodb_costmodel_decisions_total",
    "filodb_costmodel_settled_total",
    "filodb_costmodel_calibration_error",
    "filodb_costmodel_signatures",
    "filodb_costmodel_evictions_total",
]


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture
def server(tmp_path):
    cfg_path = tmp_path / "server.json"
    cfg_path.write_text(json.dumps({
        "node_name": "metrics-node",
        "data_dir": str(tmp_path / "data"),
        "http_port": 0,
        "gateway_port": 0,
        "datasets": {"timeseries": {
            "num_shards": 2, "spread": 1,
            "store": {"max_chunk_size": 50, "groups_per_shard": 2}}},
    }))
    cfg = ServerConfig.load(str(cfg_path))
    object.__setattr__(cfg, "gateway_port", _free_port())
    srv = FiloServer(cfg).start()
    yield srv
    srv.shutdown()


def _scrape(port: int) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics") as r:
        assert r.status == 200
        return r.read().decode()


class TestMetricsScrape:
    def test_shard_metric_breadth(self, server):
        srv = server
        # drive ingest so counters move
        with socket.create_connection(("127.0.0.1",
                                       srv.gateway.port)) as s:
            for i in range(150):
                ts_ns = (START + i * 10) * 1_000_000_000
                s.sendall(f"scrape_metric,host=h{i % 5},_ws_=demo,"
                          f"_ns_=App-0 value={i} {ts_ns}\n".encode())
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            srv.gateway.sink.flush()
            ingested = sum(s2.stats.rows_ingested.value
                           for s2 in srv.memstore.shards_for("timeseries"))
            if ingested >= 150:  # wait for the FULL batch, not first rows
                break
            time.sleep(0.3)
        # flush + query so flush/query metric families move too
        for shard in srv.memstore.shards_for("timeseries"):
            shard.flush_all()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.http.port}/promql/timeseries/api/v1/"
                f"query_range?query=sum(rate(scrape_metric%5B1m%5D))"
                f"&start={START}&end={START + 1500}&step=60") as r:
            assert r.status == 200

        text = _scrape(srv.http.port)
        names_present = set()
        for line in text.splitlines():
            if not line or line.startswith("#"):
                continue
            name = line.split("{")[0].split(" ")[0]
            names_present.add(name)
        missing = [n for n in EXPECTED_NAMES if n not in names_present]
        assert not missing, f"missing metric families: {missing}"
        assert len([n for n in EXPECTED_NAMES if n in names_present]) >= 40

        # result-cache counters are exposed, and the range query above
        # (splittable: sum(rate(...))) actually drove them
        missing_rc = [n for n in RESULT_CACHE_NAMES
                      if n not in names_present]
        assert not missing_rc, f"missing result-cache metrics: {missing_rc}"

        # distributed-aggregation pushdown + wire counters are exposed
        # (decision-counter movement is covered in test_agg_pushdown.py —
        # the mesh engine can satisfy this query without planner
        # materialization, so movement here would be engine-dependent)
        missing_da = [n for n in DIST_AGG_NAMES if n not in names_present]
        assert not missing_da, f"missing dist-agg metrics: {missing_da}"

        # object-store tier families render even on the local backend
        # (pre-registered at import so dashboards see stable zeros)
        missing_os = [n for n in OBJECTSTORE_NAMES if n not in names_present]
        assert not missing_os, f"missing objectstore metrics: {missing_os}"

        # aggregate-pyramid families render at zero before any cold fold
        # (counters register when objectstore imports pyramid at boot)
        missing_pyr = [n for n in PYRAMID_NAMES if n not in names_present]
        assert not missing_pyr, f"missing pyramid metrics: {missing_pyr}"

        # query-path resilience counters render from import time
        missing_qr = [n for n in QUERY_RESILIENCE_NAMES
                      if n not in names_present]
        assert not missing_qr, f"missing resilience metrics: {missing_qr}"

        # governor + gateway overload families are exposed, and the range
        # query above passed the admission gate so admissions moved
        missing_gov = [n for n in GOVERNOR_NAMES if n not in names_present]
        assert not missing_gov, f"missing governor metrics: {missing_gov}"

        # live-migration families render before any migration runs
        # (standalone imports cluster → migration at boot)
        missing_mig = [n for n in MIGRATION_NAMES if n not in names_present]
        assert not missing_mig, f"missing migration metrics: {missing_mig}"

        # per-tenant isolation families render before any tenant config
        missing_t = [n for n in TENANT_NAMES if n not in names_present]
        assert not missing_t, f"missing tenant metrics: {missing_t}"

        # standing-query + alert families render with no rules configured
        missing_r = [n for n in RULES_NAMES + ALERTS_NAMES
                     if n not in names_present]
        assert not missing_r, f"missing rules metrics: {missing_r}"

        # tracing stage histograms + flight-recorder counters render from
        # import time (stage labels are a bounded whitelist)
        missing_tr = [n for n in TRACING_NAMES if n not in names_present]
        assert not missing_tr, f"missing tracing metrics: {missing_tr}"

        # ingest-path freshness + selfmon families: the import-time ones
        # render unconditionally; the per-shard lag gauges register at
        # shard start and the lag-seconds GaugeFn emits once the ingest
        # above has landed
        missing_io = [n for n in INGEST_OBS_NAMES if n not in names_present]
        assert not missing_io, f"missing ingest-obs metrics: {missing_io}"

        # tier-federation + ODP cache families render before any
        # federated query (http front imports federation at boot)
        missing_fed = [n for n in FEDERATION_NAMES
                       if n not in names_present]
        assert not missing_fed, f"missing federation metrics: {missing_fed}"

        # mesh-engine observability: dispatch form, device caches, lane
        # routing — all render from mesh_engine import at boot, before
        # the first mesh-eligible query
        missing_mesh = [n for n in MESH_NAMES if n not in names_present]
        assert not missing_mesh, f"missing mesh metrics: {missing_mesh}"

        # multi-process mesh runtime: dispatch/fallback counters, worker
        # gauge, and collective-latency histogram render at zero from the
        # mesh_cluster import at boot — no worker pool needs to exist
        missing_mp = [n for n in MESH_PROC_NAMES
                      if n not in names_present]
        assert not missing_mp, f"missing mesh-proc metrics: {missing_mp}"

        # adaptive-planner cost model: decision/settle counters and
        # calibration gauges pre-register at cost_model import (pulled in
        # by the query-service admission path at boot)
        missing_cm = [n for n in COSTMODEL_NAMES if n not in names_present]
        assert not missing_cm, f"missing costmodel metrics: {missing_cm}"

        # shard-replication + hedged-read families render at zero before
        # any replica set is configured
        missing_rep = [n for n in REPLICATION_NAMES
                       if n not in names_present]
        assert not missing_rep, f"missing replication metrics: {missing_rep}"

        def total(name):
            return sum(float(line.rsplit(" ", 1)[1])
                       for line in text.splitlines()
                       if line.startswith(name + "{") or
                       line.split(" ")[0] == name)

        assert total("filodb_result_cache_hits_total") \
            + total("filodb_result_cache_misses_total") >= 1

        assert total("filodb_governor_admitted_total") >= 1

        # per-shard tagging: both shards of THIS dataset expose the
        # counter (the registry is process-wide; other tests' datasets may
        # coexist in the same exposition)
        tagged = [line for line in text.splitlines()
                  if line.startswith("memstore_rows_ingested_total")
                  and 'dataset="timeseries"' in line]
        assert any('shard="0"' in t for t in tagged), tagged
        assert any('shard="1"' in t for t in tagged), tagged

        # ingest actually counted
        total = sum(float(t.rsplit(" ", 1)[1]) for t in tagged)
        assert total >= 150

    def test_flush_and_query_counters_move(self, server):
        srv = server
        with socket.create_connection(("127.0.0.1",
                                       srv.gateway.port)) as s:
            for i in range(60):
                ts_ns = (START + i * 10) * 1_000_000_000
                s.sendall(f"fq_metric,host=h1,_ws_=demo,_ns_=App-0 "
                          f"value={i} {ts_ns}\n".encode())
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            srv.gateway.sink.flush()
            if any(s2.stats.rows_ingested.value
                   for s2 in srv.memstore.shards_for("timeseries")):
                break
            time.sleep(0.3)
        for shard in srv.memstore.shards_for("timeseries"):
            shard.flush_all()
        text = _scrape(srv.http.port)

        def total(name):
            return sum(float(line.rsplit(" ", 1)[1])
                       for line in text.splitlines()
                       if line.startswith(name + "{") or line == name)

        assert total("memstore_flushes_success_total") >= 1
        assert total("memstore_samples_encoded_total") >= 60
        assert total("memstore_encoded_bytes_allocated_total") > 0
        assert total("memstore_flushes_chunks_written_total") >= 1
        # scrape-time gauges read live state
        assert total("memstore_index_entries") >= 1
        assert total("memstore_index_ram_bytes") > 0
