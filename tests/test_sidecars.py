"""Chunk aggregate sidecars: summary algebra, chunk/segment formats, and
the sidecar-served evaluation lane.

Covers the exactness contract end to end:

- the summary fold is strictly sequential, NaN-excluding, and merges across
  segment boundaries with Prometheus counter-reset carry — recomputing a
  summary from losslessly-decoded vectors reproduces the stored bits for
  every production codec (delta-delta, const, xor-double, packed-int, raw);
- the serialized sidecar rides as a trailing section old readers never see,
  and FSG1 (pre-sidecar) segments parse, serve, and get their summaries
  backfilled on compaction;
- query results served from sidecars (``FILODB_SIDECARS=1``) are
  bit-identical to the same lane recomputing every summary from decoded
  vectors (``=decode``), and kernel-tolerance equal to the decode/kernel
  lane (``=0``) across every eligible range function, with genuine counter
  resets and NaN staleness markers in the data;
- the valve, the ``filodb_sidecar_*`` counters, and queryStats attribution.
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.record import IngestRecord, RecordContainer, SomeData
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.memory import codecs
from filodb_tpu.memory.chunk import (
    SIDECAR_BACKFILLED,
    SKETCH_BUCKETS,
    STATS_WIDTH,
    S_CHANGES,
    S_CORR,
    S_COUNT,
    S_FIRST_TS,
    S_FIRST_VAL,
    S_LAST_TS,
    S_LAST_VAL,
    S_MAX,
    S_MIN,
    S_RESETS,
    S_SUM,
    S_SUMSQ,
    Chunk,
    chunk_id,
    encode_chunk,
    ensure_summary,
    summarize_values,
)
from filodb_tpu.query.engine import sidecar_lane
from filodb_tpu.query.engine.aggregations import sketch_quantile
from filodb_tpu.testing.data import (
    counter_series,
    counter_stream,
    gauge_stream,
    machine_metrics_series,
)

NUM_SHARDS = 4
START = 1_600_000_000  # epoch sec
INTERVAL = 10_000
N_SAMPLES = 400

GAUGE = DEFAULT_SCHEMAS["gauge"]


# ---------------------------------------------------------------- fixtures

def _nan_gauge_stream(keys, n_samples, start_ms, interval_ms):
    """Gauge stream with NaN staleness markers every 7th sample."""
    rng = np.random.default_rng(5)
    container = RecordContainer()
    offset = 0
    for s in range(n_samples):
        ts = start_ms + s * interval_ms
        for j, k in enumerate(keys):
            v = np.nan if (s + j) % 7 == 0 else 40.0 + rng.normal(0, 3.0)
            container.add(IngestRecord(k, ts, (float(v),)))
            if len(container) >= 100:
                yield SomeData(container, offset)
                offset += 1
                container = RecordContainer()
    if len(container):
        yield SomeData(container, offset)


@pytest.fixture(scope="module")
def store():
    ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        # small chunks: every query window below spans several sealed
        # chunks plus the live write buffer
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=50,
                                              groups_per_shard=4))
    streams = [
        gauge_stream(machine_metrics_series(6), N_SAMPLES,
                     start_ms=START * 1000, interval_ms=INTERVAL, seed=11),
        # genuine counter resets: drops at samples 120, 240, 360
        counter_stream(counter_series(4), N_SAMPLES,
                       start_ms=START * 1000, interval_ms=INTERVAL, seed=3,
                       reset_every=120),
        _nan_gauge_stream(machine_metrics_series(3, metric="spotty_gauge",
                                                 ns="App-3"),
                          N_SAMPLES, START * 1000, INTERVAL),
    ]
    for stream in streams:
        ingest_routed(ms, "timeseries", stream, NUM_SHARDS, spread=1)
    return ms


@pytest.fixture(scope="module")
def svc(store):
    return QueryService(store, "timeseries", NUM_SHARDS, spread=1)


def _q(svc, monkeypatch, mode, promql, qs, qe, step=60):
    monkeypatch.setenv("FILODB_SIDECARS", mode)
    return svc.query_range(promql, qs, step, qe)


def assert_same_result(a, b, bitwise: bool, rtol: float = 2e-5):
    m0, m1 = a.result, b.result
    i0 = {k: i for i, k in enumerate(m0.keys)}
    i1 = {k: i for i, k in enumerate(m1.keys)}
    assert set(i0) == set(i1)
    assert m0.num_series == m1.num_series
    if m0.num_series:
        assert np.array_equal(m0.steps_ms, m1.steps_ms)
    for k, i in i0.items():
        va = np.asarray(m0.values[i], np.float64)
        vb = np.asarray(m1.values[i], np.float64)
        if bitwise:
            assert va.tobytes() == vb.tobytes(), k
        else:
            na, nb = np.isnan(va), np.isnan(vb)
            assert np.array_equal(na, nb), k
            assert np.allclose(va[~na], vb[~nb], rtol=rtol, atol=1e-9), k


# ---------------------------------------------------------- summary algebra

class TestSummaryAlgebra:
    def test_stats_exclude_nan_and_track_resets(self):
        ts = np.arange(1000, 11000, 1000, dtype=np.int64)
        vals = np.array([5.0, np.nan, 7.0, 3.0, 3.0, np.nan, 9.0, 2.0,
                         2.0, 4.0])
        cs = summarize_values(ts, vals)
        st = cs.stats
        assert st[S_COUNT] == 8
        assert st[S_SUM] == 5.0 + 7 + 3 + 3 + 9 + 2 + 2 + 4
        assert st[S_SUMSQ] == sum(v * v for v in (5, 7, 3, 3, 9, 2, 2, 4))
        assert st[S_MIN] == 2.0 and st[S_MAX] == 9.0
        assert st[S_FIRST_TS] == 1000 and st[S_FIRST_VAL] == 5.0
        assert st[S_LAST_TS] == 10000 and st[S_LAST_VAL] == 4.0
        # drops: 7->3 and 9->2 (NaN-adjacent pairs bridge the gap)
        assert st[S_RESETS] == 2
        assert st[S_CORR] == 7.0 + 9.0
        # changes: 5->7->3->3->9->2->2->4 has 5 transitions
        assert st[S_CHANGES] == 5

    def test_empty_and_all_nan(self):
        ts = np.array([1, 2, 3], dtype=np.int64)
        for vals in (np.array([], np.float64),
                     np.array([np.nan, np.nan, np.nan])):
            cs = summarize_values(ts[:len(vals)] if len(vals) else ts, vals)
            assert cs.stats[S_COUNT] == 0
            assert np.all(np.isnan(cs.stats[S_MIN:S_LAST_VAL + 1]))
            assert cs.sketch is not None and cs.sketch.sum() == 0

    def test_merge_matches_whole_series_bitwise(self):
        """Splitting a series at any point and merging the halves'
        summaries reproduces the whole-series summary bit for bit —
        including a counter reset landing exactly on the split."""
        rng = np.random.default_rng(17)
        n = 60
        ts = np.arange(n, dtype=np.int64) * 1000 + 1000
        vals = np.cumsum(rng.integers(0, 9, n).astype(np.float64))
        vals[37:] -= vals[37]  # counter reset at sample 37
        whole = summarize_values(ts, vals).stats.reshape(1, STATS_WIDTH)
        for cut in (1, 20, 37, 59):
            a = summarize_values(ts[:cut], vals[:cut]).stats.reshape(1, -1)
            b = summarize_values(ts[cut:], vals[cut:]).stats.reshape(1, -1)
            merged = sidecar_lane._merge_vec(a, b)
            assert merged.tobytes() == whole.tobytes(), cut

    def test_sketch_quantile_bounds(self):
        sk = np.zeros(SKETCH_BUCKETS, np.int64)
        sk[40] = 10
        assert sketch_quantile(-0.1, sk) == -np.inf
        assert sketch_quantile(1.1, sk) == np.inf
        v = sketch_quantile(0.5, sk)
        assert np.isfinite(v) and v > 0


# ------------------------------------------------------------ chunk format

def _mk_chunk(ts, vals, with_summary=True):
    return encode_chunk(GAUGE, ts, [vals], with_summary=with_summary)


class TestChunkFormat:
    TS = np.arange(1000, 51000, 1000, dtype=np.int64)

    def test_roundtrip_preserves_summary_bits(self):
        vals = np.sin(np.arange(50)) * 100
        ch = _mk_chunk(self.TS, vals)
        back = Chunk.deserialize(ch.serialize())
        assert back.summary is not None
        assert back.summary[0] is None  # timestamp column carries none
        assert back.summary[1].stats.tobytes() == \
            ch.summary[1].stats.tobytes()
        assert np.array_equal(back.summary[1].sketch, ch.summary[1].sketch)
        assert back.vectors == ch.vectors

    def test_presidecar_payload_is_legacy_layout(self):
        """with_summary=False serializes the exact pre-sidecar byte layout
        and deserializes with summary None (old-reader compatibility)."""
        vals = np.arange(50, dtype=np.float64)
        new = _mk_chunk(self.TS, vals)
        old = _mk_chunk(self.TS, vals, with_summary=False)
        assert old.serialize() == new.serialize()[:len(old.serialize())]
        assert Chunk.deserialize(old.serialize()).summary is None

    @pytest.mark.parametrize("codec,vals", [
        # encode_double picks const for all-bitwise-equal values
        ("const", np.full(50, 42.5)),
        ("xor-double", np.sin(np.arange(50)) * 100 + 7),
        ("packed-int", np.arange(50, dtype=np.float64) * 3),
        ("raw-double", np.tan(np.arange(50)) * 1e6),
        ("nan-bearing", np.where(np.arange(50) % 7 == 0, np.nan,
                                 np.arange(50, dtype=np.float64))),
    ])
    def test_recompute_matches_stored_bitwise(self, codec, vals):
        """ensure_summary over losslessly-decoded vectors reproduces the
        seal-time summary bit for bit, per production codec."""
        if codec == "packed-int":
            vec = codecs.encode_int(vals.astype(np.int64))
        elif codec == "raw-double":
            vec = codecs.encode_raw_double(vals)
        else:
            vec = codecs.encode_double(vals)
        stored = _mk_chunk(self.TS, vals)
        bare = Chunk(chunk_id(int(self.TS[0])), 50, int(self.TS[0]),
                     int(self.TS[-1]),
                     (codecs.encode_delta_delta(self.TS), vec))
        recomputed = ensure_summary(bare)
        assert recomputed is not None and recomputed[1] is not None
        assert recomputed[1].stats.tobytes() == \
            stored.summary[1].stats.tobytes()
        assert np.array_equal(recomputed[1].sketch,
                              stored.summary[1].sketch)

    def test_ensure_summary_memoizes_and_tolerates_garbage(self):
        ch = Chunk(1, 10, 0, 9, (b"\x99garbage", b"\x98junk"))
        assert ensure_summary(ch) is None  # undecodable ts: no summary
        good = _mk_chunk(self.TS, np.arange(50, dtype=np.float64),
                         with_summary=False)
        s1 = ensure_summary(good)
        assert s1 is not None and ensure_summary(good) is s1


# ---------------------------------------------------------- segment format

class TestFsgCompat:
    def _legacy_segment(self, chunks, pk_blob=b"pk0"):
        """Craft an FSG1 segment: write with the current writer, swap the
        magic, recompute the footer CRC over the patched body."""
        from filodb_tpu.core.store.objectstore import (
            _FOOTER,
            _FOOTER_MARK,
            _OpenSegment,
            crc32c,
        )
        seg = _OpenSegment(seq=1, bucket=0)
        for ch in chunks:
            seg.add_chunk(pk_blob, ch, ingestion_time=1, upd=1)
        data = seg.finish()
        body = b"FSG1" + data[4:len(data) - _FOOTER.size]
        return body + _FOOTER.pack(_FOOTER_MARK, seg.entries, crc32c(body))

    def test_fsg1_parses_and_chunks_decode(self):
        from filodb_tpu.core.store.objectstore import parse_segment
        ts = np.arange(1000, 11000, 1000, dtype=np.int64)
        legacy = self._legacy_segment(
            [encode_chunk(GAUGE, ts, [np.arange(10, dtype=np.float64)],
                          with_summary=False)])
        entries = list(parse_segment(legacy, "legacy.seg"))
        assert len(entries) == 1 and entries[0][0] == "chunk"
        ch = Chunk.deserialize(entries[0][10])
        assert ch.summary is None
        assert np.array_equal(ch.decode_column(1),
                              np.arange(10, dtype=np.float64))

    def test_fsg1_store_reads_and_compaction_backfills(self, tmp_path):
        """A store written entirely by a pre-sidecar build (FSG1 magic,
        summary-less chunk payloads) recovers, serves reads, and gets
        summaries + FSG2 magic backfilled by compaction."""
        from unittest import mock

        from filodb_tpu.core.store import objectstore as osmod
        from filodb_tpu.testing.fake_s3 import FakeS3
        s3root = str(tmp_path / "s3")
        pk = PartKey.create("gauge", {"_metric_": "heap_usage",
                                      "_ws_": "demo", "_ns_": "app-0"})
        with mock.patch.object(osmod, "_MAGIC", b"FSG1"):
            cs = osmod.ObjectStoreColumnStore(FakeS3(root=s3root),
                                              bucket_count=1,
                                              auto_compact=False)
            for i in range(3):
                ts = np.arange(10, dtype=np.int64) * 1000 + i * 100_000
                ch = encode_chunk(GAUGE, ts,
                                  [np.arange(10, dtype=np.float64) + i],
                                  seq=i, with_summary=False)
                cs.write_chunks("timeseries", 0, pk, [ch],
                                ingestion_time=i)
                cs.flush()
            cs.close()

        segs = [k for k in FakeS3(root=s3root).list_objects("")
                if k.endswith(".seg")]
        assert segs
        assert all(FakeS3(root=s3root).get_object(k)[:4] == b"FSG1"
                   for k in segs)

        cs2 = osmod.ObjectStoreColumnStore(FakeS3(root=s3root),
                                           bucket_count=1,
                                           auto_compact=False)
        back = cs2.read_chunks("timeseries", 0, pk, 0, 2**62)
        assert len(back) == 3
        assert all(c.summary is None for c in back)

        b0 = SIDECAR_BACKFILLED.value
        assert cs2.compact("timeseries", 0) >= 1
        cs2.flush()
        assert SIDECAR_BACKFILLED.value > b0
        back2 = cs2.read_chunks("timeseries", 0, pk, 0, 2**62)
        assert len(back2) == 3
        for c in back2:
            assert c.summary is not None and c.summary[1] is not None
            want = summarize_values(c.decode_column(0), c.decode_column(1))
            assert c.summary[1].stats.tobytes() == want.stats.tobytes()
        s3 = FakeS3(root=s3root)
        live = [k for k in s3.list_objects("") if k.endswith(".seg")]
        assert any(s3.get_object(k)[:4] == b"FSG2" for k in live)
        cs2.close()


# --------------------------------------------------- lane query equivalence

GAUGE_FNS = ["count_over_time", "sum_over_time", "avg_over_time",
             "min_over_time", "max_over_time", "stddev_over_time",
             "stdvar_over_time", "last_over_time", "present_over_time",
             "changes", "zscore", "timestamp"]
COUNTER_FNS = ["rate", "increase", "delta", "resets"]


class TestLaneEquivalence:
    """FILODB_SIDECARS=1 (serve stored) vs =decode (recompute) must be
    bit-identical; vs =0 (kernel lane) kernel-dtype equal."""

    QS, QE = START + 2000, START + 3950

    def _sweep(self, svc, monkeypatch, promql, qs=None, qe=None):
        qs, qe = qs or self.QS, qe or self.QE
        served0 = sidecar_lane.SIDECAR_SERVED.value
        r1 = _q(svc, monkeypatch, "1", promql, qs, qe)
        assert sidecar_lane.SIDECAR_SERVED.value > served0, promql
        assert r1.result.num_series > 0, promql
        rd = _q(svc, monkeypatch, "decode", promql, qs, qe)
        r0 = _q(svc, monkeypatch, "0", promql, qs, qe)
        assert_same_result(r1, rd, bitwise=True)
        assert_same_result(r1, r0, bitwise=False)
        return r1

    @pytest.mark.parametrize("fn", GAUGE_FNS)
    def test_gauge_functions(self, svc, monkeypatch, fn):
        self._sweep(svc, monkeypatch, f"{fn}(heap_usage[30m])")

    @pytest.mark.parametrize("fn", COUNTER_FNS)
    def test_counter_functions_with_genuine_resets(self, store, svc,
                                                   monkeypatch, fn):
        # the fixture's counters reset at samples 120/240/360 — prove the
        # summaries actually saw drops so the reset algebra is exercised
        resets = 0.0
        for s in range(NUM_SHARDS):
            shard = store.get_shard("timeseries", s)
            for pid in shard.lookup_partitions([], 0, 2**62):
                p = shard.partition(pid)
                if p is None or p.part_key.label_map.get("_metric_") \
                        != "http_requests_total":
                    continue
                for ch in p.chunks:
                    summ = ensure_summary(ch)
                    if summ is not None and summ[1] is not None:
                        resets += summ[1].stats[S_RESETS]
        assert resets > 0
        self._sweep(svc, monkeypatch,
                    f"{fn}(http_requests_total[30m])")

    def test_nan_bearing_series(self, svc, monkeypatch):
        for fn in ("avg_over_time", "count_over_time", "max_over_time"):
            self._sweep(svc, monkeypatch, f"{fn}(spotty_gauge[30m])")

    def test_aggregations_and_grouping(self, svc, monkeypatch):
        for q in ("sum(rate(http_requests_total[20m]))",
                  "avg by (host) (sum_over_time(heap_usage[25m]))",
                  "max(max_over_time(heap_usage[30m]))"):
            self._sweep(svc, monkeypatch, q)

    def test_windows_cover_multiple_chunks(self, svc, monkeypatch):
        # 30m window = 180 samples = 3.6 chunks of 50: interiors must fold
        r1 = self._sweep(svc, monkeypatch, "sum_over_time(heap_usage[30m])")
        assert r1.stats.sidecar_chunks >= 3
        assert r1.stats.samples_scanned > 0

    def test_instant_selector(self, svc, monkeypatch):
        self._sweep(svc, monkeypatch, "heap_usage")


class TestValveAndMetrics:
    def test_valve_off_never_serves(self, svc, monkeypatch):
        served0 = sidecar_lane.SIDECAR_SERVED.value
        r = _q(svc, monkeypatch, "0", "sum_over_time(heap_usage[10m])",
               START + 2000, START + 3000)
        assert r.result.num_series > 0
        assert sidecar_lane.SIDECAR_SERVED.value == served0

    def test_ineligible_function_bypasses(self, svc, monkeypatch):
        monkeypatch.delenv("FILODB_SIDECAR_APPROX", raising=False)
        b0 = sidecar_lane.SIDECAR_BYPASSED.value
        _q(svc, monkeypatch, "1",
           "quantile_over_time(0.9, heap_usage[10m])",
           START + 2000, START + 3000)
        assert sidecar_lane.SIDECAR_BYPASSED.value > b0

    def test_query_stats_attribution(self, svc, monkeypatch):
        r1 = _q(svc, monkeypatch, "1", "avg_over_time(heap_usage[30m])",
                START + 2500, START + 3800)
        assert r1.stats.sidecar_chunks > 0
        assert r1.stats.chunks_touched >= r1.stats.sidecar_chunks
        r0 = _q(svc, monkeypatch, "0", "avg_over_time(heap_usage[30m])",
                START + 2500, START + 3800)
        assert r0.stats.sidecar_chunks == 0

    def test_quantile_served_only_under_declared_approx(self, svc,
                                                        monkeypatch):
        monkeypatch.setenv("FILODB_SIDECAR_APPROX", "1")
        served0 = sidecar_lane.SIDECAR_SERVED.value
        r = _q(svc, monkeypatch, "1",
               "quantile_over_time(0.9, heap_usage[30m])",
               START + 2000, START + 3000)
        assert sidecar_lane.SIDECAR_SERVED.value > served0
        exact = _q(svc, monkeypatch, "0",
                   "quantile_over_time(0.9, heap_usage[30m])",
                   START + 2000, START + 3000)
        # log2-bucket sketch: representative within a power of two
        m1 = r.result
        me = exact.result
        ie = {k: i for i, k in enumerate(me.keys)}
        for k, i in ((k, i) for i, k in enumerate(m1.keys)):
            a = np.asarray(m1.values[i], np.float64)
            b = np.asarray(me.values[ie[k]], np.float64)
            both = ~np.isnan(a) & ~np.isnan(b) & (b > 0)
            assert np.all(a[both] <= b[both] * 2.0 + 1e-9)
            assert np.all(a[both] >= b[both] * 0.25 - 1e-9)
