"""Runtime lock-order validator (filodb_tpu/utils/lockcheck.py).

Each scenario builds fresh locks INSIDE an installed session (only
locks created after install are wrapped) and checks what the validator
records — and, just as important, what it does not.
"""

import queue
import threading
import time

import pytest

from filodb_tpu.utils import lockcheck


@pytest.fixture(autouse=True)
def _clean_install():
    lockcheck.uninstall()
    yield
    lockcheck.uninstall()


def make_locks(n=2):
    # one lock per source line: the checker keys nodes by creation site,
    # and same-site edges are skipped by design
    out = []
    for _ in range(n):
        out.append(threading.Lock())
    return out


class TestCycleDetection:
    def test_opposite_orders_recorded(self):
        with lockcheck.session():
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass
            vs = lockcheck.violations()
        assert [v.kind for v in vs] == ["lock-order-cycle"]

    def test_consistent_order_clean(self):
        with lockcheck.session():
            a = threading.Lock()
            b = threading.Lock()
            for _ in range(3):
                with a:
                    with b:
                        pass
            vs = lockcheck.violations()
        assert vs == []

    def test_same_site_reacquisition_not_a_cycle(self):
        # two instances of one class nest in both orders; the site graph
        # cannot order instances, so this must stay silent (documented
        # gap: the static pass / a dedicated hierarchy handles it)
        with lockcheck.session():
            a, b = make_locks(2)
            with a:
                with b:
                    pass
            vs = lockcheck.violations()
        assert vs == []

    def test_cycle_across_threads(self):
        with lockcheck.session():
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass

            def other():
                with b:
                    with a:
                        pass

            t = threading.Thread(target=other)
            t.start()
            t.join()
            vs = lockcheck.violations()
        assert [v.kind for v in vs] == ["lock-order-cycle"]

    def test_strict_mode_raises(self):
        with lockcheck.session(strict=True):
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass
            with pytest.raises(lockcheck.LockOrderViolation):
                with b:
                    with a:
                        pass


class TestBlockingUnderLock:
    def test_sleep_under_lock(self):
        with lockcheck.session():
            a = threading.Lock()
            with a:
                time.sleep(0)
            vs = lockcheck.violations()
        assert [v.kind for v in vs] == ["blocking-under-lock"]
        assert "time.sleep" in vs[0].detail

    def test_queue_get_under_lock(self):
        with lockcheck.session():
            a = threading.Lock()
            q = queue.Queue()
            q.put(1)
            with a:
                q.get()
            vs = lockcheck.violations()
        assert [v.kind for v in vs] == ["blocking-under-lock"]

    def test_nonblocking_get_is_fine(self):
        with lockcheck.session():
            a = threading.Lock()
            q = queue.Queue()
            q.put(1)
            with a:
                q.get(block=False)
            vs = lockcheck.violations()
        assert vs == []

    def test_thread_join_under_lock(self):
        with lockcheck.session():
            a = threading.Lock()
            t = threading.Thread(target=lambda: None)
            t.start()
            with a:
                t.join()
            vs = lockcheck.violations()
        assert [v.kind for v in vs] == ["blocking-under-lock"]

    def test_sleep_outside_lock_is_fine(self):
        with lockcheck.session():
            a = threading.Lock()
            with a:
                pass
            time.sleep(0)
            vs = lockcheck.violations()
        assert vs == []

    def test_duplicate_shapes_reported_once(self):
        with lockcheck.session():
            a = threading.Lock()
            for _ in range(5):
                with a:
                    time.sleep(0)
            vs = lockcheck.violations()
        assert len(vs) == 1


class TestConditionCompat:
    def test_condition_over_checked_rlock(self):
        # Condition(wrapped RLock) relies on the private
        # _release_save/_acquire_restore/_is_owned protocol; wait() must
        # release the lock (else the notifier deadlocks) and not count
        # as blocking under it
        with lockcheck.session():
            lk = threading.RLock()
            cond = threading.Condition(lk)
            ready = []

            def producer():
                with cond:
                    ready.append(1)
                    cond.notify()

            t = threading.Thread(target=producer)
            with cond:
                t.start()
                deadline = time.monotonic() + 5.0
                while not ready and time.monotonic() < deadline:
                    cond.wait(0.1)
            t.join()
            assert ready
            vs = lockcheck.violations()
        assert vs == []


class TestLifecycle:
    def test_install_uninstall_restores_primitives(self):
        real_lock = threading.Lock
        real_sleep = time.sleep
        lockcheck.install(strict=False)
        assert threading.Lock is not real_lock
        assert lockcheck.installed()
        lockcheck.uninstall()
        assert threading.Lock is real_lock
        assert time.sleep is real_sleep
        assert not lockcheck.installed()

    def test_locks_survive_uninstall(self):
        # a wrapped lock created during the session keeps working after
        # uninstall (worker threads may outlive a test session)
        lockcheck.install(strict=False)
        lk = threading.Lock()
        lockcheck.uninstall()
        with lk:
            pass
        assert not lk.locked()

    def test_delegates_fork_hook(self):
        # concurrent.futures registers _at_fork_reinit on a module-level
        # lock; the wrapper must expose the full primitive surface
        lockcheck.install(strict=False)
        try:
            lk = threading.Lock()
            lk._at_fork_reinit()
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(max_workers=1) as ex:
                assert ex.submit(lambda: 42).result() == 42
        finally:
            lockcheck.uninstall()

    def test_reset_clears_state(self):
        lockcheck.install(strict=False)
        a = threading.Lock()
        with a:
            time.sleep(0)
        assert lockcheck.violations()
        lockcheck.reset()
        assert lockcheck.violations() == []
        lockcheck.uninstall()

    def test_env_flag(self, monkeypatch):
        monkeypatch.delenv("FILODB_LOCKCHECK", raising=False)
        assert not lockcheck.enabled_by_env()
        monkeypatch.setenv("FILODB_LOCKCHECK", "0")
        assert not lockcheck.enabled_by_env()
        monkeypatch.setenv("FILODB_LOCKCHECK", "1")
        assert lockcheck.enabled_by_env()
