"""Distributed query tracing: cross-node span trees over TCP plan
shipping, merged per-query stats, deterministic head sampling, the
slow-query flight recorder, and the debug/slow_queries HTTP surface.

A sampled aggregate fanned out over two plan-executor peers must come
back as ONE span tree: the remote leaves' scan/decode/reduce spans are
shipped in the result frame and grafted — node-tagged — under the root's
dispatch spans, and the leaves' QueryStats fold into the root's.
"""

import dataclasses
import json
import urllib.parse
import urllib.request

import pytest

from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.coordinator.remote import (
    PlanExecutorServer,
    RemotePlanDispatcher,
    reset_pool,
)
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.testing.data import gauge_stream, machine_metrics_series
from filodb_tpu.utils import tracing
from filodb_tpu.utils.resilience import reset_breakers

NUM_SHARDS = 4
START = 1_600_000_000
QS, STEP, QE = START + 100, 60, START + 2000
PROMQL = "sum(heap_usage) by (host)"


def build_store():
    ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=100,
                                              groups_per_shard=4))
    stream = gauge_stream(machine_metrics_series(10, ns="App-0"), 240,
                          start_ms=START * 1000, interval_ms=10_000, seed=5)
    ingest_routed(ms, "timeseries", stream, NUM_SHARDS, spread=1)
    return ms


@pytest.fixture(scope="module")
def store():
    return build_store()


@pytest.fixture(autouse=True)
def restore_tracing():
    prev = dataclasses.asdict(tracing.config())
    yield
    tracing.configure(**prev)
    tracing.flight_recorder().clear()


def _clear_batch_caches(store):
    for sh in store.shards_for("timeseries"):
        sh.batch_cache.clear()


@pytest.fixture()
def two_peer_env(store):
    reset_breakers()
    reset_pool()
    srv_a = PlanExecutorServer(store).start()
    srv_b = PlanExecutorServer(store).start()
    disp_a = RemotePlanDispatcher("127.0.0.1", srv_a.port)
    disp_b = RemotePlanDispatcher("127.0.0.1", srv_b.port)
    svc = QueryService(store, "timeseries", NUM_SHARDS, spread=1)
    svc.planner.dispatcher_for_shard = \
        lambda s: disp_a if s < NUM_SHARDS // 2 else disp_b
    yield svc, disp_a.peer, disp_b.peer
    srv_a.stop()
    srv_b.stop()
    reset_pool()
    reset_breakers()


class TestDistributedSpanTree:
    def test_one_tree_with_node_tagged_remote_children(self, store,
                                                       two_peer_env):
        svc, peer_a, peer_b = two_peer_env
        _clear_batch_caches(store)
        with tracing.start_trace() as trace:
            r = svc.query_range(PROMQL, QS, STEP, QE)
        spans = trace.as_dicts()

        # every shard's dispatch span is in THIS trace (worker threads
        # adopted the root's trace handle instead of dropping spans)
        dispatch = [s for s in spans if s["name"] == "dispatch"]
        assert len(dispatch) == NUM_SHARDS
        assert {s["tags"]["peer"] for s in dispatch} == {peer_a, peer_b}

        # the remote trees arrived node-tagged, from BOTH peers
        nodes = {s["tags"]["node"] for s in spans
                 if "node" in (s.get("tags") or {})}
        assert nodes == {peer_a, peer_b}

        # remote leaf stage spans were shipped back and grafted
        names = {s["name"] for s in spans}
        assert {"scan", "decode", "reduce"} <= names

        # parent links: every remote scan span walks up to a dispatch span
        # (one connected tree, not four disjoint fragments)
        by_id = {s["span_id"]: s for s in spans}
        scans = [s for s in spans if s["name"] == "scan"]
        assert len(scans) == NUM_SHARDS
        for s in scans:
            ancestors, cur, hops = [], s, 0
            while cur.get("parent_id") and hops < 32:
                cur = by_id[cur["parent_id"]]
                ancestors.append(cur["name"])
                hops += 1
            assert "dispatch" in ancestors, (s, ancestors)

        # the leaves' stats folded into the root result
        assert r.stats.series_scanned > 0
        assert r.stats.samples_scanned > 0
        assert r.stats.chunks_touched > 0
        assert r.stats.wire_bytes > 0
        assert r.stats.decode_s > 0
        # remote spans were stripped from the result after grafting
        assert r.spans == []

    def test_stats_equivalence_local_vs_remote(self, store, two_peer_env):
        svc_remote, _, _ = two_peer_env
        svc_local = QueryService(store, "timeseries", NUM_SHARDS, spread=1)
        _clear_batch_caches(store)
        local = svc_local.query_range(PROMQL, QS, STEP, QE)
        _clear_batch_caches(store)
        remote = svc_remote.query_range(PROMQL, QS, STEP, QE)
        assert remote.stats.series_scanned == local.stats.series_scanned
        assert remote.stats.samples_scanned == local.stats.samples_scanned
        assert remote.stats.chunks_touched == local.stats.chunks_touched
        # wire accounting exists only on the remote path
        assert local.stats.wire_bytes == 0
        assert remote.stats.wire_bytes > 0

    def test_unsampled_query_has_zero_spans(self, two_peer_env):
        svc, _, _ = two_peer_env
        tracing.configure(sample_rate=0.0, slow_query_threshold_ms=0.0)
        before = len(tracing.flight_recorder())
        r = svc.query_range(PROMQL, QS, STEP, QE)
        assert r.spans == []
        assert tracing.current_trace() is None
        assert len(tracing.flight_recorder()) == before
        assert r.stats.samples_scanned > 0  # stats still collected

    def test_head_sampled_slow_query_lands_in_recorder(self, two_peer_env):
        svc, peer_a, peer_b = two_peer_env
        tracing.configure(sample_rate=1.0, slow_query_threshold_ms=0.001,
                          slowlog_capacity=16)
        tracing.flight_recorder().clear()
        svc.query_range(PROMQL, QS, STEP, QE)
        entries = tracing.slow_queries()
        assert entries
        e = entries[0]
        assert e["kind"] == "query"
        assert e["sampled"] is True
        assert e["query"] == PROMQL
        assert e["dataset"] == "timeseries"
        assert e["stats"]["samples_scanned"] > 0
        names = {s["name"] for s in e["spans"]}
        # root-side parse + dispatch AND remote leaf scans, one tree
        assert {"parse", "dispatch", "scan"} <= names
        nodes = {s["tags"]["node"] for s in e["spans"]
                 if "node" in (s.get("tags") or {})}
        assert nodes == {peer_a, peer_b}


class TestSampling:
    def test_deterministic_verdicts(self):
        ids = [f"query-{i:04d}" for i in range(400)]
        first = [tracing.should_sample(q, rate=0.3) for q in ids]
        second = [tracing.should_sample(q, rate=0.3) for q in ids]
        assert first == second
        frac = sum(first) / len(first)
        assert 0.15 < frac < 0.45  # roughly the configured rate
        assert not any(tracing.should_sample(q, rate=0.0) for q in ids)
        assert all(tracing.should_sample(q, rate=1.0) for q in ids)

    def test_rate_zero_never_starts_a_trace(self, store):
        tracing.configure(sample_rate=0.0, slow_query_threshold_ms=0.0)
        svc = QueryService(store, "timeseries", NUM_SHARDS, spread=1)
        svc.query_range(PROMQL, QS, STEP, QE)
        assert tracing.current_trace() is None


class TestFlightRecorder:
    def test_ring_bounds_and_evicts_oldest(self):
        rec = tracing.FlightRecorder(capacity=4)
        for i in range(10):
            rec.record({"kind": "query", "i": i})
        assert len(rec) == 4
        assert [e["i"] for e in rec.snapshot()] == [6, 7, 8, 9]
        rec.resize(2)  # shrink keeps the newest entries
        assert [e["i"] for e in rec.snapshot()] == [8, 9]
        rec.clear()
        assert len(rec) == 0

    def test_slow_queries_newest_first_with_limit(self):
        tracing.configure(sample_rate=0.0, slow_query_threshold_ms=1.0,
                          slowlog_capacity=8)
        tracing.flight_recorder().clear()
        for i in range(5):
            tracing.record_slow("query", 50.0 + i, query=f"q{i}")
        entries = tracing.slow_queries()
        assert [e["query"] for e in entries] == ["q4", "q3", "q2", "q1", "q0"]
        assert [e["query"] for e in tracing.slow_queries(limit=2)] \
            == ["q4", "q3"]

    def test_threshold_gates_recording(self):
        tracing.configure(sample_rate=0.0, slow_query_threshold_ms=100.0,
                          slowlog_capacity=8)
        tracing.flight_recorder().clear()
        tracing.record_slow("query", 50.0, query="fast")
        tracing.record_slow("query", 150.0, query="slow")
        assert [e["query"] for e in tracing.slow_queries()] == ["slow"]

    def test_traced_operation_records_slow_runs(self):
        tracing.configure(sample_rate=0.0, slow_query_threshold_ms=0.001,
                          slowlog_capacity=8)
        tracing.flight_recorder().clear()
        with tracing.traced_operation("rules", group="g1", steps=3):
            pass
        entries = tracing.slow_queries()
        assert entries and entries[0]["kind"] == "rules"
        assert entries[0]["group"] == "g1"
        assert entries[0]["spans"][0]["name"] == "rules"


class TestHttpSurface:
    @pytest.fixture(params=["threaded", "fast"])
    def http_env(self, request, store):
        svc = QueryService(store, "timeseries", NUM_SHARDS, spread=1)
        if request.param == "threaded":
            from filodb_tpu.http.server import FiloHttpServer
            srv = FiloHttpServer({"timeseries": svc}, port=0).start()
        else:
            from filodb_tpu.http.fastserver import FastHttpServer
            srv = FastHttpServer({"timeseries": svc}, port=0).start()
        yield srv
        srv.stop()

    def _get(self, srv, path):
        url = f"http://127.0.0.1:{srv.port}{path}"
        with urllib.request.urlopen(url) as r:
            assert r.status == 200
            return json.load(r)

    def test_stats_all_param_expands_query_stats(self, http_env):
        qs = urllib.parse.urlencode({
            "query": PROMQL, "start": QS, "end": QE, "step": STEP,
            "stats": "all"})
        doc = self._get(http_env,
                        f"/promql/timeseries/api/v1/query_range?{qs}")
        stats = doc["queryStats"]
        for key in ("seriesScanned", "samplesScanned", "chunksTouched",
                    "cacheHits", "cacheMisses", "wireBytes",
                    "admissionWaitMs", "decodeMs", "reduceMs"):
            assert key in stats, key
        assert stats["samplesScanned"] > 0

        # without the param the compact stats render (no expanded keys)
        qs = urllib.parse.urlencode({
            "query": PROMQL, "start": QS, "end": QE, "step": STEP})
        doc = self._get(http_env,
                        f"/promql/timeseries/api/v1/query_range?{qs}")
        assert "chunksTouched" not in doc["queryStats"]

    def test_slow_queries_endpoint_serves_recorder(self, http_env):
        tracing.configure(sample_rate=1.0, slow_query_threshold_ms=0.001,
                          slowlog_capacity=16)
        tracing.flight_recorder().clear()
        qs = urllib.parse.urlencode({
            "query": PROMQL, "start": QS, "end": QE, "step": STEP})
        self._get(http_env, f"/promql/timeseries/api/v1/query_range?{qs}")
        doc = self._get(http_env,
                        "/promql/timeseries/api/v1/debug/slow_queries")
        entries = doc["data"]["slow_queries"]
        assert entries
        e = entries[0]
        assert e["kind"] == "query"
        assert e["query"] == PROMQL
        assert e["stats"]["samples_scanned"] > 0
        assert any(s["name"] == "parse" for s in e["spans"])
        # ?limit= caps the dump
        doc = self._get(
            http_env,
            "/promql/timeseries/api/v1/debug/slow_queries?limit=1")
        assert len(doc["data"]["slow_queries"]) == 1

    def test_debug_trace_joins_and_records(self, http_env):
        tracing.configure(sample_rate=0.0, slow_query_threshold_ms=0.001,
                          slowlog_capacity=16)
        tracing.flight_recorder().clear()
        qs = urllib.parse.urlencode({
            "query": PROMQL, "start": QS, "end": QE, "step": STEP})
        doc = self._get(http_env,
                        f"/promql/timeseries/api/v1/debug/trace?{qs}")
        names = [s["name"] for s in doc["data"]["spans"]]
        assert "parse" in names
        assert doc["data"]["stats"]["samples_scanned"] > 0
        # the traced query ALSO tail-captured into the flight recorder
        # (traced_query joined the endpoint's active trace)
        assert any(e["kind"] == "query"
                   for e in tracing.slow_queries())
