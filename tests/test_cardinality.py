"""Cardinality tracking + quota tests.

Mirrors reference ``CardinalityTrackerSpec`` (ratelimit package).
"""

import pytest

from filodb_tpu.core.memstore.cardinality import (
    CardinalityTracker,
    QuotaExceededError,
)
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.testing.data import gauge_stream, machine_metrics_series


def labels(ws, ns, metric, **extra):
    return {"_ws_": ws, "_ns_": ns, "_metric_": metric, **extra}


class TestTracker:
    def test_counts_along_path(self):
        t = CardinalityTracker(0)
        for i in range(5):
            t.series_created(labels("w1", "ns1", "m1", instance=str(i)))
        for i in range(3):
            t.series_created(labels("w1", "ns2", "m2", instance=str(i)))
        assert t.cardinality(["w1"]).active_ts == 8
        assert t.cardinality(["w1", "ns1"]).active_ts == 5
        assert t.cardinality(["w1", "ns1", "m1"]).active_ts == 5
        assert t.cardinality(["w1"]).children == 2

    def test_quota_enforced(self):
        t = CardinalityTracker(0)
        t.set_quota(["w1", "ns1"], 3)
        for i in range(3):
            t.series_created(labels("w1", "ns1", "m1", i=str(i)))
        with pytest.raises(QuotaExceededError):
            t.series_created(labels("w1", "ns1", "m1", i="overflow"))
        # other namespaces unaffected
        t.series_created(labels("w1", "ns2", "m1"))

    def test_series_stopped_decrements(self):
        t = CardinalityTracker(0)
        t.series_created(labels("w", "n", "m", i="a"))
        t.series_created(labels("w", "n", "m", i="b"))
        t.series_stopped(labels("w", "n", "m", i="a"))
        c = t.cardinality(["w", "n", "m"])
        assert c.active_ts == 1 and c.total_ts == 2

    def test_top_k(self):
        t = CardinalityTracker(0)
        for ns, n in (("big", 10), ("mid", 5), ("small", 1)):
            for i in range(n):
                t.series_created(labels("w", ns, "m", i=str(i)))
        top = t.top_k(["w"], 2)
        assert [c.name for c in top] == ["big", "mid"]

    def test_unknown_prefix_empty(self):
        t = CardinalityTracker(0)
        assert t.cardinality(["nope"]).active_ts == 0
        assert t.top_k(["nope"]) == []


class TestShardQuota:
    def test_ingest_respects_quota(self):
        ms = TimeSeriesMemStore()
        shard = ms.setup("timeseries", 0, StoreConfig(max_chunk_size=50))
        shard.cardinality.set_quota(["demo", "App-0"], 4)
        keys = machine_metrics_series(10)  # all in demo/App-0
        for sd in gauge_stream(keys, 10):
            shard.ingest(sd)
        assert shard.num_partitions == 4
        assert shard.stats.quota_dropped.value > 0
