"""AdaptiveQueryEngine: two-lane cost routing (parallel/adaptive.py).

On the CPU-only test backend the host lane is declined (the default
backend IS the cpu), so routing is exercised with injected lanes.
"""

import time

import numpy as np
import pytest

from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.parallel.adaptive import (
    AdaptiveQueryEngine,
    _bucket,
    _LaneCost,
)
from filodb_tpu.testing.data import counter_series, counter_stream

START = 1_600_000_000


def _service(engine="adaptive"):
    ms = TimeSeriesMemStore()
    for s in range(2):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=100))
    ingest_routed(ms, "timeseries",
                  counter_stream(counter_series(4), 300,
                                 start_ms=START * 1000), 2, 1)
    return QueryService(ms, "timeseries", 2, spread=1, engine=engine)


class TestAdaptiveOnCpu:
    def test_degenerates_to_device_lanes_on_cpu(self):
        """With a cpu default backend there is no separate host lane: the
        adaptive engine must route everything to the device-backend lanes
        (sharded mesh or the single-device form) and produce results
        identical to engine="mesh"."""
        svc = _service("adaptive")
        ref = _service("mesh")
        q = ("sum(rate(http_requests_total[5m]))", START + 900, 60,
             START + 1800)
        a = svc.query_range(*q).result.materialize()
        b = ref.query_range(*q).result.materialize()
        np.testing.assert_allclose(np.asarray(a.values),
                                   np.asarray(b.values), rtol=1e-12)
        eng = svc.mesh_engine
        assert isinstance(eng, AdaptiveQueryEngine)
        assert eng._host() is None
        assert eng.routed["device"] + eng.routed["single"] >= 1
        assert eng.routed["host"] == 0

    def test_execute_many_parity(self):
        svc = _service("adaptive")
        ref = _service("mesh")
        qs = [("sum(rate(http_requests_total[5m]))", START + 900, 60,
               START + 1800)] * 5
        ra = svc.query_range_many(qs)
        rb = ref.query_range_many(qs)
        for x, y in zip(ra, rb):
            np.testing.assert_allclose(np.asarray(x.result.values),
                                       np.asarray(y.result.values),
                                       rtol=1e-12)


class _FakeLane:
    """Counts calls; pretends each call takes ``cost`` seconds/query."""

    def __init__(self, cost):
        self.cost = cost
        self.calls = 0

    def execute(self, memstore, dataset, plan, stats=None):
        self.calls += 1
        from filodb_tpu.query.model import StepMatrix
        return StepMatrix.empty(np.array([0], np.int64))

    def execute_many(self, plans, memstore, dataset, stats_list=None):
        self.calls += 1
        from filodb_tpu.query.model import StepMatrix
        return [StepMatrix.empty(np.array([0], np.int64)) for _ in plans]

    def execute_lowered_many(self, lows, memstore, dataset, stats=None):
        from filodb_tpu.query.model import StepMatrix
        return [StepMatrix.empty(np.array([0], np.int64)) for _ in lows]

    def _lower(self, plan):
        return object()


class TestRouting:
    def _engine_with_lanes(self):
        eng = AdaptiveQueryEngine()
        eng.device_engine = _FakeLane(0.070)
        eng._host_engine = _FakeLane(0.001)
        eng._host_checked = True
        eng.sync_floor_s = 0.070
        return eng

    def test_cold_start_routes_host_and_costs_learned(self):
        eng = self._engine_with_lanes()
        # seed costs as a serving loop would
        eng._record("host", 1, 0.001)
        eng._record("device", 1, 0.070)
        assert eng._route(1) == "host"
        # large batches amortize the device sync: device wins there
        eng._record("host", 256, 0.256)     # 1ms/query
        eng._record("device", 256, 0.020)   # 0.08ms/query
        assert eng._route(256) == "device"

    def test_cold_start_prefers_host(self):
        eng = self._engine_with_lanes()
        assert eng._route(1) == "host"

    def test_warmup_sample_replaced_not_blended(self):
        c = _LaneCost()
        c.record(5.0)     # compile-skewed first sample
        c.record(0.001)   # first real sample replaces outright
        assert c.est == pytest.approx(0.001)
        c.record(0.002)   # later samples blend
        assert 0.001 < c.est < 0.002

    def test_shadow_probe_prices_other_lane(self):
        eng = self._engine_with_lanes()
        eng._record("host", 1, 0.001)
        svc = _service("mesh")  # donor memstore + a lowerable plan
        from filodb_tpu.promql.parser import TimeStepParams, parse_query
        plan = parse_query("sum(rate(http_requests_total[5m]))",
                           TimeStepParams(START + 900, 60, START + 1800))
        # device estimate missing -> shadow probe is due
        eng._maybe_shadow("host", [plan], svc.memstore, "timeseries")
        deadline = time.time() + 5
        while eng.shadowed["device"] == 0 and time.time() < deadline:
            time.sleep(0.01)
        assert eng.shadowed["device"] == 1
        assert eng._cost[("device", 1)].est is not None

    def test_buckets(self):
        assert _bucket(1) == 1
        assert _bucket(3) == 4
        assert _bucket(100) == 256
        assert _bucket(5000) == 1024
