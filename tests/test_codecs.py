"""Round-trip tests for the format layer.

Mirrors the reference's codec test strategy
(``memory/src/test/scala/filodb.memory/format/NibblePackTest.scala``,
``DeltaDeltaVectorTest``, ``DoubleVectorTest``, ``HistogramVectorTest``):
exhaustive round-trips over realistic and adversarial streams.
"""

import numpy as np
import pytest

from filodb_tpu.memory import nibble_pack, nibble_unpack
from filodb_tpu.memory.codecs import (
    decode_any,
    decode_delta_delta,
    decode_dict_string,
    decode_hist_2d_delta,
    decode_raw_double,
    decode_xor_double,
    encode_delta_delta,
    encode_dict_string,
    encode_hist_2d_delta,
    encode_raw_double,
    encode_xor_double,
)
from filodb_tpu.memory.nibblepack import zigzag_decode, zigzag_encode


class TestNibblePack:
    def test_zeros(self):
        v = np.zeros(20, dtype=np.uint64)
        packed = nibble_pack(v)
        assert len(packed) == 3  # 3 groups, bitmap byte each
        np.testing.assert_array_equal(nibble_unpack(packed, 20), v)

    def test_small_values(self):
        v = np.array([1, 2, 3, 4, 5, 6, 7, 8, 9, 10], dtype=np.uint64)
        np.testing.assert_array_equal(nibble_unpack(nibble_pack(v), 10), v)

    def test_mixed_zero_nonzero(self):
        v = np.array([0, 5, 0, 0, 1000, 0, 3, 0, 0, 0, 0, 7], dtype=np.uint64)
        np.testing.assert_array_equal(nibble_unpack(nibble_pack(v), len(v)), v)

    def test_large_values(self):
        v = np.array([2**63, 2**64 - 1, 0, 2**32, 12345678901234], dtype=np.uint64)
        np.testing.assert_array_equal(nibble_unpack(nibble_pack(v), len(v)), v)

    def test_trailing_zero_nibbles(self):
        # values with common trailing zero nibbles compress via tz field
        v = np.array([0x1000, 0x2000, 0x3000, 0xFF000], dtype=np.uint64)
        packed = nibble_pack(v)
        np.testing.assert_array_equal(nibble_unpack(packed, len(v)), v)

    def test_random_round_trip(self):
        rng = np.random.default_rng(42)
        for scale_bits in (4, 16, 32, 63):
            v = rng.integers(0, 2**scale_bits, size=1000, dtype=np.uint64)
            np.testing.assert_array_equal(nibble_unpack(nibble_pack(v), 1000), v)

    def test_not_multiple_of_8(self):
        for n in range(1, 20):
            v = np.arange(n, dtype=np.uint64) * 100
            np.testing.assert_array_equal(nibble_unpack(nibble_pack(v), n), v)

    def test_compression_ratio_small_deltas(self):
        # 10s-interval timestamps deltas after delta-delta ≈ 0 → ~1 byte/8 samples
        v = np.zeros(720, dtype=np.uint64)
        assert len(nibble_pack(v)) == 90


class TestZigzag:
    def test_round_trip(self):
        v = np.array([0, -1, 1, -2, 2, 2**62, -(2**62), np.iinfo(np.int64).min],
                     dtype=np.int64)
        np.testing.assert_array_equal(zigzag_decode(zigzag_encode(v)), v)

    def test_small_magnitude(self):
        assert zigzag_encode(np.array([-1], dtype=np.int64))[0] == 1
        assert zigzag_encode(np.array([1], dtype=np.int64))[0] == 2


class TestDeltaDelta:
    def test_regular_timestamps_const(self):
        # perfectly regular timestamps hit the const-slope fast path
        ts = np.arange(0, 720 * 10_000, 10_000, dtype=np.int64) + 1_600_000_000_000
        enc = encode_delta_delta(ts)
        assert len(enc) == 21  # header only: codec+count+base+slope
        np.testing.assert_array_equal(decode_delta_delta(enc), ts)

    def test_jittered_timestamps(self):
        rng = np.random.default_rng(7)
        ts = (np.arange(1000, dtype=np.int64) * 10_000
              + 1_600_000_000_000
              + rng.integers(-50, 50, 1000))
        enc = encode_delta_delta(ts)
        np.testing.assert_array_equal(decode_delta_delta(enc), ts)
        assert len(enc) < 8 * len(ts) / 4  # ≥4x vs raw

    def test_single_value(self):
        ts = np.array([1234567], dtype=np.int64)
        np.testing.assert_array_equal(decode_delta_delta(encode_delta_delta(ts)), ts)

    def test_empty(self):
        ts = np.array([], dtype=np.int64)
        assert len(decode_delta_delta(encode_delta_delta(ts))) == 0

    def test_counter_values(self):
        v = np.cumsum(np.random.default_rng(0).integers(0, 100, 500)).astype(np.int64)
        np.testing.assert_array_equal(decode_delta_delta(encode_delta_delta(v)), v)

    def test_negative_values(self):
        v = np.array([-5, -3, 0, 7, -100], dtype=np.int64)
        np.testing.assert_array_equal(decode_delta_delta(encode_delta_delta(v)), v)


class TestXorDouble:
    def test_round_trip(self):
        v = np.array([1.5, 1.5, 2.5, 3.75, -1.25, 0.0, 1e300, -1e-300], dtype=np.float64)
        np.testing.assert_array_equal(decode_xor_double(encode_xor_double(v)), v)

    def test_nan_preserved(self):
        v = np.array([1.0, np.nan, 3.0], dtype=np.float64)
        out = decode_xor_double(encode_xor_double(v))
        assert out[0] == 1.0 and np.isnan(out[1]) and out[2] == 3.0

    def test_slowly_varying_compresses(self):
        v = 100.0 + np.sin(np.arange(720) / 50.0)
        enc = encode_xor_double(v)
        out = decode_xor_double(enc)
        np.testing.assert_array_equal(out, v)

    def test_identical_values_compress_well(self):
        v = np.full(720, 42.5)
        enc = encode_xor_double(v)
        assert len(enc) < 200  # one real value + ~1 bitmap byte per 8
        np.testing.assert_array_equal(decode_xor_double(enc), v)

    def test_random(self):
        v = np.random.default_rng(3).normal(size=1000)
        np.testing.assert_array_equal(decode_xor_double(encode_xor_double(v)), v)


class TestHist2DDelta:
    def test_round_trip_increasing(self):
        # cumulative bucket counts increasing in both axes (typical prom histogram)
        rng = np.random.default_rng(5)
        incr = rng.integers(0, 10, size=(50, 8))
        rows = np.cumsum(np.cumsum(incr, axis=0), axis=1).astype(np.int64)
        les = np.arange(8, dtype=np.float64)
        enc = encode_hist_2d_delta(rows, les)
        out = decode_hist_2d_delta(enc)
        np.testing.assert_array_equal(out.rows, rows)
        np.testing.assert_array_equal(out.les, les)
        assert len(enc) < rows.nbytes / 4

    def test_counter_reset(self):
        rows = np.array([[5, 10, 15], [7, 12, 20], [1, 2, 3]], dtype=np.int64)
        np.testing.assert_array_equal(
            decode_hist_2d_delta(encode_hist_2d_delta(rows)).rows, rows)

    def test_empty(self):
        rows = np.zeros((0, 0), dtype=np.int64)
        assert decode_hist_2d_delta(encode_hist_2d_delta(rows)).rows.size == 0


class TestDictString:
    def test_round_trip(self):
        vals = ["a", "b", "a", "c", "a", "b", ""]
        assert decode_dict_string(encode_dict_string(vals)) == vals

    def test_empty(self):
        assert decode_dict_string(encode_dict_string([])) == []

    def test_unicode(self):
        vals = ["héllo", "wörld", "héllo"]
        assert decode_dict_string(encode_dict_string(vals)) == vals

    def test_nul_bytes_in_values(self):
        # entries are length-prefixed, so embedded NULs must round-trip
        vals = ["a\x00b", "", "\x00", "a\x00b", "plain"]
        assert decode_dict_string(encode_dict_string(vals)) == vals

    def test_legacy_nul_separated_format_still_decodes(self):
        # chunks persisted before the length-prefix change carry codec id 5
        # with a NUL-joined dictionary; they must keep decoding
        import struct
        from filodb_tpu.memory.codecs import CODEC_DICT_STRING, nibble_pack
        vals = ["a", "b", "a"]
        blob = b"\x00".join(s.encode() for s in ("a", "b"))
        codes = nibble_pack(np.array([0, 1, 0], dtype=np.uint64))
        legacy = struct.pack("<BIII", CODEC_DICT_STRING, 3, 2, len(blob)) \
            + blob + codes
        assert decode_dict_string(legacy) == vals
        from filodb_tpu.memory.codecs import decode_any
        assert decode_any(legacy) == vals


class TestDispatch:
    def test_decode_any(self):
        ts = np.arange(10, dtype=np.int64) * 1000
        np.testing.assert_array_equal(decode_any(encode_delta_delta(ts)), ts)
        v = np.array([1.0, 2.0], dtype=np.float64)
        np.testing.assert_array_equal(decode_any(encode_xor_double(v)), v)
        np.testing.assert_array_equal(
            decode_any(encode_raw_double(v)), decode_raw_double(encode_raw_double(v)))

    def test_unknown_codec(self):
        with pytest.raises(ValueError):
            decode_any(b"\xff\x00\x00\x00")
