"""DNS SRV discovery against a stub UDP resolver.

VERDICT r2 #10: the third seed-discovery strategy must be real, testable
code — a stdlib wire-format resolver (``utils/dns_srv.py``), exercised here
against a canned-response DNS server including name compression.
Reference: ``akka-bootstrapper/.../DnsSrvClusterSeedDiscovery.scala:1-122``.
"""

import socket
import struct
import threading

import pytest

from filodb_tpu.coordinator.bootstrap import DnsSrvDiscovery
from filodb_tpu.utils.dns_srv import (
    DnsError,
    build_query,
    encode_qname,
    parse_srv_response,
    read_name,
    resolve_srv,
)


def _srv_rdata(prio, weight, port, target: bytes) -> bytes:
    return struct.pack(">HHH", prio, weight, port) + target


def _answer(name_bytes: bytes, rdata: bytes) -> bytes:
    return name_bytes + struct.pack(">HHIH", 33, 1, 60, len(rdata)) + rdata


def _response(query: bytes, answers: list[bytes], rcode=0) -> bytes:
    txid = struct.unpack(">H", query[:2])[0]
    q_section = query[12:]
    header = struct.pack(">HHHHHH", txid, 0x8180 | rcode, 1, len(answers),
                         0, 0)
    return header + q_section + b"".join(answers)


class StubResolver:
    """One-shot UDP DNS server answering every query with canned SRV
    records (compression pointer to the question name exercised)."""

    def __init__(self, records):
        self.records = records  # list of (prio, weight, port, target_str)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", 0))
        self.port = self.sock.getsockname()[1]
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        try:
            while True:
                query, addr = self.sock.recvfrom(4096)
                # name pointer to offset 12 (the question name)
                ptr = struct.pack(">H", 0xC000 | 12)
                answers = [
                    _answer(ptr, _srv_rdata(p, w, port,
                                            encode_qname(target)))
                    for (p, w, port, target) in self.records
                ]
                self.sock.sendto(_response(query, answers), addr)
        except OSError:
            pass  # socket closed

    def close(self):
        self.sock.close()


class TestWireFormat:
    def test_qname_roundtrip(self):
        raw = encode_qname("_filodb._tcp.example.com")
        name, off = read_name(raw, 0)
        assert name == "_filodb._tcp.example.com"
        assert off == len(raw)

    def test_compression_pointer(self):
        # message: [2 pad bytes][example.com][label "a" + ptr->2]
        base = b"xx" + encode_qname("example.com")
        ptr_name = b"\x01a" + struct.pack(">H", 0xC000 | 2)
        msg = base + ptr_name
        name, off = read_name(msg, len(base))
        assert name == "a.example.com"
        assert off == len(msg)

    def test_compression_loop_rejected(self):
        # pointer at offset 2 pointing to offset 0, which points to 2 …
        msg = struct.pack(">H", 0xC000 | 2) + struct.pack(">H", 0xC000 | 0)
        with pytest.raises(DnsError):
            read_name(msg, 2)

    def test_txid_mismatch_rejected(self):
        q = build_query("x.example.com", 7)
        resp = _response(q, [])
        with pytest.raises(DnsError):
            parse_srv_response(resp, 8)


class TestStubResolution:
    def test_resolves_and_orders_by_priority_weight(self):
        stub = StubResolver([
            (10, 5, 9001, "node-b.example.com"),
            (5, 1, 9000, "node-a.example.com"),
            (5, 9, 9002, "node-c.example.com"),
        ])
        try:
            recs = resolve_srv("_filodb._tcp.example.com",
                               server="127.0.0.1", port=stub.port)
            assert [(r.target, r.port) for r in recs] == [
                ("node-c.example.com", 9002),   # prio 5, weight 9 first
                ("node-a.example.com", 9000),
                ("node-b.example.com", 9001),
            ]
        finally:
            stub.close()

    def test_discovery_strategy(self):
        stub = StubResolver([(1, 1, 7070, "seed.example.com")])
        try:
            d = DnsSrvDiscovery("_filodb._tcp.example.com",
                                server="127.0.0.1", port=stub.port)
            assert d.discover() == [("seed.example.com", 7070)]
        finally:
            stub.close()

    def test_unreachable_resolver_yields_no_seeds(self):
        # closed port: discovery must swallow the timeout and return []
        d = DnsSrvDiscovery("_filodb._tcp.example.com",
                            server="127.0.0.1", port=1)
        import filodb_tpu.utils.dns_srv as mod
        orig = mod.resolve_srv

        def fast_timeout(name, server=None, port=None, timeout=2.0):
            return orig(name, server=server, port=port, timeout=0.2)

        mod.resolve_srv = fast_timeout
        try:
            assert d.discover() == []
        finally:
            mod.resolve_srv = orig

    def test_nxdomain_is_empty(self):
        class NxStub(StubResolver):
            def _serve(self):
                try:
                    while True:
                        query, addr = self.sock.recvfrom(4096)
                        self.sock.sendto(_response(query, [], rcode=3), addr)
                except OSError:
                    pass

        stub = NxStub([])
        try:
            assert resolve_srv("_nope._tcp.example.com",
                               server="127.0.0.1", port=stub.port) == []
        finally:
            stub.close()
