"""End-to-end query tests: ingest → PromQL → plan → TPU-kernel execution.

Mirrors the reference's query-engine specs that build ExecPlans against an
in-memory MemStore and compare samples
(``query/src/test/scala/filodb/query/exec/*Spec.scala``).
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.query.model import QueryLimitExceeded
from filodb_tpu.testing.data import (
    counter_series,
    counter_stream,
    gauge_stream,
    histogram_series,
    histogram_stream,
    machine_metrics_series,
)

NUM_SHARDS = 4
START = 1_600_000_000  # epoch sec
INTERVAL = 10_000


def build_store(streams, num_shards=NUM_SHARDS):
    ms = TimeSeriesMemStore()
    for s in range(num_shards):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=100,
                                              groups_per_shard=4))
    for stream in streams:
        ingest_routed(ms, "timeseries", stream, num_shards, spread=1)
    return ms


@pytest.fixture(scope="module")
def gauge_svc():
    keys = machine_metrics_series(10, ns="App-2")
    stream = gauge_stream(keys, 720, start_ms=START * 1000,
                          interval_ms=INTERVAL, seed=11)
    ms = build_store([stream])
    return QueryService(ms, "timeseries", NUM_SHARDS, spread=1), keys


@pytest.fixture(scope="module")
def counter_svc():
    keys = counter_series(6, ns="App-1")
    stream = counter_stream(keys, 720, start_ms=START * 1000,
                            interval_ms=INTERVAL, seed=3, reset_every=250)
    ms = build_store([stream])
    return QueryService(ms, "timeseries", NUM_SHARDS, spread=1), keys


def expected_series(keys, stream_fn, **kw):
    """Re-generate the synthetic stream host-side for ground truth."""
    data = {k: ([], []) for k in keys}
    for sd in stream_fn(keys, **kw):
        for rec in sd.container:
            data[rec.part_key][0].append(rec.timestamp)
            data[rec.part_key][1].append(rec.values[0])
    return {k: (np.array(t), np.array(v)) for k, (t, v) in data.items()}


class TestRawAndOverTime:
    def test_raw_selector_range_query(self, gauge_svc):
        svc, keys = gauge_svc
        r = svc.query_range('heap_usage{_ws_="demo",_ns_="App-2"}',
                            START + 3600, 60, START + 7200)
        m = r.result
        assert m.num_series == 10
        assert m.num_steps == 61
        # each step carries the latest sample within 5m staleness
        truth = expected_series(keys, gauge_stream, n_samples=720,
                                start_ms=START * 1000, interval_ms=INTERVAL,
                                seed=11)
        for i, k in enumerate(m.keys):
            t, v = truth[_match_key(truth, k)]
            for ks, step_ms in enumerate(m.steps_ms):
                sel = t[(t <= step_ms) & (t > step_ms - 300_000)]
                expect = v[t == sel[-1]][0] if len(sel) else np.nan
                np.testing.assert_allclose(m.values[i, ks], expect,
                                           rtol=1e-9, err_msg=str(k))

    def test_sum_over_time(self, gauge_svc):
        svc, keys = gauge_svc
        r = svc.query_range(
            'sum_over_time(heap_usage{_ns_="App-2"}[5m])',
            START + 3600, 300, START + 5400)
        truth = expected_series(keys, gauge_stream, n_samples=720,
                                start_ms=START * 1000, interval_ms=INTERVAL,
                                seed=11)
        m = r.result
        assert m.num_series == 10
        for i, k in enumerate(m.keys):
            t, v = truth[_match_key(truth, k)]
            for ks, step_ms in enumerate(m.steps_ms):
                mask = (t <= step_ms) & (t > step_ms - 300_000)
                expect = v[mask].sum() if mask.any() else np.nan
                np.testing.assert_allclose(m.values[i, ks], expect, rtol=1e-9)

    def test_avg_max_agree(self, gauge_svc):
        svc, _ = gauge_svc
        avg = svc.query_range('avg_over_time(heap_usage[5m])',
                              START + 3600, 300, START + 4500).result
        mx = svc.query_range('max_over_time(heap_usage[5m])',
                             START + 3600, 300, START + 4500).result
        assert (np.nan_to_num(mx.values) >= np.nan_to_num(avg.values)).all()


class TestAggregations:
    def test_sum_rate_benchmark_query(self, counter_svc):
        svc, keys = counter_svc
        r = svc.query_range(
            'sum(rate(http_requests_total{_ws_="demo",_ns_="App-1"}[5m]))',
            START + 3600, 60, START + 5400)
        m = r.result
        assert m.num_series == 1
        assert m.keys[0].labels == ()
        # cross-check: sum of individual rates
        r2 = svc.query_range(
            'rate(http_requests_total{_ws_="demo",_ns_="App-1"}[5m])',
            START + 3600, 60, START + 5400)
        np.testing.assert_allclose(m.values[0],
                                   np.nansum(r2.result.values, axis=0),
                                   rtol=1e-9)
        assert r2.result.num_series == 6

    def test_sum_by(self, counter_svc):
        svc, _ = counter_svc
        r = svc.query_range('sum by (job) (rate(http_requests_total[5m]))',
                            START + 3600, 300, START + 4500)
        m = r.result
        jobs = {k.label_map.get("job") for k in m.keys}
        assert jobs == {"job-0", "job-1", "job-2"}

    def test_topk(self, gauge_svc):
        svc, _ = gauge_svc
        r = svc.query_range('topk(3, heap_usage)', START + 3600, 300,
                            START + 3900)
        m = r.result
        # at each step at most 3 series have values
        present = (~np.isnan(m.values)).sum(axis=0)
        assert (present <= 3).all() and present.max() == 3

    def test_count_and_group(self, gauge_svc):
        svc, _ = gauge_svc
        r = svc.query_range('count(heap_usage)', START + 3600, 300,
                            START + 3900)
        assert (r.result.values == 10).all()

    def test_quantile_agg(self, gauge_svc):
        svc, _ = gauge_svc
        r = svc.query_range('quantile(0.5, heap_usage)', START + 3600, 300,
                            START + 3900).result
        r_all = svc.query_range('heap_usage', START + 3600, 300,
                                START + 3900).result
        expect = np.quantile(r_all.values, 0.5, axis=0)
        np.testing.assert_allclose(r.values[0], expect, rtol=1e-9)


class TestBinaryOps:
    def test_scalar_multiply(self, gauge_svc):
        svc, _ = gauge_svc
        r1 = svc.query_range('heap_usage', START + 3600, 300, START + 3900)
        r2 = svc.query_range('heap_usage * 2', START + 3600, 300, START + 3900)
        np.testing.assert_allclose(r2.result.values, r1.result.values * 2,
                                   rtol=1e-9)

    def test_comparison_filter(self, gauge_svc):
        svc, _ = gauge_svc
        r1 = svc.query_range('heap_usage', START + 3600, 300, START + 3900)
        thresh = float(np.nanmedian(r1.result.values))
        r2 = svc.query_range(f'heap_usage > {thresh}', START + 3600, 300,
                             START + 3900)
        vals = r2.result.values
        assert np.all(np.isnan(vals) | (vals > thresh))

    def test_vector_vector_join(self, gauge_svc):
        svc, _ = gauge_svc
        r = svc.query_range('heap_usage / heap_usage', START + 3600, 300,
                            START + 3900)
        vals = r.result.values
        assert r.result.num_series == 10
        np.testing.assert_allclose(vals[~np.isnan(vals)], 1.0)

    def test_and_or(self, gauge_svc):
        svc, _ = gauge_svc
        r = svc.query_range('heap_usage and heap_usage', START + 3600, 300,
                            START + 3900)
        assert r.result.num_series == 10
        r = svc.query_range('heap_usage unless heap_usage', START + 3600,
                            300, START + 3900)
        assert r.result.num_series == 0


class TestHistograms:
    @pytest.fixture(scope="class")
    def hist_svc(self):
        keys = histogram_series(4)
        stream = histogram_stream(keys, 400, start_ms=START * 1000,
                                  interval_ms=INTERVAL, seed=7)
        ms = build_store([stream])
        return QueryService(ms, "timeseries", NUM_SHARDS, spread=1)

    def test_first_class_histogram_quantile(self, hist_svc):
        r = hist_svc.query_range(
            'histogram_quantile(0.9, rate(http_req_latency[5m]))',
            START + 1800, 300, START + 3600)
        m = r.result
        assert m.num_series == 4
        vals = m.values[~np.isnan(m.values)]
        assert len(vals) and (vals > 0).all() and (vals <= 10.0).all()

    def test_hist_sum_then_quantile(self, hist_svc):
        r = hist_svc.query_range(
            'histogram_quantile(0.5, sum(rate(http_req_latency[5m])))',
            START + 1800, 300, START + 3600)
        assert r.result.num_series == 1


class TestInstantAndMisc:
    def test_abs_ceil(self, gauge_svc):
        svc, _ = gauge_svc
        r1 = svc.query_range('heap_usage', START + 3600, 300, START + 3900)
        r2 = svc.query_range('ceil(heap_usage)', START + 3600, 300,
                             START + 3900)
        np.testing.assert_allclose(r2.result.values,
                                   np.ceil(r1.result.values), rtol=1e-12)

    def test_label_replace(self, gauge_svc):
        svc, _ = gauge_svc
        r = svc.query_range(
            'label_replace(heap_usage, "inst_num", "$1", "instance", '
            '"instance-([0-9]+)")', START + 3600, 300, START + 3900)
        nums = {k.label_map.get("inst_num") for k in r.result.keys}
        assert nums == {str(i) for i in range(10)}

    def test_absent_of_missing_metric(self, gauge_svc):
        svc, _ = gauge_svc
        r = svc.query_range('absent(nonexistent_metric)', START + 3600, 300,
                            START + 3900)
        assert r.result.num_series == 1
        assert (r.result.values == 1.0).all()

    def test_absent_of_present_metric(self, gauge_svc):
        svc, _ = gauge_svc
        r = svc.query_range('absent(heap_usage)', START + 3600, 300,
                            START + 3900)
        assert r.result.num_series == 0

    def test_scalar_fn(self, gauge_svc):
        svc, _ = gauge_svc
        r = svc.query_range('scalar(sum(heap_usage))', START + 3600, 300,
                            START + 3900)
        assert r.result.num_series == 1

    def test_time_fn(self, gauge_svc):
        svc, _ = gauge_svc
        r = svc.query_range('time()', START + 3600, 300, START + 3900)
        np.testing.assert_allclose(r.result.values[0],
                                   r.result.steps_ms / 1000.0)

    def test_vector_of_scalar(self, gauge_svc):
        svc, _ = gauge_svc
        r = svc.query_range('vector(42)', START + 3600, 300, START + 3900)
        assert (r.result.values == 42).all()

    def test_subquery(self, counter_svc):
        svc, _ = counter_svc
        r = svc.query_range(
            'max_over_time(rate(http_requests_total[1m])[10m:1m])',
            START + 3600, 300, START + 4500)
        assert r.result.num_series == 6
        # max over subquery >= direct rate at aligned steps
        assert np.nanmax(r.result.values) > 0

    def test_subquery_semantics_vs_direct(self, gauge_svc):
        # avg_over_time(g[10m:INTERVAL]) samples every raw point, so it must
        # closely track avg_over_time(g[10m]) at the same steps
        svc, _ = gauge_svc
        sub = svc.query_range('avg_over_time(heap_usage[10m:10s])',
                              START + 3600, 300, START + 4500)
        direct = svc.query_range('avg_over_time(heap_usage[10m])',
                                 START + 3600, 300, START + 4500)
        assert sub.result.num_series == direct.result.num_series == 10
        os_ = np.argsort([str(k) for k in sub.result.keys])
        od = np.argsort([str(k) for k in direct.result.keys])
        np.testing.assert_allclose(sub.result.values[os_],
                                   direct.result.values[od], rtol=5e-2)

    def test_nested_subquery(self, gauge_svc):
        # the subquery evaluates the inner expression on its own aligned
        # grid; the outer max at T covers grid points in (T-20m, T]
        svc, _ = gauge_svc
        r = svc.query_range(
            'max_over_time(max_over_time(heap_usage[5m])[20m:5m])',
            START + 3600, 300, START + 4500)
        assert r.result.num_series == 10
        sub_step = 300
        g_start = ((START + 3600 - 1200) // sub_step) * sub_step
        g_end = ((START + 4500) // sub_step) * sub_step
        grid = svc.query_range('max_over_time(heap_usage[5m])',
                               g_start, sub_step, g_end)
        og = np.argsort([str(k) for k in grid.result.keys])
        orr = np.argsort([str(k) for k in r.result.keys])
        gv = grid.result.values[og]
        gt = grid.result.steps_ms
        for ks, t_ms in enumerate(r.result.steps_ms):
            sel = (gt > t_ms - 1_200_000) & (gt <= t_ms)
            expect = np.max(gv[:, sel], axis=1)
            np.testing.assert_allclose(r.result.values[orr][:, ks], expect,
                                       rtol=1e-9)

    def test_subquery_with_offset_inside(self, counter_svc):
        # offset applies to the inner selector; the subquery result at T
        # equals the un-offset subquery at T-5m
        svc, _ = counter_svc
        off = svc.query_range(
            'max_over_time(rate(http_requests_total[1m] offset 5m)[10m:1m])',
            START + 3900, 300, START + 4500)
        plain = svc.query_range(
            'max_over_time(rate(http_requests_total[1m])[10m:1m])',
            START + 3600, 300, START + 4200)
        assert off.result.num_series == plain.result.num_series == 6
        oo = np.argsort([str(k) for k in off.result.keys])
        op = np.argsort([str(k) for k in plain.result.keys])
        np.testing.assert_allclose(off.result.values[oo],
                                   plain.result.values[op],
                                   rtol=1e-5, equal_nan=True)

    def test_subquery_offset_outside(self, gauge_svc):
        svc, _ = gauge_svc
        r = svc.query_range('avg_over_time(heap_usage[10m:1m] offset 10m)',
                            START + 3600, 300, START + 4200)
        plain = svc.query_range('avg_over_time(heap_usage[10m:1m])',
                                START + 3000, 300, START + 3600)
        assert r.result.num_series == 10
        orr = np.argsort([str(k) for k in r.result.keys])
        op = np.argsort([str(k) for k in plain.result.keys])
        np.testing.assert_allclose(r.result.values[orr],
                                   plain.result.values[op],
                                   rtol=1e-6, equal_nan=True)


class TestLimitsAndMetadata:
    def test_sample_limit(self, gauge_svc):
        svc, _ = gauge_svc
        from filodb_tpu.query.model import PlannerParams, QueryContext
        qc = QueryContext(planner_params=PlannerParams(sample_limit=5))
        with pytest.raises(QueryLimitExceeded):
            svc.query_range('heap_usage', START + 3600, 60, START + 7200,
                            qcontext=qc)

    def test_series_api(self, gauge_svc):
        svc, _ = gauge_svc
        from filodb_tpu.core.filters import ColumnFilter, Equals
        out = svc.series([ColumnFilter("_metric_", Equals("heap_usage"))],
                         START, START + 7200)
        assert len(out) == 10

    def test_label_values_api(self, gauge_svc):
        svc, _ = gauge_svc
        vals = svc.memstore.label_values("timeseries", "host")
        assert vals == ["H0", "H1", "H2", "H3"]


def _match_key(truth, key):
    # result keys may have dropped _metric_; match on the remaining labels
    lm = key.label_map
    for k in truth:
        tm = k.label_map
        if all(tm.get(lk) == lv for lk, lv in lm.items()):
            return k
    raise KeyError(key)


class TestSpreadOverrides:
    def test_per_key_spread_override(self, counter_svc):
        svc, keys = counter_svc
        # override spread for (demo, App-1): fan out to all 4 shards
        svc.planner.spread_overrides = {("demo", "App-1"): 2}
        shards = svc.planner.shards_for_filters(
            [__import__("filodb_tpu.core.filters", fromlist=["ColumnFilter"])
             .ColumnFilter(lbl, __import__(
                 "filodb_tpu.core.filters", fromlist=["Equals"]).Equals(v))
             for lbl, v in (("_ws_", "demo"), ("_ns_", "App-1"),
                            ("_metric_", "http_requests_total"))])
        assert len(shards) == 4
        # queries still correct at the wider spread
        r = svc.query_range(
            'sum(rate(http_requests_total{_ws_="demo",_ns_="App-1"}[5m]))',
            START + 3600, 300, START + 4500)
        assert r.result.num_series == 1
        svc.planner.spread_overrides = None

    def test_per_query_spread_beats_config(self, counter_svc):
        svc, _ = counter_svc
        from filodb_tpu.query.model import PlannerParams, QueryContext
        svc.planner.spread_overrides = {("demo", "App-1"): 0}
        qc = QueryContext(planner_params=PlannerParams(spread=2))
        r = svc.query_range(
            'rate(http_requests_total{_ws_="demo",_ns_="App-1"}[5m])',
            START + 3600, 300, START + 4500, qcontext=qc)
        assert r.result.num_series == 6
        svc.planner.spread_overrides = None


class TestAtModifier:
    def test_at_pins_evaluation_time(self, gauge_svc):
        svc, _ = gauge_svc
        at = START + 3600
        r = svc.query_range(f'heap_usage @ {at}', START + 3600, 300,
                            START + 5400).result
        # every step carries the value at the pinned instant
        for k in range(r.num_steps):
            np.testing.assert_allclose(r.values[:, k], r.values[:, 0],
                                       rtol=0, equal_nan=True)
        direct = svc.query_range('heap_usage', at, 60, at).result
        np.testing.assert_allclose(np.sort(r.values[:, 0]),
                                   np.sort(direct.values[:, 0]), rtol=1e-9)

    def test_at_start_end(self, gauge_svc):
        svc, _ = gauge_svc
        r1 = svc.query_range('heap_usage @ start()', START + 3600, 300,
                             START + 4500).result
        r2 = svc.query_range('heap_usage', START + 3600, 60,
                             START + 3600).result
        np.testing.assert_allclose(np.sort(r1.values[:, 0]),
                                   np.sort(r2.values[:, 0]), rtol=1e-9)

    def test_at_with_range_function(self, counter_svc):
        svc, _ = counter_svc
        at = START + 4000
        r = svc.query_range(
            f'sum(rate(http_requests_total[5m] @ {at}))',
            START + 3600, 300, START + 5400).result
        for k in range(r.num_steps):
            np.testing.assert_allclose(r.values[0, k], r.values[0, 0],
                                       rtol=0)


class TestVectorMatching:
    @pytest.fixture(scope="class")
    def join_svc(self):
        """requests (per instance+job) and limits (one per instance)."""
        from filodb_tpu.core.partkey import PartKey
        from filodb_tpu.core.record import (
            IngestRecord,
            RecordContainer,
            SomeData,
        )
        ms = TimeSeriesMemStore()
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=100))
        c = RecordContainer()
        for i in range(60):
            ts = (START + i * 10) * 1000
            for inst in range(3):
                for job in ("api", "web"):
                    k = PartKey.create("gauge", {
                        "_metric_": "used", "_ws_": "w", "_ns_": "n",
                        "instance": f"i{inst}", "job": job})
                    c.add(IngestRecord(k, ts, (float(10 * inst + 1),)))
                k = PartKey.create("gauge", {
                    "_metric_": "cap", "_ws_": "w", "_ns_": "n",
                    "instance": f"i{inst}", "zone": f"z{inst % 2}"})
                c.add(IngestRecord(k, ts, (100.0 * (inst + 1),)))
        ms.ingest("timeseries", 0, SomeData(c, 0))
        return QueryService(ms, "timeseries", 1, spread=0)

    def test_group_left_many_to_one(self, join_svc):
        r = join_svc.query_range(
            'used / on (instance) group_left cap',
            START + 400, 60, START + 580).result
        # 6 "used" series (3 inst x 2 jobs) each matched to its instance cap
        assert r.num_series == 6
        for i, k in enumerate(r.keys):
            inst = int(k.label_map["instance"][1])
            expect = (10 * inst + 1) / (100.0 * (inst + 1))
            np.testing.assert_allclose(r.values[i], expect, rtol=1e-9)

    def test_group_left_include_labels(self, join_svc):
        r = join_svc.query_range(
            'used * on (instance) group_left (zone) cap',
            START + 400, 60, START + 400).result
        # zone copied from the "one" side onto results
        for k in r.keys:
            inst = int(k.label_map["instance"][1])
            assert k.label_map["zone"] == f"z{inst % 2}"

    def test_one_to_one_requires_unique(self, join_svc):
        from filodb_tpu.query.model import QueryError
        with pytest.raises(Exception, match="group_left|multiple matches"):
            join_svc.query_range('used / on (instance) cap',
                                 START + 400, 60, START + 400)

    def test_ignoring(self, join_svc):
        r = join_svc.query_range(
            'used / ignoring (job, zone) group_left cap',
            START + 400, 60, START + 400).result
        assert r.num_series == 6

    def test_group_right(self, join_svc):
        r = join_svc.query_range(
            'cap / on (instance) group_right used',
            START + 400, 60, START + 400).result
        assert r.num_series == 6
        for i, k in enumerate(r.keys):
            inst = int(k.label_map["instance"][1])
            expect = (100.0 * (inst + 1)) / (10 * inst + 1)
            np.testing.assert_allclose(r.values[i, 0], expect, rtol=1e-9)


class TestZeroArgTimeFns:
    def test_hour_of_query_time(self, gauge_svc):
        import datetime as dt
        svc, _ = gauge_svc
        r = svc.query_range('hour()', START + 3600, 300, START + 4200).result
        assert r.num_series == 1
        for k, step_ms in enumerate(r.steps_ms):
            expect = dt.datetime.fromtimestamp(
                step_ms / 1000, dt.timezone.utc).hour
            assert r.values[0, k] == expect


class TestPromFlatBuckets:
    """bucket-per-series histograms (metric_bucket{le=...}) — the layout the
    reference compares first-class histograms against."""

    @pytest.fixture(scope="class")
    def flat_svc(self):
        from filodb_tpu.core.partkey import PartKey
        from filodb_tpu.core.record import (
            IngestRecord,
            RecordContainer,
            SomeData,
        )
        ms = TimeSeriesMemStore()
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=100))
        les = [0.1, 0.5, 1.0, float("inf")]
        rng = np.random.default_rng(6)
        c = RecordContainer()
        for app in ("a", "b"):
            cum = np.zeros(len(les))
            for i in range(240):
                cum += np.cumsum(rng.integers(0, 4, len(les)))
                for le, v in zip(les, cum):
                    le_str = "+Inf" if le == float("inf") else str(le)
                    k = PartKey.create("prom-counter", {
                        "_metric_": "lat_bucket", "_ws_": "w", "_ns_": "n",
                        "app": app, "le": le_str})
                    c.add(IngestRecord(k, (START + i * 10) * 1000,
                                       (float(v),)))
        ms.ingest("timeseries", 0, SomeData(c, 0))
        return QueryService(ms, "timeseries", 1, spread=0)

    def test_flat_histogram_quantile(self, flat_svc):
        r = flat_svc.query_range(
            'histogram_quantile(0.9, sum(rate(lat_bucket[5m])) by (le, app))',
            START + 600, 120, START + 2300).result
        assert r.num_series == 2  # one per app
        vals = r.values[np.isfinite(r.values)]
        assert len(vals) and (vals > 0).all() and (vals <= 1.0).all()

    def test_flat_quantile_ordering(self, flat_svc):
        lo = flat_svc.query_range(
            'histogram_quantile(0.5, sum(rate(lat_bucket[5m])) by (le))',
            START + 600, 300, START + 2300).result
        hi = flat_svc.query_range(
            'histogram_quantile(0.99, sum(rate(lat_bucket[5m])) by (le))',
            START + 600, 300, START + 2300).result
        m = np.isfinite(lo.values) & np.isfinite(hi.values)
        assert (hi.values[m] >= lo.values[m]).all()


class TestAbsentOverTime:
    def test_absent_over_time_semantics(self, gauge_svc):
        svc, _ = gauge_svc
        # present metric → empty result
        r = svc.query_range('absent_over_time(heap_usage[5m])',
                            START + 3600, 300, START + 3900).result
        assert r.num_series == 0
        # missing metric → single all-ones series
        r = svc.query_range('absent_over_time(no_such_metric[5m])',
                            START + 3600, 300, START + 3900).result
        assert r.num_series == 1
        assert (r.values == 1.0).all()
        # present data but window entirely before it → absent
        r = svc.query_range('absent_over_time(heap_usage[5m])',
                            START - 7200, 300, START - 6900).result
        assert r.num_series == 1


class TestScalarOfEmpty:
    def test_scalar_of_missing_metric(self, gauge_svc):
        svc, _ = gauge_svc
        # scalar() over a selector matching nothing: vector arithmetic
        # proceeds with NaN per step (promql semantics)
        r = svc.query_range('heap_usage * scalar(no_such_metric)',
                            START + 3600, 300, START + 3900).result
        assert r.compact().num_series == 0  # all NaN
        r2 = svc.query_range('scalar(no_such_metric)',
                             START + 3600, 300, START + 3900).result
        assert r2.num_series == 1
        assert np.isnan(r2.values).all()
