"""Repair jobs + debug plane tests (reference spark-jobs repair specs,
TracingTimeSeriesPartition, chunk-info debug queries, corruption tripwires).
"""

import logging

import numpy as np
import pytest

from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.filters import ColumnFilter, Equals
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.api import InMemoryColumnStore, InMemoryMetaStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.core.store.repair import (
    CardinalityBuster,
    ChunkCopier,
    DSIndexJob,
    PartitionKeysCopier,
)
from filodb_tpu.memory.chunk import Chunk, CorruptVectorError
from filodb_tpu.testing.data import gauge_stream, machine_metrics_series

START = 1_600_000_000


def _populated_store(n_series=6):
    cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
    ms = TimeSeriesMemStore(cs, meta)
    for s in range(2):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=100))
    keys = machine_metrics_series(n_series)
    ingest_routed(ms, "timeseries",
                  gauge_stream(keys, 200, start_ms=START * 1000), 2, 1)
    ms.flush_all("timeseries")
    return ms, cs


class TestRepairJobs:
    def test_chunk_copier(self):
        ms, src = _populated_store()
        dst = InMemoryColumnStore()
        stats = ChunkCopier(src, dst, "timeseries", 2).run(0, 2**62)
        assert stats["partitions"] == 6 and stats["chunks"] >= 6
        # copied chunks readable from the target
        key = machine_metrics_series(6)[0]
        assert dst.read_chunks("timeseries", _shard_of(src, key), key,
                               0, 2**62)

    def test_partition_keys_copier(self):
        ms, src = _populated_store()
        dst = InMemoryColumnStore()
        n = PartitionKeysCopier(src, dst, "timeseries", 2).run()
        assert n == 6
        assert sum(len(dst.scan_part_keys("timeseries", s))
                   for s in range(2)) == 6

    def test_cardinality_buster(self):
        ms, cs = _populated_store()
        buster = CardinalityBuster(cs, "timeseries", 2)
        busted = buster.run([ColumnFilter("instance", Equals("instance-0"))])
        assert busted == 1
        remaining = sum(len(cs.scan_part_keys("timeseries", s))
                        for s in range(2))
        assert remaining == 5

    def test_ds_index_job(self):
        ms, cs = _populated_store()
        n = DSIndexJob(cs, "timeseries", "timeseries_ds_5m", 2).run()
        assert n == 6
        recs = sum((cs.scan_part_keys("timeseries_ds_5m", s)
                    for s in range(2)), [])
        assert len(recs) == 6
        assert all(r.part_key.schema == "ds-gauge" for r in recs)


class TestDebugPlane:
    def test_chunk_infos(self):
        ms, _ = _populated_store()
        svc = QueryService(ms, "timeseries", 2, spread=1)
        infos = svc.chunk_infos(
            [ColumnFilter("_metric_", Equals("heap_usage"))], 0, 2**62)
        assert len(infos) >= 6
        assert {"chunkId", "numRows", "startTime", "numBytes"} <= set(
            infos[0].keys())

    def test_tracing_partition_logs(self, caplog):
        ms = TimeSeriesMemStore()
        shard = ms.setup("timeseries", 0, StoreConfig(
            max_chunk_size=50,
            trace_part_key_substrings=("instance-1",)))
        keys = machine_metrics_series(2)
        with caplog.at_level(logging.INFO, logger="filodb_tpu.trace"):
            for sd in gauge_stream(keys, 5):
                shard.ingest(sd)
        assert any("TRACE" in r.message for r in caplog.records)
        traced = [r for r in caplog.records if "instance-1" in r.getMessage()]
        assert len(traced) == 5

    def test_corrupt_vector_error(self):
        good = Chunk(1, 2, 0, 1000, (b"\x01garbage-not-a-vector", b"\xff"))
        with pytest.raises(CorruptVectorError, match="corrupt vector"):
            good.decode_column(1)

    def test_single_writer_assert(self):
        import threading
        ms = TimeSeriesMemStore()
        shard = ms.setup("timeseries", 0,
                         StoreConfig(assert_single_writer=True))
        keys = machine_metrics_series(1)
        stream = list(gauge_stream(keys, 2, batch=1))
        shard.ingest(stream[0])
        errs = []

        def other():
            try:
                shard.ingest(stream[1])
            except AssertionError as e:
                errs.append(e)

        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert errs


def _shard_of(cs, key):
    for s in range(2):
        if any(r.part_key == key for r in cs.scan_part_keys("timeseries", s)):
            return s
    raise AssertionError("key not found")


class TestProfilerAndSources:
    def test_simple_profiler_samples(self):
        import time
        from filodb_tpu.utils.profiler import SimpleProfiler

        prof = SimpleProfiler(sample_interval_s=0.002).start()
        t0 = time.monotonic()
        x = 0
        while time.monotonic() - t0 < 0.15:
            x += sum(range(1000))
        report = prof.stop()
        assert report  # captured at least one hot frame

    def test_csv_stream_source(self, tmp_path):
        from filodb_tpu.coordinator.sources import csv_stream

        p = tmp_path / "x.csv"
        p.write_text("\n".join(f"{1000 + i},{i}.5,host=h{i % 2}"
                               for i in range(25)))
        out = list(csv_stream(str(p), "csv_metric", batch=10))
        assert len(out) == 3
        total = sum(len(sd.container) for sd in out)
        assert total == 25
        rec = out[0].container.records[0]
        assert rec.part_key.metric == "csv_metric"

    def test_influx_file_stream(self, tmp_path):
        from filodb_tpu.coordinator.sources import influx_file_stream

        p = tmp_path / "x.influx"
        p.write_text("\n".join(
            f"m,host=h value={i} {(1000 + i) * 1_000_000}"
            for i in range(5)))
        out = list(influx_file_stream(str(p)))
        assert sum(len(sd.container) for sd in out) == 5

    def test_hist_to_prom_vectors(self):
        import numpy as np
        from filodb_tpu.query.exec.transformers import (
            InstantVectorFunctionMapper,
        )
        from filodb_tpu.query.model import RangeVectorKey, StepMatrix

        m = StepMatrix([RangeVectorKey.of({"app": "a"})],
                       np.arange(6, dtype=float).reshape(1, 2, 3),
                       np.array([0, 1000]), les=np.array([1.0, 2.0, np.inf]))
        out = InstantVectorFunctionMapper("hist_to_prom_vectors").apply(m)
        assert out.num_series == 3
        les = sorted(k.label_map["le"] for k in out.keys)
        assert "+Inf" in les
