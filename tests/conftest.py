"""Test harness config.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-run-compiles the
multi-chip path via ``__graft_entry__.dryrun_multichip``).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Prom semantics are defined on float64; tests verify parity at full precision.
os.environ.setdefault("JAX_ENABLE_X64", "1")
