"""Test harness config.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-run-compiles the
multi-chip path via ``__graft_entry__.dryrun_multichip``).

The session environment boots every interpreter with an ``axon`` TPU backend
registration that overrides ``jax_platforms`` to "axon,cpu" (sitecustomize).
Unit tests must never dial the TPU tunnel, so we force the config back to CPU
before any backend is initialized.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Prom semantics are defined on float64; tests verify parity at full precision.
os.environ["JAX_ENABLE_X64"] = "1"

import jax  # noqa: E402

# The axon PJRT plugin registers itself at interpreter start (sitecustomize,
# keyed on PALLAS_AXON_POOL_IPS) and its backend init hangs EVERY jax call
# machine-wide while the TPU tunnel is down — even with JAX_PLATFORMS=cpu.
# Unit tests must never depend on tunnel health: drop the factory before any
# backend initializes.
import jax._src.xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _cold_cost_models():
    """Every test starts with a cold cost model: learned-routing state is
    process-global (query/cost_model.py), and a model warmed by one test
    must never flip a decision site's arm in another — static behavior is
    the contract while cold. Tests that exercise warm routing seed their
    own observations after this reset."""
    from filodb_tpu.query import cost_model
    cost_model.reset_models()
    yield
    cost_model.reset_models()
