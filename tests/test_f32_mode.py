"""TPU-numerics simulation: the whole query path with x64 DISABLED (f32/i32
everywhere, as on the real chip). Catches dtype leaks that CPU tests (which
force x64 for exact Prometheus parity) would mask.
"""

import json
import os
import subprocess
import sys

import numpy as np

SCRIPT = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("JAX_ENABLE_X64", None)
import jax
jax.config.update("jax_platforms", "cpu")
assert not jax.config.jax_enable_x64

import json
import numpy as np
from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.testing.data import (
    counter_series, counter_stream, gauge_stream, histogram_series,
    histogram_stream, machine_metrics_series,
)

START = 1_600_000_000
out = {}

for device_pages in (False, True):
    ms = TimeSeriesMemStore()
    for s in range(2):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=100,
                                              device_pages=device_pages))
    ingest_routed(ms, "timeseries",
                  gauge_stream(machine_metrics_series(6), 400,
                               start_ms=START * 1000, seed=2), 2, 1)
    ingest_routed(ms, "timeseries",
                  counter_stream(counter_series(4), 400,
                                 start_ms=START * 1000, seed=3,
                                 reset_every=150), 2, 1)
    ingest_routed(ms, "timeseries",
                  histogram_stream(histogram_series(2), 300,
                                   start_ms=START * 1000), 2, 1)
    svc = QueryService(ms, "timeseries", 2, spread=1)
    tag = "dev" if device_pages else "host"

    r = svc.query_range("sum(rate(http_requests_total[5m]))",
                        START + 1800, 60, START + 3600).result
    vals = r.values[np.isfinite(r.values)]
    out[f"{tag}_rate_median"] = float(np.median(vals))

    r = svc.query_range("avg_over_time(heap_usage[5m])",
                        START + 1800, 300, START + 3600).result
    out[f"{tag}_gauge_series"] = r.num_series
    out[f"{tag}_gauge_finite"] = bool(np.isfinite(r.values).all())

    r = svc.query_range(
        "histogram_quantile(0.9, rate(http_req_latency[5m]))",
        START + 1500, 300, START + 2700).result
    hv = r.values[np.isfinite(r.values)]
    out[f"{tag}_hist_ok"] = bool(len(hv) and (hv > 0).all()
                                 and (hv <= 10.0).all())

    r = svc.query_range("topk(2, max_over_time(heap_usage[5m]))",
                        START + 1800, 300, START + 2400).result
    out[f"{tag}_topk_present"] = int((~np.isnan(r.values)).sum(0).max())

print(json.dumps(out))
"""


def test_f32_engine_mode():
    env = dict(os.environ)
    env.pop("JAX_ENABLE_X64", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, "-c", SCRIPT], env=env,
                          capture_output=True, text=True, timeout=600,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-3000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    for tag in ("host", "dev"):
        assert out[f"{tag}_gauge_series"] == 6
        assert out[f"{tag}_gauge_finite"]
        assert out[f"{tag}_hist_ok"]
        assert out[f"{tag}_topk_present"] == 2
        assert out[f"{tag}_rate_median"] > 0
    # host vs device paths agree in f32 too
    assert abs(out["host_rate_median"] - out["dev_rate_median"]) \
        / out["host_rate_median"] < 1e-3


BIG_COUNTER_SCRIPT = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("JAX_ENABLE_X64", None)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", MODE == "f64")
assert jax.config.jax_enable_x64 == (MODE == "f64")

import json
import numpy as np
from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.testing.data import counter_series, counter_stream

START = 1_600_000_000

ms = TimeSeriesMemStore()
for s in range(2):
    ms.setup("timeseries", s, StoreConfig(max_chunk_size=100))
# long-lived counters: values start at 2e9 (>> 2^24 = 16.7M), per-sample
# deltas ~10 — an f32 cast of the raw values collapses every window delta
ingest_routed(ms, "timeseries",
              counter_stream(counter_series(4), 400, start_ms=START * 1000,
                             seed=7, start_value=2.0e9), 2, 1)
# and one set WITH resets at the big magnitude
ingest_routed(ms, "timeseries",
              counter_stream(counter_series(3, metric="reset_total"), 400,
                             start_ms=START * 1000, seed=8, reset_every=120,
                             start_value=3.0e9), 2, 1)

out = {}
for engine in ("exec", "mesh"):
    svc = QueryService(ms, "timeseries", 2, spread=1, engine=engine)
    r = svc.query_range("sum(rate(http_requests_total[5m]))",
                        START + 1800, 60, START + 3600).result
    out[f"{engine}_rate"] = np.asarray(r.values)[0].tolist()
    r = svc.query_range("sum(increase(reset_total[10m]))",
                        START + 1800, 120, START + 3600).result
    out[f"{engine}_increase"] = np.asarray(r.values)[0].tolist()
    r = svc.query_range("delta(http_requests_total[5m])",
                        START + 1800, 300, START + 3600).result
    out[f"{engine}_delta"] = np.asarray(r.values).tolist()
print(json.dumps(out))
"""


def _run_big_counter(mode):
    env = dict(os.environ)
    env.pop("JAX_ENABLE_X64", None)
    env["JAX_PLATFORMS"] = "cpu"
    script = f"MODE = {mode!r}\n" + BIG_COUNTER_SCRIPT
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=600,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-3000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_f32_counter_precision_rebased():
    """VERDICT r4 acceptance: counters >= 1e9 with per-window deltas ~10.
    The f32 device path (exec kernels AND the mesh engine) must match the
    f64 host path to rtol 1e-5 — without per-series f64 rebasing the f32
    cast returns garbage (window deltas collapse to 0 or +/-256)."""
    f32 = _run_big_counter("f32")
    f64 = _run_big_counter("f64")
    for key in ("exec_rate", "mesh_rate", "exec_increase", "mesh_increase",
                "exec_delta", "mesh_delta"):
        a = np.asarray(f32[key], float)
        b = np.asarray(f64[key], float)
        assert a.shape == b.shape
        finite = np.isfinite(b)
        assert finite.any(), key
        np.testing.assert_allclose(a[finite], b[finite], rtol=1e-5,
                                   err_msg=key)
        # sanity: the rates are real (deltas ~10 per 10s => ~1/s per series)
        if key.endswith("_rate"):
            assert (np.abs(b[finite]) > 0.1).all()
