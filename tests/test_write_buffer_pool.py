"""WriteBufferPool: appender recycling across series churn.

Reference ``core/.../memstore/WriteBufferPool.scala:1-92`` (pre-allocated
reusable appender sets). Recycling is quarantined against in-flight
lock-free readers (doc/memory_safety.md).
"""

import numpy as np

from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.memstore.partition import WriteBufferPool
from filodb_tpu.core.store.api import InMemoryColumnStore, InMemoryMetaStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.testing.data import gauge_stream, machine_metrics_series

START = 1_600_000_000


def _store():
    ms = TimeSeriesMemStore(InMemoryColumnStore(), InMemoryMetaStore())
    # native_ingest off: the C++ lane owns its own buffers; the pool
    # covers host-backed partitions (histograms, strings, no-toolchain)
    ms.setup("timeseries", 0, StoreConfig(max_chunk_size=50,
                                          groups_per_shard=2,
                                          native_ingest=False))
    return ms


class TestWriteBufferPool:
    def test_churn_reuses_buffers(self):
        ms = _store()
        shard = ms.get_shard("timeseries", 0)
        keys = machine_metrics_series(6)
        for sd in gauge_stream(keys, 60, start_ms=START * 1000):
            shard.ingest(sd)
        shard.flush_all(ingestion_time=1)
        pools = [p for p in shard.buffer_pools.values()]
        assert pools and all(isinstance(p, WriteBufferPool) for p in pools)
        evicted = sum(bool(shard.evict_partition(part.part_id))
                      for part in list(shard.partitions) if part)
        assert evicted > 0
        # new series obtain the recycled appender sets
        keys2 = machine_metrics_series(6, metric="other_metric")
        for sd in gauge_stream(keys2, 60, start_ms=(START + 9000) * 1000,
                               start_offset=10_000):  # past the watermark
            shard.ingest(sd)
        assert sum(p.reused for p in shard.buffer_pools.values()) > 0

    def test_recycled_buffers_hold_correct_data(self):
        ms = _store()
        shard = ms.get_shard("timeseries", 0)
        keys = machine_metrics_series(3)
        for sd in gauge_stream(keys, 120, start_ms=START * 1000, seed=5):
            shard.ingest(sd)
        shard.flush_all(ingestion_time=1)
        for part in list(shard.partitions):
            if part:
                shard.evict_partition(part.part_id)
        # second generation reuses buffers; old data must be invisible
        keys2 = machine_metrics_series(3, metric="gen2")
        for sd in gauge_stream(keys2, 40, start_ms=(START + 5000) * 1000,
                               seed=9, start_offset=10_000):
            shard.ingest(sd)
        from filodb_tpu.coordinator.query_service import QueryService
        svc = QueryService(ms, "timeseries", 1, spread=0)
        r = svc.query_range("count_over_time(gen2[11m])",
                            START + 5600, 60, START + 5600).result
        assert r.num_series == 3
        np.testing.assert_array_equal(np.asarray(r.values)[:, 0], 40.0)
        # evicted gen-1 series still queryable via ODP paging
        r1 = svc.query_range("count_over_time(heap_usage[30m])",
                             START + 1200, 60, START + 1200).result
        assert r1.num_series == 3
        np.testing.assert_array_equal(np.asarray(r1.values)[:, 0], 120.0)

    def test_reader_reference_blocks_reuse(self):
        """Deterministic reclamation: a reader holding the buffer object or
        a VIEW of one of its arrays keeps it out of circulation; dropping
        the reference makes it immediately reusable (no wall-clock)."""
        from filodb_tpu.core.schemas import GAUGE
        schema = GAUGE
        pool = WriteBufferPool(schema, 50)
        from filodb_tpu.core.memstore.partition import TimeSeriesPartition
        from filodb_tpu.core.partkey import PartKey
        key = PartKey.create("gauge", {"_metric_": "m"})
        part = TimeSeriesPartition(0, key, schema, 50, buffer_pool=pool)
        buf = part._buf
        part.release_buffers()
        # a stalled reader still holds the buffer: fresh buffer issued
        part2 = TimeSeriesPartition(1, key, schema, 50, buffer_pool=pool)
        assert part2._buf is not buf
        assert pool.blocked > 0
        # holding only a VIEW of an array also pins it (view.base refcount)
        view = buf.ts[:10]
        del buf
        part3 = TimeSeriesPartition(2, key, schema, 50, buffer_pool=pool)
        assert len(part3._buf.ts) == 50 and view is not None
        assert pool.reused == 0
        del view
        part4 = TimeSeriesPartition(3, key, schema, 50, buffer_pool=pool)
        assert pool.reused == 1
        # recycled buffer serves the new partition, zeroed fill count
        assert part4._buf.n == 0
