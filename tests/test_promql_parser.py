"""PromQL parser conformance tests.

Mirrors the reference's ParserSpec
(``prometheus/src/test/scala/filodb/prometheus/parse/ParserSpec.scala``, 761
lines asserting PromQL → LogicalPlan for hundreds of queries): asserts the
logical-plan structure for a representative corpus.
"""

import pytest

from filodb_tpu.core.filters import ColumnFilter, Equals, EqualsRegex, NotEquals
from filodb_tpu.promql.parser import (
    ParseError,
    TimeStepParams,
    parse_duration_ms,
    parse_query,
)
from filodb_tpu.query import logical as lp

P = TimeStepParams(start=1000, step=10, end=2000)


def parse(q):
    return parse_query(q, P)


def filters_of(plan):
    return {f.column: f.filter for f in plan.filters}


class TestSelectors:
    def test_bare_metric(self):
        p = parse("http_requests_total")
        assert isinstance(p, lp.PeriodicSeries)
        assert p.start == 1_000_000 and p.end == 2_000_000 and p.step == 10_000
        f = filters_of(p.raw)
        assert f["_metric_"] == Equals("http_requests_total")
        assert p.raw.lookback == 300_000

    def test_label_matchers(self):
        p = parse('hu{_ws_="demo",_ns_!="x",instance=~"i.*",job!~"j[0-9]"}')
        f = filters_of(p.raw)
        assert f["_ws_"] == Equals("demo")
        assert isinstance(f["_ns_"], NotEquals)
        assert isinstance(f["instance"], EqualsRegex)

    def test_name_label(self):
        p = parse('{__name__="up",job="api"}')
        f = filters_of(p.raw)
        assert f["_metric_"] == Equals("up")

    def test_offset(self):
        p = parse("metric offset 5m")
        assert p.offset == 300_000

    def test_range_requires_function(self):
        with pytest.raises(ParseError):
            parse("metric[5m]")

    def test_empty_selector_error(self):
        with pytest.raises(ParseError):
            parse("{}")


class TestDurations:
    def test_units(self):
        assert parse_duration_ms("5m") == 300_000
        assert parse_duration_ms("1h30m") == 5_400_000
        assert parse_duration_ms("90s") == 90_000
        assert parse_duration_ms("1d") == 86_400_000
        assert parse_duration_ms("2w") == 1_209_600_000
        assert parse_duration_ms("500ms") == 500

    def test_step_multiple(self):
        # reference README.md:429-460: [Ni] = N × step
        assert parse_duration_ms("5i", step_ms=10_000) == 50_000
        with pytest.raises(ParseError):
            parse_duration_ms("5i", step_ms=0)

    def test_rate_with_step_multiple(self):
        p = parse("rate(m[5i])")
        assert p.window == 50_000


class TestRangeFunctions:
    def test_rate(self):
        p = parse("rate(http_requests_total[5m])")
        assert isinstance(p, lp.PeriodicSeriesWithWindowing)
        assert p.function == "rate" and p.window == 300_000

    def test_all_over_time(self):
        for fn in ("sum_over_time", "avg_over_time", "min_over_time",
                   "max_over_time", "count_over_time", "stddev_over_time",
                   "last_over_time", "present_over_time"):
            p = parse(f"{fn}(m[10m])")
            assert p.function == fn and p.window == 600_000

    def test_quantile_over_time_param(self):
        p = parse("quantile_over_time(0.95, m[5m])")
        assert p.function == "quantile_over_time" and p.params == (0.95,)

    def test_holt_winters(self):
        p = parse("holt_winters(m[10m], 0.5, 0.1)")
        assert p.params == (0.5, 0.1)

    def test_predict_linear(self):
        p = parse("predict_linear(m[30m], 3600)")
        assert p.params == (3600.0,)

    def test_offset_range(self):
        p = parse("rate(m[5m] offset 10m)")
        assert p.offset == 600_000


class TestAggregations:
    def test_sum(self):
        p = parse("sum(rate(m[5m]))")
        assert isinstance(p, lp.Aggregate) and p.op == "sum"
        assert isinstance(p.vector, lp.PeriodicSeriesWithWindowing)

    def test_by_prefix_and_suffix(self):
        p1 = parse("sum by (job, instance) (m)")
        p2 = parse("sum(m) by (job, instance)")
        assert p1.by == ("job", "instance") == p2.by

    def test_without(self):
        p = parse("avg without (instance) (m)")
        assert p.without == ("instance",)

    def test_topk(self):
        p = parse("topk(5, sum by (app) (rate(cpu[1m])))")
        assert p.op == "topk" and p.params == (5.0,)
        inner = p.vector
        assert inner.op == "sum" and inner.by == ("app",)

    def test_quantile_agg(self):
        p = parse("quantile(0.9, m)")
        assert p.op == "quantile" and p.params == (0.9,)

    def test_count_values(self):
        p = parse('count_values("version", build_info)')
        assert p.op == "count_values" and p.params == ("version",)


class TestBinaryOps:
    def test_vector_vector(self):
        p = parse("a + b")
        assert isinstance(p, lp.BinaryJoin) and p.op == "+"

    def test_precedence(self):
        p = parse("a + b * c")
        assert p.op == "+" and p.rhs.op == "*"
        p = parse("(a + b) * c")
        assert p.op == "*"

    def test_power_right_assoc(self):
        p = parse("a ^ b ^ c")
        assert p.op == "^" and p.rhs.op == "^"

    def test_scalar_vector(self):
        p = parse("2 * m")
        assert isinstance(p, lp.ScalarVectorBinaryOperation)
        assert p.scalar_is_lhs and p.scalar.value == 2.0

    def test_scalar_scalar_folds(self):
        p = parse("1 + 2 * 3")
        assert isinstance(p, lp.ScalarFixedDoublePlan) and p.value == 7.0

    def test_comparison_bool(self):
        p = parse("m > bool 5")
        assert isinstance(p, lp.ScalarVectorBinaryOperation)
        assert p.bool_mode and not p.scalar_is_lhs

    def test_set_ops(self):
        for op in ("and", "or", "unless"):
            p = parse(f"a {op} b")
            assert isinstance(p, lp.BinaryJoin) and p.op == op
            assert p.cardinality == "many-to-many"

    def test_on_group_left(self):
        p = parse("a * on (job) group_left (extra) b")
        assert p.on == ("job",) and p.cardinality == "many-to-one"
        assert p.include == ("extra",)

    def test_ignoring(self):
        p = parse("a / ignoring (instance) b")
        assert p.ignoring == ("instance",)

    def test_unary_minus(self):
        p = parse("-m")
        assert isinstance(p, lp.ScalarVectorBinaryOperation) and p.op == "*"


class TestFunctions:
    def test_instant_functions(self):
        for fn in ("abs", "ceil", "floor", "exp", "ln", "sqrt", "sgn"):
            p = parse(f"{fn}(m)")
            assert isinstance(p, lp.ApplyInstantFunction) and p.function == fn

    def test_histogram_quantile(self):
        p = parse("histogram_quantile(0.99, sum(rate(lat_bucket[5m])) by (le))")
        assert p.function == "histogram_quantile" and p.args == (0.99,)
        assert isinstance(p.vector, lp.Aggregate)

    def test_clamp(self):
        p = parse("clamp(m, 0, 10)")
        assert p.args == (0.0, 10.0)

    def test_absent(self):
        p = parse('absent(m{job="x"})')
        assert isinstance(p, lp.ApplyAbsentFunction)

    def test_sort(self):
        assert parse("sort(m)").descending is False
        assert parse("sort_desc(m)").descending is True

    def test_label_replace(self):
        p = parse('label_replace(m, "dst", "$1", "src", "(.*)")')
        assert isinstance(p, lp.ApplyMiscellaneousFunction)
        assert p.args == ("dst", "$1", "src", "(.*)")

    def test_scalar_vector_fns(self):
        p = parse("scalar(m)")
        assert isinstance(p, lp.ScalarVaryingDoublePlan)
        p = parse("vector(1)")
        assert isinstance(p, lp.VectorPlan)
        p = parse("time()")
        assert isinstance(p, lp.ScalarTimeBasedPlan)

    def test_timestamp(self):
        p = parse("timestamp(m)")
        assert p.function == "timestamp"

    def test_subquery(self):
        p = parse("max_over_time(rate(m[1m])[30m:1m])")
        assert isinstance(p, lp.SubqueryWithWindowing)
        assert p.function == "max_over_time"
        assert p.subquery_window == 1_800_000 and p.subquery_step == 60_000
        assert isinstance(p.inner, lp.PeriodicSeriesWithWindowing)


class TestComplexQueries:
    """Queries of the shape the reference benchmarks/specs exercise."""

    def test_benchmark_query(self):
        p = parse('sum(rate(heap_usage{_ws_="demo",_ns_="App-2"}[5m]))')
        assert p.op == "sum"
        assert p.vector.function == "rate"
        f = filters_of(p.vector.raw)
        assert f["_ws_"] == Equals("demo")

    def test_histogram_p99(self):
        parse('histogram_quantile(0.99, sum(rate(req_latency{_ws_="demo"'
              '}[5m])) by (le))')

    def test_nested_binary(self):
        p = parse('sum(rate(a[1m])) / sum(rate(b[1m])) * 100')
        assert p.op == "*"
        assert isinstance(p, lp.ScalarVectorBinaryOperation)

    def test_division_ratio(self):
        p = parse('sum(rate(err[5m])) / sum(rate(total[5m]))')
        assert isinstance(p, lp.BinaryJoin) and p.op == "/"

    def test_trailing_garbage(self):
        with pytest.raises(ParseError):
            parse("m ,")
