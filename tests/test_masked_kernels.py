"""Mask-aware kernel tests: interior gaps (block-aligned device-page layout)
must produce results identical to the compacted gap-free arrays.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from filodb_tpu.query.engine import kernels
from filodb_tpu.query.engine.batch import TS_PAD

FNS = ["sum_over_time", "avg_over_time", "count_over_time", "min_over_time",
       "max_over_time", "stddev_over_time", "last_over_time", "changes",
       "resets", "rate", "increase", "delta", "irate", "idelta", "deriv",
       "zscore", "present_over_time"]


def make_gappy(n=200, gap_every=50, gap_len=14, seed=0, counter=False):
    """Dense series → gap-padded layout (gaps carry the previous real ts)."""
    rng = np.random.default_rng(seed)
    t = np.cumsum(rng.integers(5_000, 15_000, n)).astype(np.int64)
    if counter:
        v = np.cumsum(rng.integers(0, 20, n)).astype(float)
        r = n // 2
        v[r:] -= v[r]
    else:
        v = rng.normal(50, 10, n)
    # insert gap runs after every `gap_every` real samples
    ts_out, vals_out, valid_out = [], [], []
    for i in range(n):
        ts_out.append(t[i])
        vals_out.append(v[i])
        valid_out.append(True)
        if (i + 1) % gap_every == 0:
            for _ in range(gap_len):
                ts_out.append(t[i])     # gap carries last real ts
                vals_out.append(0.0)
                valid_out.append(False)
    S = len(ts_out)
    return (t, v,
            np.array(ts_out, np.int32)[None, :],
            np.array(vals_out, np.float64)[None, :],
            np.array(valid_out, bool)[None, :])


class TestMaskedEquivalence:
    @pytest.mark.parametrize("fn", FNS)
    def test_gaps_match_compact(self, fn):
        t, v, ts_g, vals_g, valid_g = make_gappy(counter=fn in
                                                 ("rate", "increase"))
        steps = np.arange(400_000, 1_800_000, 70_000, dtype=np.int32)
        window = np.int32(300_000)
        # compact reference
        S = 1 << (len(t) - 1).bit_length()
        ts_c = np.full((1, S), TS_PAD, np.int32)
        vals_c = np.zeros((1, S), np.float64)
        ts_c[0, : len(t)] = t
        vals_c[0, : len(t)] = v
        counts = np.array([len(t)], np.int32)
        ref = np.asarray(kernels.range_eval(
            fn, jnp.asarray(ts_c), jnp.asarray(vals_c), jnp.asarray(counts),
            jnp.asarray(steps), jnp.asarray(window)))
        out = np.asarray(kernels.range_eval_masked(
            fn, jnp.asarray(ts_g), jnp.asarray(vals_g), jnp.asarray(valid_g),
            jnp.asarray(steps), jnp.asarray(window)))
        np.testing.assert_allclose(out, ref, rtol=1e-9, atol=1e-12,
                                   equal_nan=True, err_msg=fn)

    def test_leading_gap_block(self):
        # an entirely-invalid leading block (e.g. padding) with INT32_MIN ts
        t = np.arange(1, 51, dtype=np.int64) * 10_000
        v = np.arange(50, dtype=float)
        ts_g = np.concatenate([np.full(16, -2**31 + 1, np.int32),
                               t.astype(np.int32)])[None, :]
        vals_g = np.concatenate([np.zeros(16), v])[None, :]
        valid_g = np.concatenate([np.zeros(16, bool),
                                  np.ones(50, bool)])[None, :]
        steps = np.array([500_000], np.int32)
        out = np.asarray(kernels.range_eval_masked(
            "sum_over_time", jnp.asarray(ts_g), jnp.asarray(vals_g),
            jnp.asarray(valid_g), jnp.asarray(steps),
            jnp.asarray(np.int32(500_000))))
        np.testing.assert_allclose(out[0, 0], v.sum())

    def test_all_invalid_is_nan(self):
        ts_g = np.full((1, 32), 1000, np.int32)
        vals_g = np.zeros((1, 32))
        valid_g = np.zeros((1, 32), bool)
        out = np.asarray(kernels.range_eval_masked(
            "avg_over_time", jnp.asarray(ts_g), jnp.asarray(vals_g),
            jnp.asarray(valid_g), jnp.asarray(np.array([2000], np.int32)),
            jnp.asarray(np.int32(5000))))
        assert np.isnan(out).all()
