"""Alert notification egress (filodb_tpu/rules/notify.py).

Covers the notifier in isolation (batching, retry, failure accounting,
bounded-queue drops) and wired into the RuleManager group commit:
transitions notify exactly once, discarded stages (failed group writes)
never notify, and the hand-off from the evaluation thread stays
non-blocking.
"""

import json
import queue
import threading
import time

import pytest

from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.rules import (
    AlertingRule,
    MemstoreSink,
    RuleGroup,
    RuleManager,
    WebhookNotifier,
)
from filodb_tpu.rules import notify
from filodb_tpu.utils.resilience import FaultInjector, RetryPolicy

from tests.test_rules import (
    GROUP_MS,
    INTERVAL,
    START,
    drain,
    ingest_temp,
    make_svc,
)


def no_sleep_policy(max_attempts=2):
    return RetryPolicy(max_attempts=max_attempts, base_backoff_s=0.0,
                       max_backoff_s=0.0, sleep=lambda s: None)


def make_notifier(post, **kw):
    kw.setdefault("retry_policy", no_sleep_policy())
    return WebhookNotifier("http://127.0.0.1:9/hook", post=post, **kw)


def wait_for(pred, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError("condition not reached in time")


def sample_events():
    key = (("alertname", "TempHigh"), ("host", "h1"))
    return notify.events_from_transitions(
        "alerts", (("summary", "too hot"),),
        [(key, notify.PENDING, 0.9, 1000, 1000),
         (key, notify.FIRING, 1.2, 1000, 61000)])


class TestWebhookNotifier:
    def test_posts_alertmanager_style_batch(self):
        posts = []
        n = make_notifier(lambda b: posts.append(json.loads(b)))
        assert n.submit(sample_events())
        n.close()
        assert len(posts) == 1
        body = posts[0]
        assert body["version"] == "4" and len(body["alerts"]) == 2
        pend, fire = body["alerts"]
        assert pend["state"] == "pending" and pend["status"] == "firing"
        assert fire["state"] == "firing"
        assert pend["labels"] == {"alertname": "TempHigh", "host": "h1"}
        assert pend["annotations"] == {"summary": "too hot"}
        assert fire["startsAt"] == 1.0 and fire["evaluatedAt"] == 61.0

    def test_resolved_maps_to_resolved_status(self):
        posts = []
        n = make_notifier(lambda b: posts.append(json.loads(b)))
        key = (("alertname", "TempHigh"),)
        n.submit(notify.events_from_transitions(
            "alerts", (), [(key, notify.RESOLVED, 1.2, 1000, 121000)]))
        n.close()
        assert posts[0]["alerts"][0]["status"] == "resolved"

    def test_retry_then_success(self):
        calls = []

        def flaky(body):
            calls.append(body)
            if len(calls) == 1:
                raise ConnectionError("transient")

        before = notify.notifications_sent.value
        n = make_notifier(flaky, retry_policy=no_sleep_policy(3))
        n.submit(sample_events())
        n.close()
        assert len(calls) == 2
        assert notify.notifications_sent.value == before + 2

    def test_exhausted_retries_count_failures(self):
        def down(body):
            raise ConnectionError("refused")

        before = notify.notification_failures.value
        n = make_notifier(down)
        n.submit(sample_events())
        n.close()
        assert notify.notification_failures.value == before + 2

    def test_full_queue_drops_and_counts(self):
        release = threading.Event()

        def slow(body):
            release.wait(5.0)

        before = notify.notifications_dropped.value
        n = make_notifier(slow, queue_depth=1)
        evs = sample_events()
        n.submit(evs)                    # taken by the worker, blocks
        wait_for(lambda: n._q.empty())   # worker picked the first batch
        assert n.submit(evs)             # fills the queue
        assert not n.submit(evs)         # bounded: dropped, not blocked
        assert notify.notifications_dropped.value == before + 2
        release.set()
        n.close()

    def test_submit_empty_is_noop(self):
        n = make_notifier(lambda b: pytest.fail("no POST expected"))
        assert n.submit([])
        n.close()

    def test_fault_injection_site(self):
        def ok(body):
            pass

        before = notify.notification_failures.value
        n = make_notifier(ok)
        try:
            FaultInjector.arm("rules.notify", error=ConnectionError,
                              times=1)
            n.submit(sample_events())
            n.close()
        finally:
            FaultInjector.reset()
        # injected before the retry loop: whole batch fails
        assert notify.notification_failures.value == before + 2


class TestManagerIntegration:
    def make(self, post, for_ms=0):
        ms = TimeSeriesMemStore()
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=100,
                                              groups_per_shard=4))
        svc = make_svc(ms, num_shards=1)
        sink = MemstoreSink(ms, "timeseries", 1, spread=0)
        g = RuleGroup(
            name="alerts", interval_ms=GROUP_MS, dataset="timeseries",
            rules=(AlertingRule(alert="TempHigh", expr="avg(temp) > 0.5",
                                for_ms=for_ms,
                                annotations=(("summary", "too hot"),)),))
        n = make_notifier(post)
        mgr = RuleManager(svc, sink, [g], ooo_allowance_ms=0, notifier=n)
        return ms, svc, sink, mgr, n

    def test_lifecycle_notifies_pending_firing_resolved(self):
        posts = []
        ms, svc, sink, mgr, n = self.make(
            lambda b: posts.append(json.loads(b)), for_ms=120_000)
        # cold → hot → cold again: full alert lifecycle
        ingest_temp(ms, sink, [(i, 0.0) for i in range(60)])
        mgr.tick()
        ingest_temp(ms, sink, [(i, 1.0) for i in range(60, 120)])
        drain(mgr)
        ingest_temp(ms, sink, [(i, 0.0) for i in range(120, 180)])
        drain(mgr)
        mgr.stop()                      # closes the notifier, drains queue
        states = [a["state"] for body in posts for a in body["alerts"]]
        assert states == ["pending", "firing", "resolved"]
        al = posts[0]["alerts"][0]
        assert al["labels"]["alertname"] == "TempHigh"
        assert al["annotations"] == {"summary": "too hot"}

    def test_discarded_stage_does_not_notify(self):
        # a failed group write discards staged alert state; the same
        # window re-evaluates next tick and must notify exactly once
        posts = []
        ms, svc, sink, mgr, n = self.make(
            lambda b: posts.append(json.loads(b)))
        ingest_temp(ms, sink, [(i, 0.0) for i in range(30)])
        mgr.tick()
        ingest_temp(ms, sink, [(i, 1.0) for i in range(30, 90)])
        try:
            FaultInjector.arm("rules.write", error=ConnectionError,
                              times=1)
            assert mgr.tick() == 0
        finally:
            FaultInjector.reset()
        drain(mgr)
        mgr.stop()
        states = [a["state"] for body in posts for a in body["alerts"]]
        # for: 0 → pending and firing commit in the same evaluation
        assert states == ["pending", "firing"]

    def test_no_notifier_is_fine(self):
        ms = TimeSeriesMemStore()
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=100,
                                              groups_per_shard=4))
        svc = make_svc(ms, num_shards=1)
        sink = MemstoreSink(ms, "timeseries", 1, spread=0)
        g = RuleGroup(
            name="alerts", interval_ms=GROUP_MS, dataset="timeseries",
            rules=(AlertingRule(alert="TempHigh", expr="avg(temp) > 0.5",
                                for_ms=0),))
        mgr = RuleManager(svc, sink, [g], ooo_allowance_ms=0)
        ingest_temp(ms, sink, [(i, 1.0) for i in range(60)])
        drain(mgr)
        mgr.stop()
        assert mgr.alerts_snapshot()
