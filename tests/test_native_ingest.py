"""Native ingest lane: C++ shard core parity with the host path.

Reference boundary replaced: the per-shard ingest hot loop
(``core/src/main/scala/filodb.core/memstore/TimeSeriesShard.scala:570``,
``TimeSeriesPartition.scala:137``). The binary-container lane must produce
identical query results, flush artifacts, and recovery behavior as the
Python record loop.
"""

import numpy as np
import pytest

from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.memstore.native_shard import native_available
from filodb_tpu.core.record import BytesContainer, SomeData
from filodb_tpu.core.store.api import InMemoryColumnStore, InMemoryMetaStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.testing.data import (
    counter_stream,
    gauge_stream,
    histogram_stream,
    histogram_series,
    machine_metrics_series,
)

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native library unavailable")


def to_bytes_stream(stream):
    for sd in stream:
        yield SomeData(BytesContainer(sd.container.serialize()), sd.offset)


def build(native: bool, stream):
    ms = TimeSeriesMemStore(InMemoryColumnStore(), InMemoryMetaStore())
    shard = ms.setup("ds", 0, StoreConfig(max_chunk_size=50,
                                          groups_per_shard=4,
                                          native_ingest=native))
    for sd in stream:
        shard.ingest(sd)
    return ms, shard


class TestNativeParity:
    def test_lane_engages(self):
        keys = machine_metrics_series(3)
        stream = list(to_bytes_stream(gauge_stream(keys, 10, batch=1)))
        _, shard = build(True, stream)
        assert shard._native_core is not None
        assert shard._native_core.stat(0) > 0  # rows went through C++
        assert type(shard.partitions[0]).__name__ == "NativeBackedPartition"

    def test_query_results_match_python_path(self):
        keys = machine_metrics_series(6)
        base = list(gauge_stream(keys, 300, batch=20, seed=11))
        stream_b = list(to_bytes_stream(base))
        _, nat = build(True, stream_b)
        _, py = build(False, base)
        assert nat._native_core is not None and py._native_core is None
        for pid in range(len(keys)):
            t1, v1 = nat.partitions[pid].read_samples(0, 10**15)
            t2, v2 = py.partitions[pid].read_samples(0, 10**15)
            np.testing.assert_array_equal(t1, t2)
            np.testing.assert_array_equal(v1, v2)
            # chunk artifacts byte-identical (same codecs, same boundaries)
            c1 = nat.partitions[pid].chunks
            c2 = py.partitions[pid].chunks
            assert [c.id for c in c1] == [c.id for c in c2]
            assert [c.vectors for c in c1] == [c.vectors for c in c2]

    def test_flush_and_recovery_parity(self):
        keys = machine_metrics_series(4)
        base = list(gauge_stream(keys, 120, batch=1, seed=2))
        cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
        ms = TimeSeriesMemStore(cs, meta)
        shard = ms.setup("ds", 0, StoreConfig(max_chunk_size=50,
                                              groups_per_shard=2))
        half = len(base) // 2
        for sd in to_bytes_stream(base[:half]):
            shard.ingest(sd)
        shard.flush_all()
        # restart: recover index + watermarks, replay everything
        ms2 = TimeSeriesMemStore(cs, meta)
        shard2 = ms2.setup("ds", 0, StoreConfig(max_chunk_size=50,
                                                groups_per_shard=2))
        assert shard2.recover_index() == 4
        shard2.setup_watermarks_for_recovery()
        for sd in to_bytes_stream(base):
            shard2.ingest(sd)
        assert shard2.stats.rows_skipped.value > 0  # below-watermark skip
        shard2.flush_all()
        for key in keys:
            chunks = cs.read_chunks("ds", 0, key, 0, 10**15)
            all_ts = [t for c in chunks for t in c.decode_column(0)]
            assert len(all_ts) == len(set(all_ts))
            assert len(set(all_ts)) == 120

    def test_histogram_containers_ingest_natively(self):
        hkeys = histogram_series(2)
        stream = list(to_bytes_stream(histogram_stream(hkeys, 30, batch=1)))
        _, shard = build(True, stream)
        # hist containers take the native lane (VERDICT r3 #3a): partitions
        # are native-backed and read back full histogram columns
        assert shard.stats.rows_ingested.value == 60
        assert type(shard.partitions[0]).__name__ == "NativeBackedPartition"
        t, v = shard.partitions[0].read_samples(0, 10**15)
        assert len(t) == 30
        from filodb_tpu.memory.codecs import HistogramColumn
        assert isinstance(v, HistogramColumn)
        assert v.rows.shape[0] == 30 and v.rows.shape[1] == len(v.les)
        # cumulative bucket counts are monotone non-decreasing per row
        assert (np.diff(v.rows, axis=1) >= 0).all()
        # sum/count scalar columns ride the same native records
        t1, sums = shard.partitions[0].read_samples(0, 10**15, col=1)
        assert len(t1) == 30 and np.isfinite(sums).all()

    def test_mixed_scalar_and_hist_pid_alignment(self):
        gkeys = machine_metrics_series(2)
        hkeys = histogram_series(1)
        g1 = list(to_bytes_stream(gauge_stream(gkeys, 5, batch=1)))
        h1 = [SomeData(sd.container, sd.offset + 100) for sd in
              to_bytes_stream(histogram_stream(hkeys, 5, batch=1))]
        g2 = [SomeData(BytesContainer(sd.container.serialize()),
                       sd.offset + 200)
              for sd in gauge_stream(gkeys, 5, batch=1, start_ms=10**9)]
        ms, shard = build(True, g1 + h1 + g2)
        assert shard.num_partitions == 3
        for pid, part in enumerate(shard.partitions):
            assert part.part_id == pid
        # native pids stay aligned after the python-backed hist partition
        total = sum(p.num_samples for p in shard.partitions)
        assert total == 2 * 10 + 5

    def test_concurrent_reads_during_ingest(self):
        # readers copy native buffers while the ingest thread appends and
        # seals; without the core lock this is a use-after-free on vector
        # realloc (the C++ analog of the reference's ChunkMap latch)
        import threading
        keys = machine_metrics_series(8)
        ms = TimeSeriesMemStore(InMemoryColumnStore(), InMemoryMetaStore())
        shard = ms.setup("ds", 0, StoreConfig(max_chunk_size=64,
                                              groups_per_shard=2))
        stream = [SomeData(BytesContainer(sd.container.serialize()),
                           sd.offset)
                  for sd in gauge_stream(keys, 2000, batch=64)]
        errors = []
        stop = threading.Event()

        def reader():
            try:
                while not stop.is_set():
                    for p in list(shard.partitions):
                        if p is None:
                            continue
                        t, v = p.read_samples(0, 10**15)
                        assert len(t) == len(v)
                        if len(t) > 1:
                            assert (np.diff(t) > 0).all()
                        _ = p.chunks
            except Exception as e:  # pragma: no cover - the regression
                errors.append(e)

        threads = [threading.Thread(target=reader) for _ in range(3)]
        for t in threads:
            t.start()
        for sd in stream:
            shard.ingest(sd)
        stop.set()
        for t in threads:
            t.join()
        assert not errors, errors
        total = sum(p.num_samples for p in shard.partitions if p)
        assert total == 8 * 2000

    def test_purge_frees_slot_for_python_backed_partition(self):
        # a histogram (python-backed) partition still owns a native slot;
        # purge must free it or re-creating the series breaks pid alignment
        hkeys = histogram_series(1)
        ms = TimeSeriesMemStore(InMemoryColumnStore(), InMemoryMetaStore())
        shard = ms.setup("ds", 0, StoreConfig(max_chunk_size=10,
                                              groups_per_shard=1,
                                              retention_ms=1_000_000))
        for sd in to_bytes_stream(histogram_stream(hkeys, 3, batch=1)):
            shard.ingest(sd)
        assert shard._native_core is not None
        assert shard.purge_expired(now_ms=10_000_000) == 1
        # same series comes back: must create cleanly at the NEW pid
        fresh = [SomeData(sd.container, sd.offset + 100) for sd in
                 to_bytes_stream(histogram_stream(hkeys, 3, batch=1,
                                                  start_ms=20_000_000))]
        for sd in fresh:
            shard.ingest(sd)
        assert shard.num_partitions == 1
        assert shard.partitions[1] is not None

    def test_eviction_and_purge(self):
        keys = machine_metrics_series(2)
        cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
        ms = TimeSeriesMemStore(cs, meta)
        shard = ms.setup("ds", 0, StoreConfig(max_chunk_size=10,
                                              groups_per_shard=1,
                                              retention_ms=1_000_000))
        for sd in to_bytes_stream(gauge_stream(keys, 25, batch=1)):
            shard.ingest(sd)
        shard.flush_all()
        p = shard.partitions[0]
        assert p.evict_flushed_chunks() >= 2
        assert not p.ingest(1000, (5.0,))  # floor holds after eviction
        # purge drops the native slot and the key
        purged = shard.purge_expired(now_ms=10_000_000)
        assert purged == 2
        assert shard.num_partitions == 0
        # re-creating the same series works (new native pid); offsets must
        # sit above the flush watermark
        fresh = [SomeData(sd.container, sd.offset + 1000) for sd in
                 to_bytes_stream(gauge_stream(keys, 3, batch=1,
                                              start_ms=20_000_000))]
        for sd in fresh:
            shard.ingest(sd)
        assert shard.num_partitions == 2


class TestMalformedContainers:
    """ADVICE r2 high: a crafted container whose later record carries a
    different value count than the partition's column count must not leave
    columns shorter than ts (seal-time encoders read ts.size() elements —
    heap OOB on the divergent layout)."""

    def _container(self, key, rows):
        from filodb_tpu.core.record import IngestRecord, RecordContainer
        c = RecordContainer()
        for ts, values in rows:
            c.add(IngestRecord(key, ts, values))
        return BytesContainer(c.serialize())

    def test_shrinking_value_count_pads_nan(self):
        key = machine_metrics_series(1)[0]
        ms = TimeSeriesMemStore(InMemoryColumnStore(), InMemoryMetaStore())
        shard = ms.setup("ds", 0, StoreConfig(max_chunk_size=2,
                                              groups_per_shard=1,
                                              native_ingest=True))
        # first record establishes 2 columns; second carries only 1 value.
        # max_chunk_size=2 seals immediately — the encoder walk over
        # ts.size() elements is exactly the OOB read being regressed.
        bad = self._container(key, [(1000, (1.0, 2.0)), (2000, (3.0,))])
        shard.ingest(SomeData(bad, 0))
        assert shard._native_core is not None
        part = shard.partitions[0]
        ts, vals = part.read_samples(0, 10**15)
        np.testing.assert_array_equal(ts, [1000, 2000])
        np.testing.assert_array_equal(vals, [1.0, 3.0])
        # the SECOND column is where the divergence lived: it must have
        # grown in lockstep (NaN pad), and the sealed encoding of exactly
        # ts.size() elements must round-trip
        from filodb_tpu.memory.codecs import decode_any
        [chunk] = part.chunks
        col1 = decode_any(chunk.vectors[2])
        assert len(col1) == 2
        assert col1[0] == 2.0 and np.isnan(col1[1])

    def test_growing_value_count_drops_extras(self):
        key = machine_metrics_series(1)[0]
        ms = TimeSeriesMemStore(InMemoryColumnStore(), InMemoryMetaStore())
        shard = ms.setup("ds", 0, StoreConfig(max_chunk_size=2,
                                              groups_per_shard=1,
                                              native_ingest=True))
        bad = self._container(key, [(1000, (1.0,)), (2000, (3.0, 9.0))])
        shard.ingest(SomeData(bad, 0))
        ts, vals = shard.partitions[0].read_samples(0, 10**15)
        np.testing.assert_array_equal(ts, [1000, 2000])
        np.testing.assert_array_equal(vals, [1.0, 3.0])
