"""Aggregate-pyramid tests: O(log) cold-tier range folds and the
sketch-served approximate lane.

Covers the zero-payload guarantee (interior windows fold stored
segment/bucket summaries — the objectstore payload-bytes counter must
not move), bucket-level composition after compaction, exact bitwise
parity between stored-summary and recompute-from-decode provenance
modes across the eligible-fn sweep, compaction backfill over legacy
FSG1 segments (including the mid-backfill read-race window: queries
demote to chunk fallback, never error), the ``FILODB_SIDECAR_APPROX``
lane (sketch quantiles with factor-of-two bounds, summary-only topk /
count-distinct), and ``queryStats`` pyramid attribution end to end
through the Prom JSON renderer.
"""

import glob
import json
import os
from unittest import mock

import numpy as np
import pytest

import filodb_tpu.core.store.objectstore as osmod
from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.coordinator.tiered_planner import build_tiered_planner
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store import pyramid as pyrmod
from filodb_tpu.core.store.api import InMemoryMetaStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.core.store.objectstore import ObjectStoreColumnStore
from filodb_tpu.promql.parser import TimeStepParams, parse_query
from filodb_tpu.query.exec.plan import ExecContext
from filodb_tpu.testing.data import (
    counter_series,
    counter_stream,
    gauge_stream,
    machine_metrics_series,
)
from filodb_tpu.testing.fake_s3 import FakeS3
from filodb_tpu.utils.resilience import RetryPolicy

START = 1_600_000_000
NOW = (START + 6000) * 1000
MEM_FLOOR = (START + 4000) * 1000  # steps reaching below this go cold


def _make_memstore(cs):
    ms = TimeSeriesMemStore(cs, InMemoryMetaStore())
    for s in range(2):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=120,
                                              groups_per_shard=2))
    return ms


def _env(tmp_path, flushes=1, compact=False, counter=False):
    """Writer + independent reader over one FakeS3 root. ``flushes``
    splits the 600-sample ingest into that many flush rounds (>=2 gives
    multi-segment buckets so ``compact`` has something to merge)."""
    s3root = str(tmp_path / "s3")
    s3 = FakeS3(root=s3root)
    cs = ObjectStoreColumnStore(s3)
    ms = _make_memstore(cs)
    if counter:
        assert flushes == 1  # counter_stream has no offset resume
        keys = counter_series(4)
        streams = [counter_stream(keys, 600, start_ms=START * 1000,
                                  seed=7)]
    else:
        keys = machine_metrics_series(6)
        per = 600 // flushes
        streams = [gauge_stream(keys, per,
                                start_ms=(START + i * per * 10) * 1000,
                                start_offset=1000 * i)
                   for i in range(flushes)]
    for stream in streams:
        ingest_routed(ms, "timeseries", stream, 2, spread=0)
        ms.flush_all("timeseries")
        cs.flush()  # seal per round: multi-segment buckets for compact
    if compact:
        for s in range(2):
            cs.compact("timeseries", s)
        cs.flush()
    read_s3 = FakeS3(root=s3root)
    read_cs = ObjectStoreColumnStore(
        read_s3, read_retry_policy=RetryPolicy(max_attempts=2,
                                               base_backoff_s=0.01,
                                               max_backoff_s=0.05))
    planner = build_tiered_planner(
        SingleClusterPlanner("timeseries", 2, spread=0), read_cs,
        "timeseries", 2, mem_retention_ms=NOW - MEM_FLOOR,
        raw_retention_ms=None, ds_planner=None, now_ms=lambda: NOW)
    return ms, cs, planner, read_s3, read_cs, keys


def _run(ms, planner, promql, start, step, end):
    plan = parse_query(promql, TimeStepParams(start, step, end))
    ep = planner.materialize(plan)
    ctx = ExecContext(ms, "timeseries")
    return ep.dispatcher.dispatch(ep, ctx), ctx


def _row_order(a, b):
    pos = {k: i for i, k in enumerate(a.keys)}
    return np.array([pos[k] for k in b.keys], dtype=np.int64)


def _assert_matches_control(ms, planner, q, start, step, end,
                            rtol=2e-5):
    r, ctx = _run(ms, planner, q, start, step, end)
    assert not r.partial
    ctl, _ = _run(ms, SingleClusterPlanner("timeseries", 2, spread=0),
                  q, start, step, end)
    assert r.result.num_series == ctl.result.num_series
    ctl_vals = ctl.result.values[_row_order(ctl.result, r.result)]
    np.testing.assert_allclose(r.result.values, ctl_vals, rtol=rtol,
                               equal_nan=True)
    return r, ctx


# chunk geometry with one 600-sample flush: 5 chunks per series, each
# 120 samples at 10s cadence -> ends at +1190s, +2390, +3590, +4790,
# +5990. A grid pinned to chunk ends with the window reaching before
# the first sample has NO seam decodes: every touched node is interior.
ALIGNED = (START + 1190, 1200, START + 3590)


class TestZeroPayload:
    def test_interior_scan_pages_zero_chunk_payload_bytes(self, tmp_path):
        ms, cs, planner, s3, read_cs, keys = _env(tmp_path)
        payload0 = osmod.PAYLOAD_BYTES_DOWN.value
        r, ctx = _assert_matches_control(
            ms, planner, "sum_over_time(heap_usage[4000s])", *ALIGNED)
        assert osmod.PAYLOAD_BYTES_DOWN.value == payload0
        p = ctx.stats.pyramid
        assert p["payloadBytes"] == 0
        assert p.get("decodeNodes", 0) == 0
        assert p.get("chunkNodes", 0) + p.get("segmentNodes", 0) > 0
        assert p["pyramidBytes"] > 0  # served from fetched summaries

    def test_full_segment_window_folds_segment_nodes(self, tmp_path):
        ms, cs, planner, s3, read_cs, keys = _env(tmp_path)
        payload0 = osmod.PAYLOAD_BYTES_DOWN.value
        # window covers every chunk of every series: each partition
        # collapses to ONE interior segment-level node
        r, ctx = _assert_matches_control(
            ms, planner, "sum_over_time(heap_usage[6100s])",
            START + 5990, 300, START + 5990)
        assert osmod.PAYLOAD_BYTES_DOWN.value == payload0
        p = ctx.stats.pyramid
        assert p["segmentNodes"] == 6  # one per series
        assert p.get("chunkNodes", 0) == 0
        assert p.get("decodeNodes", 0) == 0

    def test_bucket_nodes_after_compaction(self, tmp_path):
        ms, cs, planner, s3, read_cs, keys = _env(tmp_path, flushes=2,
                                                  compact=True)
        payload0 = osmod.PAYLOAD_BYTES_DOWN.value
        r, ctx = _assert_matches_control(
            ms, planner, "sum_over_time(heap_usage[6100s])",
            START + 5990, 300, START + 5990)
        assert osmod.PAYLOAD_BYTES_DOWN.value == payload0
        p = ctx.stats.pyramid
        # compaction rolled each bucket into one segment + bucket
        # pyramid; the full-history window folds the bucket level
        assert p["bucketNodes"] == 6
        assert p.get("segmentNodes", 0) == 0
        assert p.get("decodeNodes", 0) == 0

    def test_seam_windows_decode_only_edges(self, tmp_path):
        """A non-aligned grid still serves, paying only edge decodes."""
        ms, cs, planner, s3, read_cs, keys = _env(tmp_path)
        r, ctx = _assert_matches_control(
            ms, planner, "sum_over_time(heap_usage[40m])",
            START + 1000, 700, START + 3500)
        p = ctx.stats.pyramid
        assert p.get("decodeNodes", 0) > 0   # seam chunks paid
        assert p.get("chunkNodes", 0) > 0    # interiors still free


GAUGE_FNS = [
    "sum_over_time", "avg_over_time", "min_over_time", "max_over_time",
    "count_over_time", "stddev_over_time", "stdvar_over_time",
    "last_over_time", "present_over_time", "changes", "resets", "delta",
]


class TestProvenanceParity:
    """Stored-summary mode ("1") vs recompute-from-decode mode
    ("decode") must agree BITWISE: codecs are lossless and both modes
    run the identical strict-left merge fold."""

    def _sweep(self, ms, planner, q, monkeypatch):
        span = (START + 900, 300, START + 3500)
        store = planner.cold_planner.store
        outs = {}
        for mode in ("1", "decode"):
            monkeypatch.setenv("FILODB_SIDECARS", mode)
            store.clear_caches()
            r, ctx = _run(ms, planner, q, *span)
            assert not r.partial
            assert ctx.stats.pyramid, (q, mode)  # lane actually served
            outs[mode] = r
        monkeypatch.setenv("FILODB_SIDECARS", "0")
        store.clear_caches()
        ctl, _ = _run(ms, planner, q, *span)
        monkeypatch.delenv("FILODB_SIDECARS")
        a, b = outs["1"].result, outs["decode"].result
        order = _row_order(b, a)
        assert a.values.tobytes() == b.values[order].tobytes(), q
        ctl_vals = ctl.result.values[_row_order(ctl.result, a)]
        np.testing.assert_allclose(a.values, ctl_vals, rtol=2e-5,
                                   equal_nan=True)

    def test_gauge_fn_sweep_bitwise(self, tmp_path, monkeypatch):
        ms, cs, planner, s3, read_cs, keys = _env(tmp_path)
        for fn in GAUGE_FNS:
            self._sweep(ms, planner, f"{fn}(heap_usage[25m])",
                        monkeypatch)

    def test_counter_rate_increase_bitwise(self, tmp_path, monkeypatch):
        ms, cs, planner, s3, read_cs, keys = _env(tmp_path, counter=True)
        for fn in ("rate", "increase", "irate"):
            if fn == "irate":
                continue  # not sidecar-eligible; covered by decode lane
            self._sweep(ms, planner,
                        f"{fn}(http_requests_total[25m])", monkeypatch)


class TestLegacyBackfill:
    def test_fsg1_segments_serve_via_fallback_then_backfill(
            self, tmp_path):
        # write the whole history as legacy FSG1 (no pyramids)
        with mock.patch.object(osmod, "_MAGIC", b"FSG1"):
            ms, cs, planner, s3, read_cs, keys = _env(tmp_path,
                                                      flushes=2)
        assert not glob.glob(os.path.join(str(tmp_path), "s3", "**",
                                          "*.pyr"), recursive=True)
        # pre-backfill reads demote to chunk fallback — correct, no error
        fb0 = pyrmod.PYR_FALLBACK.value
        r, ctx = _assert_matches_control(
            ms, planner, "max_over_time(heap_usage[4000s])", *ALIGNED)
        assert pyrmod.PYR_FALLBACK.value > fb0
        assert ctx.stats.pyramid.get("decodeNodes", 0) > 0

        # compaction (FSG2 writer again) backfills pyramid coverage
        bf0 = pyrmod.PYR_BACKFILLED.value
        removed = sum(cs.compact("timeseries", s) for s in range(2))
        cs.flush()
        assert removed > 0
        assert pyrmod.PYR_BACKFILLED.value > bf0
        assert glob.glob(os.path.join(str(tmp_path), "s3", "**",
                                      "*.pyr"), recursive=True)

        # a fresh reader over the compacted bucket folds zero payloads
        read_cs2 = ObjectStoreColumnStore(FakeS3(
            root=str(tmp_path / "s3")))
        planner2 = build_tiered_planner(
            SingleClusterPlanner("timeseries", 2, spread=0), read_cs2,
            "timeseries", 2, mem_retention_ms=NOW - MEM_FLOOR,
            raw_retention_ms=None, ds_planner=None, now_ms=lambda: NOW)
        payload0 = osmod.PAYLOAD_BYTES_DOWN.value
        r2, ctx2 = _assert_matches_control(
            ms, planner2, "max_over_time(heap_usage[6100s])",
            START + 5990, 300, START + 5990)
        assert osmod.PAYLOAD_BYTES_DOWN.value == payload0
        assert ctx2.stats.pyramid["bucketNodes"] == 6

    def test_read_race_missing_pyramid_objects_never_error(
            self, tmp_path):
        """Manifest advertises pyramids a concurrent compaction already
        deleted (the mid-backfill window): the reader demotes to chunk
        fallback and stays exact."""
        ms, cs, planner, s3, read_cs, keys = _env(tmp_path)
        pyrs = glob.glob(os.path.join(str(tmp_path), "s3", "**",
                                      "*.pyr"), recursive=True)
        assert pyrs
        for f in pyrs:
            os.remove(f)
        fb0 = pyrmod.PYR_FALLBACK.value
        r, ctx = _assert_matches_control(
            ms, planner, "sum_over_time(heap_usage[4000s])", *ALIGNED)
        assert pyrmod.PYR_FALLBACK.value > fb0
        assert not r.partial and not r.warnings


class TestApproxLane:
    def test_quantile_served_from_sketches_within_bounds(
            self, tmp_path, monkeypatch):
        ms, cs, planner, s3, read_cs, keys = _env(tmp_path)
        q = "quantile_over_time(0.9,heap_usage[4000s])"
        # exact control first (approx off: decode path)
        ctl, _ = _run(ms, SingleClusterPlanner("timeseries", 2,
                                               spread=0), q, *ALIGNED)
        monkeypatch.setenv("FILODB_SIDECAR_APPROX", "1")
        planner.cold_planner.store.clear_caches()
        r, ctx = _run(ms, planner, q, *ALIGNED)
        assert not r.partial
        assert ctx.stats.pyramid  # pyramid lane served the fold
        ctl_vals = ctl.result.values[_row_order(ctl.result, r.result)]
        # log2-sketch quantiles are bounded by the bucket width: the
        # estimate sits within a factor of two of the true quantile
        ratio = r.result.values / ctl_vals
        assert np.isfinite(ratio).all()
        assert (ratio >= 0.45).all() and (ratio <= 2.2).all()

    def test_quantile_exact_without_declared_approx(self, tmp_path):
        ms, cs, planner, s3, read_cs, keys = _env(tmp_path)
        assert os.environ.get("FILODB_SIDECAR_APPROX", "0") != "1"
        q = "quantile_over_time(0.9,heap_usage[4000s])"
        # undeclared: the pyramid lane refuses and the decode path
        # answers exactly
        r, ctx = _assert_matches_control(ms, planner, q, *ALIGNED,
                                         rtol=1e-9)
        assert not ctx.stats.pyramid

    def test_topk_and_cardinality_summary_only(self, tmp_path,
                                               monkeypatch):
        ms, cs, planner, s3, read_cs, keys = _env(tmp_path, flushes=2,
                                                  compact=True)
        store = planner.cold_planner.store
        with pytest.raises(RuntimeError, match="FILODB_SIDECAR_APPROX"):
            store.approx_topk(3)
        with pytest.raises(RuntimeError, match="FILODB_SIDECAR_APPROX"):
            store.approx_cardinality()
        monkeypatch.setenv("FILODB_SIDECAR_APPROX", "1")
        payload0 = osmod.PAYLOAD_BYTES_DOWN.value
        top = store.approx_topk(10)
        card = store.approx_cardinality()
        assert osmod.PAYLOAD_BYTES_DOWN.value == payload0
        # topk values are EXACT per-series maxima (S_MAX merges are
        # lossless; the sketch only caps how many keys it tracks)
        ctl, _ = _run(ms, SingleClusterPlanner("timeseries", 2,
                                               spread=0),
                      "max_over_time(heap_usage[6100s])",
                      START + 5990, 300, START + 5990)
        truth = {k.label_map["instance"]: float(ctl.result.values[i, -1])
                 for i, k in enumerate(ctl.result.keys)}
        assert len(top) == 6
        got = {e["labels"]["instance"]: e["value"] for e in top}
        assert got == pytest.approx(truth)
        vals = [e["value"] for e in top]
        assert vals == sorted(vals, reverse=True)
        # HLL count-distinct within its error bound (σ≈3.25%, small-n
        # range uses linear counting: near exact at 6 series)
        assert abs(card - 6) / 6 < 0.10


class TestStatsAttribution:
    def test_tier_buckets_and_promjson_pyramid_keys(self, tmp_path):
        from filodb_tpu.http.promjson import matrix_json_str
        from filodb_tpu.query.federation import OBJECTSTORE
        ms, cs, planner, s3, read_cs, keys = _env(tmp_path)
        r, ctx = _run(ms, planner, "sum_over_time(heap_usage[4000s])",
                      *ALIGNED)
        p = ctx.stats.pyramid
        for k in ("segmentNodes", "chunkNodes", "decodeNodes",
                  "pyramidBytes", "payloadBytes"):
            assert k in p, k
        # per-tier attribution: the cold bucket carries the same keys
        tier = ctx.stats.tiers[OBJECTSTORE]
        assert tier["pyramidBytes"] == p["pyramidBytes"]
        assert tier["payloadBytes"] == p["payloadBytes"]
        # stats=all renders them; the default stats block does not
        r.stats = ctx.stats
        full = json.loads(matrix_json_str(r, full_stats=True))
        assert full["queryStats"]["pyramid"]["payloadBytes"] == 0
        brief = json.loads(matrix_json_str(r, full_stats=False))
        assert "pyramid" not in brief["queryStats"]
