"""Runtime shared-state race sanitizer (filodb_tpu/utils/racecheck.py).

Each scenario registers fresh objects INSIDE an installed session (only
objects registered after install are tracked) and checks what the
Eraser-style lockset tracker records — and what it does not. Guard
identity comes from lockcheck's creation-site keys, so every scenario
runs under both checkers, exactly as the chaos fixtures arm them.
"""

import threading

import pytest

from filodb_tpu.utils import lockcheck, racecheck


@pytest.fixture(autouse=True)
def _clean_install():
    racecheck.uninstall()
    lockcheck.uninstall()
    yield
    racecheck.uninstall()
    lockcheck.uninstall()


class Shared:
    pass


def write_from_thread(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()


class TestLockset:
    def test_guard_free_write_flagged(self):
        with racecheck.session():
            obj = racecheck.register(Shared(), "t.obj")
            write_from_thread(lambda: setattr(obj, "x", 1))
            obj.x = 2
            vs = racecheck.violations()
        assert [v.kind for v in vs] == ["guard-free"]
        assert "t.obj.x" in vs[0].detail

    def test_common_guard_clean(self):
        with racecheck.session():
            lk = threading.Lock()
            obj = racecheck.register(Shared(), "t.obj")

            def w():
                with lk:
                    obj.x = 1

            write_from_thread(w)
            with lk:
                obj.x = 2
            vs = racecheck.violations()
        assert vs == []

    def test_mixed_guard_flagged(self):
        with racecheck.session():
            la = threading.Lock()
            lb = threading.Lock()
            obj = racecheck.register(Shared(), "t.obj")

            def w():
                with la:
                    obj.x = 1

            write_from_thread(w)
            with lb:
                obj.x = 2
            vs = racecheck.violations()
        assert [v.kind for v in vs] == ["mixed-guard"]

    def test_single_thread_needs_no_lock(self):
        # Eraser's point: single-threaded state is not a race, however
        # it is written
        with racecheck.session():
            obj = racecheck.register(Shared(), "t.obj")
            obj.x = 1
            with threading.Lock():
                obj.x = 2
            obj.x = 3
            vs = racecheck.violations()
        assert vs == []

    def test_one_outer_lock_among_several_clean(self):
        # writers may hold extra locks as long as ONE stays common
        with racecheck.session():
            common = threading.Lock()
            extra = threading.Lock()
            obj = racecheck.register(Shared(), "t.obj")

            def w():
                with common:
                    with extra:
                        obj.x = 1

            write_from_thread(w)
            with common:
                obj.x = 2
            vs = racecheck.violations()
        assert vs == []

    def test_duplicate_shapes_reported_once(self):
        with racecheck.session():
            obj = racecheck.register(Shared(), "t.obj")
            write_from_thread(lambda: setattr(obj, "x", 1))
            for i in range(5):
                obj.x = i
            vs = racecheck.violations()
        assert len(vs) == 1

    def test_unregistered_object_ignored(self):
        with racecheck.session():
            racecheck.register(Shared(), "t.tracked")
            loose = Shared()   # same class, never registered
            write_from_thread(lambda: setattr(loose, "x", 1))
            loose.x = 2
            vs = racecheck.violations()
        assert vs == []

    def test_strict_mode_raises(self):
        with racecheck.session(strict=True):
            obj = racecheck.register(Shared(), "t.obj")
            write_from_thread(lambda: setattr(obj, "x", 1))
            with pytest.raises(racecheck.RaceViolation):
                obj.x = 2


class TestTrackedDict:
    def test_per_key_guard_free_flagged(self):
        with racecheck.session():
            d = racecheck.tracked_dict("t.map")
            write_from_thread(lambda: d.__setitem__("k", 1))
            d["k"] = 2
            vs = racecheck.violations()
        assert [v.kind for v in vs] == ["guard-free"]
        assert "t.map" in vs[0].detail

    def test_distinct_keys_are_distinct_cells(self):
        # two threads each owning their own key is not a race
        with racecheck.session():
            d = racecheck.tracked_dict("t.map")
            write_from_thread(lambda: d.__setitem__("a", 1))
            d["b"] = 2
            vs = racecheck.violations()
        assert vs == []

    def test_stays_a_real_dict(self):
        with racecheck.session():
            d = racecheck.tracked_dict("t.map", {"a": 1})
            assert isinstance(d, dict)
            assert dict(d) == {"a": 1}
            d.update(b=2)
            assert d.pop("a") == 1
            assert d.setdefault("c", 3) == 3
            d.clear()
            assert d == {}

    def test_plain_dict_when_uninstalled(self):
        d = racecheck.tracked_dict("t.map", {"a": 1})
        assert type(d) is dict


class TestWireCompat:
    def test_registered_manifest_still_encodes(self):
        # the tracker patches __setattr__ on the ORIGINAL class — it
        # must never swap __class__, because wire encode checks exact
        # class identity and MigrationManifest is wire-registered
        from filodb_tpu.coordinator import wire
        from filodb_tpu.coordinator.migration import MigrationManifest

        with racecheck.session():
            m = MigrationManifest("ds", 3, "a", "b")
            assert type(m) is MigrationManifest
            assert wire.decode(wire.encode(m)) == m
            m.phase = "syncing"   # tracked write keeps working
            assert wire.decode(wire.encode(m)).phase == "syncing"


class TestLifecycle:
    def test_install_installs_lockcheck_and_uninstall_undoes(self):
        assert not lockcheck.installed()
        racecheck.install()
        assert racecheck.installed()
        # guard sets come from lockcheck's held stack, so install
        # piggybacks it...
        assert lockcheck.installed()
        racecheck.uninstall()
        assert not racecheck.installed()
        # ...and uninstall tears the piggyback down again
        assert not lockcheck.installed()

    def test_does_not_steal_existing_lockcheck(self):
        lockcheck.install(strict=False)
        racecheck.install()
        racecheck.uninstall()
        assert lockcheck.installed()
        lockcheck.uninstall()

    def test_class_patch_removed_on_uninstall(self):
        racecheck.install()
        obj = racecheck.register(Shared(), "t.obj")
        assert "__setattr__" in Shared.__dict__
        racecheck.uninstall()
        assert "__setattr__" not in Shared.__dict__
        obj.x = 1   # plain write, no tracking, no error

    def test_register_is_noop_when_uninstalled(self):
        obj = Shared()
        assert racecheck.register(obj, "t.obj") is obj
        assert "__setattr__" not in Shared.__dict__

    def test_reset_clears_cells_and_violations(self):
        racecheck.install()
        obj = racecheck.register(Shared(), "t.obj")
        write_from_thread(lambda: setattr(obj, "x", 1))
        obj.x = 2
        assert racecheck.violations()
        racecheck.reset()
        assert racecheck.violations() == []
        # cells cleared too: the next write pair re-evaluates fresh
        write_from_thread(lambda: setattr(obj, "x", 3))
        obj.x = 4
        assert [v.kind for v in racecheck.violations()] == ["guard-free"]
        racecheck.uninstall()

    def test_metrics_registry_swapped_and_restored(self):
        from filodb_tpu.utils import metrics
        racecheck.install()
        assert isinstance(metrics._registry, racecheck._TrackedDict)
        racecheck.uninstall()
        assert type(metrics._registry) is dict

    def test_env_flag(self, monkeypatch):
        monkeypatch.delenv("FILODB_RACECHECK", raising=False)
        assert not racecheck.enabled_by_env()
        monkeypatch.setenv("FILODB_RACECHECK", "0")
        assert not racecheck.enabled_by_env()
        monkeypatch.setenv("FILODB_RACECHECK", "1")
        assert racecheck.enabled_by_env()
