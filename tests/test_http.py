"""HTTP API tests against a live in-process server.

Mirrors reference ``http/src/test/scala/filodb/http/PrometheusApiRouteSpec``.
"""

import json
import numpy as np
import urllib.parse
import urllib.request

import pytest

from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.http.server import FiloHttpServer
from filodb_tpu.testing.data import counter_series, counter_stream

START = 1_600_000_000


@pytest.fixture(scope="module", params=["threaded", "fast"])
def server(request):
    """Every API test runs against BOTH fronts: the threaded stdlib server
    and the selector event-loop server (shared HttpDispatcher routing)."""
    ms = TimeSeriesMemStore()
    for s in range(4):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=100))
    keys = counter_series(5, metric="http_requests_total")
    ingest_routed(ms, "timeseries",
                  counter_stream(keys, 400, start_ms=START * 1000), 4, 1)
    svc = QueryService(ms, "timeseries", 4, spread=1)
    if request.param == "fast":
        from filodb_tpu.http.fastserver import FastHttpServer
        srv = FastHttpServer({"timeseries": svc}, port=0).start()
    else:
        srv = FiloHttpServer({"timeseries": svc}, port=0).start()
    yield srv
    srv.stop()


def get(server, path, **params):
    qs = urllib.parse.urlencode(params, doseq=True)
    url = f"http://127.0.0.1:{server.port}{path}" + (f"?{qs}" if qs else "")
    with urllib.request.urlopen(url) as r:
        return r.status, json.loads(r.read())


class TestPromApi:
    def test_query_range(self, server):
        code, body = get(
            server, "/promql/timeseries/api/v1/query_range",
            query='sum(rate(http_requests_total[5m]))',
            start=START + 600, end=START + 3000, step=60)
        assert code == 200 and body["status"] == "success"
        data = body["data"]
        assert data["resultType"] == "matrix"
        assert len(data["result"]) == 1
        values = data["result"][0]["values"]
        assert len(values) == 41
        ts0, v0 = values[0]
        assert ts0 == START + 600 and float(v0) > 0

    def test_query_instant(self, server):
        code, body = get(server, "/promql/timeseries/api/v1/query",
                         query="http_requests_total", time=START + 1000)
        assert code == 200
        data = body["data"]
        assert data["resultType"] == "vector"
        assert len(data["result"]) == 5
        assert data["result"][0]["metric"]["__name__"] == \
            "http_requests_total"

    def test_series(self, server):
        code, body = get(server, "/promql/timeseries/api/v1/series",
                         **{"match[]": "http_requests_total"},
                         start=START, end=START + 4000)
        assert code == 200 and len(body["data"]) == 5

    def test_labels_and_values(self, server):
        code, body = get(server, "/promql/timeseries/api/v1/labels")
        assert code == 200 and "instance" in body["data"]
        code, body = get(server,
                         "/promql/timeseries/api/v1/label/job/values")
        assert code == 200
        assert body["data"] == ["job-0", "job-1", "job-2"]

    def test_parse_error_400(self, server):
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as e:
            get(server, "/promql/timeseries/api/v1/query_range",
                query="sum(((", start=START, end=START + 60, step=60)
        assert e.value.code == 400

    def test_unknown_dataset_404(self, server):
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as e:
            get(server, "/promql/nope/api/v1/query", query="x", time=0)
        assert e.value.code == 404


class TestAdminApi:
    def test_health(self, server):
        code, body = get(server, "/__health")
        assert code == 200 and body["status"] == "healthy"

    def test_cluster_status(self, server):
        code, body = get(server, "/api/v1/cluster/timeseries/status")
        assert code == 200
        assert len(body["data"]) == 4
        assert sum(s["numPartitions"] for s in body["data"]) == 5

    def test_metrics_exposition(self, server):
        url = f"http://127.0.0.1:{server.port}/metrics"
        with urllib.request.urlopen(url) as r:
            text = r.read().decode()
        assert "rows_ingested_total" in text


class TestRemoteRead:
    def test_round_trip(self, server):
        from filodb_tpu.http import remote_read as rr
        from filodb_tpu.core.filters import ColumnFilter, Equals
        from filodb_tpu.core.partkey import METRIC_LABEL

        # build a ReadRequest: Query(start, end, matcher __name__ EQ ...)
        matcher = (rr._ld(2, b"__name__")
                   + rr._ld(3, b"http_requests_total"))
        query = (rr._key(1, 0) + rr._varint(START * 1000)
                 + rr._key(2, 0) + rr._varint((START + 4000) * 1000)
                 + rr._ld(3, matcher))
        req = rr._ld(1, query)

        import urllib.request
        u = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/promql/timeseries/api/v1/read",
            data=rr.maybe_compress(req), method="POST")
        with urllib.request.urlopen(u) as resp:
            payload = rr.maybe_decompress(resp.read())
        # decode response: 1 QueryResult with 5 TimeSeries x 400 samples
        n_series = 0
        n_samples = 0
        for field, _, qr in rr._iter_fields(payload):
            assert field == 1
            for f2, _, ts_msg in rr._iter_fields(qr):
                n_series += 1
                labels = {}
                for f3, _, v in rr._iter_fields(ts_msg):
                    if f3 == 1:
                        kv = dict()
                        for f4, _, x in rr._iter_fields(v):
                            kv[f4] = x.decode()
                        labels[kv[1]] = kv[2]
                    elif f3 == 2:
                        n_samples += 1
                assert labels["__name__"] == "http_requests_total"
        assert n_series == 5
        assert n_samples == 5 * 400

    def test_request_decode(self):
        from filodb_tpu.http import remote_read as rr
        from filodb_tpu.core.filters import EqualsRegex
        matcher = (rr._key(1, 0) + rr._varint(2)
                   + rr._ld(2, b"job") + rr._ld(3, b"api.*"))
        query = (rr._key(1, 0) + rr._varint(1000)
                 + rr._key(2, 0) + rr._varint(2000) + rr._ld(3, matcher))
        out = rr.decode_read_request(rr._ld(1, query))
        assert out[0]["start_ms"] == 1000 and out[0]["end_ms"] == 2000
        f = out[0]["filters"][0]
        assert f.column == "job" and isinstance(f.filter, EqualsRegex)


class TestStartStopShards:
    def test_stop_and_start_shard(self):
        import time as _time
        from filodb_tpu.coordinator.cluster import FilodbCluster, Node
        from filodb_tpu.core.store.api import (
            InMemoryColumnStore,
            InMemoryMetaStore,
        )
        from filodb_tpu.core.store.config import IngestionConfig
        from filodb_tpu.kafka.log import InMemoryLog

        cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
        cluster = FilodbCluster()
        cluster.join(Node("n1", TimeSeriesMemStore(cs, meta)))
        logs = {s: InMemoryLog() for s in range(2)}
        cluster.setup_dataset(
            IngestionConfig("timeseries", 2,
                            store=StoreConfig(max_chunk_size=50)), logs)
        assert cluster.wait_active("timeseries", 5)
        svc = QueryService(cluster.nodes["n1"].memstore, "timeseries", 2, 1)
        srv = FiloHttpServer({"timeseries": svc}, port=0,
                             cluster=cluster).start()
        try:
            code, body = get(srv, "/api/v1/cluster/timeseries/stopshards",
                             shards="1")
            assert code == 200 and body["data"] == [1]
            assert cluster.nodes["n1"].owned_shards("timeseries") == [0]
            code, body = get(srv, "/api/v1/cluster/timeseries/startshards",
                             shards="1", node="n1")
            assert code == 200 and body["data"] == [1]
            _time.sleep(0.1)
            assert cluster.nodes["n1"].owned_shards("timeseries") == [0, 1]
        finally:
            srv.stop()
            cluster.stop()


class TestFiloClient:
    def test_client_round_trip(self, server):
        from filodb_tpu.client import FiloClient, FiloClientError

        c = FiloClient(port=server.port)
        assert c.health()
        result = c.query_range('sum(rate(http_requests_total[5m]))',
                               START + 600, START + 1800, 60)
        assert len(result) == 1 and result[0]["values"]
        labels, values, steps = c.query_range_matrix(
            'rate(http_requests_total[5m])', START + 600, START + 1800, 60)
        assert values.shape == (5, 21)
        assert np.isfinite(values).all()
        assert c.label_values("job") == ["job-0", "job-1", "job-2"]
        assert "instance" in c.label_names()
        assert len(c.series("http_requests_total", START, START + 4000)) == 5
        inst = c.query("http_requests_total", START + 1000)
        assert len(inst) == 5
        with pytest.raises(FiloClientError):
            c.query_range("((bad", START, START + 60, 60)


class TestNameLabelMapping:
    def test_labels_shows_dunder_name(self, server):
        code, body = get(server, "/promql/timeseries/api/v1/labels")
        assert "__name__" in body["data"] and "_metric_" not in body["data"]

    def test_name_values(self, server):
        code, body = get(server,
                         "/promql/timeseries/api/v1/label/__name__/values")
        assert body["data"] == ["http_requests_total"]


class TestTimeFormats:
    def test_rfc3339_times(self, server):
        import datetime as dt
        start = dt.datetime.fromtimestamp(START + 600, dt.timezone.utc)
        end = dt.datetime.fromtimestamp(START + 1200, dt.timezone.utc)
        code, body = get(server, "/promql/timeseries/api/v1/query_range",
                         query="http_requests_total",
                         start=start.isoformat().replace("+00:00", "Z"),
                         end=end.isoformat().replace("+00:00", "Z"), step=60)
        assert code == 200
        assert len(body["data"]["result"]) == 5


class TestResponseCache:
    """The rendered-response cache must serve identical bytes on repeat and
    drop entries the moment any shard of the dataset applies a write."""

    @pytest.fixture()
    def fast(self):
        from filodb_tpu.http.fastserver import FastHttpServer
        ms = TimeSeriesMemStore()
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=100))
        keys = counter_series(3, metric="http_requests_total")
        ingest_routed(ms, "timeseries",
                      counter_stream(keys, 200, start_ms=START * 1000), 1, 0)
        svc = QueryService(ms, "timeseries", 1, spread=0)
        srv = FastHttpServer({"timeseries": svc}, port=0).start()
        yield srv, ms, keys
        srv.stop()

    def test_hit_and_invalidate(self, fast):
        srv, ms, keys = fast
        q = dict(query="count(http_requests_total)", time=START + 1500)
        _, r1 = get(srv, "/promql/timeseries/api/v1/query", **q)
        h0 = srv.response_cache.hits
        _, r2 = get(srv, "/promql/timeseries/api/v1/query", **q)
        assert r1 == r2
        assert srv.response_cache.hits == h0 + 1

        # a write to the dataset orphans the entry: new series must appear
        more = counter_series(5, metric="http_requests_total")
        ingest_routed(ms, "timeseries",
                      counter_stream(more, 200, start_ms=START * 1000), 1, 0)
        _, r3 = get(srv, "/promql/timeseries/api/v1/query", **q)
        assert float(r3["data"]["result"][0]["value"][1]) == 5.0

    def test_instant_without_time_not_aliased(self, fast):
        srv, _, _ = fast
        # resolved-params keying: two bare instant queries in different
        # seconds must not collide (regression guard for raw-path keying)
        from filodb_tpu.http.server import HttpDispatcher
        q1, t1 = HttpDispatcher.instant_params({"query": ["up"]})
        import time as _t
        _t.sleep(1.1)
        q2, t2 = HttpDispatcher.instant_params({"query": ["up"]})
        assert (q1, t1) != (q2, t2)


class TestFastServerPipelining:
    def test_cold_then_hot_in_one_segment(self):
        """Regression: a flushed cold response must not shift the slot a
        pending hot (batched) request writes into."""
        import socket as _socket

        from filodb_tpu.http.fastserver import FastHttpServer

        ms = TimeSeriesMemStore()
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=100))
        keys = counter_series(2, metric="http_requests_total")
        ingest_routed(ms, "timeseries",
                      counter_stream(keys, 100, start_ms=START * 1000), 1, 0)
        svc = QueryService(ms, "timeseries", 1, spread=0)
        srv = FastHttpServer({"timeseries": svc}, port=0).start()
        try:
            q = urllib.parse.urlencode(dict(
                query="count(http_requests_total)", time=START + 500))
            req = (b"GET /__health HTTP/1.1\r\nHost: x\r\n\r\n"
                   b"GET /promql/timeseries/api/v1/query?" + q.encode()
                   + b" HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n")
            with _socket.create_connection(("127.0.0.1", srv.port),
                                           timeout=10) as s:
                s.sendall(req)
                buf = b""
                while True:
                    chunk = s.recv(65536)
                    if not chunk:
                        break
                    buf = buf + chunk
            assert buf.count(b"HTTP/1.1 200") == 2
            assert b"healthy" in buf
            assert b'"2.0"' in buf  # count(http_requests_total) == 2
        finally:
            srv.stop()


class TestClusterCacheBypass:
    """ADVICE r3 (high): a facade that does not host every shard locally
    cannot witness remote ingest in its data_version stamp, so the response
    cache must be bypassed entirely (never served, never populated)."""

    def test_partial_local_shards_disable_cache(self):
        from filodb_tpu.http.server import service_version

        ms = TimeSeriesMemStore()
        # host only 1 of the dataset's shards locally → stamp must be None
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=100))
        keys = counter_series(2, metric="http_requests_total")
        ingest_routed(ms, "timeseries",
                      counter_stream(keys, 50, start_ms=START * 1000), 1, 0)
        svc = QueryService(ms, "timeseries", 1, spread=0)
        # simulate the cluster facade: the dataset spans 4 shards but only
        # shard 0 is resident (the planner still routes locally here)
        svc.num_shards = 4
        assert service_version(svc) is None

        srv = FiloHttpServer({"timeseries": svc}, port=0).start()
        try:
            q = dict(query="count(http_requests_total)", time=START + 100)
            get(srv, "/promql/timeseries/api/v1/query", **q)
            get(srv, "/promql/timeseries/api/v1/query", **q)
            assert srv.response_cache.hits == 0
            assert len(srv.response_cache._lru) == 0
        finally:
            srv.stop()

    def test_full_local_shards_keep_cache(self):
        from filodb_tpu.http.server import service_version

        ms = TimeSeriesMemStore()
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=100))
        svc = QueryService(ms, "timeseries", 1, spread=0)
        assert service_version(svc) is not None


class TestFastServerChunkedTE:
    def test_chunked_transfer_encoding_rejected(self):
        """ADVICE r3 (medium): a chunked body must not be parsed as
        pipelined requests — the server answers 501 and closes."""
        import socket as _socket

        from filodb_tpu.http.fastserver import FastHttpServer

        ms = TimeSeriesMemStore()
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=100))
        svc = QueryService(ms, "timeseries", 1, spread=0)
        srv = FastHttpServer({"timeseries": svc}, port=0).start()
        try:
            body = (b"5\r\nGET /\r\n0\r\n\r\n")
            req = (b"POST /promql/timeseries/api/v1/query HTTP/1.1\r\n"
                   b"Host: x\r\nTransfer-Encoding: chunked\r\n\r\n" + body)
            with _socket.create_connection(("127.0.0.1", srv.port),
                                           timeout=10) as s:
                s.sendall(req)
                buf = b""
                while True:
                    chunk = s.recv(65536)
                    if not chunk:
                        break
                    buf += chunk
            assert buf.startswith(b"HTTP/1.1 501")
            # exactly one response: the chunked bytes were NOT desynced
            # into extra pipelined requests
            assert buf.count(b"HTTP/1.1 ") == 1
        finally:
            srv.stop()

    def test_duplicate_conflicting_content_length_rejected(self):
        """Differing duplicate Content-Length headers are the CL.CL request
        smuggling vector — the connection must be dropped, not desynced."""
        import socket as _socket

        from filodb_tpu.http.fastserver import FastHttpServer

        ms = TimeSeriesMemStore()
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=100))
        svc = QueryService(ms, "timeseries", 1, spread=0)
        srv = FastHttpServer({"timeseries": svc}, port=0).start()
        try:
            req = (b"POST /__health HTTP/1.1\r\nHost: x\r\n"
                   b"Content-Length: 10\r\nContent-Length: 0\r\n\r\n"
                   b"GET / HTTP")
            with _socket.create_connection(("127.0.0.1", srv.port),
                                           timeout=10) as s:
                s.sendall(req)
                buf = b""
                while True:
                    chunk = s.recv(65536)
                    if not chunk:
                        break
                    buf += chunk
            assert buf == b""  # dropped without a response, nothing desynced
        finally:
            srv.stop()
