"""Persistent part-key index snapshots (reference PartKeyLuceneIndex
durability + IndexBootstrapper): snapshot → restart → delta replay."""

import numpy as np
import pytest

from filodb_tpu.core.filters import ColumnFilter, Equals
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.memstore.native_shard import native_available
from filodb_tpu.core.record import BytesContainer, SomeData
from filodb_tpu.core.store.api import InMemoryColumnStore, InMemoryMetaStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.core.store.localstore import (
    LocalDiskColumnStore,
    LocalDiskMetaStore,
)
from filodb_tpu.testing.data import (
    gauge_stream,
    histogram_series,
    histogram_stream,
    machine_metrics_series,
)

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native library unavailable")


def bytes_stream(stream, extra_offset=0):
    for sd in stream:
        yield SomeData(BytesContainer(sd.container.serialize()),
                       sd.offset + extra_offset)


def small_cfg(**kw):
    d = dict(max_chunk_size=50, groups_per_shard=2)
    d.update(kw)
    return StoreConfig(**d)


class TestSnapshotRoundTrip:
    def build(self, cs, meta, n_series=6, n_samples=40):
        ms = TimeSeriesMemStore(cs, meta)
        shard = ms.setup("ds", 0, small_cfg())
        keys = machine_metrics_series(n_series)
        for sd in bytes_stream(gauge_stream(keys, n_samples, batch=1)):
            shard.ingest(sd)
        shard.flush_all()
        return ms, shard, keys

    def test_restore_matches_full_rebuild(self, tmp_path):
        cs = LocalDiskColumnStore(str(tmp_path))
        meta = LocalDiskMetaStore(str(tmp_path))
        _, shard, keys = self.build(cs, meta)
        assert shard.snapshot_index() > 0

        # restart via snapshot
        ms2 = TimeSeriesMemStore(cs, meta)
        s2 = ms2.setup("ds", 0, small_cfg())
        n = s2.recover_index()
        assert n == 6
        assert s2.num_partitions == 6
        f = [ColumnFilter("_metric_", Equals("heap_usage"))]
        pids = s2.lookup_partitions(f, 0, 10**15)
        assert len(pids) == 6
        # lazy keys materialize correctly
        for pid in pids:
            assert s2.index.part_key(pid) == shard.index.part_key(pid)
        # floors restored: replaying flushed rows is a no-op
        s2.setup_watermarks_for_recovery()
        for sd in bytes_stream(gauge_stream(keys, 40, batch=1)):
            s2.ingest(sd)
        total = sum(p.num_samples for p in s2.partitions if p is not None)
        assert total == 0  # everything below watermark or floor

    def test_delta_partkeys_after_snapshot(self, tmp_path):
        cs = LocalDiskColumnStore(str(tmp_path))
        meta = LocalDiskMetaStore(str(tmp_path))
        _, shard, keys = self.build(cs, meta)
        shard.snapshot_index()
        # new series and chunks AFTER the snapshot
        new_keys = machine_metrics_series(2, metric="late_metric")
        for sd in bytes_stream(gauge_stream(new_keys, 30, batch=1),
                               extra_offset=10_000):
            shard.ingest(sd)
        shard.flush_all()

        ms2 = TimeSeriesMemStore(cs, meta)
        s2 = ms2.setup("ds", 0, small_cfg())
        n = s2.recover_index()
        assert n == 8  # 6 from snapshot + 2 delta
        f = [ColumnFilter("_metric_", Equals("late_metric"))]
        assert len(s2.lookup_partitions(f, 0, 10**15)) == 2
        # delta floors: replaying the late chunks doesn't duplicate
        s2.setup_watermarks_for_recovery()
        for sd in bytes_stream(gauge_stream(new_keys, 30, batch=1),
                               extra_offset=10_000):
            s2.ingest(sd)
        s2.flush_all()
        for key in new_keys:
            chunks = cs.read_chunks("ds", 0, key, 0, 10**15)
            all_ts = [t for c in chunks for t in c.decode_column(0)]
            assert len(all_ts) == len(set(all_ts))

    def test_snapshot_with_purged_and_hist_partitions(self, tmp_path):
        cs = LocalDiskColumnStore(str(tmp_path))
        meta = LocalDiskMetaStore(str(tmp_path))
        ms = TimeSeriesMemStore(cs, meta)
        shard = ms.setup("ds", 0, small_cfg(retention_ms=1_000_000))
        gkeys = machine_metrics_series(3)
        hkeys = histogram_series(1)
        for sd in bytes_stream(gauge_stream(gkeys, 10, batch=1)):
            shard.ingest(sd)
        for sd in bytes_stream(histogram_stream(hkeys, 10, batch=1),
                               extra_offset=100):
            shard.ingest(sd)
        late = machine_metrics_series(1, metric="fresh")
        for sd in bytes_stream(gauge_stream(late, 5, batch=1,
                                            start_ms=10_000_000),
                               extra_offset=200):
            shard.ingest(sd)
        # purge everything old (3 gauges + 1 hist), keep 'fresh'
        assert shard.purge_expired(now_ms=8_000_000) == 4
        shard.flush_all()
        shard.snapshot_index()

        ms2 = TimeSeriesMemStore(cs, meta)
        s2 = ms2.setup("ds", 0, small_cfg(retention_ms=1_000_000))
        n = s2.recover_index()
        assert n == 1
        f = [ColumnFilter("_metric_", Equals("fresh"))]
        assert len(s2.lookup_partitions(f, 0, 10**15)) == 1
        # tombstone pids stay dead; pid numbering is preserved
        assert s2.partitions[0] is None and s2.partitions[4] is not None

    def test_hist_partition_restored_and_odp_readable(self, tmp_path):
        cs = LocalDiskColumnStore(str(tmp_path))
        meta = LocalDiskMetaStore(str(tmp_path))
        ms = TimeSeriesMemStore(cs, meta)
        shard = ms.setup("ds", 0, small_cfg())
        hkeys = histogram_series(1)
        for sd in bytes_stream(histogram_stream(hkeys, 10, batch=1)):
            shard.ingest(sd)
        shard.flush_all()
        shard.snapshot_index()
        ms2 = TimeSeriesMemStore(cs, meta)
        s2 = ms2.setup("ds", 0, small_cfg())
        assert s2.recover_index() == 1
        # hist schemas ride the native ingest lane (round 5), so the
        # restored partition is native-backed
        assert type(s2.partitions[0]).__name__ == "NativeBackedPartition"
        # ODP still serves the flushed hist chunks through this partition
        from filodb_tpu.core.memstore.odp import page_partitions
        extra = page_partitions(s2, [s2.partitions[0]], 0, 10**15,
                                s2.odp_cache)
        ts, vals = s2.partitions[0].read_samples(
            0, 10**15, extra_chunks=extra.get(0))
        assert len(ts) == 10

    def test_corrupt_snapshot_falls_back_to_scan(self, tmp_path):
        cs = LocalDiskColumnStore(str(tmp_path))
        meta = LocalDiskMetaStore(str(tmp_path))
        _, shard, keys = self.build(cs, meta)
        cs.write_index_snapshot("ds", 0, b"FIDX2garbage-not-a-snapshot")
        ms2 = TimeSeriesMemStore(cs, meta)
        s2 = ms2.setup("ds", 0, small_cfg())
        assert s2.recover_index() == 6  # full scan fallback

    def test_cardinality_survives_restore(self, tmp_path):
        cs = LocalDiskColumnStore(str(tmp_path))
        meta = LocalDiskMetaStore(str(tmp_path))
        _, shard, keys = self.build(cs, meta)
        before = shard.cardinality.cardinality([]).active_ts
        assert before == 6
        shard.snapshot_index()
        ms2 = TimeSeriesMemStore(cs, meta)
        s2 = ms2.setup("ds", 0, small_cfg())
        s2.recover_index()
        assert s2.cardinality.cardinality([]).active_ts == 6

    def test_tailer_truncates_flushed_segments(self, tmp_path):
        # the shard owner (read-only tailer) drives WAL retention on the
        # shared FS; the appender survives the unlink and both sides skip
        # the deleted segment afterwards
        from filodb_tpu.kafka.log import SegmentedFileLog
        from filodb_tpu.testing.data import gauge_stream, machine_metrics_series
        keys = machine_metrics_series(1)
        writer = SegmentedFileLog(str(tmp_path / "wal"), segment_entries=4)
        for sd in gauge_stream(keys, 10, batch=1):
            writer.append(sd.container)
        tailer = SegmentedFileLog(str(tmp_path / "wal"), segment_entries=4,
                                  read_only=True)
        assert len(list(tailer.read_from(0))) == 10
        removed = tailer.truncate_before(8)
        assert removed == 2  # two wholly-flushed segments deleted
        assert [e.offset for e in tailer.read_from(0)] == [8, 9]
        # the appender keeps working and skips the deleted files
        for sd in gauge_stream(keys, 2, batch=1, start_ms=10**9):
            writer.append(sd.container)
        assert [e.offset for e in writer.read_from(0)] == [8, 9, 10, 11]
        writer.close()
        tailer.close()

    def test_stale_tailer_never_deletes_live_segments(self, tmp_path):
        # the tailer's record counts freeze at open; deletability must come
        # from the NEXT segment's first offset, or records appended after
        # the tailer opened (above the watermark) would be unlinked
        from filodb_tpu.kafka.log import SegmentedFileLog
        from filodb_tpu.testing.data import gauge_stream, machine_metrics_series
        keys = machine_metrics_series(1)
        writer = SegmentedFileLog(str(tmp_path / "wal"), segment_entries=4)
        stream = list(gauge_stream(keys, 8, batch=1))
        writer.append(stream[0].container)
        # tailer opens while seg-0 holds ONE record (stale count = 1)
        tailer = SegmentedFileLog(str(tmp_path / "wal"), segment_entries=4,
                                  read_only=True)
        for sd in stream[1:]:
            writer.append(sd.container)  # fills seg-0 (0..3), rolls seg-4
        # watermark only reached offset 1: seg-0 still holds live 2,3
        assert tailer.truncate_before(2) == 0
        assert [e.offset for e in tailer.read_from(2)] == [2, 3, 4, 5, 6, 7]
        # once the watermark passes the whole segment it may go
        assert tailer.truncate_before(4) == 1
        writer.close()
        tailer.close()

    def test_negative_filters_on_frozen_index_lazy(self, tmp_path):
        from filodb_tpu.core.filters import (
            ColumnFilter,
            Equals,
            NotEquals,
            NotEqualsRegex,
        )
        cs = LocalDiskColumnStore(str(tmp_path))
        meta = LocalDiskMetaStore(str(tmp_path))
        _, shard, keys = self.build(cs, meta)
        shard.snapshot_index()
        ms2 = TimeSeriesMemStore(cs, meta)
        s2 = ms2.setup("ds", 0, small_cfg())
        s2.recover_index()
        f_pos = [ColumnFilter("_metric_", Equals("heap_usage"))]
        want = set(s2.lookup_partitions(f_pos, 0, 10**15))
        inst0 = s2.index.part_key(sorted(want)[0]).label_map["instance"]
        got = s2.lookup_partitions(
            f_pos + [ColumnFilter("instance", NotEquals(inst0))], 0, 10**15)
        assert set(got) == want - {sorted(want)[0]}
        # absent label: negative regex matching "" keeps label-less series
        got2 = s2.lookup_partitions(
            f_pos + [ColumnFilter("no_such_label", NotEqualsRegex("x.*"))],
            0, 10**15)
        assert set(got2) == want
        # keys were not mass-materialized by the negative filter
        # (entries stay unset sentinels or raw blobs until someone needs
        # the actual PartKey; we materialized exactly one above)
        from filodb_tpu.core.partkey import PartKey
        materialized = sum(1 for k in s2.index._part_keys._items
                           if isinstance(k, PartKey))
        assert materialized <= 1

    def test_failed_restore_resets_cardinality(self, tmp_path):
        cs = LocalDiskColumnStore(str(tmp_path))
        meta = LocalDiskMetaStore(str(tmp_path))
        _, shard, keys = self.build(cs, meta)
        shard.snapshot_index()
        ms2 = TimeSeriesMemStore(cs, meta)
        s2 = ms2.setup("ds", 0, small_cfg())
        # force the delta-replay step to explode AFTER load_snapshot loaded
        # the cardinality state
        def boom(*a, **kw):
            raise RuntimeError("delta exploded")
        cs.scan_part_keys_since = boom
        assert s2.recover_index() == 6  # fallback full scan
        # tracker counts are NOT doubled by the fallback
        assert s2.cardinality.cardinality([]).active_ts == 6

    def test_inmemory_store_snapshot(self):
        cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
        _, shard, keys = self.build(cs, meta)
        shard.snapshot_index()
        ms2 = TimeSeriesMemStore(cs, meta)
        s2 = ms2.setup("ds", 0, small_cfg())
        assert s2.recover_index() == 6
