"""Downsampling tests: downsamplers, batch job, ds read store, and the
raw-vs-downsample split planner.

Mirrors reference ``ShardDownsamplerSpec``, ``DownsamplerMainSpec`` and
``LongTimeRangePlannerSpec``.
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.longtime_planner import LongTimeRangePlanner
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.downsample import (
    DownsampledTimeSeriesStore,
    DownsamplerJob,
    downsample_partition,
)
from filodb_tpu.core.downsample.downsampler import (
    downsample_samples,
    ds_dataset_name,
)
from filodb_tpu.core.filters import ColumnFilter, Equals
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.api import InMemoryColumnStore, InMemoryMetaStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.promql.parser import TimeStepParams, parse_query
from filodb_tpu.query.exec.plan import ExecContext
from filodb_tpu.testing.data import gauge_stream, machine_metrics_series

START = 1_600_000_000
RES = 300_000  # 5m


class TestDownsampleSamples:
    def test_basic_rollup(self):
        ts = np.arange(0, 600_000, 10_000, dtype=np.int64)  # 60 samples
        vals = np.arange(60, dtype=np.float64)
        t_last, mins, maxs, sums, counts, avgs, lasts = downsample_samples(
            ts, vals, RES)
        assert len(t_last) == 2  # two 5m periods
        assert mins[0] == 0 and maxs[0] == 29 and counts[0] == 30
        assert mins[1] == 30 and maxs[1] == 59
        assert t_last[0] == 290_000 and t_last[1] == 590_000
        np.testing.assert_allclose(avgs, [14.5, 44.5])
        assert lasts[1] == 59

    def test_irregular_buckets(self):
        ts = np.array([100, 299_000, 300_000, 900_001], dtype=np.int64)
        vals = np.array([1.0, 2.0, 3.0, 4.0])
        t_last, mins, maxs, sums, counts, avgs, lasts = downsample_samples(
            ts, vals, RES)
        assert counts.tolist() == [2.0, 1.0, 1.0]


def build_raw(num_shards=2, n_samples=600):
    cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
    ms = TimeSeriesMemStore(cs, meta)
    for s in range(num_shards):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=120,
                                              groups_per_shard=2))
    keys = machine_metrics_series(6)
    from filodb_tpu.coordinator.ingestion import ingest_routed
    ingest_routed(ms, "timeseries",
                  gauge_stream(keys, n_samples, start_ms=START * 1000),
                  num_shards, spread=0)
    ms.flush_all("timeseries")
    return ms, cs, keys


class TestBatchJob:
    def test_job_writes_ds_chunks(self):
        ms, cs, keys = build_raw()
        job = DownsamplerJob(cs, "timeseries", 2, resolutions_ms=(RES,))
        stats = job.run(0, 2**62)
        assert stats["partitions"] == 6
        assert stats["ds_chunks"] >= 6
        # 600 samples @10s = 100 min → 21 5m-buckets per series (START*1000
        # is not bucket-aligned, so first and last buckets are partial)
        assert stats["ds_samples"] == 6 * 21
        # ds partkeys written
        recs = []
        for s in range(2):
            recs += cs.scan_part_keys(ds_dataset_name("timeseries", RES), s)
        assert len(recs) == 6

    def test_ds_store_query(self):
        ms, cs, keys = build_raw()
        DownsamplerJob(cs, "timeseries", 2, resolutions_ms=(RES,)).run(0, 2**62)
        ds_store = DownsampledTimeSeriesStore(cs, "timeseries", RES, 2)
        f = [ColumnFilter("_metric_", Equals("heap_usage"))]
        per_shard = {s: ds_store.get_shard("timeseries", s)
                     .lookup_partitions(f, 0, 2**62) for s in (0, 1)}
        assert sum(len(p) for p in per_shard.values()) == 6
        shard, pids = next((s, p) for s, p in per_shard.items() if p)
        part = ds_store.get_shard("timeseries", shard).partition(pids[0])
        ts, vals = part.read_samples(0, 2**62)  # default col = avg
        assert len(ts) == 21

    def test_query_ds_store_via_planner(self):
        ms, cs, keys = build_raw()
        DownsamplerJob(cs, "timeseries", 2, resolutions_ms=(RES,)).run(0, 2**62)
        ds_store = DownsampledTimeSeriesStore(cs, "timeseries", RES, 2)
        planner = SingleClusterPlanner(
            "timeseries", 2, spread=0, store=ds_store)
        plan = parse_query(
            "max_over_time(heap_usage[10m])",
            TimeStepParams(START + 1800, 300, START + 3600))
        from filodb_tpu.coordinator.longtime_planner import (
            rewrite_for_downsample,
        )
        ep = planner.materialize(rewrite_for_downsample(plan))
        ctx = ExecContext(ms, "timeseries")
        result = ep.dispatcher.dispatch(ep, ctx).result
        assert result.num_series == 6
        assert np.isfinite(result.values).any()


class TestLongTimeRangePlanner:
    def _setup(self):
        ms, cs, keys = build_raw(num_shards=1, n_samples=600)
        DownsamplerJob(cs, "timeseries", 1, resolutions_ms=(RES,)).run(0, 2**62)
        ds_store = DownsampledTimeSeriesStore(cs, "timeseries", RES, 1)
        raw_planner = SingleClusterPlanner("timeseries", 1, spread=0)
        ds_planner = SingleClusterPlanner("timeseries", 1, spread=0,
                                          store=ds_store)
        # pretend raw retention starts 50 min into the data
        earliest_raw = (START + 3000) * 1000
        now = (START + 6000) * 1000
        planner = LongTimeRangePlanner(
            raw_planner, ds_planner,
            raw_retention_ms=now - earliest_raw, now_ms=lambda: now)
        return ms, planner

    def _run(self, ms, planner, promql, start, step, end):
        plan = parse_query(promql, TimeStepParams(start, step, end))
        ep = planner.materialize(plan)
        ctx = ExecContext(ms, "timeseries")
        return ep.dispatcher.dispatch(ep, ctx).result, ep

    def test_all_raw(self, ):
        ms, planner = self._setup()
        r, ep = self._run(ms, planner, "max_over_time(heap_usage[5m])",
                          START + 4000, 300, START + 5000)
        assert r.num_series == 6

    def test_all_downsample(self):
        ms, planner = self._setup()
        r, ep = self._run(ms, planner, "max_over_time(heap_usage[10m])",
                          START + 900, 300, START + 2400)
        assert r.num_series == 6
        assert np.isfinite(r.values).any()

    def test_straddling_stitches(self):
        from filodb_tpu.query.exec.plan import StitchRvsExec
        ms, planner = self._setup()
        r, ep = self._run(ms, planner, "max_over_time(heap_usage[10m])",
                          START + 900, 300, START + 5400)
        assert isinstance(ep, StitchRvsExec)
        assert r.num_series == 6
        # steps span the whole range after stitching
        assert r.steps_ms[0] == (START + 900) * 1000
        assert r.steps_ms[-1] == (START + 5400) * 1000
        # values exist on both sides of the boundary
        assert np.isfinite(r.values[:, 0]).any()
        assert np.isfinite(r.values[:, -1]).any()

    def test_exact_boundary_stays_all_raw(self):
        """start − lookback landing EXACTLY on earliest_raw_time is
        all-raw (``>=`` boundary) — the off-by-one a strict ``>`` would
        push into a needless, lossier stitched plan."""
        from filodb_tpu.query.exec.plan import StitchRvsExec
        ms, planner = self._setup()
        # earliest_raw = START+3000s; [10m] lookback = 600s
        r, ep = self._run(ms, planner, "max_over_time(heap_usage[10m])",
                          START + 3600, 300, START + 5000)
        assert not isinstance(ep, StitchRvsExec)
        assert r.num_series == 6
        assert np.isfinite(r.values[:, 0]).any()

    def test_one_step_before_boundary_stitches(self):
        """One grid step earlier the first window dips below raw
        retention: exactly that one step routes to the ds tier, and the
        stitched grid has no dropped or duplicated steps at the seam."""
        from filodb_tpu.query.exec.plan import StitchRvsExec
        ms, planner = self._setup()
        r, ep = self._run(ms, planner, "max_over_time(heap_usage[10m])",
                          START + 3300, 300, START + 5000)
        assert isinstance(ep, StitchRvsExec)
        expected = np.arange((START + 3300) * 1000,
                             (START + 5000) * 1000 + 1, 300 * 1000)
        np.testing.assert_array_equal(r.steps_ms, expected)
        assert np.isfinite(r.values[:, 0]).any()  # ds-served first step
        assert np.isfinite(r.values[:, -1]).any()

    def test_avg_rewrite_nested_under_aggregate(self):
        """The Σsum/Σcount avg rewrite fires on windows nested under an
        aggregate — the whole subtree is rewritten, not just top-level
        windowing nodes — and the result matches the raw average."""
        from filodb_tpu.query import logical as lp
        rewrite = rewrite_for_downsample_import()
        plan = parse_query("sum(avg_over_time(heap_usage[10m]))",
                           TimeStepParams(START + 900, 300, START + 2400))
        rw = rewrite(plan)
        assert isinstance(rw, lp.Aggregate) and rw.op == "sum"
        j = rw.vector
        assert isinstance(j, lp.BinaryJoin) and j.op == "/"
        assert j.lhs.function == "sum_over_time"
        assert j.lhs.raw.column == "sum"
        assert j.rhs.raw.column == "count"
        # correctness: all-ds range through the tiered planner vs raw
        ms, planner = self._setup()
        r, ep = self._run(ms, planner, "sum(avg_over_time(heap_usage[10m]))",
                          START + 900, 300, START + 2400)
        assert r.num_series == 1
        from filodb_tpu.coordinator.query_service import QueryService
        raw = QueryService(ms, "timeseries", 1, spread=0).query_range(
            "sum(avg_over_time(heap_usage[10m]))",
            START + 900, 300, START + 2400).result
        m = np.isfinite(r.values) & np.isfinite(raw.values)
        assert m.any()
        # rollup boundary effect: a raw sample exactly on the left window
        # edge belongs to the period but not the left-exclusive window
        np.testing.assert_allclose(r.values[m], raw.values[m], rtol=5e-2)

    def test_avg_rewrite_nested_under_binary_join(self):
        """Both sides of a binary join are rewritten independently;
        avg/avg over the ds tier is identically 1 wherever defined."""
        from filodb_tpu.query import logical as lp
        rewrite = rewrite_for_downsample_import()
        q = ("avg_over_time(heap_usage[10m])"
             " / avg_over_time(heap_usage[10m])")
        plan = parse_query(q, TimeStepParams(START + 900, 300, START + 2400))
        rw = rewrite(plan)
        assert isinstance(rw, lp.BinaryJoin)
        for side in (rw.lhs, rw.rhs):
            assert isinstance(side, lp.BinaryJoin) and side.op == "/"
            assert side.lhs.raw.column == "sum"
            assert side.rhs.raw.column == "count"
        ms, planner = self._setup()
        r, ep = self._run(ms, planner, q, START + 900, 300, START + 2400)
        assert r.num_series == 6
        vals = r.values[np.isfinite(r.values)]
        assert len(vals)
        np.testing.assert_allclose(vals, 1.0, rtol=1e-12)


class TestStreamingDownsampler:
    def test_on_flush_publishes(self):
        from filodb_tpu.core.downsample.downsampler import ShardDownsampler
        ms = TimeSeriesMemStore(InMemoryColumnStore(), InMemoryMetaStore())
        shard = ms.setup("timeseries", 0, StoreConfig(max_chunk_size=60,
                                                      groups_per_shard=2))
        published = []
        shard.downsampler = ShardDownsampler(
            resolutions_ms=(RES,),
            publish=lambda res, cont: published.append((res, len(cont))))
        keys = machine_metrics_series(3)
        for sd in gauge_stream(keys, 120, start_ms=START * 1000):
            shard.ingest(sd)
        shard.flush_all(ingestion_time=1)
        assert published
        total = sum(n for _, n in published)
        # 120 samples @10s = 20min → 5 periods per series (fencepost)
        assert total >= 3 * 4


class TestStreamingPipeline:
    def test_streaming_ds_queryable(self):
        """Flush-time rollups land in a co-sharded ds dataset and serve
        queries through the downsample planner immediately."""
        from filodb_tpu.coordinator.cluster import FilodbCluster, Node
        from filodb_tpu.coordinator.ingestion import route_container
        from filodb_tpu.core.store.config import IngestionConfig
        from filodb_tpu.kafka.log import InMemoryLog

        cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
        cluster = FilodbCluster()
        node = Node("n1", TimeSeriesMemStore(cs, meta), flush_tick_s=0.05)
        cluster.join(node)
        logs = {0: InMemoryLog(), 1: InMemoryLog()}
        keys = machine_metrics_series(4)
        for sd in gauge_stream(keys, 240, start_ms=START * 1000):
            for shard, cont in route_container(sd.container, 2, 1).items():
                logs[shard].append(cont)
        config = IngestionConfig(
            "timeseries", 2,
            store=StoreConfig(max_chunk_size=60, groups_per_shard=2,
                              retention_ms=10**15),  # synthetic 2020 data
            downsample={"streaming": True, "resolutions_ms": [RES]})
        cluster.setup_dataset(config, logs)
        assert cluster.wait_active("timeseries", 10)
        import time as _time
        ds_name = ds_dataset_name("timeseries", RES)
        deadline = _time.monotonic() + 15
        n = 0
        while _time.monotonic() < deadline:
            try:
                shards = [node.memstore.get_shard(ds_name, s)
                          for s in range(2)]
                n = sum(s.num_partitions for s in shards)
                if n >= 4:
                    break
            except KeyError:
                pass
            _time.sleep(0.2)
        assert n >= 4  # rollup series materialized in the ds dataset
        # query the ds dataset via a planner override
        planner = SingleClusterPlanner("timeseries", 2, spread=0,
                                       dataset_name_override=ds_name)
        from filodb_tpu.coordinator.longtime_planner import (
            rewrite_for_downsample,
        )
        plan = parse_query("max_over_time(heap_usage[10m])",
                           TimeStepParams(START + 900, 300, START + 2400))
        ep = planner.materialize(rewrite_for_downsample(plan))
        ctx = ExecContext(node.memstore, "timeseries")
        result = ep.dispatcher.dispatch(ep, ctx).result
        assert result.num_series == 4
        assert np.isfinite(result.values).any()
        cluster.stop()


class TestCounterDownsample:
    def test_rate_over_downsampled_counters(self):
        """prom-counter rollups keep last-sample counter semantics (dLast);
        rate() over the ds dataset stays meaningful."""
        from filodb_tpu.coordinator.ingestion import ingest_routed
        from filodb_tpu.testing.data import counter_series, counter_stream

        cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
        ms = TimeSeriesMemStore(cs, meta)
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=120))
        keys = counter_series(3)
        ingest_routed(ms, "timeseries",
                      counter_stream(keys, 600, start_ms=START * 1000,
                                     seed=8),
                      1, spread=0)
        ms.flush_all("timeseries")
        DownsamplerJob(cs, "timeseries", 1, resolutions_ms=(RES,)).run(
            0, 2**62)
        ds_store = DownsampledTimeSeriesStore(cs, "timeseries", RES, 1)
        planner = SingleClusterPlanner("timeseries", 1, spread=0,
                                       store=ds_store)
        plan = parse_query('sum(rate(http_requests_total[15m]))',
                           TimeStepParams(START + 1800, 300, START + 4500))
        ep = planner.materialize(plan)
        ctx = ExecContext(ms, "timeseries")
        r = ep.dispatcher.dispatch(ep, ctx).result
        assert r.num_series == 1
        vals = r.values[np.isfinite(r.values)]
        assert len(vals) and (vals > 0).all()
        # coarse agreement with the raw-data rate (rollup loses resolution,
        # not magnitude)
        from filodb_tpu.coordinator.query_service import QueryService
        raw = QueryService(ms, "timeseries", 1, spread=0).query_range(
            'sum(rate(http_requests_total[15m]))',
            START + 1800, 300, START + 4500).result
        m = np.isfinite(r.values) & np.isfinite(raw.values)
        ratio = r.values[m] / raw.values[m]
        assert 0.5 < np.median(ratio) < 2.0


class TestColumnSelection:
    def test_double_colon_column(self):
        """filodb extension metric::column reads a specific value column
        (reference ``promFilterToPartKeyBR``-era ::col syntax)."""
        ms, cs, keys = build_raw(num_shards=1, n_samples=300)
        DownsamplerJob(cs, "timeseries", 1, resolutions_ms=(RES,)).run(0, 2**62)
        ds_store = DownsampledTimeSeriesStore(cs, "timeseries", RES, 1)
        planner = SingleClusterPlanner("timeseries", 1, spread=0,
                                       store=ds_store)
        ctx = ExecContext(ms, "timeseries")
        out = {}
        for col in ("min", "max"):
            plan = parse_query(f"heap_usage::{col}",
                               TimeStepParams(START + 1500, 300, START + 2400))
            r = planner.materialize(plan).execute(ctx).result
            assert r.num_series == 6
            out[col] = r.values
        m = np.isfinite(out["min"]) & np.isfinite(out["max"])
        assert (out["max"][m] >= out["min"][m]).all()
        assert (out["max"][m] > out["min"][m]).any()


class TestExactDsAvg:
    def test_avg_over_time_sum_count_semantics(self):
        """avg_over_time over rollups = Σsum/Σcount (reference dAvgAc
        semantics) — matches the raw average up to the inherent rollup
        boundary effect (a raw sample exactly on the left window edge
        belongs to the period but not the left-exclusive window)."""
        ms, cs, keys = build_raw(num_shards=1, n_samples=600)
        DownsamplerJob(cs, "timeseries", 1, resolutions_ms=(RES,)).run(0, 2**62)
        ds_store = DownsampledTimeSeriesStore(cs, "timeseries", RES, 1)
        planner = SingleClusterPlanner("timeseries", 1, spread=0,
                                       store=ds_store)
        ctx = ExecContext(ms, "timeseries")
        # window = 2 whole 5m periods, step lands on period boundaries
        bucket0 = (START * 1000 // RES) * RES
        start_s = (bucket0 + 4 * RES) // 1000
        plan = parse_query("avg_over_time(heap_usage[10m])",
                           TimeStepParams(start_s, RES // 1000,
                                          start_s + 2 * RES // 1000))
        ep = planner.materialize(rewrite_for_downsample_import()(plan))
        r = ep.execute(ctx).result
        assert r.num_series == 6
        # ground truth from raw samples
        from filodb_tpu.coordinator.query_service import QueryService
        raw = QueryService(ms, "timeseries", 1, spread=0).query_range(
            "avg_over_time(heap_usage[10m])", start_s, RES // 1000,
            start_s + 2 * RES // 1000).result
        def by_inst(mat):
            return {k.label_map["instance"]: mat.values[i]
                    for i, k in enumerate(mat.keys)}
        got, want = by_inst(r), by_inst(raw)
        for inst in want:
            m = np.isfinite(want[inst]) & np.isfinite(got[inst])
            np.testing.assert_allclose(got[inst][m], want[inst][m],
                                       rtol=1e-2, err_msg=inst)


def rewrite_for_downsample_import():
    from filodb_tpu.coordinator.longtime_planner import rewrite_for_downsample
    return rewrite_for_downsample


class TestCheckpointedCatchUp:
    """Regression (downsample catch-up gap): a raw flush between two
    scheduled downsample runs used to be lost if the process crashed
    before the next run — the restarted job only scanned forward from
    'now'.  catch_up() persists a per-shard ingestion-time watermark and
    rescans from it, so the crash window is recovered."""

    def _ingest_window(self, ms, keys, n, start_ms, ingestion_time,
                       start_offset=0):
        from filodb_tpu.coordinator.ingestion import ingest_routed
        ingest_routed(ms, "timeseries",
                      gauge_stream(keys, n, start_ms=start_ms,
                                   start_offset=start_offset),
                      num_shards=1, spread=0)
        for s in ms.shards_for("timeseries"):
            s.flush_all(ingestion_time=ingestion_time)

    def test_crash_window_recovered(self):
        cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
        ms = TimeSeriesMemStore(cs, meta)
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=120,
                                              groups_per_shard=2))
        keys = machine_metrics_series(3)
        ds_name = ds_dataset_name("timeseries", RES)

        # window A flushed at itime=100; first scheduled run downsamples it
        self._ingest_window(ms, keys, 300, START * 1000, ingestion_time=100)
        job1 = DownsamplerJob(cs, "timeseries", 1, resolutions_ms=(RES,),
                              meta_store=meta)
        s1 = job1.catch_up(now_ms=101)
        assert s1["partitions"] == 3 and s1["scanned_from"][0] == 0
        assert job1.last_checkpoint(0) == 101
        a_samples = sum(len(ch.decode_column(0))
                        for _, chs in cs.scan_chunks_by_ingestion_time(
                            ds_name, 0, 0, 2**62) for ch in chs)

        # window B flushed at itime=200 ... then CRASH before the next run
        self._ingest_window(ms, keys, 300,
                            START * 1000 + 300 * 10_000, ingestion_time=200,
                            start_offset=1000)
        del job1

        # restarted job (fresh instance, same stores) must rescan from the
        # checkpoint — not from "now" — and pick up window B
        job2 = DownsamplerJob(cs, "timeseries", 1, resolutions_ms=(RES,),
                              meta_store=meta)
        s2 = job2.catch_up(now_ms=300)
        assert s2["scanned_from"][0] == 101   # resumed at the watermark
        assert s2["partitions"] == 3
        assert job2.last_checkpoint(0) == 300
        ab_samples = sum(len(ch.decode_column(0))
                         for _, chs in cs.scan_chunks_by_ingestion_time(
                             ds_name, 0, 0, 2**62) for ch in chs)
        # 300 more raw samples @10s = 50 min ≈ 10-11 more 5m periods/series
        assert ab_samples >= a_samples + 3 * 10

        # idempotent: re-running an overlapping window adds nothing
        job2.catch_up(now_ms=300)
        again = sum(len(ch.decode_column(0))
                    for _, chs in cs.scan_chunks_by_ingestion_time(
                        ds_name, 0, 0, 2**62) for ch in chs)
        assert again == ab_samples

    def test_catch_up_on_object_store(self, tmp_path):
        """Same story end-to-end on the object-store tier: checkpoints and
        ds chunks survive a process restart (new store instances)."""
        from filodb_tpu.core.store.objectstore import (
            ObjectStoreColumnStore, ObjectStoreMetaStore)
        from filodb_tpu.testing.fake_s3 import FakeS3
        root = str(tmp_path / "s3")
        cs = ObjectStoreColumnStore(FakeS3(root=root))
        meta = ObjectStoreMetaStore(cs)
        ms = TimeSeriesMemStore(cs, meta)
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=120,
                                              groups_per_shard=2))
        keys = machine_metrics_series(2)
        self._ingest_window(ms, keys, 120, START * 1000, ingestion_time=100)
        DownsamplerJob(cs, "timeseries", 1, resolutions_ms=(RES,),
                       meta_store=meta).catch_up(now_ms=101)
        self._ingest_window(ms, keys, 120, START * 1000 + 120 * 10_000,
                            ingestion_time=200, start_offset=1000)
        cs.close()   # crash: drain pending uploads, drop process state

        cs2 = ObjectStoreColumnStore(FakeS3(root=root))
        meta2 = ObjectStoreMetaStore(cs2)
        job = DownsamplerJob(cs2, "timeseries", 1, resolutions_ms=(RES,),
                             meta_store=meta2, n_splits=4)
        assert job.last_checkpoint(0) == 101
        s = job.catch_up(now_ms=300)
        assert s["scanned_from"][0] == 101 and s["partitions"] == 2
        cs2.flush()
        ds_name = ds_dataset_name("timeseries", RES)
        per_series = dict(cs2.scan_chunks_by_ingestion_time(
            ds_name, 0, 0, 2**62))
        assert len(per_series) == 2
        # both raw windows are represented in the rollups
        all_ts = np.concatenate(
            [ch.decode_column(0) for chs in per_series.values()
             for ch in chs])
        assert all_ts.min() < START * 1000 + 120 * 10_000 <= all_ts.max()
        cs2.close()
