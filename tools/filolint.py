#!/usr/bin/env python3
"""Run filolint (static concurrency/invariant analysis) over the repo.

Thin wrapper so the tool works from a checkout without installation:

    python tools/filolint.py                 # gate against the baseline
    python tools/filolint.py --no-baseline   # show everything
    python tools/filolint.py --update-baseline

Installed entry point: ``filolint`` (see pyproject.toml).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from filodb_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    argv = sys.argv[1:]
    if not any(a.startswith("--root") for a in argv):
        argv = ["--root", repo] + argv
    sys.exit(main(argv))
