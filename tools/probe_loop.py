"""Round-long TPU tunnel probe loop.

VERDICT r2 #1: probe the axon TPU tunnel from round *start* on a repeating
timer, logging every attempt, so the round either lands a real-TPU benchmark
or carries an auditable probe timeline proving continuous attempts.

Each probe runs in a subprocess with a hard timeout (a hung axon backend init
must never wedge this loop — and a stuck init blocks ``import jax`` machine-
wide, so the timeout also bounds collateral stalls for test runs). On the
first successful device hit the loop immediately runs the full TPU bench
suite (the tunnel flaps; grab the number while it's up) and records it.

Usage:  python tools/probe_loop.py >/dev/null 2>&1 &
Stop:   touch tools/probe_stop
Log:    PROBE_r03.jsonl (one JSON line per attempt)
"""

import datetime
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(ROOT, "PROBE_r03.jsonl")
STOP = os.path.join(ROOT, "tools", "probe_stop")
SNAPSHOT = os.path.join(ROOT, "BENCH_TPU_SNAPSHOT.json")
PERIOD_S = int(os.environ.get("PROBE_PERIOD_S", "900"))
TIMEOUT_S = int(os.environ.get("PROBE_TIMEOUT_S", "90"))

PROBE_CMD = ("import jax; d = jax.devices(); "
             "import jax.numpy as jnp; "
             "jnp.arange(4).sum().block_until_ready(); "
             "print(d[0].platform)")


def log_line(rec):
    rec["at"] = datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds")
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")


def probe_once():
    t0 = time.time()
    try:
        out = subprocess.run(
            [sys.executable, "-c", PROBE_CMD],
            check=True, timeout=TIMEOUT_S, capture_output=True, text=True,
            cwd=ROOT)
        plat = out.stdout.strip().splitlines()[-1]
        log_line({"outcome": "ok", "platform": plat,
                  "elapsed_s": round(time.time() - t0, 1)})
        return plat
    except subprocess.TimeoutExpired:
        log_line({"outcome": "timeout",
                  "elapsed_s": round(time.time() - t0, 1)})
    except subprocess.CalledProcessError as e:
        tail = (e.stderr or "").strip().splitlines()[-1:] or [""]
        log_line({"outcome": "error",
                  "elapsed_s": round(time.time() - t0, 1),
                  "detail": tail[0][:200]})
    except Exception as e:  # never die; the timeline must keep going
        log_line({"outcome": "loop-error", "detail": repr(e)[:200]})
    return None


def run_tpu_bench(platform):
    """Device is up: run the bench suite now and snapshot the result."""
    log_line({"outcome": "bench-start", "platform": platform})
    try:
        out = subprocess.run(
            [sys.executable, "bench.py"], cwd=ROOT, timeout=3600,
            capture_output=True, text=True,
            env={**os.environ, "FILODB_BENCH_PROBE_ATTEMPTS": "2"})
        last = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else ""
        with open(SNAPSHOT, "a") as f:
            f.write(last + "\n")
        log_line({"outcome": "bench-done", "rc": out.returncode,
                  "stdout_tail": last[:500],
                  "stderr_tail": out.stderr.strip()[-300:]})
        return out.returncode == 0 and '"platform": "cpu"' not in last
    except Exception as e:
        log_line({"outcome": "bench-error", "detail": repr(e)[:300]})
        return False


def main():
    log_line({"outcome": "loop-start", "period_s": PERIOD_S,
              "timeout_s": TIMEOUT_S, "pid": os.getpid()})
    benched = False
    while not os.path.exists(STOP):
        plat = probe_once()
        if plat is not None and plat != "cpu" and not benched:
            benched = run_tpu_bench(plat)
        time.sleep(PERIOD_S)
    log_line({"outcome": "loop-stop"})


if __name__ == "__main__":
    main()
