"""Run the Pallas device-page decode kernels with interpret=False on a real
TPU and validate against the host codecs (VERDICT r2 #1b: the kernels had
only ever executed in interpreter mode).

Emits one JSON line: correctness + timing for ts and f32 decode at a
realistic page population, and a fused decode+rate timing.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    plat = jax.devices()[0].platform
    from filodb_tpu.memory.device_pages import (
        BLOCK, decode_f32_page_pallas, decode_ts_page_pallas,
        encode_f32_page, encode_ts_page, page_to_arrays)

    rng = np.random.default_rng(42)
    out = {"platform": plat}

    # --- encode a realistic population: 512 series x 720 samples
    n = 720
    nseries = 512
    ts_pages = []
    f32_pages = []
    for s in range(nseries):
        base = 1_600_000_000_000 + int(rng.integers(0, 5_000))
        ts = base + np.arange(n, dtype=np.int64) * 10_000 \
            + rng.integers(-40, 40, n)
        ts = np.maximum.accumulate(ts)
        vals = (50 + 10 * np.sin(np.arange(n) / 30.0)
                + rng.normal(0, 1, n)).astype(np.float32)
        ts_pages.append(encode_ts_page(ts))
        f32_pages.append((ts, vals, encode_f32_page(vals)))

    # --- stack page arrays into one batch (all series share nb)
    nb = ts_pages[0].num_blocks
    t_slopes = jnp.asarray(np.stack([p.slopes for p in ts_pages]).reshape(-1))
    t_widths = jnp.asarray(np.stack([p.widths for p in ts_pages]).reshape(-1))
    t_words = jnp.asarray(
        np.stack([p.words for p in ts_pages]).reshape(nseries * nb, -1))
    f_firsts = jnp.asarray(
        np.stack([p.bases for _, _, p in f32_pages]).reshape(-1))
    f_shifts = jnp.asarray(
        np.stack([p.slopes for _, _, p in f32_pages]).reshape(-1))
    f_widths = jnp.asarray(
        np.stack([p.widths for _, _, p in f32_pages]).reshape(-1))
    f_words = jnp.asarray(
        np.stack([p.words for _, _, p in f32_pages]).reshape(nseries * nb, -1))

    # --- correctness: pallas interpret=False vs host truth
    dec_ts = jax.jit(lambda s, w, wd: decode_ts_page_pallas(s, w, wd))
    dec_f = jax.jit(
        lambda f, sh, w, wd: decode_f32_page_pallas(f, sh, w, wd))

    got_ts = np.asarray(dec_ts(t_slopes, t_widths, t_words)).reshape(
        nseries, nb, BLOCK)
    got_f = np.asarray(dec_f(f_firsts, f_shifts, f_widths, f_words)).reshape(
        nseries, nb, BLOCK)

    ts_ok = True
    f_ok = True
    for s in range(nseries):
        ts_true, vals_true, _ = f32_pages[s]
        bases = ts_pages[s].bases
        flat = (got_ts[s] + bases[:, None]).reshape(-1)[:n]
        if not np.array_equal(flat, ts_true):
            ts_ok = False
        if not np.array_equal(got_f[s].reshape(-1)[:n], vals_true):
            f_ok = False
    out["ts_decode_exact"] = bool(ts_ok)
    out["f32_decode_exact"] = bool(f_ok)

    # --- timing (after warmup)
    for _ in range(2):
        dec_ts(t_slopes, t_widths, t_words).block_until_ready()
        dec_f(f_firsts, f_shifts, f_widths, f_words).block_until_ready()
    reps = 20
    t0 = time.perf_counter()
    for _ in range(reps):
        r = dec_ts(t_slopes, t_widths, t_words)
    r.block_until_ready()
    ts_ms = (time.perf_counter() - t0) / reps * 1e3
    t0 = time.perf_counter()
    for _ in range(reps):
        r = dec_f(f_firsts, f_shifts, f_widths, f_words)
    r.block_until_ready()
    f_ms = (time.perf_counter() - t0) / reps * 1e3
    total = nseries * n
    out["ts_decode_ms"] = round(ts_ms, 3)
    out["f32_decode_ms"] = round(f_ms, 3)
    out["ts_decode_msamples_s"] = round(total / ts_ms / 1e3, 1)
    out["f32_decode_msamples_s"] = round(total / f_ms / 1e3, 1)
    out["pallas_interpret"] = False
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
