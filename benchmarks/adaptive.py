"""Static heuristics vs trace-driven adaptive routing on a mixed soak.

The static sidecar gate reasons from chunk *geometry* (interior samples
the fold would skip) and cannot see cache state: over a store whose
decoded-chunk memos are warm, the decode lane is nearly free while the
sealed fold still pays O(chunks) per series — the geometry estimate
picks the fold and loses. The adaptive planner settles actual wall
times per (site, partition-window signature) and routes to whichever
arm measured cheaper, so a mixed workload where different scenarios
want different arms is exactly where it should beat any one fixed
heuristic.

Three scenario classes soak together, mixed round-robin:

* ``alert_probe_cold_large`` — cold large sealed chunks, single-step
  probe: the fold's design center; both static and adaptive should
  serve sidecar. Parity expected.
* ``dashboard_wide_fanout_cold`` — a cold dashboard scan whose
  partition-window count sits ABOVE the static amortization gate
  (``1200 series x 60 steps > 65536``), so geometry refuses the fold —
  but the store is cold and the decode lane pays the full window while
  the batched fold amortizes across the whole group. Static mis-routes
  every repeat; adaptive learns the fold after calibration. This class
  sets the mixed-soak tail.
* ``adhoc_small_chunks`` — warm small-chunk scans under the
  amortization gate: static already bypasses; parity expected.

Phases per run:

1. **static soak** — ``FILODB_ADAPTIVE=0``, default valves.
2. **oracle replay** — both arms forced per scenario via the sealed
   gate valve (``FILODB_SIDECAR_SEALED_GATE`` 0 = always-fold
   override, 1 = geometry-refuses so decode) with routing still pinned
   static; the model observes every settled wall time, so this doubles
   as calibration. The per-query minimum over the two forced arms is
   the **oracle** — the best any router could have picked.
3. **adaptive soak** — ``FILODB_ADAPTIVE=1``, default valves, the
   now-warm model routes.

Latencies land in a flight-recorder ring; the headline is soak p99
static vs adaptive. The machine-checked **oracle gate**: per (scenario,
query) site the adaptive best must be within 2x of the oracle best —
a regression guard that fails the benchmark result (``gate_ok``)
rather than eyeballing a table.
"""

from __future__ import annotations

import os
import time

START = 1_600_000_000

SCENARIOS = [
    {"name": "alert_probe_cold_large", "series": 96, "chunk": 2048,
     "samples": 8192, "window": "1300m", "steps": 1, "cold": True,
     "repeats": 6,
     "queries": ["sum(avg_over_time(heap_usage[{w}]))"]},
    {"name": "dashboard_wide_fanout_cold", "series": 1200, "chunk": 512,
     "samples": 3072, "window": "300m", "steps": 60, "cold": True,
     "repeats": 6,
     "queries": ["sum(avg_over_time(heap_usage[{w}]))"]},
    {"name": "adhoc_small_chunks", "series": 256, "chunk": 64,
     "samples": 720, "window": "40m", "steps": 6, "cold": False,
     "repeats": 6,
     "queries": ["sum(avg_over_time(heap_usage[{w}]))"]},
]
ORACLE_REPEATS = 3   # forced-arm replays per (scenario, query, arm)
GATE_FACTOR = 2.0    # adaptive must stay within this factor of oracle


def _build(sc):
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.testing.data import gauge_stream, machine_metrics_series

    ms = TimeSeriesMemStore()
    shard = ms.setup("bench", 0, StoreConfig(max_chunk_size=sc["chunk"]))
    stream = gauge_stream(machine_metrics_series(sc["series"]),
                          sc["samples"], start_ms=START * 1000, seed=11)
    for batch in stream:
        shard.ingest(batch)
    return ms


def _go_cold(ms):
    for shard in ms.shards_for("bench"):
        shard.batch_cache.clear()
        for pid in shard.lookup_partitions([], 0, 2 ** 62):
            p = shard.partition(pid)
            if p is None:
                continue
            for ch in p.chunks:
                ch.__dict__.pop("_decoded", None)


def _params(sc):
    end = START + (sc["samples"] - 1) * 10
    qs = end - (sc["steps"] - 1) * 60
    return qs, end


def _run_query(svc, ms, sc, q):
    qs, end = _params(sc)
    if sc["cold"]:
        _go_cold(ms)
    else:
        for shard in ms.shards_for("bench"):
            shard.batch_cache.clear()
    t0 = time.perf_counter()
    svc.query_range(q, qs, 60, end)
    return (time.perf_counter() - t0) * 1000.0


def _soak(stores, services, ring, mode):
    """One mixed pass: scenarios interleave round-robin so no class
    runs back-to-back (cache effects stay realistic)."""
    lat = {}
    for rep in range(max(sc["repeats"] for sc in SCENARIOS)):
        for sc in SCENARIOS:
            if rep >= sc["repeats"]:
                continue
            for q in sc["queries"]:
                query = q.format(w=sc["window"])
                ms = _run_query(services[sc["name"]], stores[sc["name"]],
                                sc, query)
                lat.setdefault((sc["name"], query), []).append(ms)
                ring.record({"mode": mode, "scenario": sc["name"],
                             "query": query, "ms": ms})
    return lat


def _p(values, q):
    xs = sorted(values)
    return xs[min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))]


def bench_adaptive():
    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.query import cost_model as cm
    from filodb_tpu.utils.tracing import FlightRecorder

    stores = {sc["name"]: _build(sc) for sc in SCENARIOS}
    services = {sc["name"]: QueryService(stores[sc["name"]], "bench", 1,
                                         spread=0)
                for sc in SCENARIOS}
    ring = FlightRecorder(capacity=4096)
    saved = {k: os.environ.get(k)
             for k in ("FILODB_ADAPTIVE", "FILODB_SIDECAR_SEALED_GATE")}
    try:
        # warm compile caches once per (scenario, query)
        for sc in SCENARIOS:
            for q in sc["queries"]:
                qs, end = _params(sc)
                services[sc["name"]].query_range(q.format(w=sc["window"]),
                                                 qs, 60, end)

        # -- phase 1: static soak ------------------------------------------
        cm.reset_models()
        os.environ["FILODB_ADAPTIVE"] = "0"
        os.environ.pop("FILODB_SIDECAR_SEALED_GATE", None)
        static_lat = _soak(stores, services, ring, "static")

        # -- phase 2: oracle replay (both arms forced; also calibrates) ----
        cm.reset_models()
        cm.model_for("bench").configure(min_samples=2)
        oracle = {}
        for arm, gate in (("sidecar", "0"), ("decode", "1")):
            os.environ["FILODB_SIDECAR_SEALED_GATE"] = gate
            for sc in SCENARIOS:
                for q in sc["queries"]:
                    query = q.format(w=sc["window"])
                    best = min(_run_query(services[sc["name"]],
                                          stores[sc["name"]], sc, query)
                               for _ in range(ORACLE_REPEATS))
                    oracle.setdefault((sc["name"], query), {})[arm] = best

        # -- phase 3: adaptive soak on the warm model ----------------------
        os.environ["FILODB_ADAPTIVE"] = "1"
        os.environ.pop("FILODB_SIDECAR_SEALED_GATE", None)
        adaptive_lat = _soak(stores, services, ring, "adaptive")
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    rows, gate_ok = [], True
    for key in sorted(oracle):
        name, query = key
        oracle_best = min(oracle[key].values())
        adaptive_best = min(adaptive_lat[key])
        static_best = min(static_lat[key])
        site_ok = adaptive_best <= GATE_FACTOR * oracle_best + 1.0
        gate_ok = gate_ok and site_ok
        rows.append({
            "scenario": name, "query": query,
            "static_ms": round(static_best, 2),
            "adaptive_ms": round(adaptive_best, 2),
            "oracle_sidecar_ms": round(oracle[key]["sidecar"], 2),
            "oracle_decode_ms": round(oracle[key]["decode"], 2),
            "oracle_ms": round(oracle_best, 2),
            "vs_oracle": round(adaptive_best / max(oracle_best, 1e-9), 2),
            "gate_ok": site_ok,
        })

    entries = ring.snapshot()
    static_all = [e["ms"] for e in entries if e["mode"] == "static"]
    adaptive_all = [e["ms"] for e in entries if e["mode"] == "adaptive"]
    headline = {
        "static_p50_ms": round(_p(static_all, 0.5), 2),
        "static_p99_ms": round(_p(static_all, 0.99), 2),
        "adaptive_p50_ms": round(_p(adaptive_all, 0.5), 2),
        "adaptive_p99_ms": round(_p(adaptive_all, 0.99), 2),
    }
    headline["beats_static_p99"] = (headline["adaptive_p99_ms"]
                                    <= headline["static_p99_ms"])
    return {"metric": "static_vs_adaptive_soak", "unit": "ms/query",
            "gate_factor": GATE_FACTOR, "gate_ok": gate_ok,
            "headline": headline, "rows": rows}


if __name__ == "__main__":
    import json
    print(json.dumps(bench_adaptive(), indent=2))
