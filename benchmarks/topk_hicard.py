"""BASELINE.json config 4: topk over high cardinality.

    topk(5, sum by (app)(rate(cpu_seconds_total[1m])))
    over 100K series / 128 shards

The reference's comparable workload is ``QueryHiCardInMemoryBenchmark``
(``jmh/src/main/scala/filodb.jmh/QueryHiCardInMemoryBenchmark.scala``).
Runs the full path — index lookup across 128 shards → chunk decode → rate
kernels → grouped sum → topk — through the exec engine and (all-shards-local)
the device-mesh engine, reporting throughput and latency percentiles.

    python benchmarks/topk_hicard.py [--series 100000] [--shards 128] [--cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

START = 1_600_000_000
QUERY = 'topk(5, sum by (app)(rate(cpu_seconds_total[1m])))'


def build(num_series: int, num_shards: int, n_samples: int, n_apps: int):
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.partkey import (
        METRIC_LABEL,
        PartKey,
        ingestion_shard,
        shard_key_hash,
    )
    from filodb_tpu.core.record import (
        BytesContainer,
        IngestRecord,
        RecordContainer,
        SomeData,
    )
    from filodb_tpu.core.store.config import StoreConfig

    ms = TimeSeriesMemStore()
    for s in range(num_shards):
        ms.setup("hicard", s, StoreConfig(max_chunk_size=120,
                                          groups_per_shard=4))
    rng = np.random.default_rng(9)
    # pre-route records per shard (the gateway's job), then ingest bytes
    per_shard: dict[int, RecordContainer] = {s: RecordContainer()
                                             for s in range(num_shards)}
    keys = []
    for i in range(num_series):
        key = PartKey.create("prom-counter", {
            METRIC_LABEL: "cpu_seconds_total", "_ws_": "demo",
            "_ns_": f"App-{i % n_apps}", "app": f"app-{i % n_apps}",
            "instance": str(i)})
        keys.append(key)
    spread = 7  # 2^7 = 128: hicard metrics spread over every shard
    shards = [ingestion_shard(
        shard_key_hash({lbl: k.label_map.get(lbl, "")
                        for lbl in ("_ws_", "_ns_", METRIC_LABEL)}),
        k.part_hash, num_shards, spread) for k in keys]
    rows = 0
    offset = 0
    t0 = time.perf_counter()
    incr = rng.integers(1, 50, num_series)
    for t in range(n_samples):
        ts = (START + t * 10) * 1000
        for i, key in enumerate(keys):
            per_shard[shards[i]].add(
                IngestRecord(key, ts, (float((t + 1) * incr[i]),)))
        for s, cont in per_shard.items():
            if len(cont):
                ms.get_shard("hicard", s).ingest(
                    SomeData(BytesContainer(cont.serialize()), offset))
                offset += 1
                rows += len(cont)
        per_shard = {s: RecordContainer() for s in range(num_shards)}
    build_dt = time.perf_counter() - t0
    return ms, rows, build_dt


def run_queries(svc, n: int, start_sec: int, end_sec: int, step: int = 60):
    lat = []
    for _ in range(n):
        t0 = time.perf_counter()
        r = svc.query_range(QUERY, start_sec, step, end_sec)
        lat.append(time.perf_counter() - t0)
        assert r.result.num_series == 5, r.result.num_series
    lat = np.asarray(lat)
    return {
        "qps": round(n / lat.sum(), 2),
        "p50_ms": round(float(np.percentile(lat, 50)) * 1000, 1),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1000, 1),
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--series", type=int, default=100_000)
    ap.add_argument("--shards", type=int, default=128)
    ap.add_argument("--samples", type=int, default=60)  # 10min @ 10s
    ap.add_argument("--apps", type=int, default=100)
    ap.add_argument("--queries", type=int, default=30)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax
        import jax._src.xla_bridge as xb
        xb._backend_factories.pop("axon", None)  # hangs when tunnel is down
        jax.config.update("jax_platforms", "cpu")

    from filodb_tpu.coordinator.query_service import QueryService

    ms, rows, build_dt = build(args.series, args.shards, args.samples,
                               args.apps)
    start_sec = START + 120
    end_sec = START + args.samples * 10 - 60

    out = {"metric": "topk_hicard", "series": args.series,
           "shards": args.shards, "samples_ingested": rows,
           "ingest_samples_per_sec": round(rows / build_dt),
           "query": QUERY}
    svc = QueryService(ms, "hicard", args.shards, spread=7)
    svc.query_range(QUERY, start_sec, 60, end_sec)  # warm/compile
    out["exec_engine"] = run_queries(svc, args.queries, start_sec, end_sec)

    mesh_svc = QueryService(ms, "hicard", args.shards, spread=7,
                            engine="mesh")
    if mesh_svc.mesh_engine is not None and mesh_svc._mesh_eligible():
        mesh_svc.query_range(QUERY, start_sec, 60, end_sec)
        out["mesh_engine"] = run_queries(mesh_svc, args.queries, start_sec,
                                         end_sec)
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
