"""Big-scan scaling curve across mesh sizes (1 → N virtual devices).

Measures the headline big-scan query (``bench.BIG_QUERY`` over
``bench.BIG_SERIES`` series) at several mesh widths, comparing the
mesh-sharded split pipeline (prepare/bounds cached, tiny per-query step)
against the single-program fused baseline (``FILODB_MESH_SPLIT=0``), and
asserts the two forms return byte-identical PromQL results before any
number is reported.

Device count is fixed at backend initialization, so each mesh width runs
in a child process launched with
``XLA_FLAGS=--xla_force_host_platform_device_count=N``.  The parent
aggregates the children's JSON lines into one curve record — this is what
``benchmarks/run_benchmarks.py --devices`` prints and what the
BENCH_LOCAL.md scaling table is built from.

On a single-core container the device-count axis cannot show wall-clock
parallel speedup (all virtual devices share one core); the curve instead
verifies the sharded program stays correct and does not REGRESS as the
mesh widens, while the split-vs-fused column shows the algorithmic win.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_DEVICES = (1, 2, 4, 8)
WARMUPS = 2
ITERS = 5


def _measure_form(engine, lows, memstore, split: bool) -> tuple[float, bytes]:
    """Warm ms/query for one form plus the result bytes for equality."""
    os.environ["FILODB_MESH_SPLIT"] = "1" if split else "0"
    out = None
    for _ in range(WARMUPS):
        out = engine.execute_lowered_many(lows, memstore,
                                          "timeseries")[0].materialize()
    import numpy as np
    blob = (np.asarray(out.values).tobytes()
            + np.asarray(out.steps_ms).tobytes())
    t0 = time.perf_counter()
    for _ in range(ITERS):
        engine.execute_lowered_many(lows, memstore,
                                    "timeseries")[0].materialize()
    return (time.perf_counter() - t0) / ITERS * 1e3, blob


def child(n_devices: int) -> dict:
    """Runs inside a process whose backend exposes ``n_devices`` devices."""
    import bench

    # the parent already ran the accelerator probe once for the whole
    # sweep; this either short-circuits on FILODB_BENCH_CPU or hits the
    # fresh TTL outcome cache — never a per-width re-probe
    bench._ensure_backend()
    import jax

    assert len(jax.devices()) >= n_devices, (
        f"backend has {len(jax.devices())} devices, need {n_devices} "
        "(parent must set --xla_force_host_platform_device_count)")
    from filodb_tpu.parallel.mesh_engine import (
        MeshQueryEngine,
        make_query_mesh,
    )
    from filodb_tpu.promql.parser import TimeStepParams

    svc = bench.build_big_service("mesh")
    start_sec = bench.START_SEC + 3600
    end_sec = start_sec + bench.BIG_RANGE_SEC
    plan = svc._parse_cached(bench.BIG_QUERY, TimeStepParams(
        start_sec, bench.QUERY_STEP_SEC, end_sec))
    engine = MeshQueryEngine(mesh=make_query_mesh(n_devices=n_devices))
    lows = [engine._lower(plan)]
    assert lows[0] is not None, "big-scan query must lower"
    split_ms, split_blob = _measure_form(engine, lows, svc.memstore, True)
    fused_ms, fused_blob = _measure_form(engine, lows, svc.memstore, False)
    assert split_blob == fused_blob, (
        f"split/fused results differ at {n_devices} devices")
    return {"devices": n_devices,
            "split_ms_per_query": round(split_ms, 1),
            "fused_ms_per_query": round(fused_ms, 1),
            "identical_results": True}


def run_sweep(devices=DEFAULT_DEVICES) -> dict:
    """Spawn one child per mesh width and aggregate the curve.

    The accelerator probe runs AT MOST ONCE per sweep: the parent probes
    here (writing bench's TTL outcome cache), and each child then either
    skips probing entirely (CPU outcome → ``FILODB_BENCH_CPU=1``) or
    reads the just-written cache — BENCH_r05 burned ~16 minutes when
    every width re-probed a dead tunnel."""
    import bench

    platform, _ = bench._ensure_backend()
    curve = []
    for n in devices:
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count={n}")
        if platform == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            env["FILODB_BENCH_CPU"] = "1"
        env.pop("FILODB_MESH_SPLIT", None)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", str(n)],
            env=env, capture_output=True, text=True, timeout=1800)
        if proc.returncode != 0:
            curve.append({"devices": n, "error":
                          proc.stderr.strip().splitlines()[-1:]})
            continue
        curve.append(json.loads(proc.stdout.strip().splitlines()[-1]))
    out = {"metric": "mesh_scaling", "unit": "ms/query", "curve": curve}
    ok = [r for r in curve if "error" not in r]
    base = next((r["fused_ms_per_query"] for r in ok if r["devices"] == 1),
                None)
    best = min((r["split_ms_per_query"] for r in ok), default=None)
    if base and best:
        out["split_speedup_vs_single_lane_fused"] = round(base / best, 2)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", type=int, default=None,
                    help="internal: measure at N devices in THIS process")
    ap.add_argument("--devices", default=",".join(map(str, DEFAULT_DEVICES)),
                    help="comma-separated mesh widths for the sweep")
    args = ap.parse_args(argv)
    if args.child is not None:
        print(json.dumps(child(args.child)), flush=True)
        return 0
    widths = tuple(int(x) for x in args.devices.split(",") if x.strip())
    print(json.dumps(run_sweep(widths)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
