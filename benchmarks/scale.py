"""Series-scale benchmark: how many actively-ingesting series one node holds.

The reference claims ~1M+ actively ingesting series per node, memory-bound
(``README.md:409-413``). This benchmark ingests N series with a few samples
each, reports per-series memory and sustained ingest rate at that
cardinality, then runs an indexed query over a 1%-of-N shard-key slice.

    python benchmarks/scale.py [--series 1000000] [--cpu]
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import resource
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

START = 1_600_000_000


def rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--series", type=int, default=1_000_000)
    ap.add_argument("--samples", type=int, default=5)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax
        import jax._src.xla_bridge as xb
        xb._backend_factories.pop("axon", None)  # hangs when tunnel is down
        jax.config.update("jax_platforms", "cpu")

    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.partkey import METRIC_LABEL, PartKey
    from filodb_tpu.core.record import (
        BytesContainer,
        IngestRecord,
        RecordContainer,
        SomeData,
    )
    from filodb_tpu.core.store.config import StoreConfig

    from filodb_tpu.core.store.api import (
        InMemoryColumnStore,
        InMemoryMetaStore,
    )
    ms = TimeSeriesMemStore(InMemoryColumnStore(), InMemoryMetaStore())
    # small chunk size bounds the per-series write-buffer footprint, the way
    # the reference sizes WriteBufferPool appenders for high cardinality
    shard = ms.setup("scale", 0, StoreConfig(max_chunk_size=64,
                                             groups_per_shard=64))
    rss0 = rss_mb()
    n = args.series
    batch = 20_000

    # Containers arrive as serialized bytes (gateway → log → shard), so the
    # timed region is shard ingest of container BYTES — record building is
    # the producer's cost (reference IngestionBenchmark likewise ingests
    # pre-built containers). Bytes are built per batch outside the timer.
    def batch_bytes(s: int, lo: int, hi: int) -> bytes:
        c = RecordContainer()
        for i in range(lo, hi):
            key = PartKey.create("gauge", {
                METRIC_LABEL: "scale_metric", "_ws_": "w",
                "_ns_": f"ns-{i % 100}", "instance": str(i)})
            c.add(IngestRecord(key, (START + s * 10) * 1000, (float(i),)))
        return c.serialize()

    create_dt = 0.0
    for lo in range(0, n, batch):
        raw = batch_bytes(0, lo, min(lo + batch, n))
        t0 = time.perf_counter()
        shard.ingest(SomeData(BytesContainer(raw), lo // batch))
        create_dt += time.perf_counter() - t0

    # steady-state: more samples for every series
    steady_dt = 0.0
    rows = 0
    for s in range(1, args.samples):
        for lo in range(0, n, batch):
            raw = batch_bytes(s, lo, min(lo + batch, n))
            t0 = time.perf_counter()
            rows += shard.ingest(SomeData(BytesContainer(raw),
                                          s * 1000 + lo // batch))
            steady_dt += time.perf_counter() - t0
    gc.collect()
    rss1 = rss_mb()

    svc = QueryService(ms, "scale", 1, spread=0)
    t0 = time.perf_counter()
    r = svc.query_range('count(scale_metric{_ns_="ns-7"})',
                        START + args.samples * 10, 60,
                        START + args.samples * 10)
    q_dt = time.perf_counter() - t0

    # restart: index snapshot write + snapshot-restored recover
    # (reference target: Lucene index ready without a full part-key scan)
    t0 = time.perf_counter()
    snap_bytes = shard.snapshot_index()
    snap_dt = time.perf_counter() - t0
    ms3 = TimeSeriesMemStore(ms.column_store, ms.meta_store)
    t0 = time.perf_counter()
    s3 = ms3.setup("scale", 0, StoreConfig(max_chunk_size=64,
                                           groups_per_shard=64))
    restored = s3.recover_index()
    restart_dt = time.perf_counter() - t0

    out = {
        "series": n,
        "create_series_per_sec": round(n / create_dt),
        "steady_ingest_samples_per_sec": round(rows / steady_dt)
        if rows else None,
        "per_series_bytes": round((rss1 - rss0) * 1024 * 1024 / n),
        "rss_mb": round(rss1, 1),
        "slice_query_series": int(r.result.values[0, 0]),
        "slice_query_sec": round(q_dt, 3),
        "index_snapshot_mb": round(snap_bytes / 1e6, 1),
        "index_snapshot_write_sec": round(snap_dt, 2),
        "restart_index_ready_sec": round(restart_dt, 2),
        "restart_series_restored": restored,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
