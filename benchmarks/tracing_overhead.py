"""Tracing overhead: sampled vs unsampled query latency.

Dashboard-style workload (8192 gauge series over 4 shards, the panel mix
from ``serving.py --dashboard``) run twice through the same QueryService:
once with ``sample_rate=0.0`` (head sampler declines every query; span()
calls are thread-local no-ops) and once with ``sample_rate=1.0`` (every
query builds a full span tree and feeds the stage histograms). The delta
is what tracing costs; the unsampled path is the one production serves at
low sample rates, so its overhead must stay in the noise (<2% p50 target).

A micro-bench of the no-op ``span()`` path is included so the per-call
cost of dormant instrumentation is visible independently of query noise.

    python benchmarks/tracing_overhead.py [--series 8192] [--cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

START = 1_600_000_000


def bench_tracing_overhead(series: int = 8192, refreshes: int = 3):
    from filodb_tpu.coordinator.ingestion import ingest_routed
    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.query.model import PlannerParams, QueryContext
    from filodb_tpu.testing.data import gauge_stream, machine_metrics_series
    from filodb_tpu.utils import tracing

    num_shards = 4
    interval_ms = 30_000
    step = 60
    base_samples = 240                   # 2h of history
    window_s = 3_600                     # 1h dashboard window
    ms = TimeSeriesMemStore()
    for s in range(num_shards):
        ms.setup("timeseries", s,
                 StoreConfig(max_chunk_size=400, groups_per_shard=4,
                             retention_ms=10**15))
    half = series // 2
    for kk in (machine_metrics_series(half, ns="App-2"),
               machine_metrics_series(series - half, ns="App-3")):
        ingest_routed(ms, "timeseries",
                      gauge_stream(kk, base_samples, start_ms=START * 1000,
                                   interval_ms=interval_ms, seed=9),
                      num_shards, spread=1)

    svc = QueryService(ms, "timeseries", num_shards, spread=1)
    panels = [
        "sum(rate(heap_usage[5m]))",
        "sum by (host) (rate(heap_usage[5m]))",
        "avg_over_time(heap_usage[5m])",
        "max_over_time(heap_usage[10m])",
        "max by (host) (avg_over_time(heap_usage[5m]))",
    ]
    qe0 = START + (base_samples - 1) * interval_ms // 1000

    def run_panel(promql, qe):
        ctx = QueryContext(
            planner_params=PlannerParams(sample_limit=50_000_000))
        t0 = time.perf_counter()
        svc.query_range(promql, qe - window_s, step, qe, ctx)
        return time.perf_counter() - t0

    prev = {f: getattr(tracing.config(), f)
            for f in ("sample_rate", "slow_query_threshold_ms",
                      "slowlog_capacity")}
    lat = {"unsampled": [], "sampled": []}
    try:
        # warm compile caches so neither mode pays tracing-unrelated
        # first-run costs
        for promql in panels:
            run_panel(promql, qe0)
        for refresh in range(refreshes):
            qe = qe0 + refresh * step
            # alternate mode order per refresh so drift (cache warmth,
            # allocator state) doesn't bias one side
            modes = [("unsampled", 0.0), ("sampled", 1.0)]
            if refresh % 2:
                modes.reverse()
            for name, rate in modes:
                tracing.configure(sample_rate=rate,
                                  slow_query_threshold_ms=10**9,
                                  slowlog_capacity=8)
                for promql in panels:
                    lat[name].append(run_panel(promql, qe))
    finally:
        tracing.configure(**prev)
        tracing.flight_recorder().clear()

    # dormant-instrumentation micro: span() with no active trace
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        with tracing.span("noop"):
            pass
    noop_ns = (time.perf_counter() - t0) / n * 1e9

    def pct(xs, p):
        return round(float(np.percentile(np.array(xs), p)) * 1000, 2)

    un_p50, sa_p50 = pct(lat["unsampled"], 50), pct(lat["sampled"], 50)
    return {
        "metric": "tracing_overhead",
        "series": series,
        "panels": len(panels),
        "refreshes": refreshes,
        "unsampled_p50_ms": un_p50,
        "unsampled_p99_ms": pct(lat["unsampled"], 99),
        "sampled_p50_ms": sa_p50,
        "sampled_p99_ms": pct(lat["sampled"], 99),
        "sampled_overhead_pct": round(
            (sa_p50 - un_p50) / max(un_p50, 1e-9) * 100, 2),
        "noop_span_ns": round(noop_ns, 1),
        "unit": "ms",
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--series", type=int, default=8192)
    ap.add_argument("--refreshes", type=int, default=3)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax
        import jax._src.xla_bridge as xb
        xb._backend_factories.pop("axon", None)
        jax.config.update("jax_platforms", "cpu")
    print(json.dumps(bench_tracing_overhead(args.series, args.refreshes)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
