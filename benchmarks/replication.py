"""Replication benchmark: warm map-flip failover vs cold recovery, and
hedged replica reads vs single-target tail latency.

Two measurements for the continuous-replication subsystem
(``coordinator/replication.py``):

- ``failover``: time from node loss to every lost shard serving again —
  once with an IN_SYNC follower per shard (promotion = ONE sequenced
  ACTIVE event, ingest resumes at the follower's applied offset) and once
  without replicas (cold recovery: DOWN, reassign, manifest read, index
  recovery, WAL replay from the checkpoints).
- ``hedged reads``: p50/p99 dispatch latency over a replica set whose
  primary stalls on a fraction of calls, with the hedge timer on vs
  dispatching at the primary alone (the reference's tail-latency story).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

START = 1_600_000_000
NUM_SHARDS = 4


def _publish(logs, stream, num_shards, spread=1):
    from filodb_tpu.coordinator.ingestion import route_container

    for sd in stream:
        for shard, cont in route_container(sd.container, num_shards,
                                           spread).items():
            logs[shard].append(cont)


def _build(replication: int):
    import tempfile

    from filodb_tpu.coordinator.cluster import FilodbCluster, Node
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.config import IngestionConfig, StoreConfig
    from filodb_tpu.core.store.objectstore import open_object_store
    from filodb_tpu.kafka.log import InMemoryLog
    from filodb_tpu.testing.data import gauge_stream, machine_metrics_series

    tmp = tempfile.mkdtemp(prefix="filodb-repl-")
    logs = {s: InMemoryLog() for s in range(NUM_SHARDS)}
    keys = machine_metrics_series(96, ns="App-3")
    _publish(logs, gauge_stream(keys, 480, start_ms=START * 1000),
             NUM_SHARDS)
    cluster = FilodbCluster(replica_in_sync_lag=0,
                            replica_durable_sync_s=3600.0)
    # per-node store instances over a shared bucket: cold recovery pays
    # real manifest/segment reads, the warm flip must pay none
    for n in ("node-a", "node-b", "node-c"):
        cs, meta = open_object_store({"endpoint": None, "bucket": "bench"},
                                     tmp)
        cluster.join(Node(n, TimeSeriesMemStore(cs, meta)))
    cluster.setup_dataset(
        IngestionConfig("timeseries", NUM_SHARDS, min_num_nodes=2,
                        store=StoreConfig(max_chunk_size=60,
                                          groups_per_shard=2)), logs)
    assert cluster.wait_active("timeseries", 15)
    # seal + checkpoint, then publish a WAL tail past the checkpoints:
    # cold recovery replays it from the durable watermarks; a promoted
    # follower already holds it and resumes at its applied offset
    for node in cluster.nodes.values():
        for (ds, s) in list(node._workers):
            node.memstore.get_shard(ds, s).flush_all()
        fl = getattr(node.memstore.column_store, "flush", None)
        if callable(fl):
            fl()
    _publish(logs, gauge_stream(keys, 240,
                                start_ms=(START + 9600) * 1000),
             NUM_SHARDS)
    # warm the query path (plan build + kernel compile) so the failover
    # measurement times the flip/recovery, not one-time compilation
    cluster.query_service("timeseries", spread=1).query_range(
        'sum(heap_usage{_ns_="App-3"})', START + 600, 300, START + 1500)
    if replication:
        cluster.replication = replication
        sm = cluster.shard_managers["timeseries"]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(sm.mapper.in_sync_followers(s)
                   and all(st.watermark >= logs[s].latest_offset
                           for st in sm.mapper.replicas_of(s).values())
                   for s in range(NUM_SHARDS)):
                break
            cluster.ensure_replicas("timeseries")
            time.sleep(0.02)
    return cluster


def _failover_ms(cluster) -> float:
    """Kill node-a; time until every shard is owned + ACTIVE again — the
    unavailability window (promotion or recovery runs synchronously inside
    ``leave``).  A full fan-out query afterwards validates the result but
    is kept out of the timed window since its cost is identical on both
    paths.  Also reports the objectstore GETs the path issued — the flip's
    zero-GET property is machine-independent, unlike wall time over a
    local-disk FakeS3."""
    from filodb_tpu.coordinator.shardmapper import ShardStatus
    from filodb_tpu.core.store.objectstore import GETS

    sm = cluster.shard_managers["timeseries"]
    gets0 = GETS.value
    t0 = time.perf_counter()
    cluster.leave("node-a")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if all(sm.mapper.node_for(s) is not None
               and sm.mapper.statuses[s] == ShardStatus.ACTIVE
               for s in range(NUM_SHARDS)):
            break
        time.sleep(0.0005)
    ms = (time.perf_counter() - t0) * 1000.0
    res = cluster.query_service("timeseries", spread=1).query_range(
        'sum(heap_usage{_ns_="App-3"})', START + 600, 300, START + 1500)
    assert res, "post-failover query returned no series"
    return ms, GETS.value - gets0


def _hedge_latencies(hedge: bool, n: int = 300):
    """Dispatch over a 2-candidate replica set whose primary stalls every
    5th call; with the hedge timer off the set degenerates to the primary
    alone."""
    from filodb_tpu.coordinator.replication import (
        ReplicaCandidate,
        ReplicaDispatcher,
    )
    from filodb_tpu.query.exec.plan import PlanDispatcher

    class _Stub(PlanDispatcher):
        def __init__(self, base_s, stall_s=0.0, stall_every=0):
            self.base_s, self.stall_s = base_s, stall_s
            self.stall_every, self.calls = stall_every, 0

        def dispatch(self, plan, ctx):
            self.calls += 1
            slow = self.stall_every and self.calls % self.stall_every == 0
            time.sleep(self.stall_s if slow else self.base_s)
            return "ok"

    cands = [ReplicaCandidate("bench-leader",
                              _Stub(0.001, stall_s=0.040, stall_every=5))]
    if hedge:
        cands.append(ReplicaCandidate("bench-follower", _Stub(0.002),
                                      follower=True))
    rd = ReplicaDispatcher(0, cands, hedge_timeout_s=0.005)
    lat = []
    for _ in range(n):
        t0 = time.perf_counter()
        rd.dispatch(None, None)
        lat.append((time.perf_counter() - t0) * 1000.0)
    lat.sort()
    return lat[len(lat) // 2], lat[int(len(lat) * 0.99)]


def bench_replication():
    from filodb_tpu.utils.resilience import reset_breakers, reset_peer_latency

    warm_cluster = _build(replication=1)
    warm_ms, warm_gets = _failover_ms(warm_cluster)
    warm_cluster.stop()
    cold_cluster = _build(replication=0)
    cold_ms, cold_gets = _failover_ms(cold_cluster)
    cold_cluster.stop()
    reset_breakers()
    reset_peer_latency()
    hedged_p50, hedged_p99 = _hedge_latencies(hedge=True)
    solo_p50, solo_p99 = _hedge_latencies(hedge=False)
    return {"metric": "replication",
            "warm_failover_ms": round(warm_ms, 1),
            "cold_failover_ms": round(cold_ms, 1),
            "failover_speedup": round(cold_ms / max(warm_ms, 1e-9), 2),
            "warm_failover_gets": warm_gets,
            "cold_failover_gets": cold_gets,
            "hedged_p50_ms": round(hedged_p50, 2),
            "hedged_p99_ms": round(hedged_p99, 2),
            "unhedged_p50_ms": round(solo_p50, 2),
            "unhedged_p99_ms": round(solo_p99, 2),
            "unit": "ms"}


if __name__ == "__main__":
    import json

    print(json.dumps(bench_replication()))
