"""Migration-under-load soak: query latency while a shard live-migrates.

A two-node cluster serves a steady closed-loop query workload; midway
through, one shard is migrated node-a → node-b through the full
PLANNED → SYNCING → CATCHUP → FLIPPING → DONE state machine. The
property being demonstrated: the HANDOFF queryability rule keeps the
shard answering on the source until the atomic flip, so p99 during the
migration stays within a small factor of baseline and NO query returns a
wrong result (every result is checked against a pre-migration control).

    python benchmarks/migration.py           # standalone, one JSON line
    python benchmarks/run_benchmarks.py --only migration
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

START = 1_600_000_000
NUM_SHARDS = 4
N_SERIES = 24
N_SAMPLES = 240

QUERY = 'sum(heap_usage{_ns_="App-0"})'
QS, STEP, QE = START + 600, 300, START + 1500

BASELINE_SECONDS = 1.5
SOAK_CLIENTS = 4


def _p(xs, q):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))] if xs else float("nan")


def _build():
    from filodb_tpu.coordinator.cluster import FilodbCluster, Node
    from filodb_tpu.coordinator.ingestion import route_container
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.api import (
        InMemoryColumnStore,
        InMemoryMetaStore,
    )
    from filodb_tpu.core.store.config import IngestionConfig, StoreConfig
    from filodb_tpu.kafka.log import InMemoryLog
    from filodb_tpu.testing.data import gauge_stream, machine_metrics_series

    cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
    logs = {s: InMemoryLog() for s in range(NUM_SHARDS)}
    keys = machine_metrics_series(N_SERIES)
    for sd in gauge_stream(keys, N_SAMPLES, start_ms=START * 1000):
        for shard, cont in route_container(sd.container, NUM_SHARDS,
                                           1).items():
            logs[shard].append(cont)
    cluster = FilodbCluster()
    for n in ("node-a", "node-b"):
        cluster.join(Node(n, TimeSeriesMemStore(cs, meta)))
    cluster.setup_dataset(
        IngestionConfig("timeseries", NUM_SHARDS, min_num_nodes=2,
                        store=StoreConfig(max_chunk_size=120,
                                          groups_per_shard=2)), logs)
    assert cluster.wait_active("timeseries", 15)
    return cluster


def bench_migration():
    import numpy as np

    cluster = _build()
    svc = cluster.query_service("timeseries", spread=1)
    control = svc.query_range(QUERY, QS, STEP, QE).result.values
    sm = cluster.shard_managers["timeseries"]
    shard = next(s for s in range(NUM_SHARDS)
                 if sm.mapper.node_for(s) == "node-a")

    lock = threading.Lock()
    lat, wrong = {"baseline": [], "migrating": []}, [0]
    phase = ["baseline"]
    running = [True]

    def client():
        while running[0]:
            t0 = time.perf_counter()
            vals = svc.query_range(QUERY, QS, STEP, QE).result.values
            dt = time.perf_counter() - t0
            ok = np.allclose(vals, control, rtol=1e-9)
            with lock:
                lat[phase[0]].append(dt)
                if not ok:
                    wrong[0] += 1

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(SOAK_CLIENTS)]
    for t in threads:
        t.start()
    try:
        time.sleep(BASELINE_SECONDS)
        with lock:
            phase[0] = "migrating"
        t0 = time.perf_counter()
        mig = cluster.migrate_shard("timeseries", shard, "node-b")
        mig_s = time.perf_counter() - t0
        assert mig.phase == "done"
    finally:
        running[0] = False
        for t in threads:
            t.join(timeout=30)
    cluster.stop()

    base, soak = lat["baseline"], lat["migrating"]
    base_p99, soak_p99 = _p(base, 0.99) * 1e3, _p(soak, 0.99) * 1e3
    return {"metric": "migration_soak", "clients": SOAK_CLIENTS,
            "migration_s": round(mig_s, 3),
            "baseline_p50_ms": round(_p(base, 0.5) * 1e3, 2),
            "baseline_p99_ms": round(base_p99, 2),
            "migrating_p50_ms": round(_p(soak, 0.5) * 1e3, 2),
            "migrating_p99_ms": round(soak_p99, 2),
            "p99_blowup_x": round(soak_p99 / base_p99, 2)
            if base_p99 else float("nan"),
            "queries_during_migration": len(soak),
            "wrong_results": wrong[0],
            "unit": "ms"}


if __name__ == "__main__":
    print(json.dumps(bench_migration()))
