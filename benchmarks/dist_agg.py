"""Distributed aggregation pushdown benchmark: wire bytes + root latency
vs series cardinality, pushdown on/off.

The reference ships one row per group from each leaf node
(``AggrOverRangeVectors.scala``); this measures what that buys on our
TCP plan-shipping path: every shard child of a ``sum(rate(...)) by``
query executes on a remote ``PlanExecutorServer`` and the root either
gathers full per-series matrices (pushdown off) or per-group partials
(pushdown on). Frame compression is active in both modes, so the
reported reduction is attributable to the pushdown alone.

    python benchmarks/dist_agg.py            # standalone, one JSON line
    python benchmarks/run_benchmarks.py --only dist_agg
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

START = 1_600_000_000
NUM_SHARDS = 4
N_SAMPLES = 40
INTERVAL_MS = 15_000
REPEAT = 3

QUERY = "sum(rate(heap_usage[2m])) by (host)"
QS = START + 150
QE = START + N_SAMPLES * (INTERVAL_MS // 1000)
STEP = 60


def _build(cardinality: int):
    from filodb_tpu.coordinator.ingestion import ingest_routed
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.testing.data import gauge_stream, machine_metrics_series

    ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=400,
                                              groups_per_shard=4))
    stream = gauge_stream(machine_metrics_series(cardinality), N_SAMPLES,
                          start_ms=START * 1000, interval_ms=INTERVAL_MS,
                          batch=1000, seed=5)
    ingest_routed(ms, "timeseries", stream, NUM_SHARDS, spread=1)
    return ms


def _measure(svc, mode: str):
    """(min wall seconds, wire bytes received per query) for one mode."""
    from filodb_tpu.coordinator import remote as rm

    svc.planner.agg_pushdown = mode
    svc.query_range(QUERY, QS, STEP, QE)  # warm compile + connections
    best, nbytes = float("inf"), 0
    for _ in range(REPEAT):
        b0 = rm.BYTES_RECEIVED.value
        t0 = time.perf_counter()
        svc.query_range(QUERY, QS, STEP, QE)
        best = min(best, time.perf_counter() - t0)
        nbytes = rm.BYTES_RECEIVED.value - b0
    return best, nbytes


def bench_dist_agg(cardinalities=(1024, 8192)):
    from filodb_tpu.coordinator import remote as rm
    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.coordinator.remote import (
        PlanExecutorServer,
        RemotePlanDispatcher,
        reset_pool,
    )

    points = []
    for card in cardinalities:
        ms = _build(card)
        srv = PlanExecutorServer(ms).start()
        disp = RemotePlanDispatcher("127.0.0.1", srv.port)
        svc = QueryService(ms, "timeseries", NUM_SHARDS, spread=1)
        svc.planner.dispatcher_for_shard = lambda s: disp
        try:
            t_off, b_off = _measure(svc, "off")
            t_on, b_on = _measure(svc, "auto")
        finally:
            srv.stop()
            reset_pool()
        points.append({
            "series": card,
            "bytes_off": b_off, "bytes_on": b_on,
            "bytes_reduction_x": round(b_off / max(b_on, 1), 1),
            "latency_off_ms": round(t_off * 1e3, 1),
            "latency_on_ms": round(t_on * 1e3, 1),
        })
    ratio = (rm.COMPRESS_BYTES_IN.value
             / max(rm.COMPRESS_BYTES_OUT.value, 1))
    return {"metric": "dist_agg_pushdown", "query": QUERY,
            "shards": NUM_SHARDS, "remote": True,
            "points": points,
            "wire_compression_ratio": round(ratio, 2),
            "unit": "bytes + ms per query"}


def main():
    out = bench_dist_agg()
    out["benchmark"] = "dist_agg"
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    sys.exit(main())
