"""Standing queries vs dashboard polling (filodb_tpu/rules).

The workload the rules subsystem exists to amortize: a dashboard panel
showing ``sum(avg_over_time(heap_usage[5m]))`` over 8192 series,
refreshed every minute. Polling re-evaluates the full trailing window
every refresh; the standing query evaluates only the one newly-completed
step per tick and the dashboard reads the recorded output series (one
series, pre-aggregated) instead.

Reported: amortized per-refresh cost of each strategy on the same
advancing store, and the speedup. The rules cost INCLUDES the write-back
and the dashboard's read of the recorded series — it is the end-to-end
cost of serving the same panel.
"""

from __future__ import annotations

import time

START = 1_600_000_000
N_SERIES = 8192
REFRESHES = 6
PANEL_STEPS = 11               # trailing 10min window at 60s resolution
Q = "sum(avg_over_time(heap_usage[5m]))"


def bench_rules():
    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.rules import MemstoreSink, RecordingRule, RuleGroup, \
        RuleManager
    from filodb_tpu.testing.data import gauge_stream, machine_metrics_series

    ms = TimeSeriesMemStore()
    shard = ms.setup("bench", 0, StoreConfig(max_chunk_size=400))
    keys = machine_metrics_series(N_SERIES)
    # batch == len(keys): exactly one container per timestep, so the
    # pre-generated stream can be fed forward one minute at a time.
    total = 90 + 2 * REFRESHES * 6
    stream = iter(gauge_stream(keys, total, start_ms=START * 1000,
                               batch=len(keys), seed=11))

    def advance(n_samples):
        for _ in range(n_samples):
            shard.ingest(next(stream))

    advance(90)                # 15min of history before the panel exists

    def horizon_s():
        return shard.max_ingested_ts // 60_000 * 60

    # -- strategy 1: dashboard polling (no rules) -------------------------
    poll_svc = QueryService(ms, "bench", 1, spread=0)
    poll_svc.query_range(Q, horizon_s() - 600, 60, horizon_s())  # compile
    t_poll = 0.0
    for _ in range(REFRESHES):
        advance(6)             # one minute of new samples
        end = horizon_s()
        t0 = time.perf_counter()
        r = poll_svc.query_range(Q, end - (PANEL_STEPS - 1) * 60, 60, end)
        t_poll += time.perf_counter() - t0
        assert r.result.num_series == 1

    # -- strategy 2: standing query + panel reads the recorded series ----
    # extent_steps=1: one extent per rule step, so a tick never recomputes
    # a partially-filled head extent — it evaluates exactly the new step.
    rule_svc = QueryService(ms, "bench", 1, spread=0,
                            result_cache={"extent_steps": 1,
                                          "ooo_allowance_ms": 0})
    mgr = RuleManager(
        rule_svc, MemstoreSink(ms, "bench", 1, spread=0),
        [RuleGroup(name="panel", interval_ms=60_000, dataset="bench",
                   rules=(RecordingRule(record="panel:heap:sum", expr=Q),))],
        ooo_allowance_ms=0)
    mgr.tick()                 # fresh start: one step, primes the output
    wm = mgr._state["panel"].last_step // 1000
    rule_svc.query_range("panel:heap:sum", wm - 60, 60, wm)  # compile
    t_tick = t_read = 0.0
    for _ in range(REFRESHES):
        advance(6)
        t0 = time.perf_counter()
        assert mgr.tick() >= 1                    # only the new step(s)
        t_tick += time.perf_counter() - t0
        end = mgr._state["panel"].last_step // 1000
        t0 = time.perf_counter()
        r = rule_svc.query_range("panel:heap:sum",
                                 end - (PANEL_STEPS - 1) * 60, 60, end)
        t_read += time.perf_counter() - t0
        assert r.result.num_series == 1

    # Per refresh: polling scans all raw series for every consumer; the
    # standing query scans them once per tick and every consumer reads
    # the single recorded series. Speedup at V consumers is therefore
    # V*poll / (tick + V*read).
    poll_ms = t_poll / REFRESHES * 1000
    tick_ms = t_tick / REFRESHES * 1000
    read_ms = t_read / REFRESHES * 1000

    def speedup(v):
        return round(v * poll_ms / (tick_ms + v * read_ms), 2)

    return {"metric": "standing_rules_vs_polling", "series": N_SERIES,
            "refreshes": REFRESHES, "panel_steps": PANEL_STEPS,
            "poll_ms_per_refresh": round(poll_ms, 1),
            "rule_tick_ms": round(tick_ms, 1),
            "recorded_read_ms": round(read_ms, 2),
            "speedup_1_consumer": speedup(1),
            "speedup_8_consumers": speedup(8), "unit": "ms/refresh"}


if __name__ == "__main__":
    import json
    print(json.dumps(bench_rules()))
