"""Soak / stress harness.

Counterpart of the reference ``stress/`` module (``IngestionStress``,
``MemStoreStress`` — Spark-driven soak jobs, disabled in the reference
build): sustained high-cardinality ingest with series churn, concurrent
queries, periodic flush + memory-pressure eviction + TTL purge, asserting
invariants throughout. Run manually:

    python benchmarks/stress.py [--seconds 30] [--series 5000]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

START = 1_600_000_000


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=20.0)
    ap.add_argument("--series", type=int, default=2000)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--device-pages", action="store_true")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax
        import jax._src.xla_bridge as xb
        xb._backend_factories.pop("axon", None)  # hangs when tunnel is down
        jax.config.update("jax_platforms", "cpu")

    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.core.record import IngestRecord, RecordContainer, SomeData
    from filodb_tpu.core.store.api import (
        InMemoryColumnStore,
        InMemoryMetaStore,
    )
    from filodb_tpu.core.store.config import StoreConfig

    ms = TimeSeriesMemStore(InMemoryColumnStore(), InMemoryMetaStore())
    shard = ms.setup("stress", 0, StoreConfig(
        max_chunk_size=200, groups_per_shard=8, flush_task_parallelism=4,
        device_pages=args.device_pages))
    svc = QueryService(ms, "stress", 1, spread=0)
    stop = threading.Event()
    errors: list[str] = []
    stats = {"rows": 0, "queries": 0, "flushes": 0, "evictions": 0,
             "churned": 0}

    def ingester():
        rng = np.random.default_rng(0)
        t = START * 1000
        gen = 0
        while not stop.is_set():
            c = RecordContainer()
            for i in range(args.series):
                # churn: 10% of series rotate identity every pass
                sid = i if i % 10 else f"{i}g{gen}"
                key = PartKey.create("gauge", {
                    "_metric_": "stress_metric", "_ws_": "w", "_ns_": "n",
                    "instance": str(sid)})
                c.add(IngestRecord(key, t, (float(rng.normal(50, 10)),)))
            try:
                shard.ingest(SomeData(c, gen))
                stats["rows"] += len(c)
                stats["churned"] += args.series // 10
            except Exception as e:  # pragma: no cover
                errors.append(f"ingest: {e!r}")
                return
            t += 10_000
            gen += 1

    def maintainer():
        while not stop.is_set():
            time.sleep(0.5)
            try:
                shard.flush_group(shard.next_flush_group())
                stats["flushes"] += 1
                stats["evictions"] += shard.enforce_memory(
                    budget_bytes=64 * 1024 * 1024)
                # purge with a "now" aligned to the synthetic data clock
                data_now = (START + stats["rows"] // max(args.series, 1)
                            * 10) * 1000
                shard.purge_expired(data_now)
            except Exception as e:  # pragma: no cover
                errors.append(f"maintain: {e!r}")
                return

    def querier():
        while not stop.is_set():
            try:
                horizon = START + stats["rows"] // max(args.series, 1) * 10
                r = svc.query_range(
                    "sum(sum_over_time(stress_metric[5m]))",
                    horizon, 60, horizon + 60)
                if r.result.num_series > 1:
                    errors.append("aggregation produced >1 series")
                stats["queries"] += 1
            except Exception as e:
                errors.append(f"query: {e!r}")
                return

    threads = [threading.Thread(target=f, daemon=True)
               for f in (ingester, maintainer, querier)]
    for th in threads:
        th.start()
    time.sleep(args.seconds)
    stop.set()
    for th in threads:
        th.join(timeout=10)

    ok = not errors
    print(json.dumps({"ok": ok, "errors": errors[:5], **stats,
                      "partitions": shard.num_partitions}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
