"""Tiered federation benchmark: one query_range spanning memstore, the
downsample tier, and object-store history — cold (first touch pages cold
chunks over the object store) vs warm (ODP cache + settled-extent result
cache), with bytes-downloaded accounting per run.

The headline numbers the tentpole is judged on: warm must be >=3x faster
than cold and move strictly fewer object-store bytes.
"""

from __future__ import annotations

import time

import numpy as np

START = 1_600_000_000
RES = 300_000


def _percentile(xs, p):
    return float(np.percentile(np.asarray(xs, dtype=np.float64), p))


def bench_federation(n_warm: int = 30):
    from filodb_tpu.coordinator.ingestion import ingest_routed
    from filodb_tpu.coordinator.planner import SingleClusterPlanner
    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.coordinator.tiered_planner import build_tiered_planner
    from filodb_tpu.core.downsample import (
        DownsampledTimeSeriesStore,
        DownsamplerJob,
    )
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.api import InMemoryMetaStore
    from filodb_tpu.core.store.objectstore import (
        BYTES_DOWN,
        ObjectStoreColumnStore,
    )
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.testing.data import counter_series, counter_stream
    from filodb_tpu.testing.fake_s3 import FakeS3

    num_shards = 2
    s3 = FakeS3()
    cs = ObjectStoreColumnStore(s3)
    ms = TimeSeriesMemStore(cs, InMemoryMetaStore())
    for s in range(num_shards):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=120,
                                              groups_per_shard=2))
    keys = counter_series(16)
    ingest_routed(ms, "timeseries",
                  counter_stream(keys, 600, start_ms=START * 1000, seed=11),
                  num_shards, spread=0)
    ms.flush_all("timeseries")
    cs.flush()
    DownsamplerJob(cs, "timeseries", num_shards,
                   resolutions_ms=(RES,)).run(0, 2**62)
    ds_store = DownsampledTimeSeriesStore(cs, "timeseries", RES, num_shards)

    now = (START + 6000) * 1000
    raw_planner = SingleClusterPlanner("timeseries", num_shards, spread=0)
    ds_planner = SingleClusterPlanner("timeseries", num_shards, spread=0,
                                      store=ds_store)
    planner = build_tiered_planner(
        raw_planner, cs, "timeseries", num_shards,
        mem_retention_ms=now - (START + 4000) * 1000,
        raw_retention_ms=now - (START + 2000) * 1000,
        ds_planner=ds_planner, now_ms=lambda: now)
    q = ("sum(rate(http_requests_total[15m]))",
         START + 1200, 300, START + 5400)

    # compile the per-tier and per-extent kernel shapes once through a
    # throwaway caching service, then drop every federation cache: "cold"
    # measures tier paging + stitch, not one-time jit compilation
    pre = QueryService(ms, "timeseries", num_shards, spread=0,
                       result_cache={"enabled": True})
    pre.planner = planner
    pre.query_range(*q)
    planner.cold_planner.store.clear_caches()

    svc = QueryService(ms, "timeseries", num_shards, spread=0,
                       result_cache={"enabled": True})
    svc.planner = planner

    # cold: empty ODP cache, empty result cache — pages every cold chunk
    b0, g0 = BYTES_DOWN.value, s3.op_counts.get("get", 0)
    t0 = time.perf_counter()
    svc.query_range(*q)
    cold_ms = (time.perf_counter() - t0) * 1000.0
    cold_bytes = BYTES_DOWN.value - b0
    cold_gets = s3.op_counts.get("get", 0) - g0

    # warm: settled extents in the result cache, chunks in the ODP cache
    b1, g1 = BYTES_DOWN.value, s3.op_counts.get("get", 0)
    lat = []
    for _ in range(n_warm):
        t0 = time.perf_counter()
        svc.query_range(*q)
        lat.append((time.perf_counter() - t0) * 1000.0)
    warm_bytes = (BYTES_DOWN.value - b1) / n_warm
    warm_gets = (s3.op_counts.get("get", 0) - g1) / n_warm
    warm_p50, warm_p99 = _percentile(lat, 50), _percentile(lat, 99)

    return {"metric": "federation_cold_vs_warm",
            "cold_ms": round(cold_ms, 2),
            "warm_p50_ms": round(warm_p50, 3),
            "warm_p99_ms": round(warm_p99, 3),
            "speedup_p50": round(cold_ms / warm_p50, 1),
            "cold_objectstore_bytes": int(cold_bytes),
            "warm_objectstore_bytes_per_query": round(warm_bytes, 1),
            "cold_gets": int(cold_gets),
            "warm_gets_per_query": round(warm_gets, 2),
            "tiers": 3, "unit": "ms"}


if __name__ == "__main__":
    import json
    print(json.dumps(bench_federation()))
