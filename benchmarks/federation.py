"""Tiered federation benchmark: one query_range spanning memstore, the
downsample tier, and object-store history — cold (first touch pages cold
chunks over the object store) vs warm (ODP cache + settled-extent result
cache), with bytes-downloaded accounting per run.

The headline numbers the tentpole is judged on: warm must be >=3x faster
than cold and move strictly fewer object-store bytes.
"""

from __future__ import annotations

import time

import numpy as np

START = 1_600_000_000
RES = 300_000


def _percentile(xs, p):
    return float(np.percentile(np.asarray(xs, dtype=np.float64), p))


def bench_federation(n_warm: int = 30):
    from filodb_tpu.coordinator.ingestion import ingest_routed
    from filodb_tpu.coordinator.planner import SingleClusterPlanner
    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.coordinator.tiered_planner import build_tiered_planner
    from filodb_tpu.core.downsample import (
        DownsampledTimeSeriesStore,
        DownsamplerJob,
    )
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.api import InMemoryMetaStore
    from filodb_tpu.core.store.objectstore import (
        BYTES_DOWN,
        ObjectStoreColumnStore,
    )
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.testing.data import counter_series, counter_stream
    from filodb_tpu.testing.fake_s3 import FakeS3

    num_shards = 2
    s3 = FakeS3()
    cs = ObjectStoreColumnStore(s3)
    ms = TimeSeriesMemStore(cs, InMemoryMetaStore())
    for s in range(num_shards):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=120,
                                              groups_per_shard=2))
    keys = counter_series(16)
    ingest_routed(ms, "timeseries",
                  counter_stream(keys, 600, start_ms=START * 1000, seed=11),
                  num_shards, spread=0)
    ms.flush_all("timeseries")
    cs.flush()
    DownsamplerJob(cs, "timeseries", num_shards,
                   resolutions_ms=(RES,)).run(0, 2**62)
    ds_store = DownsampledTimeSeriesStore(cs, "timeseries", RES, num_shards)

    now = (START + 6000) * 1000
    raw_planner = SingleClusterPlanner("timeseries", num_shards, spread=0)
    ds_planner = SingleClusterPlanner("timeseries", num_shards, spread=0,
                                      store=ds_store)
    planner = build_tiered_planner(
        raw_planner, cs, "timeseries", num_shards,
        mem_retention_ms=now - (START + 4000) * 1000,
        raw_retention_ms=now - (START + 2000) * 1000,
        ds_planner=ds_planner, now_ms=lambda: now)
    q = ("sum(rate(http_requests_total[15m]))",
         START + 1200, 300, START + 5400)

    # compile the per-tier and per-extent kernel shapes once through a
    # throwaway caching service, then drop every federation cache: "cold"
    # measures tier paging + stitch, not one-time jit compilation
    pre = QueryService(ms, "timeseries", num_shards, spread=0,
                       result_cache={"enabled": True})
    pre.planner = planner
    pre.query_range(*q)
    planner.cold_planner.store.clear_caches()

    svc = QueryService(ms, "timeseries", num_shards, spread=0,
                       result_cache={"enabled": True})
    svc.planner = planner

    # cold: empty ODP cache, empty result cache — pages every cold chunk
    b0, g0 = BYTES_DOWN.value, s3.op_counts.get("get", 0)
    t0 = time.perf_counter()
    svc.query_range(*q)
    cold_ms = (time.perf_counter() - t0) * 1000.0
    cold_bytes = BYTES_DOWN.value - b0
    cold_gets = s3.op_counts.get("get", 0) - g0

    # warm: settled extents in the result cache, chunks in the ODP cache
    b1, g1 = BYTES_DOWN.value, s3.op_counts.get("get", 0)
    lat = []
    for _ in range(n_warm):
        t0 = time.perf_counter()
        svc.query_range(*q)
        lat.append((time.perf_counter() - t0) * 1000.0)
    warm_bytes = (BYTES_DOWN.value - b1) / n_warm
    warm_gets = (s3.op_counts.get("get", 0) - g1) / n_warm
    warm_p50, warm_p99 = _percentile(lat, 50), _percentile(lat, 99)

    return {"metric": "federation_cold_vs_warm",
            "cold_ms": round(cold_ms, 2),
            "warm_p50_ms": round(warm_p50, 3),
            "warm_p99_ms": round(warm_p99, 3),
            "speedup_p50": round(cold_ms / warm_p50, 1),
            "cold_objectstore_bytes": int(cold_bytes),
            "warm_objectstore_bytes_per_query": round(warm_bytes, 1),
            "cold_gets": int(cold_gets),
            "warm_gets_per_query": round(warm_gets, 2),
            "tiers": 3, "unit": "ms"}


def bench_federation_yearscan(repeats: int = 5):
    """Cold-tier long-history scan: demand paging (``FILODB_SIDECARS=0``,
    the pre-pyramid baseline) vs the pyramid lane folding stored
    aggregates. The grid is pinned to chunk seal boundaries — the shape
    a dashboard's aligned range query takes — so the pyramid pass pages
    ZERO chunk payload bytes; the baseline decodes every chunk. Caches
    are dropped before every timed pass (both lanes run cold)."""
    import os

    from filodb_tpu.coordinator.ingestion import ingest_routed
    from filodb_tpu.coordinator.planner import SingleClusterPlanner
    from filodb_tpu.coordinator.tiered_planner import build_tiered_planner
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.api import InMemoryMetaStore
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.core.store.objectstore import (
        BYTES_DOWN,
        PAYLOAD_BYTES_DOWN,
        ObjectStoreColumnStore,
    )
    from filodb_tpu.promql.parser import TimeStepParams, parse_query
    from filodb_tpu.query.exec.plan import ExecContext
    from filodb_tpu.testing.data import gauge_stream, machine_metrics_series
    from filodb_tpu.testing.fake_s3 import FakeS3

    num_shards, series, chunk, samples = 2, 16, 512, 4096
    s3 = FakeS3()
    cs = ObjectStoreColumnStore(s3)
    ms = TimeSeriesMemStore(cs, InMemoryMetaStore())
    for s in range(num_shards):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=chunk,
                                              groups_per_shard=2))
    ingest_routed(ms, "timeseries",
                  gauge_stream(machine_metrics_series(series), samples,
                               start_ms=START * 1000, seed=11),
                  num_shards, spread=0)
    ms.flush_all("timeseries")
    cs.flush()

    # everything below the memory floor: the whole scan is cold-tier
    now = (START + samples * 10 + 100) * 1000
    planner = build_tiered_planner(
        SingleClusterPlanner("timeseries", num_shards, spread=0), cs,
        "timeseries", num_shards, mem_retention_ms=1000,
        raw_retention_ms=None, ds_planner=None, now_ms=lambda: now)
    store = planner.cold_planner.store
    # steps at seal boundaries (chunk k ends at sample 512k-1), window
    # reaching before the first sample: interior-only composition
    span_s = chunk * 10
    q = parse_query(f"sum_over_time(heap_usage[{samples * 10 + 100}s])",
                    TimeStepParams(START + 2 * span_s - 10, 2 * span_s,
                                   START + 8 * span_s - 10))
    ep = planner.materialize(q)

    def one_pass():
        store.clear_caches()
        b0, p0 = BYTES_DOWN.value, PAYLOAD_BYTES_DOWN.value
        t0 = time.perf_counter()
        ep.dispatcher.dispatch(ep, ExecContext(ms, "timeseries"))
        dt = (time.perf_counter() - t0) * 1000.0
        return dt, BYTES_DOWN.value - b0, PAYLOAD_BYTES_DOWN.value - p0

    out = {}
    for label, valve in (("paging", "0"), ("pyramid", "1")):
        os.environ["FILODB_SIDECARS"] = valve
        try:
            one_pass()  # jit/compile warmup, then timed cold passes
            runs = [one_pass() for _ in range(repeats)]
        finally:
            os.environ.pop("FILODB_SIDECARS", None)
        out[label] = {
            "p50_ms": round(_percentile([r[0] for r in runs], 50), 3),
            "bytes_down": int(runs[0][1]),
            "payload_bytes": int(runs[0][2]),
        }
    return {"metric": "federation_yearscan_paging_vs_pyramid",
            "series": series, "chunks_per_series": samples // chunk,
            **{f"{k}_{kk}": vv for k, v in out.items()
               for kk, vv in v.items()},
            "speedup_p50": round(out["paging"]["p50_ms"]
                                 / out["pyramid"]["p50_ms"], 1),
            "unit": "ms"}


def bench_pyramid_topk_1m(n_series: int = 1_000_000,
                          n_segments: int = 64):
    """Sketch-served ``topk(10)`` / count-distinct at 1M series: build
    per-segment TopK + HLL footers over a synthetic splitmix64 key
    population, then merge + rank — the summary-only scan the approx
    lane runs, with zero chunk payloads by construction."""
    import numpy as np

    from filodb_tpu.memory.sketches import HLLSketch, TopKSketch, splitmix64

    rng = np.random.default_rng(5)
    hashes = splitmix64(np.arange(1, n_series + 1, dtype=np.uint64))
    values = rng.pareto(2.0, n_series) * 100.0
    per = n_series // n_segments

    t0 = time.perf_counter()
    topks, hlls = [], []
    for s in range(n_segments):
        tk, hl = TopKSketch(capacity=64), HLLSketch()
        lo = s * per
        hl.update_hashes(hashes[lo:lo + per])
        # only candidates can place: feeding the per-segment top slice
        # mirrors the seal-time fold (every row passes through update)
        seg_vals = values[lo:lo + per]
        for i in np.argpartition(seg_vals, -64)[-64:]:
            tk.update(int(hashes[lo + i]).to_bytes(8, "little"),
                      float(seg_vals[i]))
        topks.append(tk)
        hlls.append(hl)
    build_ms = (time.perf_counter() - t0) * 1000.0

    t0 = time.perf_counter()
    topk, hll = TopKSketch(capacity=256), HLLSketch()
    for tk, hl in zip(topks, hlls):
        topk.merge(tk)
        hll.merge(hl)
    top10 = topk.top(10)
    est = hll.estimate()
    merge_ms = (time.perf_counter() - t0) * 1000.0

    true10 = np.sort(values)[-10:][::-1]
    got10 = np.array([v for _, v in top10])
    return {"metric": "pyramid_topk_1m", "series": n_series,
            "segments": n_segments,
            "build_ms": round(build_ms, 1),
            "merge_and_rank_ms": round(merge_ms, 3),
            "topk_exact": bool(np.allclose(got10, true10)),
            "cardinality_est": int(est),
            "cardinality_err_pct": round(
                abs(est - n_series) / n_series * 100.0, 2),
            "unit": "ms"}


if __name__ == "__main__":
    import json
    print(json.dumps(bench_federation()))
    print(json.dumps(bench_federation_yearscan()))
    print(json.dumps(bench_pyramid_topk_1m()))
