"""Self-monitoring overhead: ingest throughput with MetaMonitor off vs on.

The ``_meta`` sampler walks the whole metric registry every tick, builds a
record container, and writes it through the normal ingest path — all on
its own daemon thread, but sharing the process (GIL, registry lock,
memstore) with real ingest. This measures what that costs: the same
pre-built ingest workload as ``run_benchmarks.py`` ``ingestion`` run with
the monitor stopped and then with it ticking. To make the delta
measurable inside a benchmark-sized run the monitor ticks every 50 ms —
300× the default 15 s cadence — and the result reports both the measured
overhead at that aggressive interval and the per-tick cost, from which
the production-cadence (15 s) overhead is projected (target: ≤2%).

    python benchmarks/selfmon_overhead.py [--samples 300000] [--cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

START = 1_600_000_000


def bench_selfmon_overhead(samples: int = 300_000, rounds: int = 3,
                           interval_s: float = 0.05):
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.record import BytesContainer, SomeData
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.rules.manager import MemstoreSink
    from filodb_tpu.testing.data import gauge_stream, machine_metrics_series
    from filodb_tpu.utils import selfmon as selfmon_mod
    from filodb_tpu.utils.selfmon import MetaMonitor

    ms = TimeSeriesMemStore()
    shard = ms.setup("bench", 0, StoreConfig(max_chunk_size=400,
                                             retention_ms=10**15))
    ms.setup("_meta", 0, StoreConfig(groups_per_shard=4,
                                     retention_ms=10**15))
    keys = machine_metrics_series(100)
    per_round = samples // 100

    # every round gets FRESH samples (advancing timestamps + offsets):
    # replaying one segment would hit the shards' out-of-order drop path
    # instead of real encode work
    segment_no = 0

    def next_segment():
        nonlocal segment_no
        base = START * 1000 + segment_no * per_round * 10_000
        seg = [SomeData(BytesContainer(sd.container.serialize()), sd.offset)
               for sd in gauge_stream(
                   keys, per_round, start_ms=base, batch=500,
                   start_offset=segment_no * samples)]
        segment_no += 1
        return seg

    def run_round():
        seg = next_segment()
        t0 = time.perf_counter()
        for sd in seg:
            shard.ingest(sd)
        return time.perf_counter() - t0

    mon = MetaMonitor(MemstoreSink(ms, "_meta", 1), interval_s=interval_s,
                      node="bench", instance="bench:0")
    # warm both lanes (compile caches, registry growth from first ticks)
    run_round()
    mon.tick()

    off, on = [], []
    ticks0 = selfmon_mod.TICKS.value
    # alternate mode order per round so allocator/cache drift doesn't
    # bias one side
    for rnd in range(rounds):
        order = [("off", off), ("on", on)]
        if rnd % 2:
            order.reverse()
        for name, acc in order:
            if name == "on":
                mon.start()
                acc.append(run_round())
                mon.stop()
            else:
                acc.append(run_round())
    ticks = selfmon_mod.TICKS.value - ticks0

    # isolated per-tick cost (sampler walk + container build + write)
    n = 20
    t0 = time.perf_counter()
    for _ in range(n):
        mon.tick()
    tick_ms = (time.perf_counter() - t0) / n * 1000

    off_s, on_s = min(off), min(on)
    thr_off, thr_on = samples / off_s, samples / on_s
    overhead = (thr_off - thr_on) / thr_off * 100
    # production cadence: one tick_ms slice out of every 15 s of wall
    # time, as a percentage
    projected = tick_ms / 150.0
    return {
        "metric": "selfmon_overhead",
        "samples": samples,
        "interval_s": interval_s,
        "ticks_during_on_rounds": ticks,
        "ingest_off_samples_per_s": round(thr_off),
        "ingest_on_samples_per_s": round(thr_on),
        "overhead_pct_at_bench_interval": round(overhead, 2),
        "tick_ms": round(tick_ms, 2),
        "projected_overhead_pct_at_15s": round(projected, 3),
        "unit": "samples/sec",
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--samples", type=int, default=300_000)
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--interval", type=float, default=0.05)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax
        import jax._src.xla_bridge as xb
        xb._backend_factories.pop("axon", None)
        jax.config.update("jax_platforms", "cpu")
    print(json.dumps(bench_selfmon_overhead(args.samples, args.rounds,
                                            args.interval)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
