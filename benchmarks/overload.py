"""Overload-protection benchmark: ramped concurrent clients against a node
with a fixed admission capacity; measures admitted-query p99 and shed rate
per concurrency level.

The property being demonstrated (the governor's reason to exist): past the
capacity knee, *admitted* latency stays bounded while the excess demand is
shed with 503s — instead of every client's latency growing without bound. A
small per-child scan delay is injected so the node has a realistic service
time and the gate actually engages.

    python benchmarks/overload.py            # standalone, one JSON line
    python benchmarks/run_benchmarks.py --only overload
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

START = 1_600_000_000
NUM_SHARDS = 4
N_SERIES = 50
N_SAMPLES = 40
INTERVAL_MS = 15_000

CAPACITY = 4
LEVELS = [1, 2, 4, 8, 16, 32]     # concurrent clients (8x capacity at top)
LEVEL_SECONDS = 1.0
CHILD_DELAY_S = 0.01              # injected per scatter-gather child

QUERY = "heap_usage"
QS = START + 150
QE = START + N_SAMPLES * (INTERVAL_MS // 1000)
STEP = 60


def _build():
    from filodb_tpu.coordinator.ingestion import ingest_routed
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.testing.data import gauge_stream, machine_metrics_series

    ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=400,
                                              groups_per_shard=4))
    stream = gauge_stream(machine_metrics_series(N_SERIES), N_SAMPLES,
                          start_ms=START * 1000, interval_ms=INTERVAL_MS,
                          batch=1000, seed=5)
    ingest_routed(ms, "timeseries", stream, NUM_SHARDS, spread=1)
    return ms


def _p99(xs):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(0.99 * len(xs)))] if xs else float("nan")


def _run_level(svc, clients: int):
    from filodb_tpu.utils.governor import QueryRejected

    stop = time.monotonic() + LEVEL_SECONDS
    lock = threading.Lock()
    admitted, shed = [], [0]

    def worker():
        while time.monotonic() < stop:
            t0 = time.perf_counter()
            try:
                svc.query_range(QUERY, QS, STEP, QE)
                dt = time.perf_counter() - t0
                with lock:
                    admitted.append(dt)
            except QueryRejected:
                with lock:
                    shed[0] += 1

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    total = len(admitted) + shed[0]
    return {"clients": clients,
            "admitted_qps": round(len(admitted) / LEVEL_SECONDS, 1),
            "admitted_p99_ms": round(_p99(admitted) * 1e3, 2),
            "shed_rate": round(shed[0] / total, 3) if total else 0.0}


def bench_overload():
    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.utils import governor as gov
    from filodb_tpu.utils.resilience import FaultInjector

    ms = _build()
    svc = QueryService(ms, "timeseries", NUM_SHARDS, spread=1)
    svc.result_cache = None  # measure the engine, not the extent cache
    gov.reset()
    gov.configure(admission_capacity=CAPACITY, max_queue_wait_s=0.2,
                  retry_after_s=1.0)
    FaultInjector.arm("gather.child", delay_s=CHILD_DELAY_S, times=None)
    try:
        svc.query_range(QUERY, QS, STEP, QE)  # warm compile caches
        levels = [_run_level(svc, n) for n in LEVELS]
    finally:
        FaultInjector.reset()
        gov.reset()
    unloaded_p99 = levels[0]["admitted_p99_ms"]
    loaded = [lv for lv in levels if lv["clients"] >= 4 * CAPACITY]
    worst_p99 = max(lv["admitted_p99_ms"] for lv in loaded) if loaded \
        else float("nan")
    return {"metric": "overload", "capacity": CAPACITY,
            "levels": levels,
            "admitted_p99_blowup_x": round(worst_p99 / unloaded_p99, 2),
            "unit": "ms / shed fraction"}


if __name__ == "__main__":
    print(json.dumps(bench_overload()))
