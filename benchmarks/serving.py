"""Serving benchmark: concurrent HTTP clients against a live server.

End-to-end throughput including HTTP, JSON rendering, planner, kernels —
the number a dashboard fleet actually experiences (the reference's JMH
benches stop at the query engine; this covers the full serving stack).

    python benchmarks/serving.py [--clients 8] [--seconds 15] [--cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

START = 1_600_000_000


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=15.0)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from filodb_tpu.client import FiloClient
    from filodb_tpu.config import ServerConfig
    from filodb_tpu.coordinator.ingestion import route_container
    from filodb_tpu.standalone import FiloServer
    from filodb_tpu.testing.data import counter_series, counter_stream

    tmp = tempfile.mkdtemp(prefix="filodb-serving-")
    cfg = os.path.join(tmp, "s.json")
    with open(cfg, "w") as f:
        json.dump({
            "node_name": "bench", "data_dir": os.path.join(tmp, "d"),
            "http_port": 0, "gateway_port": 0,
            "datasets": {"timeseries": {
                "num_shards": 4, "spread": 1,
                "store": {"max_chunk_size": 400, "groups_per_shard": 4,
                          "retention_ms": 10**15}}},
        }, f)
    server = FiloServer(ServerConfig.load(cfg)).start()
    try:
        keys = counter_series(100, metric="heap_usage", ns="App-2")
        for sd in counter_stream(keys, 720, start_ms=START * 1000, seed=1):
            for shard, cont in route_container(sd.container, 4, 1).items():
                server.logs[("timeseries", shard)].append(cont)
        # wait for ingest workers
        c0 = FiloClient(port=server.http.port)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            r = c0.query("count(heap_usage)", START + 7100)
            if r and float(r[0]["value"][1]) == 100:
                break
            time.sleep(0.2)

        queries = [
            ("range", 'sum(rate(heap_usage{_ws_="demo",_ns_="App-2"}[5m]))',
             START + 3600, START + 5400, 60),
            ("range", 'rate(heap_usage[5m])', START + 3600, START + 5400,
             300),
            ("range", 'topk(5, rate(heap_usage[5m]))', START + 3600,
             START + 4500, 300),
            ("instant", 'sum by (job) (rate(heap_usage[5m]))',
             START + 5000, 0, 0),
        ]
        # warm all query shapes
        for kind, q, a, b, step in queries:
            if kind == "range":
                c0.query_range(q, a, b, step)
            else:
                c0.query(q, a)

        stop = threading.Event()
        counts = [0] * args.clients
        lats: list[list[float]] = [[] for _ in range(args.clients)]

        def worker(i):
            client = FiloClient(port=server.http.port)
            rng = np.random.default_rng(i)
            while not stop.is_set():
                kind, q, a, b, step = queries[rng.integers(len(queries))]
                t0 = time.perf_counter()
                if kind == "range":
                    client.query_range(q, a, b, step)
                else:
                    client.query(q, a)
                lats[i].append(time.perf_counter() - t0)
                counts[i] += 1

        threads = [threading.Thread(target=worker, args=(i,), daemon=True)
                   for i in range(args.clients)]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(args.seconds)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        wall = time.perf_counter() - t_start
        all_lats = np.array([x for lt in lats for x in lt])
        print(json.dumps({
            "metric": "http_serving_throughput",
            "value": round(sum(counts) / wall, 2),
            "unit": "queries/sec",
            "clients": args.clients,
            "p50_ms": round(float(np.percentile(all_lats, 50)) * 1000, 2),
            "p99_ms": round(float(np.percentile(all_lats, 99)) * 1000, 2),
        }))
    finally:
        server.shutdown()


if __name__ == "__main__":
    sys.exit(main())
