"""Serving benchmark: concurrent HTTP clients against a live server.

End-to-end throughput including HTTP, JSON rendering, planner, kernels —
the number a dashboard fleet actually experiences (the reference's JMH
benches stop at the query engine; this covers the full serving stack).

    python benchmarks/serving.py [--clients 8] [--seconds 15] [--cpu]

Dashboard mode (--dashboard) measures the extent result cache on the
workload it exists for: N panels re-rendered every refresh with the window
slid one step, against a store that keeps ingesting. Cache-on and cache-off
services share one memstore and every refresh cross-checks their answers,
so the speedup number is only reported if zero stale reads occurred.

    python benchmarks/serving.py --dashboard [--series 8192] [--cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

START = 1_600_000_000


def dashboard(args):
    """Sliding-dashboard bench: extent result cache on vs off, live ingest.

    In-process (no HTTP) so the number isolates the query path the cache
    fronts; the HTTP rendered-response cache can't help here because every
    refresh has different start/end params.
    """
    from filodb_tpu.coordinator.ingestion import ingest_routed
    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.query import result_cache as rc
    from filodb_tpu.query.model import PlannerParams, QueryContext
    from filodb_tpu.testing.data import gauge_stream, machine_metrics_series

    num_shards = 4
    interval_ms = 30_000
    step = 60
    window_s = 21_600                    # 6h big-scan dashboard window
    base_samples = 800                   # ~6.7h of history before t0
    ms = TimeSeriesMemStore()
    for s in range(num_shards):
        ms.setup("timeseries", s,
                 StoreConfig(max_chunk_size=400, groups_per_shard=4,
                             retention_ms=10**15))
    # two namespaces so the router populates every shard at spread=1
    half = args.series // 2
    keysets = [machine_metrics_series(half, ns="App-2"),
               machine_metrics_series(args.series - half, ns="App-3")]
    t_ing0 = time.perf_counter()
    for kk in keysets:
        ingest_routed(ms, "timeseries",
                      gauge_stream(kk, base_samples, start_ms=START * 1000,
                                   interval_ms=interval_ms, seed=9),
                      num_shards, spread=1)
    ingest_s = time.perf_counter() - t_ing0

    plain = QueryService(ms, "timeseries", num_shards, spread=1)
    # short extents: under live ingest only the head extent re-evaluates
    # each refresh, and its cost scales with extent+lookback length
    cached = QueryService(ms, "timeseries", num_shards, spread=1,
                          result_cache={"extent_steps": 8})

    panels = [
        "sum(rate(heap_usage[5m]))",
        "sum by (host) (rate(heap_usage[5m]))",
        "avg_over_time(heap_usage[5m])",
        "max_over_time(heap_usage[10m])",
        "max by (host) (avg_over_time(heap_usage[5m]))",
    ]

    def check_equiv(a, b, promql):
        m0, m1 = a.result, b.result
        i0 = {k: i for i, k in enumerate(m0.keys)}
        i1 = {k: i for i, k in enumerate(m1.keys)}
        if set(i0) != set(i1):
            return f"{promql}: key sets differ"
        for k, i in i0.items():
            va = np.asarray(m0.values[i])
            vb = np.asarray(m1.values[i1[k]])
            if not np.array_equal(np.isnan(va), np.isnan(vb)):
                return f"{promql}: NaN masks differ for {k}"
            # float32 prefix sums over a 6h, 800-sample scan carry up to
            # ~1e-3 absolute noise vs per-extent scans (eps x prefix
            # magnitude); a stale head step would differ by a random-walk
            # increment, O(0.1-10), so detection power is intact
            if not np.allclose(va, vb, rtol=1e-3, atol=5e-3,
                               equal_nan=True):
                m = ~np.isnan(va)
                d = np.abs(va[m] - vb[m])
                j = int(np.argmax(d))
                at = int(np.nonzero(m)[0][j])
                return (f"{promql}: values differ for {k}: "
                        f"max |d|={float(d[j]):.2e} at step {at}/"
                        f"{len(va)} (a={float(va[m][j]):.6g} "
                        f"b={float(vb[m][j]):.6g})")
        return None

    qe0 = START + (base_samples - 1) * interval_ms // 1000  # last sample
    plain_lat, cached_lat, cold_lat = [], [], []
    stale = []
    samples_done = base_samples
    for refresh in range(args.refreshes):
        # live ingest: data keeps arriving between refreshes (appended
        # synchronously so cache-on and cache-off compare the same store;
        # delta-only — value continuity across batches doesn't matter here)
        if refresh:
            t_new = START * 1000 + samples_done * interval_ms
            new_samples = step * 1000 // interval_ms
            for kk in keysets:
                ingest_routed(
                    ms, "timeseries",
                    gauge_stream(kk, new_samples, start_ms=t_new,
                                 interval_ms=interval_ms,
                                 seed=100 + refresh),
                    num_shards, spread=1)
            samples_done += new_samples
        qe = qe0 + refresh * step
        qs = qe - window_s
        for promql in panels:
            # big-scan panels return series x steps well past the default
            # sample limit; raise it (fresh context per query)
            t0 = time.perf_counter()
            r_cached = cached.query_range(promql, qs, step, qe, QueryContext(
                planner_params=PlannerParams(sample_limit=50_000_000)))
            t1 = time.perf_counter()
            r_plain = plain.query_range(promql, qs, step, qe, QueryContext(
                planner_params=PlannerParams(sample_limit=50_000_000)))
            t2 = time.perf_counter()
            (cold_lat if refresh == 0 else cached_lat).append(t1 - t0)
            plain_lat.append(t2 - t1)
            err = check_equiv(r_plain, r_cached, promql)
            if err:
                stale.append(f"refresh {refresh}: {err}")

    def pct(xs, p):
        return round(float(np.percentile(np.array(xs), p)) * 1000, 2)

    out = {
        "metric": "dashboard_refresh_latency",
        "series": args.series,
        "panels": len(panels),
        "refreshes": args.refreshes,
        "window_s": window_s,
        "step_s": step,
        "ingest_seconds": round(ingest_s, 1),
        "cache_off_p50_ms": pct(plain_lat, 50),
        "cache_off_p99_ms": pct(plain_lat, 99),
        "cache_cold_p50_ms": pct(cold_lat, 50),
        "cache_warm_p50_ms": pct(cached_lat, 50),
        "cache_warm_p99_ms": pct(cached_lat, 99),
        "warm_speedup_p50": round(
            pct(plain_lat, 50) / max(pct(cached_lat, 50), 1e-9), 1),
        "cache_hits": int(rc.cache_hits.value),
        "cache_misses": int(rc.cache_misses.value),
        "cache_bytes": int(cached.result_cache.nbytes),
        "stale_reads": stale[:5] if stale else 0,
    }
    print(json.dumps(out))
    return 1 if stale else 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--workers", type=int, default=1,
                    help="server processes sharing the port (SO_REUSEPORT "
                         "log-replica serving plane)")
    ap.add_argument("--seconds", type=float, default=15.0)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--dashboard", action="store_true",
                    help="sliding-dashboard bench of the extent result "
                         "cache (in-process, cache on vs off)")
    ap.add_argument("--series", type=int, default=8192)
    ap.add_argument("--refreshes", type=int, default=20)
    args = ap.parse_args(argv)
    if args.cpu:
        import jax
        import jax._src.xla_bridge as xb
        xb._backend_factories.pop("axon", None)  # hangs when tunnel is down
        jax.config.update("jax_platforms", "cpu")
    if args.dashboard:
        return dashboard(args)

    from filodb_tpu.client import FiloClient
    from filodb_tpu.config import ServerConfig
    from filodb_tpu.coordinator.ingestion import route_container
    from filodb_tpu.standalone import FiloServer
    from filodb_tpu.testing.data import counter_series, counter_stream

    tmp = tempfile.mkdtemp(prefix="filodb-serving-")
    cfg = os.path.join(tmp, "s.json")
    with open(cfg, "w") as f:
        json.dump({
            "node_name": "bench", "data_dir": os.path.join(tmp, "d"),
            "wal_dir": os.path.join(tmp, "wal"),
            "http_port": 0, "gateway_port": 0,
            # headline measures real serving: the rendered-response cache is
            # off (it would trivially absorb this bench's fixed query mix);
            # a second short phase measures it separately (cached_qps)
            "http_response_cache": False,
            "datasets": {"timeseries": {
                "num_shards": 4, "spread": 1,
                "store": {"max_chunk_size": 400, "groups_per_shard": 4,
                          "retention_ms": 10**15}}},
        }, f)
    server = FiloServer(ServerConfig.load(cfg)).start()
    extra_procs = []
    try:
        keys = counter_series(100, metric="heap_usage", ns="App-2")
        for sd in counter_stream(keys, 720, start_ms=START * 1000, seed=1):
            for shard, cont in route_container(sd.container, 4, 1).items():
                server.logs[("timeseries", shard)].append(cont)
        # wait for ingest workers
        c0 = FiloClient(port=server.http.port)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            r = c0.query("count(heap_usage)", START + 7100)
            if r and float(r[0]["value"][1]) == 100:
                break
            time.sleep(0.2)

        if args.workers > 1:
            # extra worker processes: each runs a full server on the SAME
            # port via SO_REUSEPORT, reading the same data dir/WAL (the
            # log-replica serving plane). The primary re-binds with
            # reuse_port so the kernel can balance across all of them.
            import subprocess
            port = server.http.port
            with open(cfg) as f:
                base = json.load(f)
            for w in range(args.workers - 1):
                wcfg = dict(base)
                wcfg["node_name"] = f"worker-{w}"
                wcfg["data_dir"] = os.path.join(tmp, f"wd{w}")
                wcfg["http_port"] = port
                wcfg["http_reuse_port"] = True
                wpath = os.path.join(tmp, f"w{w}.json")
                with open(wpath, "w") as f:
                    json.dump(wcfg, f)
                code = (
                    "import jax, sys;"
                    "import jax._src.xla_bridge as xb;"
                    "xb._backend_factories.pop('axon', None);"
                    "jax.config.update('jax_platforms', 'cpu');"
                    "from filodb_tpu.config import ServerConfig;"
                    "from filodb_tpu.standalone import FiloServer;"
                    f"s = FiloServer(ServerConfig.load({wpath!r})).start();"
                    "import time;"
                    "print('WORKER_READY', flush=True);"
                    "time.sleep(10**9)")
                env = {k: v for k, v in os.environ.items()
                       if k != "PALLAS_AXON_POOL_IPS"}
                env["JAX_PLATFORMS"] = "cpu"
                pr = subprocess.Popen(
                    [sys.executable, "-c", code], env=env,
                    cwd=os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__))),
                    stdout=subprocess.PIPE, text=True)
                extra_procs.append(pr)
            # rebind the primary with reuse_port on the same port
            server.http.stop()
            from filodb_tpu.http.server import FiloHttpServer
            server.http = FiloHttpServer(
                server.http.services, port=port,
                cluster=server.http.cluster,
                shard_maps=server.http.shard_maps,
                reuse_port=True).start()
            for pr in extra_procs:
                line = pr.stdout.readline()
                assert "WORKER_READY" in line, line
            # wait for every worker to finish ingesting (query via the
            # shared port until all answers stabilize at full count)
            deadline = time.monotonic() + 120
            stable = 0
            while time.monotonic() < deadline and stable < args.workers * 3:
                r = FiloClient(port=port).query("count(heap_usage)",
                                                START + 7100)
                if r and float(r[0]["value"][1]) == 100:
                    stable += 1
                else:
                    stable = 0
                    time.sleep(0.5)

        queries = [
            ("range", 'sum(rate(heap_usage{_ws_="demo",_ns_="App-2"}[5m]))',
             START + 3600, START + 5400, 60),
            ("range", 'rate(heap_usage[5m])', START + 3600, START + 5400,
             300),
            ("range", 'topk(5, rate(heap_usage[5m]))', START + 3600,
             START + 4500, 300),
            ("instant", 'sum by (job) (rate(heap_usage[5m]))',
             START + 5000, 0, 0),
        ]
        # warm all query shapes
        for kind, q, a, b, step in queries:
            if kind == "range":
                c0.query_range(q, a, b, step)
            else:
                c0.query(q, a)

        # client load runs in separate PROCESSES: in-process client threads
        # would share the server's GIL and measure the bench, not the server
        import multiprocessing as mp

        def client_proc(i, port, seconds, warm_seconds, out_q):
            import time as _t

            client = FiloClient(port=port)
            rng = np.random.default_rng(i)
            deadline_warm = _t.monotonic() + warm_seconds
            while _t.monotonic() < deadline_warm:  # unmeasured warm phase
                kind, q, a, b, step = queries[rng.integers(len(queries))]
                if kind == "range":
                    client.query_range(q, a, b, step)
                else:
                    client.query(q, a)
            lat = []
            deadline = _t.monotonic() + seconds
            while _t.monotonic() < deadline:
                kind, q, a, b, step = queries[rng.integers(len(queries))]
                t0 = _t.perf_counter()
                if kind == "range":
                    client.query_range(q, a, b, step)
                else:
                    client.query(q, a)
                lat.append(_t.perf_counter() - t0)
            out_q.put(lat)

        ctx = mp.get_context("fork")
        out_q = ctx.Queue()
        warm_s = 4.0 if args.workers <= 1 else 4.0 + 4.0 * args.workers
        procs = [ctx.Process(target=client_proc,
                             args=(i, server.http.port, args.seconds,
                                   warm_s, out_q), daemon=True)
                 for i in range(args.clients)]
        for pr in procs:
            pr.start()
        t_start = time.perf_counter() + warm_s
        per_client = [out_q.get(timeout=args.seconds + warm_s + 60)
                      for _ in procs]
        for pr in procs:
            pr.join(timeout=10)
        wall = args.seconds
        counts = [len(lt) for lt in per_client]
        all_lats = np.array([x for lt in per_client for x in lt])

        # second phase: rendered-response cache on (the query-frontend
        # pattern) — the dashboard-refresh workload where the same panel
        # queries repeat against unchanged data
        cached_qps = None
        if args.workers <= 1:
            from filodb_tpu.http.server import ResponseCache
            server.http.response_cache = ResponseCache()
            out_q2 = ctx.Queue()
            procs2 = [ctx.Process(target=client_proc,
                                  args=(i, server.http.port, 5.0, 2.0,
                                        out_q2), daemon=True)
                      for i in range(args.clients)]
            for pr in procs2:
                pr.start()
            per_client2 = [out_q2.get(timeout=60) for _ in procs2]
            for pr in procs2:
                pr.join(timeout=10)
            cached_qps = round(sum(len(lt) for lt in per_client2) / 5.0, 2)

        print(json.dumps({
            "metric": "http_serving_throughput",
            "value": round(sum(counts) / wall, 2),
            "unit": "queries/sec",
            "clients": args.clients,
            "p50_ms": round(float(np.percentile(all_lats, 50)) * 1000, 2),
            "p99_ms": round(float(np.percentile(all_lats, 99)) * 1000, 2),
            "response_cache_qps": cached_qps,
        }))
    finally:
        for pr in extra_procs:
            pr.terminate()
        server.shutdown()


if __name__ == "__main__":
    sys.exit(main())
