"""Serving benchmark: concurrent HTTP clients against a live server.

End-to-end throughput including HTTP, JSON rendering, planner, kernels —
the number a dashboard fleet actually experiences (the reference's JMH
benches stop at the query engine; this covers the full serving stack).

    python benchmarks/serving.py [--clients 8] [--seconds 15] [--cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

START = 1_600_000_000


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--workers", type=int, default=1,
                    help="server processes sharing the port (SO_REUSEPORT "
                         "log-replica serving plane)")
    ap.add_argument("--seconds", type=float, default=15.0)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax
        import jax._src.xla_bridge as xb
        xb._backend_factories.pop("axon", None)  # hangs when tunnel is down
        jax.config.update("jax_platforms", "cpu")

    from filodb_tpu.client import FiloClient
    from filodb_tpu.config import ServerConfig
    from filodb_tpu.coordinator.ingestion import route_container
    from filodb_tpu.standalone import FiloServer
    from filodb_tpu.testing.data import counter_series, counter_stream

    tmp = tempfile.mkdtemp(prefix="filodb-serving-")
    cfg = os.path.join(tmp, "s.json")
    with open(cfg, "w") as f:
        json.dump({
            "node_name": "bench", "data_dir": os.path.join(tmp, "d"),
            "wal_dir": os.path.join(tmp, "wal"),
            "http_port": 0, "gateway_port": 0,
            # headline measures real serving: the rendered-response cache is
            # off (it would trivially absorb this bench's fixed query mix);
            # a second short phase measures it separately (cached_qps)
            "http_response_cache": False,
            "datasets": {"timeseries": {
                "num_shards": 4, "spread": 1,
                "store": {"max_chunk_size": 400, "groups_per_shard": 4,
                          "retention_ms": 10**15}}},
        }, f)
    server = FiloServer(ServerConfig.load(cfg)).start()
    extra_procs = []
    try:
        keys = counter_series(100, metric="heap_usage", ns="App-2")
        for sd in counter_stream(keys, 720, start_ms=START * 1000, seed=1):
            for shard, cont in route_container(sd.container, 4, 1).items():
                server.logs[("timeseries", shard)].append(cont)
        # wait for ingest workers
        c0 = FiloClient(port=server.http.port)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            r = c0.query("count(heap_usage)", START + 7100)
            if r and float(r[0]["value"][1]) == 100:
                break
            time.sleep(0.2)

        if args.workers > 1:
            # extra worker processes: each runs a full server on the SAME
            # port via SO_REUSEPORT, reading the same data dir/WAL (the
            # log-replica serving plane). The primary re-binds with
            # reuse_port so the kernel can balance across all of them.
            import subprocess
            port = server.http.port
            with open(cfg) as f:
                base = json.load(f)
            for w in range(args.workers - 1):
                wcfg = dict(base)
                wcfg["node_name"] = f"worker-{w}"
                wcfg["data_dir"] = os.path.join(tmp, f"wd{w}")
                wcfg["http_port"] = port
                wcfg["http_reuse_port"] = True
                wpath = os.path.join(tmp, f"w{w}.json")
                with open(wpath, "w") as f:
                    json.dump(wcfg, f)
                code = (
                    "import jax, sys;"
                    "import jax._src.xla_bridge as xb;"
                    "xb._backend_factories.pop('axon', None);"
                    "jax.config.update('jax_platforms', 'cpu');"
                    "from filodb_tpu.config import ServerConfig;"
                    "from filodb_tpu.standalone import FiloServer;"
                    f"s = FiloServer(ServerConfig.load({wpath!r})).start();"
                    "import time;"
                    "print('WORKER_READY', flush=True);"
                    "time.sleep(10**9)")
                env = {k: v for k, v in os.environ.items()
                       if k != "PALLAS_AXON_POOL_IPS"}
                env["JAX_PLATFORMS"] = "cpu"
                pr = subprocess.Popen(
                    [sys.executable, "-c", code], env=env,
                    cwd=os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__))),
                    stdout=subprocess.PIPE, text=True)
                extra_procs.append(pr)
            # rebind the primary with reuse_port on the same port
            server.http.stop()
            from filodb_tpu.http.server import FiloHttpServer
            server.http = FiloHttpServer(
                server.http.services, port=port,
                cluster=server.http.cluster,
                shard_maps=server.http.shard_maps,
                reuse_port=True).start()
            for pr in extra_procs:
                line = pr.stdout.readline()
                assert "WORKER_READY" in line, line
            # wait for every worker to finish ingesting (query via the
            # shared port until all answers stabilize at full count)
            deadline = time.monotonic() + 120
            stable = 0
            while time.monotonic() < deadline and stable < args.workers * 3:
                r = FiloClient(port=port).query("count(heap_usage)",
                                                START + 7100)
                if r and float(r[0]["value"][1]) == 100:
                    stable += 1
                else:
                    stable = 0
                    time.sleep(0.5)

        queries = [
            ("range", 'sum(rate(heap_usage{_ws_="demo",_ns_="App-2"}[5m]))',
             START + 3600, START + 5400, 60),
            ("range", 'rate(heap_usage[5m])', START + 3600, START + 5400,
             300),
            ("range", 'topk(5, rate(heap_usage[5m]))', START + 3600,
             START + 4500, 300),
            ("instant", 'sum by (job) (rate(heap_usage[5m]))',
             START + 5000, 0, 0),
        ]
        # warm all query shapes
        for kind, q, a, b, step in queries:
            if kind == "range":
                c0.query_range(q, a, b, step)
            else:
                c0.query(q, a)

        # client load runs in separate PROCESSES: in-process client threads
        # would share the server's GIL and measure the bench, not the server
        import multiprocessing as mp

        def client_proc(i, port, seconds, warm_seconds, out_q):
            import time as _t

            client = FiloClient(port=port)
            rng = np.random.default_rng(i)
            deadline_warm = _t.monotonic() + warm_seconds
            while _t.monotonic() < deadline_warm:  # unmeasured warm phase
                kind, q, a, b, step = queries[rng.integers(len(queries))]
                if kind == "range":
                    client.query_range(q, a, b, step)
                else:
                    client.query(q, a)
            lat = []
            deadline = _t.monotonic() + seconds
            while _t.monotonic() < deadline:
                kind, q, a, b, step = queries[rng.integers(len(queries))]
                t0 = _t.perf_counter()
                if kind == "range":
                    client.query_range(q, a, b, step)
                else:
                    client.query(q, a)
                lat.append(_t.perf_counter() - t0)
            out_q.put(lat)

        ctx = mp.get_context("fork")
        out_q = ctx.Queue()
        warm_s = 4.0 if args.workers <= 1 else 4.0 + 4.0 * args.workers
        procs = [ctx.Process(target=client_proc,
                             args=(i, server.http.port, args.seconds,
                                   warm_s, out_q), daemon=True)
                 for i in range(args.clients)]
        for pr in procs:
            pr.start()
        t_start = time.perf_counter() + warm_s
        per_client = [out_q.get(timeout=args.seconds + warm_s + 60)
                      for _ in procs]
        for pr in procs:
            pr.join(timeout=10)
        wall = args.seconds
        counts = [len(lt) for lt in per_client]
        all_lats = np.array([x for lt in per_client for x in lt])

        # second phase: rendered-response cache on (the query-frontend
        # pattern) — the dashboard-refresh workload where the same panel
        # queries repeat against unchanged data
        cached_qps = None
        if args.workers <= 1:
            from filodb_tpu.http.server import ResponseCache
            server.http.response_cache = ResponseCache()
            out_q2 = ctx.Queue()
            procs2 = [ctx.Process(target=client_proc,
                                  args=(i, server.http.port, 5.0, 2.0,
                                        out_q2), daemon=True)
                      for i in range(args.clients)]
            for pr in procs2:
                pr.start()
            per_client2 = [out_q2.get(timeout=60) for _ in procs2]
            for pr in procs2:
                pr.join(timeout=10)
            cached_qps = round(sum(len(lt) for lt in per_client2) / 5.0, 2)

        print(json.dumps({
            "metric": "http_serving_throughput",
            "value": round(sum(counts) / wall, 2),
            "unit": "queries/sec",
            "clients": args.clients,
            "p50_ms": round(float(np.percentile(all_lats, 50)) * 1000, 2),
            "p99_ms": round(float(np.percentile(all_lats, 99)) * 1000, 2),
            "response_cache_qps": cached_qps,
        }))
    finally:
        for pr in extra_procs:
            pr.terminate()
        server.shutdown()


if __name__ == "__main__":
    sys.exit(main())
