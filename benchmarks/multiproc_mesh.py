"""Multi-process mesh scaling sweep: N worker processes × 1 CPU device.

Runs the headline big scan (``bench.BIG_QUERY`` over ``bench.BIG_SERIES``
series) through the multi-process mesh runtime at several worker counts.
Each width spawns real worker processes via ``MeshWorkerSupervisor``
(seeded with ``bench:build_big_store`` — deterministic, so every process
derives identical per-shard data) and the root reduces their partial
matrices with the cross-process collective path. Before any number is
reported, every width's result is asserted BYTE-IDENTICAL to the
single-process mesh engine over the same store.

On a single-core container the worker axis cannot show wall-clock
speedup (all processes share one core, plus per-query IPC cost); the
sweep verifies the distributed path stays correct and bounds its
overhead vs the in-process engine. On real multi-host hardware the same
harness is the scaling measurement (doc/mesh_engine.md §multi-process).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

DEFAULT_WORKERS = (1, 2, 4)
WARMUPS = 1
ITERS = 5


def run_sweep(widths=DEFAULT_WORKERS) -> dict:
    import bench

    # probe once for the whole sweep (workers are pinned to CPU × 1
    # device by the supervisor regardless of what the root runs on)
    bench._ensure_backend()
    import numpy as np

    from filodb_tpu.coordinator.mesh_cluster import MeshClusterRuntime
    from filodb_tpu.parallel.mesh_engine import (
        MeshQueryEngine,
        make_query_mesh,
    )
    from filodb_tpu.parallel.multiproc import MeshWorkerSupervisor
    from filodb_tpu.promql.parser import TimeStepParams, parse_query

    store = bench.build_big_store()
    start_sec = bench.START_SEC + 3600
    plan = parse_query(bench.BIG_QUERY, TimeStepParams(
        start_sec, bench.QUERY_STEP_SEC, start_sec + bench.BIG_RANGE_SEC))

    # single-process reference: same 1-device mesh the workers use
    engine = MeshQueryEngine(mesh=make_query_mesh(n_devices=1))
    want = engine.execute(store, "timeseries", plan)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        engine.execute(store, "timeseries", plan)
    single_ms = (time.perf_counter() - t0) / ITERS * 1e3
    blob = np.asarray(want.values).tobytes()

    curve = []
    for w in widths:
        sup = MeshWorkerSupervisor(
            dataset="timeseries", num_shards=bench.NUM_SHARDS, workers=w,
            seed="bench:build_big_store",
            env={"PYTHONPATH": REPO_ROOT, "FILODB_BENCH_CPU": "1"})
        t_ready = time.perf_counter()
        sup.spawn()
        try:
            sup.wait_ready(timeout_s=600.0)
            ready_s = time.perf_counter() - t_ready
            rt = MeshClusterRuntime(store, "timeseries", bench.NUM_SHARDS,
                                    sup.slices, timeout=120.0)
            got = None
            for _ in range(WARMUPS + 1):
                got = rt.execute_plan(plan)
            assert got is not None, f"multiproc fell back at {w} workers"
            assert np.asarray(got.values).tobytes() == blob, (
                f"multiproc result differs from single-process at "
                f"{w} workers")
            t0 = time.perf_counter()
            for _ in range(ITERS):
                rt.execute_plan(plan)
            ms = (time.perf_counter() - t0) / ITERS * 1e3
            curve.append({"workers": w,
                          "ms_per_query": round(ms, 1),
                          "ready_s": round(ready_s, 1),
                          "identical_results": True})
        except Exception as e:  # noqa: BLE001 - record and keep sweeping
            curve.append({"workers": w, "error": repr(e)[:200]})
        finally:
            sup.stop()
    return {"metric": "multiproc_mesh", "unit": "ms/query",
            "series": bench.BIG_SERIES,
            "single_process_ms_per_query": round(single_ms, 1),
            "curve": curve}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", default=",".join(map(str, DEFAULT_WORKERS)),
                    help="comma-separated worker counts for the sweep")
    args = ap.parse_args(argv)
    widths = tuple(int(x) for x in args.workers.split(",") if x.strip())
    print(json.dumps(run_sweep(widths)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
