"""Object-store durable tier benchmark: write-behind flush throughput and
full vs split scan over the segment layout (fake S3, in-memory).

Measures the costs the Cassandra tier's JMH suite would — segment encode +
upload on the write side, ranged-GET read-back and key-prefix split scans
(the token-range analog used by downsample/repair fan-out) on the read side.
"""

from __future__ import annotations

import time

import numpy as np

START = 1_600_000_000


def bench_objectstore(n_series: int = 200, chunks_per_series: int = 5,
                      rows_per_chunk: int = 400, n_splits: int = 4):
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.core.store.api import PartKeyRecord
    from filodb_tpu.core.store.objectstore import ObjectStoreColumnStore
    from filodb_tpu.memory.chunk import Chunk
    from filodb_tpu.testing.fake_s3 import FakeS3

    s3 = FakeS3()
    cs = ObjectStoreColumnStore(s3, segment_target_bytes=256 * 1024)
    pks = [PartKey.create("gauge", {"_metric_": "bench_os", "_ws_": "demo",
                                    "_ns_": f"app-{i}"})
           for i in range(n_series)]
    rows_ms = rows_per_chunk * 1000

    def mk_chunk(cid, t0):
        ts = np.arange(t0, t0 + rows_ms, 1000, dtype=np.int64)
        vals = np.sin(ts / 7e4)
        return Chunk(cid, rows_per_chunk, int(ts[0]), int(ts[-1]),
                     [ts.tobytes(), vals.tobytes()])

    total_rows = n_series * chunks_per_series * rows_per_chunk
    t0 = time.perf_counter()
    for i, pk in enumerate(pks):
        cs.write_chunks("bench", 0, pk,
                        [mk_chunk(c + 1, START * 1000 + c * rows_ms)
                         for c in range(chunks_per_series)],
                        ingestion_time=i)
    cs.write_part_keys("bench", 0,
                       [PartKeyRecord(pk, START * 1000, 2**62) for pk in pks])
    cs.flush()   # barrier: segments + manifest durable on the fake S3
    write_dt = time.perf_counter() - t0

    t0 = time.perf_counter()
    read_rows = 0
    for pk in pks:
        for ch in cs.read_chunks("bench", 0, pk, 0, 2**62):
            read_rows += ch.num_rows
    read_dt = time.perf_counter() - t0

    t0 = time.perf_counter()
    full = sum(1 for _ in cs.scan_chunks_by_ingestion_time(
        "bench", 0, 0, 2**62))
    full_dt = time.perf_counter() - t0

    t0 = time.perf_counter()
    split_total = 0
    for s in range(n_splits):
        split_total += sum(1 for _ in cs.scan_chunks_by_ingestion_time_split(
            "bench", 0, 0, 2**62, s, n_splits))
    split_dt = time.perf_counter() - t0
    assert split_total == full == n_series
    cs.close()

    return {"metric": "objectstore_flush_throughput",
            "value": round(total_rows / write_dt),
            "unit": "rows/sec",
            "read_rows_per_sec": round(read_rows / read_dt),
            "scan_full_ms": round(full_dt * 1000, 2),
            "scan_split_ms": round(split_dt * 1000, 2),
            "n_splits": n_splits,
            "segments": sum(1 for k in s3.list_objects("")
                            if k.endswith(".seg")),
            "s3_bytes": s3.total_bytes()}


if __name__ == "__main__":
    import json
    print(json.dumps(bench_objectstore()))
