"""Benchmark suite: reproductions of the reference's JMH workloads.

Counterpart of ``jmh/src/main/scala/filodb.jmh/`` (see SURVEY.md §6 /
``run_benchmarks.sh``). Each benchmark prints one JSON line; run all with

    python benchmarks/run_benchmarks.py [--only NAME] [--cpu]

Workload definitions mirror the JMH classes:
- ingestion        — ``IngestionBenchmark``: 100k samples through the shard
  ingest path, samples/sec.
- hist_ingest      — ``HistogramIngestBenchmark``: 30k first-class histograms.
- query_inmemory   — ``QueryInMemoryBenchmark``: handled by ../bench.py.
- query_hicard     — ``QueryHiCardInMemoryBenchmark``: 1 shard, 5k series.
- query_and_ingest — ``QueryAndIngestBenchmark``: queries under concurrent
  ingest.
- hist_query       — ``HistogramQueryBenchmark``: histogram_quantile of rate.
- partkey_index    — ``PartKeyIndexBenchmark``: index add + filter queries.
- gateway          — ``GatewayBenchmark``: Influx line parse ops/sec.
- encoding         — ``EncodingBenchmark``: vector encode/decode ops.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

START = 1_600_000_000


def _force_cpu():
    import jax
    import jax._src.xla_bridge as xb
    xb._backend_factories.pop("axon", None)  # hangs when tunnel is down
    jax.config.update("jax_platforms", "cpu")


def bench_ingestion():
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.testing.data import gauge_stream, machine_metrics_series

    ms = TimeSeriesMemStore()
    shard = ms.setup("bench", 0, StoreConfig(max_chunk_size=400))
    keys = machine_metrics_series(100)
    # shard-ingest of pre-built binary containers (the gateway→log→shard
    # contract; reference IngestionBenchmark likewise pre-builds records)
    from filodb_tpu.core.record import BytesContainer, SomeData
    stream = [SomeData(BytesContainer(sd.container.serialize()), sd.offset)
              for sd in gauge_stream(keys, 1000, start_ms=START * 1000,
                                     batch=500)]
    t0 = time.perf_counter()
    for sd in stream:
        shard.ingest(sd)
    dt = time.perf_counter() - t0
    native = shard._native_core is not None
    return {"metric": "ingestion_throughput", "value": round(100_000 / dt),
            "unit": "samples/sec", "native_lane": native}


def bench_hist_ingest():
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.testing.data import histogram_series, histogram_stream

    ms = TimeSeriesMemStore()
    shard = ms.setup("bench", 0, StoreConfig(max_chunk_size=400))
    keys = histogram_series(30)
    # binary containers (gateway->log->shard contract) take the C++ hist
    # ingest lane (VERDICT r3 #3a / #7)
    from filodb_tpu.core.record import BytesContainer, SomeData
    stream = [SomeData(BytesContainer(sd.container.serialize()), sd.offset)
              for sd in histogram_stream(keys, 1000, start_ms=START * 1000,
                                         batch=500)]
    t0 = time.perf_counter()
    for sd in stream:
        shard.ingest(sd)
    dt = time.perf_counter() - t0
    native = shard._native_core is not None
    return {"metric": "histogram_ingestion_throughput",
            "value": round(30_000 / dt), "unit": "histograms/sec",
            "native_lane": native}


def bench_query_hicard():
    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.testing.data import counter_series, counter_stream

    ms = TimeSeriesMemStore()
    shard = ms.setup("bench", 0, StoreConfig(max_chunk_size=400))
    keys = counter_series(5000, metric="hicard_total")
    for sd in counter_stream(keys, 60, start_ms=START * 1000, batch=5000):
        shard.ingest(sd)
    svc = QueryService(ms, "bench", 1, spread=0, engine="adaptive")
    q = 'sum(rate(hicard_total[5m]))'
    svc.query_range(q, START + 300, 60, START + 540)  # warm
    n = 20
    t0 = time.perf_counter()
    for _ in range(n):
        r = svc.query_range(q, START + 300, 60, START + 540)
    dt = time.perf_counter() - t0
    return {"metric": "hicard_query_throughput", "value": round(n / dt, 2),
            "unit": "queries/sec", "series": 5000}


def bench_query_and_ingest():
    import threading

    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.testing.data import counter_series, counter_stream

    ms = TimeSeriesMemStore()
    shard = ms.setup("bench", 0, StoreConfig(max_chunk_size=400))
    keys = counter_series(100, metric="qi_total")
    for sd in counter_stream(keys, 720, start_ms=START * 1000):
        shard.ingest(sd)
    svc = QueryService(ms, "bench", 1, spread=0, engine="adaptive")
    q = 'sum(rate(qi_total[5m]))'
    svc.query_range(q, START + 3600, 60, START + 5400)
    stop = threading.Event()

    def ingester():
        t = START + 7200
        while not stop.is_set():
            for sd in counter_stream(keys, 10, start_ms=t * 1000, batch=1000):
                shard.ingest(sd)
            t += 100

    th = threading.Thread(target=ingester, daemon=True)
    th.start()
    n = 50
    t0 = time.perf_counter()
    for _ in range(n):
        svc.query_range(q, START + 3600, 60, START + 5400)
    dt = time.perf_counter() - t0
    stop.set()
    th.join(1)
    return {"metric": "query_under_ingest_throughput",
            "value": round(n / dt, 2), "unit": "queries/sec"}


def bench_hist_flat_vs_first_class():
    """First-class histogram columns vs prom-flat bucket-per-series — the
    reference's headline histogram claim (README.md:437: "up to two orders
    of magnitude")."""
    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.core.record import IngestRecord, RecordContainer, SomeData
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.testing.data import histogram_series, histogram_stream

    # the reference's claim regime is high-bucket-count histograms
    # (README.md:437); 64 buckets matches its quoted hist shapes
    n_series, n_samples, nb = 96, 240, 64

    # first-class
    ms1 = TimeSeriesMemStore()
    ms1.setup("bench", 0, StoreConfig(max_chunk_size=400))
    for sd in histogram_stream(histogram_series(n_series), n_samples,
                               start_ms=START * 1000, batch=2000):
        ms1.get_shard("bench", 0).ingest(sd)
    svc1 = QueryService(ms1, "bench", 1, spread=0, engine="mesh")
    q1 = 'histogram_quantile(0.99, sum(rate(http_req_latency[5m])))'

    # prom-flat: same data as bucket-per-series counters
    ms2 = TimeSeriesMemStore()
    ms2.setup("bench", 0, StoreConfig(max_chunk_size=400))
    rng = np.random.default_rng(0)
    c = RecordContainer()
    flat_keys = [[PartKey.create("prom-counter", {
        "_metric_": "lat_bucket", "_ws_": "demo", "_ns_": "App-0",
        "instance": f"i{s}", "le": str(float(b + 1))})
        for b in range(nb)] for s in range(n_series)]
    for s in range(n_series):
        cum = np.zeros(nb)
        for i in range(n_samples):
            cum += np.cumsum(rng.integers(0, 5, nb))
            for b in range(nb):
                c.add(IngestRecord(flat_keys[s][b], (START + i * 10) * 1000,
                                   (float(cum[b]),)))
            if len(c) >= 5000:
                ms2.get_shard("bench", 0).ingest(SomeData(c, i))
                c = RecordContainer()
    if len(c):
        ms2.get_shard("bench", 0).ingest(SomeData(c, 0))
    svc2 = QueryService(ms2, "bench", 1, spread=0, engine="mesh")
    q2 = ('histogram_quantile(0.99, sum(rate(lat_bucket[5m])) '
          'by (le, instance))')

    args1 = (START + 900, 60, START + 2100)
    svc1.query_range(q1, *args1)
    svc2.query_range(q2, *args1)
    n = 15
    t0 = time.perf_counter()
    for _ in range(n):
        svc1.query_range(q1, *args1)
    first_class = n / (time.perf_counter() - t0)
    t0 = time.perf_counter()
    for _ in range(n):
        svc2.query_range(q2, *args1)
    flat = n / (time.perf_counter() - t0)
    return {"metric": "hist_first_class_vs_flat",
            "first_class_qps": round(first_class, 2),
            "prom_flat_qps": round(flat, 2),
            "speedup": round(first_class / flat, 2), "unit": "queries/sec"}


def bench_hist_query():
    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.testing.data import histogram_series, histogram_stream

    ms = TimeSeriesMemStore()
    shard = ms.setup("bench", 0, StoreConfig(max_chunk_size=400))
    keys = histogram_series(20)
    for sd in histogram_stream(keys, 720, start_ms=START * 1000, batch=2000):
        shard.ingest(sd)
    svc = QueryService(ms, "bench", 1, spread=0, engine="adaptive")
    q = 'histogram_quantile(0.99, sum(rate(http_req_latency[5m])))'
    svc.query_range(q, START + 3600, 60, START + 5400)
    n = 30
    t0 = time.perf_counter()
    for _ in range(n):
        svc.query_range(q, START + 3600, 60, START + 5400)
    dt = time.perf_counter() - t0
    return {"metric": "histogram_query_throughput",
            "value": round(n / dt, 2), "unit": "queries/sec"}


def bench_partkey_index():
    from filodb_tpu.core.filters import ColumnFilter, Equals, EqualsRegex
    from filodb_tpu.core.memstore.index import PartKeyIndex
    from filodb_tpu.core.partkey import PartKey

    from filodb_tpu.core.filters import EqualsRegex
    from filodb_tpu.core.memstore.native_shard import part_key_blob

    # keys/filters built in setup, like the reference JMH benchmark
    # (partKeys prepared in @Setup; the measured op is the index call)
    idx = PartKeyIndex()
    n = 50_000
    keys = [PartKey.create("gauge", {
        "_metric_": f"metric_{i % 100}", "_ws_": "demo",
        "_ns_": f"App-{i % 16}", "instance": f"i{i}",
        "host": f"h{i % 1000}"}) for i in range(n)]
    blobs = [part_key_blob(k) for k in keys]
    t0 = time.perf_counter()
    for i, (k, b) in enumerate(zip(keys, blobs)):
        idx.add_part_key_blob(i, k, b, i)
    add_rate = n / (time.perf_counter() - t0)
    m = 2000
    filter_sets = [
        [ColumnFilter("_metric_", Equals(f"metric_{i % 100}")),
         ColumnFilter("_ns_", Equals(f"App-{i % 16}"))]
        for i in range(100)]
    idx.part_ids_from_filters(filter_sets[0], 0, 2**62)  # warm caches
    t0 = time.perf_counter()
    for i in range(m):
        idx.part_ids_from_filters(filter_sets[i % 100], 0, 2**62)
    q_rate = m / (time.perf_counter() - t0)
    regex_sets = [
        [ColumnFilter("_ns_", Equals(f"App-{i % 16}")),
         ColumnFilter("instance", EqualsRegex(f"i{i % 10}.*"))]
        for i in range(20)]
    for fs in regex_sets:
        idx.part_ids_from_filters(fs, 0, 2**62)  # cold scans
    t0 = time.perf_counter()
    for i in range(m):
        idx.part_ids_from_filters(regex_sets[i % 20], 0, 2**62)
    rx_rate = m / (time.perf_counter() - t0)
    return {"metric": "partkey_index", "add_per_sec": round(add_rate),
            "equals_query_per_sec": round(q_rate),
            "regex_query_per_sec": round(rx_rate), "unit": "ops/sec"}


def bench_gateway():
    from filodb_tpu.gateway.influx import parse_influx_line

    lines = [f"cpu,host=h{i % 50},app=api,_ws_=demo,_ns_=App-0 "
             f"value={i}.5 {(START + i) * 1_000_000_000}"
             for i in range(5000)]
    t0 = time.perf_counter()
    for line in lines:
        parse_influx_line(line)
    dt = time.perf_counter() - t0
    return {"metric": "gateway_influx_parse", "value": round(len(lines) / dt),
            "unit": "lines/sec"}


def bench_encoding():
    from filodb_tpu.memory import codecs

    rng = np.random.default_rng(0)
    ts = (np.arange(10_000) * 10_000 + START * 1000
          + rng.integers(-50, 50, 10_000)).astype(np.int64)
    vals = rng.normal(100, 10, 10_000)
    n = 50
    t0 = time.perf_counter()
    for _ in range(n):
        e1 = codecs.encode_delta_delta(ts)
        e2 = codecs.encode_xor_double(vals)
    enc_rate = n * 20_000 / (time.perf_counter() - t0)
    t0 = time.perf_counter()
    for _ in range(n):
        codecs.decode_delta_delta(e1)
        codecs.decode_xor_double(e2)
    dec_rate = n * 20_000 / (time.perf_counter() - t0)
    ratio = (len(e1) + len(e2)) / (ts.nbytes + vals.nbytes)
    return {"metric": "encoding", "encode_samples_per_sec": round(enc_rate),
            "decode_samples_per_sec": round(dec_rate),
            "compression_ratio": round(ratio, 3), "unit": "samples/sec"}


def bench_query_odp():
    """On-demand-paging query throughput (reference
    ``jmh/.../QueryOnDemandBenchmark.scala``): data lives only in the
    column store; queries page chunks back in. ``cold`` clears the paged
    cache every query (pure ODP path incl. store reads + decode); ``warm``
    reuses the demand-paged cache."""
    import tempfile

    from filodb_tpu.coordinator.ingestion import ingest_routed
    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.core.store.localstore import (
        LocalDiskColumnStore,
        LocalDiskMetaStore,
    )
    from filodb_tpu.testing.data import counter_series, counter_stream

    tmp = tempfile.mkdtemp(prefix="filodb-odp-")
    cs = LocalDiskColumnStore(tmp + "/store")
    ms = TimeSeriesMemStore(cs, LocalDiskMetaStore(tmp + "/meta"))
    n_shards = 2
    for s in range(n_shards):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=400,
                                              groups_per_shard=4,
                                              flush_interval_ms=0))
    keys = counter_series(100, metric="heap_usage", ns="App-2")
    stream = counter_stream(keys, 720, start_ms=START * 1000, seed=11)
    ingest_routed(ms, "timeseries", stream, n_shards, spread=1)
    for shard in ms.shards_for("timeseries"):
        shard.flush_all()
        shard.evict_cold_partitions(max_evict=10**9)  # all data now cold
    svc = QueryService(ms, "timeseries", n_shards, spread=1)
    q = 'sum(rate(heap_usage{_ws_="demo",_ns_="App-2"}[5m]))'
    a, b = START + 1800, START + 3600

    def run(m, clear):
        for shard in ms.shards_for("timeseries"):
            shard.batch_cache.clear()
            shard.odp_cache.clear()
        svc.query_range(q, a, 60, b)  # warm compile
        t0 = time.perf_counter()
        for _ in range(m):
            if clear:
                for shard in ms.shards_for("timeseries"):
                    shard.batch_cache.clear()
                    shard.odp_cache.clear()
            r = svc.query_range(q, a, 60, b)
            assert r.result.num_series == 1
        return m / (time.perf_counter() - t0)

    return {"metric": "query_odp", "cold_qps": round(run(50, True), 1),
            "warm_qps": round(run(200, False), 1), "unit": "queries/sec"}


def bench_dict_string():
    """Dict-string column codec micro (reference
    ``jmh/.../DictStringBenchmark.scala``)."""
    from filodb_tpu.memory import codecs

    rng = np.random.default_rng(1)
    vocab = [f"value-{i}" for i in range(64)]
    vals = [vocab[i] for i in rng.integers(0, 64, 10_000)]
    n = 50
    t0 = time.perf_counter()
    for _ in range(n):
        enc = codecs.encode_dict_string(vals)
    enc_rate = n * len(vals) / (time.perf_counter() - t0)
    t0 = time.perf_counter()
    for _ in range(n):
        codecs.decode_dict_string(enc)
    dec_rate = n * len(vals) / (time.perf_counter() - t0)
    return {"metric": "dict_string",
            "encode_strings_per_sec": round(enc_rate),
            "decode_strings_per_sec": round(dec_rate),
            "encoded_bytes": len(enc), "unit": "ops/sec"}


def bench_mesh_churn():
    """Mesh engine under ingest churn and shard imbalance (VERDICT r3 #9):
    q/s with a static store vs with every query preceded by an ingest tick
    (data_version bump -> batch rebuild + re-upload), on a 10:1 skewed
    shard distribution."""
    import sys as _sys
    _sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tests"))
    from test_mesh_stress import NUM_SHARDS, skewed_store
    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.core.record import (
        IngestRecord,
        RecordContainer,
        SomeData,
    )

    ms = skewed_store(per_shard=(80, 8, 8, 8), n_samples=120)
    svc = QueryService(ms, "timeseries", NUM_SHARDS, spread=1,
                       engine="mesh")
    q = 'sum(rate(skew_total[5m])) by (shardtag)'
    args = (START + 400, 10, START + 1100)
    svc.query_range(q, *args)  # warm/compile
    n = 30
    t0 = time.perf_counter()
    for _ in range(n):
        svc.query_range(q, *args)
    static_qps = n / (time.perf_counter() - t0)

    key = PartKey.create("prom-counter", {
        "_metric_": "skew_total", "_ws_": "demo", "_ns_": "App-0",
        "shardtag": "s0", "instance": "i0-0"})
    shard = ms.get_shard("timeseries", 0)
    t0 = time.perf_counter()
    for i in range(n):
        c = RecordContainer()
        c.add(IngestRecord(key, (START + (121 + i) * 10) * 1000,
                           (1e6 + i,)))
        shard.ingest(SomeData(c, 10_000 + i))
        svc.query_range(q, *args)
    churn_qps = n / (time.perf_counter() - t0)
    eng = svc.mesh_engine
    return {"metric": "mesh_churn", "static_qps": round(static_qps, 1),
            "churn_qps": round(churn_qps, 1),
            "rebuild_overhead_x": round(static_qps / churn_qps, 2),
            "mesh_hit_rate": round(eng.hit_rate, 3),
            "skew": "10:1 over 4 shards", "unit": "queries/sec"}


def _bench_dist_agg():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from dist_agg import bench_dist_agg
    return bench_dist_agg()


def _bench_objectstore():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from objectstore import bench_objectstore
    return bench_objectstore()


def _bench_overload():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from overload import bench_overload
    return bench_overload()


def _bench_migration():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from migration import bench_migration
    return bench_migration()


def _bench_replication():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from replication import bench_replication
    return bench_replication()


def _bench_rules():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from rules import bench_rules
    return bench_rules()


def _bench_sidecars():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from sidecars import bench_sidecars
    return bench_sidecars()


def _bench_tracing_overhead():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tracing_overhead import bench_tracing_overhead
    return bench_tracing_overhead()


def _bench_selfmon_overhead():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from selfmon_overhead import bench_selfmon_overhead
    return bench_selfmon_overhead()


def _bench_federation():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from federation import bench_federation
    return bench_federation()


def _bench_federation_yearscan():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from federation import bench_federation_yearscan
    return bench_federation_yearscan()


def _bench_pyramid_topk_1m():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from federation import bench_pyramid_topk_1m
    return bench_pyramid_topk_1m()


def _bench_adaptive():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from adaptive import bench_adaptive
    return bench_adaptive()


def _bench_multiproc_mesh():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from multiproc_mesh import run_sweep
    return run_sweep()


def _bench_mesh_scaling(devices=None):
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from mesh_scaling import DEFAULT_DEVICES, run_sweep
    return run_sweep(tuple(devices) if devices else DEFAULT_DEVICES)


ALL = {
    "ingestion": bench_ingestion,
    "hist_ingest": bench_hist_ingest,
    "query_hicard": bench_query_hicard,
    "query_and_ingest": bench_query_and_ingest,
    "hist_query": bench_hist_query,
    "hist_flat_vs_fc": bench_hist_flat_vs_first_class,
    "partkey_index": bench_partkey_index,
    "gateway": bench_gateway,
    "encoding": bench_encoding,
    "query_odp": bench_query_odp,
    "dict_string": bench_dict_string,
    "mesh_churn": bench_mesh_churn,
    "dist_agg": _bench_dist_agg,
    "overload": _bench_overload,
    "objectstore": _bench_objectstore,
    "migration": _bench_migration,
    "replication": _bench_replication,
    "rules": _bench_rules,
    "sidecars": _bench_sidecars,
    "tracing_overhead": _bench_tracing_overhead,
    "selfmon_overhead": _bench_selfmon_overhead,
    "federation": _bench_federation,
    "federation_yearscan": _bench_federation_yearscan,
    "pyramid_topk_1m": _bench_pyramid_topk_1m,
    "adaptive": _bench_adaptive,
    "mesh_scaling": _bench_mesh_scaling,
    "multiproc_mesh": _bench_multiproc_mesh,
}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", default=None)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--devices", default=None,
                    help="comma-separated mesh widths; runs ONLY the "
                         "mesh_scaling sweep at those sizes (each width in "
                         "a child process, so --cpu is implied there)")
    args = ap.parse_args(argv)
    if args.cpu:
        _force_cpu()
    if args.devices:
        widths = [int(x) for x in args.devices.split(",") if x.strip()]
        out = _bench_mesh_scaling(widths)
        out["benchmark"] = "mesh_scaling"
        print(json.dumps(out), flush=True)
        return
    for name, fn in ALL.items():
        if args.only and name != args.only:
            continue
        out = fn()
        out["benchmark"] = name
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    sys.exit(main())
