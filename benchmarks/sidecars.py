"""Chunk aggregate sidecars vs full decode: where each lane wins.

The sidecar fold pays O(chunks) per series — decode the (at most two)
edge chunks per window, fold every interior chunk from its fixed-size
seal-time summary — where the decode lane pays O(samples). The fold's
only edge over decode is the interior samples it never touches, so the
economics hinge on chunk size and cache state:

* ``cold_tick_large_chunks`` — the design-center workload: an alert
  probe over series with large sealed chunks whose decoded arrays are
  not resident (steady-state ingest keeps sealing fresh chunks and
  memory pressure evicts decode memos). Interiors fold in O(1);
  decode pays the full window. The lane wins, and the win grows with
  chunk size.
* ``cold_scan_medium_chunks`` — a dashboard range scan over medium
  chunks, cold. Less interior skipped per partition-window, smaller win.
* ``wide_fanout_batched_fold`` — 1024 partitions x 6 steps: 6144
  partition-windows, ABOVE the pre-batching gate default (4096) and
  well under the current one (65536). The flat-batch sealed fold
  (``_eval_sealed_batch``) amortizes the python cost across the whole
  group in one composite-key pass, so the lane now wins where the
  per-partition fold used to bypass — the measurement the 16x gate
  widening rests on.
* ``gated_scan_small_chunks`` — many partitions, small chunks, warm
  decode memos: tiny chunk spans leave almost no interior to skip, the
  amortization check (``FILODB_SIDECAR_SEALED_GATE`` + the
  skipped-samples estimate) detects it from chunk geometry and the
  lane bypasses. Reported to show the gate holds the lane at parity
  instead of regressing.

Identical stores and queries per scenario; the valve (``FILODB_SIDECARS``)
is the only variable. "Cold" scenarios drop per-chunk decode memos and
batch caches between timed passes; the gated scenario runs warm (its
point is the bypass, not the decode cost).
"""

from __future__ import annotations

import os
import time

START = 1_600_000_000

SCENARIOS = [
    {"name": "cold_tick_large_chunks", "series": 128, "chunk": 2048,
     "samples": 16384, "window": "2040m", "steps": 1, "cold": True,
     "queries": ["sum(avg_over_time(heap_usage[{w}]))",
                 "sum(rate(http_requests_total[{w}]))"]},
    {"name": "cold_scan_medium_chunks", "series": 256, "chunk": 512,
     "samples": 6144, "window": "680m", "steps": 6, "cold": True,
     "queries": ["sum(avg_over_time(heap_usage[{w}]))"]},
    {"name": "wide_fanout_batched_fold", "series": 1024, "chunk": 512,
     "samples": 3072, "window": "500m", "steps": 6, "cold": True,
     "queries": ["sum(avg_over_time(heap_usage[{w}]))"]},
    {"name": "gated_scan_small_chunks", "series": 1024, "chunk": 64,
     "samples": 720, "window": "40m", "steps": 6, "cold": False,
     "queries": ["sum(avg_over_time(heap_usage[{w}]))"]},
]
REPEATS = 3


def _build(sc):
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.testing.data import (
        counter_series,
        counter_stream,
        gauge_stream,
        machine_metrics_series,
    )
    ms = TimeSeriesMemStore()
    shard = ms.setup("bench", 0, StoreConfig(max_chunk_size=sc["chunk"]))
    n_gauge = sc["series"]
    streams = [gauge_stream(machine_metrics_series(n_gauge), sc["samples"],
                            start_ms=START * 1000, seed=11)]
    if any("http_requests" in q for q in sc["queries"]):
        streams.append(counter_stream(counter_series(n_gauge // 4),
                                      sc["samples"], start_ms=START * 1000,
                                      seed=3, reset_every=300))
    for stream in streams:
        for batch in stream:
            shard.ingest(batch)
    return ms


def _go_cold(ms):
    """Steady-state ingest proxy: decoded-chunk memos and batch caches
    are not resident when the next probe fires."""
    for shard in ms.shards_for("bench"):
        shard.batch_cache.clear()
        for pid in shard.lookup_partitions([], 0, 2 ** 62):
            p = shard.partition(pid)
            if p is None:
                continue
            for ch in p.chunks:
                ch.__dict__.pop("_decoded", None)


def bench_sidecars():
    from filodb_tpu.coordinator.query_service import QueryService

    rows = []
    for sc in SCENARIOS:
        ms = _build(sc)
        end = START + (sc["samples"] - 1) * 10
        qs = end - (sc["steps"] - 1) * 60
        queries = [q.format(w=sc["window"]) for q in sc["queries"]]

        def run(mode):
            os.environ["FILODB_SIDECARS"] = mode
            svc = QueryService(ms, "bench", 1, spread=0)
            out = {}
            for q in queries:
                svc.query_range(q, qs, 60, end)      # compile / warm code
                t_best = float("inf")
                for _ in range(REPEATS):
                    if sc["cold"]:
                        _go_cold(ms)
                    else:
                        for shard in ms.shards_for("bench"):
                            shard.batch_cache.clear()
                    t0 = time.perf_counter()
                    r = svc.query_range(q, qs, 60, end)
                    t_best = min(t_best, time.perf_counter() - t0)
                    assert r.result.num_series == 1
                out[q] = (t_best * 1000, r.stats)
            return out

        try:
            decode = run("0")
            sidecar = run("1")
        finally:
            os.environ.pop("FILODB_SIDECARS", None)

        for q in queries:
            d_ms, _ = decode[q]
            s_ms, st = sidecar[q]
            rows.append({
                "scenario": sc["name"],
                "query": q,
                "decode_ms": round(d_ms, 2),
                "sidecar_ms": round(s_ms, 2),
                "speedup": round(d_ms / s_ms, 2),
                "sidecar_chunks": st.sidecar_chunks,
                "decoded_chunks": st.chunks_touched - st.sidecar_chunks,
            })
    return {"metric": "sidecar_vs_decode", "unit": "ms/query",
            "repeats": REPEATS, "rows": rows}


if __name__ == "__main__":
    import json
    print(json.dumps(bench_sidecars(), indent=2))
